"""Vectorized node-utilization classification for the descheduler.

Semantics oracle: pkg/descheduler/framework/plugins/loadaware/
{low_node_load.go:286-326, utilization_util.go getNodeThresholds /
isNodeOverutilized / isNodeUnderutilized / calcAverageResourceUsagePercent,
newThresholds}. The reference classifies nodes one by one; here the whole
(nodes × resources) matrix classifies in one fused pass so a 5k-node pool
(BASELINE config #5) is a single vector op.

Two stages, split by arithmetic domain:

- ``threshold_quantities`` resolves percent thresholds into absolute
  quantities on the host in **float64**, because the reference's
  ``resourceThreshold`` computes ``int64(float64(pct) * 0.01 *
  float64(capacity))`` — float rounding included (0.29 * 100 truncates
  to 28, not 29). Integer ``pct * cap // 100`` is NOT equivalent, and
  these quantities are the semantics the oracle checks bit-for-bit.
  It also resolves the *participating resource set* (``resourceNames``
  in the reference): union of low/high threshold names **plus memory,
  always** (utilization_util.go newThresholds), missing entries filled
  with 100% (or 0% in deviation mode, which resolves to full capacity).
- ``classify_nodes`` compares usage against the resolved quantities as
  one vector op: *underutilized* iff usage <= low_q on every
  participating resource, *overutilized* iff usage > high_q on any.

Both stages run on the HOST in numpy. At descheduler pool sizes the
classification is a [N, 8] compare — microseconds — while a device
round trip through a tunneled TPU costs ~100 ms; r5 measured the
device-classify sweep at 2.3/s vs ~10/s host (the placement solver's
measured host-fallback logic, applied to this op's scale).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.apis.extension import ResourceName
from koordinator_tpu.obs.device import DEVICE_OBS


class RebalanceVerdict(NamedTuple):
    low: np.ndarray          # [N] bool: underutilized
    high: np.ndarray         # [N] bool: overutilized
    over_resource: np.ndarray  # [N, R] bool: which resources are over
    low_quantity: np.ndarray   # [N, R] i64 resolved low threshold quantities
    high_quantity: np.ndarray  # [N, R] i64 resolved high threshold quantities


def threshold_quantities(
    usage: np.ndarray,        # [N, R] int
    alloc: np.ndarray,        # [N, R] int capacity/allocatable
    low_percent: np.ndarray,  # [R] int, -1 = unset
    high_percent: np.ndarray,  # [R] int, -1 = unset
    active: np.ndarray,       # [N] bool (nodes with fresh metrics)
    use_deviation: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Resolve (low_q, high_q, resource_mask) exactly as the reference.

    resource_mask[r] is True iff r participates at all (is in the
    reference's ``resourceNames``): explicitly thresholded on either
    side, or MEMORY (always appended by newThresholds). Non-participating
    resources never classify a node and get quantity = capacity so any
    downstream compare is inert.
    """
    alloc = np.asarray(alloc, dtype=np.int64)
    usage = np.asarray(usage, dtype=np.int64)
    low_percent = np.asarray(low_percent, dtype=np.int64)
    high_percent = np.asarray(high_percent, dtype=np.int64)
    mask = (low_percent >= 0) | (high_percent >= 0)
    mask[int(ResourceName.MEMORY)] = True

    # missing names fill with MaxResourcePercentage (100) — or
    # MinResourcePercentage (0) in deviation mode, where the 0 fill is
    # special-cased to full capacity (getNodeThresholds:100-102)
    fill = 0.0 if use_deviation else 100.0
    low_p = np.where(low_percent >= 0, low_percent, fill).astype(np.float64)
    high_p = np.where(high_percent >= 0, high_percent, fill).astype(np.float64)

    if use_deviation:
        # calcAverageResourceUsagePercent: float percent per (node,
        # resource) over nodes with usable metrics, zero-capacity
        # resources skipped, averaged over that node count
        n_active = max(int(np.asarray(active).sum()), 1)
        with np.errstate(divide="ignore", invalid="ignore"):
            pct = np.where(
                alloc > 0, usage.astype(np.float64) / alloc * 100.0, 0.0
            )
        avg = (pct * np.asarray(active, dtype=np.float64)[:, None]).sum(
            axis=0
        ) / n_active
        dev_low = np.clip(avg - low_p, 0.0, 100.0)
        dev_high = np.clip(avg + high_p, 0.0, 100.0)
        # Reference quirk, kept deliberately (getNodeThresholds:100-102):
        # the full-capacity special case keys BOTH sides off the LOW
        # percent equaling MinResourcePercentage. So with only a high
        # threshold set (low filled to 0), both resolve to capacity —
        # the explicit high threshold is inert in deviation mode — and
        # with only a low threshold set, the high side resolves to
        # avg+0: anything above pool average is overutilized.
        low_q = np.where(
            low_p == 0.0, alloc,
            (dev_low[None, :] * 0.01 * alloc.astype(np.float64)).astype(
                np.int64
            ),
        )
        high_q = np.where(
            low_p == 0.0, alloc,
            (dev_high[None, :] * 0.01 * alloc.astype(np.float64)).astype(
                np.int64
            ),
        )
    else:
        # q = int64(float64(pct) * 0.01 * float64(cap)) — float on
        # purpose, see module docstring
        low_q = (low_p[None, :] * 0.01 * alloc.astype(np.float64)).astype(
            np.int64
        )
        high_q = (high_p[None, :] * 0.01 * alloc.astype(np.float64)).astype(
            np.int64
        )
    low_q = np.where(mask[None, :], low_q, alloc)
    high_q = np.where(mask[None, :], high_q, alloc)
    return low_q, high_q, mask


def classify_nodes(
    usage,          # [N, R] int
    low_q,          # [N, R] int resolved low quantities
    high_q,         # [N, R] int resolved high quantities
    resource_mask,  # [R] bool: participates in classification
    active,         # [N] bool: nodes participating (pool + fresh
                    # metric, reference low_node_load.go:153)
    schedulable,    # [N] bool: unschedulable nodes can't be "low"
) -> RebalanceVerdict:
    usage = np.asarray(usage, dtype=np.int64)
    low_q = np.asarray(low_q, dtype=np.int64)
    high_q = np.asarray(high_q, dtype=np.int64)
    resource_mask = np.asarray(resource_mask, bool)
    active = np.asarray(active, bool)
    schedulable = np.asarray(schedulable, bool)

    under_each = (usage <= low_q) | ~resource_mask[None, :]
    over_each = (usage > high_q) & resource_mask[None, :]

    low = under_each.all(axis=1) & active & schedulable
    high = over_each.any(axis=1) & active
    return RebalanceVerdict(low, high, over_each, low_q, high_q)


# -- the device Balance sweep (docs/DESIGN.md §27) ---------------------------
#
# The host sweep above classifies; the EVICTION sweep (reference
# low_node_load.go balanceNodes → evictPodsFromSourceNodes) then walks
# abnormal nodes in score order and pods in sort-key order, stopping per
# node when it drops below its high threshold and globally when the low
# nodes' absorbing headroom is exhausted. That walk is sequential state —
# available and node usage shrink as victims are chosen — so the port is
# a ``lax.scan`` over the HOST-ORDERED flattened candidate list (node
# score sort and pod sort-key order are pure host preprocessing, kept
# verbatim in descheduler/loadaware.py), with the carry holding exactly
# the two mutating vectors:
#
#   carry = (available [R] i32, cur_usage [R] i32)   # cur = current node
#   per candidate: cur     = where(node_start, usage0, cur)
#                  over    = any((cur > high_q) & res_mask)
#                  avail_ok= ~any((available <= 0) & res_mask)
#                  propose = valid & over & avail_ok & ~blocked
#                  subtract the masked metric from both on propose
#
# Three monotonicities make the flat scan reproduce the nested loops
# bit-for-bit: ``over`` is monotone-false within a node (usage only
# decreases), ``available`` is monotone nonincreasing (so the global
# exhaustion exit persists across later nodes), and a ``blocked``
# candidate (an evictor refusal) changes no state — so the per-candidate
# (propose, over, avail_ok) stream is sufficient for the caller to
# replay every host-side side effect (proposal order, detector resets,
# early exits). ``blocked`` is how the arbiter's deferrals and the
# evictor's refusals feed back: the caller re-runs the scan with the
# refused candidate masked, and the decision prefix up to that candidate
# is invariant (nothing earlier depended on it).
#
# All quantities are host int64; staging validates that every value AND
# every reachable endpoint (available minus all masked metrics, per-node
# usage minus that node's metrics) fits int32 and raises ValueError
# otherwise — the x32 substrate contract (§24), loud instead of clipped.


def sweep_candidate_bucket(n: int) -> int:
    """Pad the flattened candidate axis to a power of two (min 8) so
    recompiles stay logarithmic in storm size."""
    n = int(n)
    return max(8, 1 << max(n - 1, 0).bit_length())


class SweepBatch(NamedTuple):
    """The staged flattened candidate list, host order (node score
    order, pod sort-key order within a node). All numpy, i32/bool."""

    node_start: np.ndarray  # [K] bool: candidate i is its node's first
    usage0: np.ndarray      # [K, R] i32: owning node's usage at entry
    high_q: np.ndarray      # [K, R] i32: owning node's high quantities
    metric: np.ndarray      # [K, R] i32: pod usage (0 where unknown)
    has_metric: np.ndarray  # [K] bool: pod usage is known
    valid: np.ndarray       # [K] bool: real row (False = padding)


def _balance_sweep(node_start, usage0, high_q, metric, has_metric,
                   valid, blocked, available0, res_mask):
    def step(carry, xs):
        avail, cur = carry
        start, u0, hq, m, hm, ok, blk = xs
        cur = jnp.where(start, u0, cur)
        over = jnp.any((cur > hq) & res_mask)
        avail_ok = ~jnp.any((avail <= 0) & res_mask)
        propose = ok & over & avail_ok & ~blk
        sub = jnp.where(propose & hm, jnp.where(res_mask, m, 0), 0)
        return (avail - sub, cur - sub), (propose, over, avail_ok)

    init = (available0, jnp.zeros_like(available0))
    xs = (node_start, usage0, high_q, metric, has_metric, valid, blocked)
    (avail, _), ys = jax.lax.scan(step, init, xs)
    propose, over, avail_ok = ys
    return propose, over, avail_ok, avail


rebalance_sweep = DEVICE_OBS.jit(
    "rebalance_sweep",
    jax.jit(_balance_sweep, donate_argnums=(), static_argnums=()),
)

_I32_MIN = np.iinfo(np.int32).min
_I32_MAX = np.iinfo(np.int32).max


def _require_i32(name: str, arr: np.ndarray) -> None:
    arr = np.asarray(arr)
    if arr.size and (
        int(arr.min()) < _I32_MIN or int(arr.max()) > _I32_MAX
    ):
        raise ValueError(
            f"rebalance sweep {name} exceeds the int32 device domain "
            f"[{int(arr.min())}, {int(arr.max())}] — the x32 substrate "
            "contract (docs/DESIGN.md §24) requires quantities staged "
            "in device units that fit i32"
        )


def run_balance_sweep(
    batch: SweepBatch,
    available: np.ndarray,   # [R] i64: absorbing headroom on low nodes
    res_mask: np.ndarray,    # [R] bool: participating resources
    blocked: np.ndarray,     # [K] bool: refused candidates (masked out)
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Stage, pad, and run the sweep; return host (propose, over,
    avail_ok) trimmed to the real candidate count."""
    k = int(batch.valid.shape[0])
    available = np.asarray(available, dtype=np.int64)
    res_mask = np.asarray(res_mask, bool)
    blocked = np.asarray(blocked, bool)
    # endpoint validation: every staged value, plus the furthest the
    # carry can travel (all masked metrics subtracted)
    masked = np.where(res_mask[None, :], batch.metric, 0).astype(np.int64)
    _require_i32("usage", batch.usage0)
    _require_i32("high quantities", batch.high_q)
    _require_i32("pod metrics", batch.metric)
    _require_i32("available headroom", available)
    _require_i32("available endpoint", available - masked.sum(axis=0))
    if k:
        if not batch.node_start[0]:
            raise ValueError(
                "sweep batch must open with a node_start candidate"
            )
        # per-node endpoint: entry usage minus that node's metric total
        group = np.cumsum(np.asarray(batch.node_start, bool)) - 1
        starts = np.flatnonzero(batch.node_start)
        if starts.size:
            node_total = np.zeros(
                (starts.size, masked.shape[1]), dtype=np.int64
            )
            np.add.at(node_total, group, masked)
            _require_i32(
                "usage endpoint",
                batch.usage0[starts].astype(np.int64) - node_total,
            )
    target = sweep_candidate_bucket(k)
    if target != k:
        DEVICE_OBS.note_padding("sweep_candidates", k, target)
    pad = target - k

    def pad1(a, fill=0):
        if not pad:
            return a
        width = ((0, pad),) + ((0, 0),) * (a.ndim - 1)
        return np.pad(a, width, constant_values=fill)

    propose, over, avail_ok, _ = rebalance_sweep(
        jnp.asarray(pad1(batch.node_start), dtype=bool),
        jnp.asarray(pad1(batch.usage0), dtype=jnp.int32),
        jnp.asarray(pad1(batch.high_q), dtype=jnp.int32),
        jnp.asarray(pad1(batch.metric), dtype=jnp.int32),
        jnp.asarray(pad1(batch.has_metric), dtype=bool),
        jnp.asarray(pad1(batch.valid), dtype=bool),
        jnp.asarray(pad1(blocked), dtype=bool),
        jnp.asarray(available, dtype=jnp.int32),
        jnp.asarray(res_mask, dtype=bool),
    )
    return (
        np.asarray(propose, bool)[:k],
        np.asarray(over, bool)[:k],
        np.asarray(avail_ok, bool)[:k],
    )


def replay_sweep_host(
    batch: SweepBatch,
    available: np.ndarray,
    res_mask: np.ndarray,
    blocked: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pure-numpy replica of the scan, same flattened candidates — the
    verify backend's second opinion (asserted bit-equal to the device
    stream before anything is applied)."""
    res_mask = np.asarray(res_mask, bool)
    avail = np.asarray(available, dtype=np.int64).copy()
    cur = np.zeros_like(avail)
    k = int(batch.valid.shape[0])
    propose = np.zeros(k, bool)
    over_s = np.zeros(k, bool)
    ok_s = np.zeros(k, bool)
    for i in range(k):
        if batch.node_start[i]:
            cur = batch.usage0[i].astype(np.int64).copy()
        over = bool(((cur > batch.high_q[i]) & res_mask).any())
        avail_ok = not bool(((avail <= 0) & res_mask).any())
        p = bool(batch.valid[i]) and over and avail_ok and not bool(
            blocked[i]
        )
        if p and batch.has_metric[i]:
            sub = np.where(res_mask, batch.metric[i], 0).astype(np.int64)
            avail -= sub
            cur -= sub
        propose[i], over_s[i], ok_s[i] = p, over, avail_ok
    return propose, over_s, ok_s
