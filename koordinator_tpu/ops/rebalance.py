"""Vectorized node-utilization classification for the descheduler.

Semantics oracle: pkg/descheduler/framework/plugins/loadaware/
{low_node_load.go:286-326, utilization_util.go getNodeThresholds /
isNodeOverutilized / isNodeUnderutilized / calcAverageResourceUsagePercent}.
The reference classifies nodes one by one; here the whole (nodes ×
resources) matrix classifies in one fused XLA computation so a 5k-node
pool (BASELINE config #5) is a single device pass.

Threshold quantities follow the reference exactly:
``q = int(percent * 0.01 * capacity)`` (truncation), a node is
*underutilized* iff usage <= low_q on every thresholded resource, and
*overutilized* iff usage > high_q on any. A percent of -1 marks an unset
threshold: the resource never triggers (its threshold becomes capacity).
Deviation mode offsets thresholds by the pool's average utilization
percent.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class RebalanceVerdict(NamedTuple):
    low: jax.Array          # [N] bool: underutilized
    high: jax.Array         # [N] bool: overutilized
    over_resource: jax.Array  # [N, R] bool: which resources are over
    low_quantity: jax.Array   # [N, R] i32 resolved low threshold quantities
    high_quantity: jax.Array  # [N, R] i32 resolved high threshold quantities


def classify_nodes(
    usage: jax.Array,        # [N, R] int
    alloc: jax.Array,        # [N, R] int capacity/allocatable
    low_percent: jax.Array,  # [R] int, -1 = unset
    high_percent: jax.Array,  # [R] int, -1 = unset
    active: jax.Array,       # [N] bool: nodes participating (pool + fresh
                             # metric, reference low_node_load.go:153)
    schedulable: jax.Array,  # [N] bool: unschedulable nodes can't be "low"
    use_deviation: bool = False,
) -> RebalanceVerdict:
    usage = usage.astype(jnp.int32)
    alloc = alloc.astype(jnp.int32)
    thresholded = low_percent >= 0

    low_p = jnp.where(thresholded, low_percent, 100).astype(jnp.int32)
    high_p = jnp.where(high_percent >= 0, high_percent, 100).astype(jnp.int32)

    if use_deviation:
        # pool-average utilization percent per resource (reference:
        # calcAverageResourceUsagePercent — mean over active nodes of
        # usage*100/capacity, integer division per node)
        node_pct = jnp.where(
            alloc > 0, usage * 100 // jnp.maximum(alloc, 1), 0
        )
        n_active = jnp.maximum(active.sum(), 1)
        avg = (node_pct * active[:, None]).sum(axis=0) // n_active
        low_p = jnp.clip(avg - low_p, 0, 100)
        high_p = jnp.clip(avg + high_p, 0, 100)
        low_p = jnp.where(thresholded, low_p, 100)
        high_p = jnp.where(high_percent >= 0, high_p, 100)

    # q = trunc(percent * 0.01 * capacity), exact in integer math
    low_q = low_p[None, :] * alloc // 100
    high_q = high_p[None, :] * alloc // 100

    under_each = usage <= low_q
    over_each = (usage > high_q) & (high_percent >= 0)[None, :]

    low = under_each.all(axis=1) & active & schedulable
    high = over_each.any(axis=1) & active
    return RebalanceVerdict(low, high, over_each, low_q, high_q)
