"""Vectorized node-utilization classification for the descheduler.

Semantics oracle: pkg/descheduler/framework/plugins/loadaware/
{low_node_load.go:286-326, utilization_util.go getNodeThresholds /
isNodeOverutilized / isNodeUnderutilized / calcAverageResourceUsagePercent,
newThresholds}. The reference classifies nodes one by one; here the whole
(nodes × resources) matrix classifies in one fused pass so a 5k-node pool
(BASELINE config #5) is a single vector op.

Two stages, split by arithmetic domain:

- ``threshold_quantities`` resolves percent thresholds into absolute
  quantities on the host in **float64**, because the reference's
  ``resourceThreshold`` computes ``int64(float64(pct) * 0.01 *
  float64(capacity))`` — float rounding included (0.29 * 100 truncates
  to 28, not 29). Integer ``pct * cap // 100`` is NOT equivalent, and
  these quantities are the semantics the oracle checks bit-for-bit.
  It also resolves the *participating resource set* (``resourceNames``
  in the reference): union of low/high threshold names **plus memory,
  always** (utilization_util.go newThresholds), missing entries filled
  with 100% (or 0% in deviation mode, which resolves to full capacity).
- ``classify_nodes`` compares usage against the resolved quantities as
  one vector op: *underutilized* iff usage <= low_q on every
  participating resource, *overutilized* iff usage > high_q on any.

Both stages run on the HOST in numpy. At descheduler pool sizes the
classification is a [N, 8] compare — microseconds — while a device
round trip through a tunneled TPU costs ~100 ms; r5 measured the
device-classify sweep at 2.3/s vs ~10/s host (the placement solver's
measured host-fallback logic, applied to this op's scale).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import numpy as np

from koordinator_tpu.apis.extension import ResourceName


class RebalanceVerdict(NamedTuple):
    low: np.ndarray          # [N] bool: underutilized
    high: np.ndarray         # [N] bool: overutilized
    over_resource: np.ndarray  # [N, R] bool: which resources are over
    low_quantity: np.ndarray   # [N, R] i64 resolved low threshold quantities
    high_quantity: np.ndarray  # [N, R] i64 resolved high threshold quantities


def threshold_quantities(
    usage: np.ndarray,        # [N, R] int
    alloc: np.ndarray,        # [N, R] int capacity/allocatable
    low_percent: np.ndarray,  # [R] int, -1 = unset
    high_percent: np.ndarray,  # [R] int, -1 = unset
    active: np.ndarray,       # [N] bool (nodes with fresh metrics)
    use_deviation: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Resolve (low_q, high_q, resource_mask) exactly as the reference.

    resource_mask[r] is True iff r participates at all (is in the
    reference's ``resourceNames``): explicitly thresholded on either
    side, or MEMORY (always appended by newThresholds). Non-participating
    resources never classify a node and get quantity = capacity so any
    downstream compare is inert.
    """
    alloc = np.asarray(alloc, dtype=np.int64)
    usage = np.asarray(usage, dtype=np.int64)
    low_percent = np.asarray(low_percent, dtype=np.int64)
    high_percent = np.asarray(high_percent, dtype=np.int64)
    mask = (low_percent >= 0) | (high_percent >= 0)
    mask[int(ResourceName.MEMORY)] = True

    # missing names fill with MaxResourcePercentage (100) — or
    # MinResourcePercentage (0) in deviation mode, where the 0 fill is
    # special-cased to full capacity (getNodeThresholds:100-102)
    fill = 0.0 if use_deviation else 100.0
    low_p = np.where(low_percent >= 0, low_percent, fill).astype(np.float64)
    high_p = np.where(high_percent >= 0, high_percent, fill).astype(np.float64)

    if use_deviation:
        # calcAverageResourceUsagePercent: float percent per (node,
        # resource) over nodes with usable metrics, zero-capacity
        # resources skipped, averaged over that node count
        n_active = max(int(np.asarray(active).sum()), 1)
        with np.errstate(divide="ignore", invalid="ignore"):
            pct = np.where(
                alloc > 0, usage.astype(np.float64) / alloc * 100.0, 0.0
            )
        avg = (pct * np.asarray(active, dtype=np.float64)[:, None]).sum(
            axis=0
        ) / n_active
        dev_low = np.clip(avg - low_p, 0.0, 100.0)
        dev_high = np.clip(avg + high_p, 0.0, 100.0)
        # Reference quirk, kept deliberately (getNodeThresholds:100-102):
        # the full-capacity special case keys BOTH sides off the LOW
        # percent equaling MinResourcePercentage. So with only a high
        # threshold set (low filled to 0), both resolve to capacity —
        # the explicit high threshold is inert in deviation mode — and
        # with only a low threshold set, the high side resolves to
        # avg+0: anything above pool average is overutilized.
        low_q = np.where(
            low_p == 0.0, alloc,
            (dev_low[None, :] * 0.01 * alloc.astype(np.float64)).astype(
                np.int64
            ),
        )
        high_q = np.where(
            low_p == 0.0, alloc,
            (dev_high[None, :] * 0.01 * alloc.astype(np.float64)).astype(
                np.int64
            ),
        )
    else:
        # q = int64(float64(pct) * 0.01 * float64(cap)) — float on
        # purpose, see module docstring
        low_q = (low_p[None, :] * 0.01 * alloc.astype(np.float64)).astype(
            np.int64
        )
        high_q = (high_p[None, :] * 0.01 * alloc.astype(np.float64)).astype(
            np.int64
        )
    low_q = np.where(mask[None, :], low_q, alloc)
    high_q = np.where(mask[None, :], high_q, alloc)
    return low_q, high_q, mask


def classify_nodes(
    usage,          # [N, R] int
    low_q,          # [N, R] int resolved low quantities
    high_q,         # [N, R] int resolved high quantities
    resource_mask,  # [R] bool: participates in classification
    active,         # [N] bool: nodes participating (pool + fresh
                    # metric, reference low_node_load.go:153)
    schedulable,    # [N] bool: unschedulable nodes can't be "low"
) -> RebalanceVerdict:
    usage = np.asarray(usage, dtype=np.int64)
    low_q = np.asarray(low_q, dtype=np.int64)
    high_q = np.asarray(high_q, dtype=np.int64)
    resource_mask = np.asarray(resource_mask, bool)
    active = np.asarray(active, bool)
    schedulable = np.asarray(schedulable, bool)

    under_each = (usage <= low_q) | ~resource_mask[None, :]
    over_each = (usage > high_q) & resource_mask[None, :]

    low = under_each.all(axis=1) & active & schedulable
    high = over_each.any(axis=1) & active
    return RebalanceVerdict(low, high, over_each, low_q, high_q)
