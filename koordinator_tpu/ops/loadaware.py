"""LoadAware filter + scoring, batched over the node axis.

TPU-native rebuild of the reference's LoadAwareScheduling plugin
(pkg/scheduler/plugins/loadaware/load_aware.go). Semantics (SURVEY.md
A.1/A.2), reproduced bit-exactly given canonical-unit inputs:

Filter — a node is schedulable for the pod unless a thresholded resource's
utilization percentage meets/exceeds its threshold. Skips (passes) when the
pod is DaemonSet-owned or the node has no fresh NodeMetric. Prod pods with
prod thresholds configured compare the *prod pods' usage sum* instead of
whole-node usage.

Score — estimated-used = estimate(pod) + node usage + assigned-pod
estimation correction (``est_extra``, precomputed at lowering; see
state/cluster.py), scored with the weighted least-requested formula.
"""

from __future__ import annotations

import jax.numpy as jnp

from koordinator_tpu.ops.common import (
    least_requested_score,
    percent_rounded,
    weighted_mean_scores,
)


def loadaware_filter(
    node_alloc: jnp.ndarray,     # [N,R] int32 (estimated node allocatable)
    node_usage: jnp.ndarray,     # [N,R] int32 (whole-node or aggregated usage)
    prod_usage: jnp.ndarray,     # [N,R] int32 (sum of prod pods' usage)
    metric_fresh: jnp.ndarray,   # [N] bool
    thresholds: jnp.ndarray,     # [R] int32, 0 = resource not thresholded
    prod_thresholds: jnp.ndarray,  # [R] int32, all-zero = prod mode disabled
    pod_is_daemonset: jnp.ndarray,  # [] bool
    pod_is_prod: jnp.ndarray,       # [] bool
) -> jnp.ndarray:
    """Boolean ``[N]`` mask of nodes that pass the LoadAware filter.

    Reference: load_aware.go:123-255. A zero threshold disables the check
    for that resource; zero allocatable skips the resource; a node without
    a fresh metric always passes (the plugin treats missing/expired metrics
    as "no load information — skip").
    """
    usage_pct = percent_rounded(node_usage, node_alloc)       # [N,R]
    prod_pct = percent_rounded(prod_usage, node_alloc)        # [N,R]

    checkable = (node_alloc > 0)
    over = checkable & (thresholds > 0) & (usage_pct >= thresholds)
    over_prod = checkable & (prod_thresholds > 0) & (prod_pct >= prod_thresholds)

    prod_mode = pod_is_prod & jnp.any(prod_thresholds > 0)
    violated = jnp.where(prod_mode, jnp.any(over_prod, axis=-1), jnp.any(over, axis=-1))
    return pod_is_daemonset | ~metric_fresh | ~violated


def loadaware_score(
    pod_est: jnp.ndarray,        # [R] int32 estimator output for the pod
    node_alloc: jnp.ndarray,     # [N,R] int32
    node_usage: jnp.ndarray,     # [N,R] int32
    est_extra: jnp.ndarray,      # [N,R] int32 assigned-pod correction
    prod_base: jnp.ndarray,      # [N,R] int32 prod-mode score base
    metric_fresh: jnp.ndarray,   # [N] bool
    weights: jnp.ndarray,        # [R] int32
    pod_is_prod: jnp.ndarray,    # [] bool — prod-usage scoring mode
    score_according_prod: bool = False,
    alloc_recip: jnp.ndarray = None,  # reciprocal_for(node_alloc), hot path
) -> jnp.ndarray:
    """LoadAware score ``[N]`` in 0..100 (load_aware.go:269-397).

    Nodes without a fresh metric score 0, matching the reference's early
    returns. Non-prod mode: estimated-used = node usage + ``est_extra``
    (the Σ max(estimate, reported) − covered-reported correction for pods
    assigned since the last metric report) + the incoming pod's estimate.
    Prod mode (ScoreAccordingProdUsage for prod pods): estimated-used =
    ``prod_base`` + incoming estimate, where prod_base was built from prod
    pods only (see state/cluster.py lower_nodes).
    """
    prod_mode = score_according_prod & pod_is_prod
    base = jnp.where(prod_mode, prod_base, node_usage + est_extra)
    estimated_used = base + pod_est                             # [N,R]
    per_resource = least_requested_score(estimated_used, node_alloc, alloc_recip)
    score = weighted_mean_scores(per_resource, weights)
    return jnp.where(metric_fresh, score, 0)
