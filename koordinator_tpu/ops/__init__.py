"""Pure, jit-safe scheduling math over the array substrate.

Every function here is shape-polymorphic, side-effect free, and traceable
under ``jax.jit`` / ``pjit`` — no data-dependent Python control flow. These
are the TPU-native equivalents of the reference's per-node Go plugin
callbacks, batched over the node (and pod) axes.
"""
