"""Gang (coscheduling) all-or-nothing admission for the batched solver.

The reference gates gangs at Permit: each placed member is "assumed" and
waits until every gang in its gang-group has assumed+bound ≥ minMember;
a Strict-mode member failure rejects the whole group and releases its
assumed resources (SURVEY.md A.5; coscheduling/core/core.go:358-430).

Batched formulation: the placement scan places gang members normally
(holding resources, exactly like assumed pods waiting at Permit); after
the scan, a segment-sum feasibility pass decides each gang-group's fate:

- every gang in the group reaches its min → all its placed pods COMMIT
  (the Permit barrier opens);
- otherwise Strict gangs are REJECTED — their placed pods are released
  (vectorized scatter-subtract of their requests/estimates) — while
  NonStrict gangs stay WAITING: pods keep holding resources into the next
  cycle, as the reference's waiting pods do until timeout.

The reference's mid-cycle rejection timing depends on goroutine
interleaving and is nondeterministic; this batched semantics — rejection
resolved at batch end — is the deterministic equivalent and is what the
host GangManager (gang/manager.py) models for the incremental path.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class GangState(NamedTuple):
    """Device-resident gang metadata, [G] arrays (static within a solve)."""

    min_member: jnp.ndarray   # [G] int32
    bound_count: jnp.ndarray  # [G] int32 members already bound/assumed earlier
    strict: jnp.ndarray       # [G] bool — Strict vs NonStrict mode
    group_id: jnp.ndarray     # [G] int32 — gangs in one gang-group share an id

    @classmethod
    def build(cls, min_member, bound_count=None, strict=None, group_id=None):
        g = len(min_member)
        if group_id is None:
            gid = np.arange(g, dtype=np.int32)
        else:
            # densify arbitrary group labels into [0, G) — segment reductions
            # inside gang_outcomes require in-range indices
            _, gid = np.unique(np.asarray(group_id), return_inverse=True)
            gid = gid.astype(np.int32)
        return cls(
            min_member=jnp.asarray(np.asarray(min_member, np.int32)),
            bound_count=jnp.asarray(
                np.asarray(
                    bound_count if bound_count is not None else np.zeros(g), np.int32
                )
            ),
            strict=jnp.asarray(
                np.asarray(strict if strict is not None else np.ones(g), bool)
            ),
            group_id=jnp.asarray(gid),
        )


def gang_outcomes(
    assignments: jnp.ndarray,  # [P] node index or -1 (raw scan output)
    gang_id: jnp.ndarray,      # [P] int32, -1 = not gang-managed
    gangs: GangState,
) -> tuple:
    """(commit[P], waiting[P], rejected[P]) booleans.

    commit: pod is bound (non-gang placed pods, or members of fully
    satisfied gang-groups). waiting: placed NonStrict member of an
    unsatisfied group — keeps holding its node. rejected: placed Strict
    member of an unsatisfied group — must be released.
    """
    g = gangs.min_member.shape[0]
    placed = assignments >= 0
    gid = jnp.maximum(gang_id, 0)
    member_placed = placed & (gang_id >= 0)
    placed_per_gang = jax.ops.segment_sum(
        member_placed.astype(jnp.int32), gid, num_segments=g
    )
    valid = (placed_per_gang + gangs.bound_count) >= gangs.min_member  # [G]

    # a gang-group is satisfied iff every gang sharing its group id is valid
    invalid = (~valid).astype(jnp.int32)
    group_invalid = jax.ops.segment_sum(
        invalid, gangs.group_id, num_segments=g
    )  # indexed by group id
    gang_ok = group_invalid[gangs.group_id] == 0                       # [G]

    pod_gang_ok = gang_ok[gid]
    commit = placed & ((gang_id < 0) | pod_gang_ok)
    waiting = member_placed & ~pod_gang_ok & ~gangs.strict[gid]
    rejected = member_placed & ~pod_gang_ok & gangs.strict[gid]
    return commit, waiting, rejected


def release_rejected(
    node_used_req: jnp.ndarray,  # [N,R]
    node_est_extra: jnp.ndarray,  # [N,R]
    node_prod_base: jnp.ndarray,  # [N,R]
    assignments: jnp.ndarray,    # [P]
    rejected: jnp.ndarray,       # [P] bool
    req: jnp.ndarray,            # [P,R]
    est: jnp.ndarray,            # [P,R]
    is_prod: jnp.ndarray,        # [P] bool
) -> tuple:
    """Vectorized release of rejected pods' held resources (the batched
    Unreserve): scatter-subtract their requests/estimates per node."""
    n = node_used_req.shape[0]
    idx = jnp.where(rejected, assignments, n)  # out-of-range -> dropped
    rel_req = jnp.where(rejected[:, None], req, 0)
    rel_est = jnp.where(rejected[:, None], est, 0)
    rel_prod = jnp.where((rejected & is_prod)[:, None], est, 0)
    sub_req = jax.ops.segment_sum(rel_req, idx, num_segments=n + 1)[:n]
    sub_est = jax.ops.segment_sum(rel_est, idx, num_segments=n + 1)[:n]
    sub_prod = jax.ops.segment_sum(rel_prod, idx, num_segments=n + 1)[:n]
    return (
        node_used_req - sub_req,
        node_est_extra - sub_est,
        node_prod_base - sub_prod,
    )
