"""Device-path elastic quota: water-filling + admission inside the solver.

The reference refreshes a quota's runtime at every pod's PreFilter
(plugin.go:221-223). Requests register with a quota when the *pod object*
is created (OnPodAdd → updatePodRequest), not when it is scheduled, so
within one solve over a fixed pending queue every group's request — and
therefore the water-filled runtime — is constant; only ``used`` moves as
pods are placed. The solver exploits this: the fixed-point redistribution
runs once per solve as a ``lax.while_loop`` over dense ``[Q, R]`` arrays
(Q quota groups × R resources, all dims independent), and the per-pod gate
is a pure ``used + req <= runtime`` mask.

Exact arithmetic: the weighted share ``round(w * T / W)`` is computed as
``w * (T // W) + round_half_up(w * (T % W) / W)`` — exact in int32 given
host-normalized weights (per-dimension Σw ≤ 2^15-1, see
``normalize_weights``) and values saturated at 2^30 (``SATURATE``;
"effectively infinite" maxes keep behaving as infinite). The host oracle
(quota/core.py water_filling with ``exact_rational=True``) matches this
bit-for-bit; the reference's float64 delta differs only in float rounding
artifacts (documented deviation, same spirit as ops/common.percent_rounded).

Scope: single-level trees (all groups under root) run fully on device —
the dominant production shape and BASELINE config #3. Deeper trees use
the host GroupQuotaManager at PreFilter.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

#: Device-path value saturation: 2^30 canonical units. Sums of two
#: saturated values still fit int32.
SATURATE = 1 << 30

#: Per-dimension normalized weight cap (Σw per resource ≤ this).
WEIGHT_CAP = (1 << 15) - 1


def normalize_weights(weights: np.ndarray) -> np.ndarray:
    """Host-side per-dimension weight normalization to Σ ≤ WEIGHT_CAP.

    Proportions are preserved up to integer rounding; dimensions already
    under the cap are untouched (bit-identical to the reference there).
    """
    weights = np.minimum(np.asarray(weights, dtype=np.int64), SATURATE)
    sums = weights.sum(axis=0)  # [R]
    scale_needed = sums > WEIGHT_CAP
    out = weights.copy()
    for r in np.nonzero(scale_needed)[0]:
        out[:, r] = (weights[:, r] * WEIGHT_CAP) // sums[r]
    return out.astype(np.int32)


class QuotaState(NamedTuple):
    """Device-resident quota arrays (single-level tree, [Q,R] int32).

    Construct via :meth:`build`, which applies the saturation and weight
    normalization the int32 arithmetic depends on.
    """

    min: jnp.ndarray            # [Q,R]
    max: jnp.ndarray            # [Q,R] (saturated)
    auto_min: jnp.ndarray       # [Q,R] max(min, guarantee)
    weight: jnp.ndarray         # [Q,R] normalized shared weights
    allow_lent: jnp.ndarray     # [Q] bool
    child_request: jnp.ndarray  # [Q,R] Σ pod requests (pending + assigned)
    used: jnp.ndarray           # [Q,R] (mutated by solve)
    np_used: jnp.ndarray        # [Q,R] non-preemptible used
    total: jnp.ndarray          # [R] cluster total minus system/default used
    #: Optional precomputed masked runtime [Q,R]. When set (trace-time
    #: check), the solver uses it directly instead of running the on-device
    #: single-level water-filling — this is how hierarchical (multi-level)
    #: quota trees are supported: the host computes the exact tree runtime
    #: once per solve (requests are static within a solve) and ships it.
    runtime: Optional[jnp.ndarray] = None

    @classmethod
    def build(
        cls,
        min,
        max,
        weight,
        allow_lent,
        total,
        guarantee=None,
        child_request=None,
        used=None,
        np_used=None,
        runtime=None,
    ) -> "QuotaState":
        """Host-side constructor enforcing the device-path preconditions:
        values saturated at ``SATURATE`` and per-dimension weight sums
        normalized under ``WEIGHT_CAP`` (see module docstring)."""
        mn = np.minimum(np.asarray(min, dtype=np.int64), SATURATE)
        mx = np.minimum(np.asarray(max, dtype=np.int64), SATURATE)
        guar = (
            np.minimum(np.asarray(guarantee, dtype=np.int64), SATURATE)
            if guarantee is not None
            else np.zeros_like(mn)
        )
        q = mn.shape[0]
        zeros = np.zeros_like(mn)
        return cls(
            min=jnp.asarray(mn, jnp.int32),
            max=jnp.asarray(mx, jnp.int32),
            auto_min=jnp.asarray(np.maximum(mn, guar), jnp.int32),
            weight=jnp.asarray(normalize_weights(np.asarray(weight))),
            allow_lent=jnp.asarray(np.asarray(allow_lent, dtype=bool)),
            child_request=jnp.asarray(
                np.minimum(
                    np.asarray(
                        child_request if child_request is not None else zeros,
                        dtype=np.int64,
                    ),
                    SATURATE,
                ),
                jnp.int32,
            ),
            used=jnp.asarray(
                np.asarray(used if used is not None else zeros, dtype=np.int64),
                jnp.int32,
            ),
            np_used=jnp.asarray(
                np.asarray(np_used if np_used is not None else zeros, dtype=np.int64),
                jnp.int32,
            ),
            total=jnp.asarray(
                np.minimum(np.asarray(total, dtype=np.int64), SATURATE), jnp.int32
            ),
            runtime=(
                None
                if runtime is None
                else jnp.asarray(
                    np.minimum(np.asarray(runtime, dtype=np.int64), SATURATE),
                    jnp.int32,
                )
            ),
        )


def limited_request(state: QuotaState) -> jnp.ndarray:
    """[Q,R] the calculator's per-group request: child request floored at
    min for non-lent groups, capped at max (quota_info.go:217-228)."""
    real = jnp.where(
        state.allow_lent[:, None],
        state.child_request,
        jnp.maximum(state.child_request, state.min),
    )
    return jnp.minimum(real, state.max)


def _exact_share(weight: jnp.ndarray, remaining: jnp.ndarray, total_w: jnp.ndarray) -> jnp.ndarray:
    """round_half_up(weight * remaining / total_w) exactly in int32:
    ``w*(T//W) + (2*w*(T%W) + W) // (2*W)`` ([Q,R] × [R] → [Q,R])."""
    w_safe = jnp.maximum(total_w, 1)              # [R]
    t_div = remaining // w_safe                   # [R]
    t_rem = remaining - t_div * w_safe            # [R]
    frac = (2 * weight * t_rem[None, :] + w_safe[None, :]) // (2 * w_safe[None, :])
    share = weight * t_div[None, :] + frac
    return jnp.where(total_w[None, :] > 0, share, 0)


def water_filling_device(
    total: jnp.ndarray,      # [R]
    request: jnp.ndarray,    # [Q,R] limited requests
    auto_min: jnp.ndarray,   # [Q,R]
    weight: jnp.ndarray,     # [Q,R]
    allow_lent: jnp.ndarray,  # [Q]
) -> jnp.ndarray:
    """Runtime[Q,R]: the reference redistribution (SURVEY.md A.4) over all
    resource dimensions at once."""
    q = request.shape[0]
    adjustable0 = request > auto_min                       # [Q,R]
    runtime0 = jnp.where(
        adjustable0,
        auto_min,
        jnp.where(allow_lent[:, None], request, auto_min),
    )
    remaining0 = total - jnp.sum(runtime0, axis=0)         # [R]
    total_w0 = jnp.sum(jnp.where(adjustable0, weight, 0), axis=0)

    def cond(carry):
        runtime, adjustable, remaining, total_w = carry
        return jnp.any((remaining > 0) & (total_w > 0) & jnp.any(adjustable, axis=0))

    def body(carry):
        runtime, adjustable, remaining, total_w = carry
        active = (remaining > 0) & (total_w > 0)           # [R]
        delta = jnp.where(
            adjustable & active[None, :],
            _exact_share(weight, jnp.maximum(remaining, 0), total_w),
            0,
        )
        grown = runtime + delta
        saturated = adjustable & (grown >= request)
        surplus = jnp.sum(jnp.where(saturated, grown - request, 0), axis=0)
        runtime = jnp.where(adjustable, jnp.minimum(grown, request), runtime)
        still = adjustable & (runtime < request)
        new_total_w = jnp.sum(jnp.where(still, weight, 0), axis=0)
        # stop a dimension when it produced no surplus (Go stops recursing
        # when toPartitionResource == 0) or nothing is adjustable
        new_remaining = jnp.where(active, surplus, remaining)
        return runtime, still, new_remaining, new_total_w

    runtime, _, _, _ = jax.lax.while_loop(
        cond, body, (runtime0, adjustable0, remaining0, total_w0)
    )
    return runtime


def quota_runtime(state: QuotaState) -> jnp.ndarray:
    """[Q,R] masked runtime: the precomputed tree runtime when provided,
    else the on-device single-level water-filling + min(runtime, max)."""
    if state.runtime is not None:
        return state.runtime
    runtime = water_filling_device(
        state.total,
        limited_request(state),
        state.auto_min,
        state.weight,
        state.allow_lent,
    )
    return jnp.minimum(runtime, state.max)


def quota_admit(
    state: QuotaState,
    runtime: jnp.ndarray,        # [Q,R] precomputed masked runtime
    quota_id: jnp.ndarray,       # [] int32, -1 = no quota
    pod_req: jnp.ndarray,        # [R]
    non_preemptible: jnp.ndarray,  # [] bool
) -> jnp.ndarray:
    """[] bool admission (SURVEY.md A.3): used + req <= runtime on the
    requested dims; non-preemptible additionally against min. ``runtime``
    is computed once per solve (requests are static within a solve)."""
    q = jnp.maximum(quota_id, 0)
    dims = pod_req > 0
    ok = jnp.all(jnp.where(dims, state.used[q] + pod_req <= runtime[q], True))
    np_ok = jnp.all(
        jnp.where(
            dims & non_preemptible,
            state.np_used[q] + pod_req <= state.min[q],
            True,
        )
    )
    return (quota_id < 0) | (ok & np_ok)


def quota_assume(
    state: QuotaState,
    quota_id: jnp.ndarray,
    pod_req: jnp.ndarray,
    non_preemptible: jnp.ndarray,
    placed: jnp.ndarray,         # [] bool — only account if actually placed
) -> QuotaState:
    """Account a placed pod's *used* into its quota group (its request was
    already registered at pod creation)."""
    take = placed & (quota_id >= 0)
    q = jnp.maximum(quota_id, 0)
    add = jnp.where(take, pod_req, 0)
    return state._replace(
        used=state.used.at[q].add(add),
        np_used=state.np_used.at[q].add(jnp.where(non_preemptible, add, 0)),
    )
