// Grouped perf-counter reader: cycles + instructions in one group, the
// native source behind the CPI collector.
//
// TPU-native counterpart of the reference's only native component, the
// cgo+libpfm4 perf-group reader (/root/reference/pkg/koordlet/util/
// perf_group/perf_group_linux.go:39-40,93,280-297). libpfm4 is used there
// to resolve event encodings; cycles/instructions are architectural
// PERF_TYPE_HARDWARE events, so this implementation calls
// perf_event_open(2) directly with PERF_FORMAT_GROUP — one leader
// (cycles) plus one sibling (instructions), read atomically as a group
// exactly like pfm-initialized groups are.
//
// A deterministic fake backend (kp_open_fake) exists for tests and for
// hosts where perf_event_open is unavailable (containers with
// perf_event_paranoid locked down).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <cstdlib>

#if defined(__linux__)
#include <linux/perf_event.h>
#include <sys/ioctl.h>
#include <sys/syscall.h>
#include <unistd.h>
#endif

extern "C" {

struct kp_group {
    int leader_fd;     // cycles (group leader)
    int instr_fd;      // instructions (sibling)
    int fake;          // 1 = deterministic fake backend
    unsigned long long fake_cycles;
    unsigned long long fake_instr;
    unsigned long long fake_cycles_step;
    unsigned long long fake_instr_step;
};

// read format with PERF_FORMAT_GROUP | PERF_FORMAT_ID:
// { nr, [ {value, id} x nr ] }
struct kp_read_group {
    unsigned long long nr;
    struct { unsigned long long value, id; } values[2];
};

#if defined(__linux__)
static int kp_perf_open(unsigned int config, int pid, int cpu, int group_fd,
                        unsigned long flags) {
    struct perf_event_attr attr;
    memset(&attr, 0, sizeof(attr));
    attr.type = PERF_TYPE_HARDWARE;
    attr.size = sizeof(attr);
    attr.config = config;
    attr.disabled = (group_fd == -1) ? 1 : 0;  // group starts disabled
    // NOTE: inherit must stay 0 — the kernel rejects inherit with
    // PERF_FORMAT_GROUP (EINVAL since 4.13); cgroup-scoped per-cpu
    // events don't need it anyway
    attr.read_format = PERF_FORMAT_GROUP | PERF_FORMAT_ID;
    attr.exclude_kernel = 1;  // unprivileged-friendly
    attr.exclude_hv = 1;
    return (int)syscall(__NR_perf_event_open, &attr, pid, cpu, group_fd,
                        flags);
}
#endif

// Open a cycles+instructions group. pid/cpu/flags follow
// perf_event_open(2): (pid=0, cpu=-1, flags=0) profiles the calling
// process; (pid=cgroup_fd, cpu>=0, flags=PERF_FLAG_PID_CGROUP) profiles
// a cgroup on one cpu, as the reference does per container.
// Returns a handle pointer, or NULL (errno in *err).
kp_group* kp_open(int pid, int cpu, unsigned long flags, int* err) {
#if defined(__linux__)
    kp_group* g = (kp_group*)calloc(1, sizeof(kp_group));
    if (!g) { if (err) *err = ENOMEM; return NULL; }
    g->leader_fd = kp_perf_open(PERF_COUNT_HW_CPU_CYCLES, pid, cpu, -1, flags);
    if (g->leader_fd < 0) {
        if (err) *err = errno;
        free(g);
        return NULL;
    }
    g->instr_fd = kp_perf_open(PERF_COUNT_HW_INSTRUCTIONS, pid, cpu,
                               g->leader_fd, flags);
    if (g->instr_fd < 0) {
        if (err) *err = errno;
        close(g->leader_fd);
        free(g);
        return NULL;
    }
    ioctl(g->leader_fd, PERF_EVENT_IOC_RESET, PERF_IOC_FLAG_GROUP);
    ioctl(g->leader_fd, PERF_EVENT_IOC_ENABLE, PERF_IOC_FLAG_GROUP);
    return g;
#else
    if (err) *err = ENOSYS;
    return NULL;
#endif
}

// Deterministic fake: every read advances by the given steps.
kp_group* kp_open_fake(unsigned long long cycles_step,
                       unsigned long long instr_step) {
    kp_group* g = (kp_group*)calloc(1, sizeof(kp_group));
    if (!g) return NULL;
    g->fake = 1;
    g->leader_fd = -1;
    g->instr_fd = -1;
    g->fake_cycles_step = cycles_step;
    g->fake_instr_step = instr_step;
    return g;
}

// Cumulative (cycles, instructions); returns 0 on success, else errno.
int kp_read_counters(kp_group* g, unsigned long long* cycles,
                     unsigned long long* instructions) {
    if (!g) return EINVAL;
    if (g->fake) {
        g->fake_cycles += g->fake_cycles_step;
        g->fake_instr += g->fake_instr_step;
        *cycles = g->fake_cycles;
        *instructions = g->fake_instr;
        return 0;
    }
#if defined(__linux__)
    kp_read_group buf;
    memset(&buf, 0, sizeof(buf));
    ssize_t n = read(g->leader_fd, &buf, sizeof(buf));
    if (n < 0) return errno;
    if (buf.nr < 2) return EIO;
    *cycles = buf.values[0].value;
    *instructions = buf.values[1].value;
    return 0;
#else
    return ENOSYS;
#endif
}

void kp_close(kp_group* g) {
    if (!g) return;
#if defined(__linux__)
    if (g->leader_fd >= 0) close(g->leader_fd);
    if (g->instr_fd >= 0) close(g->instr_fd);
#endif
    free(g);
}

int kp_is_fake(kp_group* g) { return g ? g->fake : 0; }

const char* kp_version() { return "koordperf-1.0"; }

}  // extern "C"
