"""Native (C++) components and their ctypes bindings.

The reference's only native code is the cgo+libpfm4 perf-group CPI reader
(pkg/koordlet/util/perf_group/perf_group_linux.go); here it is a small
C++ shared library (perf_group.cpp) built on demand with g++ and bound
via ctypes — no pybind11 required.
"""

from koordinator_tpu.native.perf import (  # noqa: F401
    PerfGroup,
    PerfUnavailable,
    ensure_built,
)
