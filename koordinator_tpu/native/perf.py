"""ctypes binding for the native perf-group reader (perf_group.cpp).

Build model: the shared library compiles on first use (g++, cached next
to the source); the reference builds its cgo module via hack/libpfm.sh at
test time, this is the equivalent. ``PerfGroup.open_self`` profiles the
current process; ``PerfGroup.open_cgroup`` profiles a cgroup (one fd per
cpu, summed on read) like the reference's per-container collectors;
``PerfGroup.fake`` is the deterministic test backend.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

_SRC = os.path.join(os.path.dirname(__file__), "perf_group.cpp")
_LIB = os.path.join(os.path.dirname(__file__), "libkoordperf.so")
_PERF_FLAG_PID_CGROUP = 1 << 2  # include/uapi/linux/perf_event.h

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


class PerfUnavailable(RuntimeError):
    """perf_event_open failed (permissions, kernel config, platform)."""


def ensure_built() -> str:
    """Compile the shared library if missing/stale; returns its path."""
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
        return _LIB
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", "-o", _LIB, _SRC],
        check=True,
        capture_output=True,
        text=True,
    )
    return _LIB


def _load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is None:
            try:
                path = ensure_built()
            except (subprocess.CalledProcessError, FileNotFoundError, OSError) as e:
                # no compiler / compile error: perf is host-unavailable,
                # not a transient per-container condition
                raise PerfUnavailable(f"native perf build failed: {e}") from e
            lib = ctypes.CDLL(path)
            lib.kp_open.restype = ctypes.c_void_p
            lib.kp_open.argtypes = [
                ctypes.c_int, ctypes.c_int, ctypes.c_ulong,
                ctypes.POINTER(ctypes.c_int),
            ]
            lib.kp_open_fake.restype = ctypes.c_void_p
            lib.kp_open_fake.argtypes = [ctypes.c_ulonglong, ctypes.c_ulonglong]
            lib.kp_read_counters.restype = ctypes.c_int
            lib.kp_read_counters.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_ulonglong),
                ctypes.POINTER(ctypes.c_ulonglong),
            ]
            lib.kp_close.restype = None
            lib.kp_close.argtypes = [ctypes.c_void_p]
            lib.kp_version.restype = ctypes.c_char_p
            _lib = lib
        return _lib


class PerfGroup:
    """One cycles+instructions counter group (possibly multiple fds for
    per-cpu cgroup profiling, summed on read)."""

    def __init__(self, handles):
        self._handles = list(handles)

    # -- constructors -------------------------------------------------------

    @classmethod
    def open_self(cls) -> "PerfGroup":
        lib = _load()
        err = ctypes.c_int(0)
        h = lib.kp_open(0, -1, 0, ctypes.byref(err))
        if not h:
            raise PerfUnavailable(f"perf_event_open failed (errno {err.value})")
        return cls([h])

    @classmethod
    def open_cgroup(cls, cgroup_dir_fd: int, cpus) -> "PerfGroup":
        """Profile a cgroup: one group per cpu (perf_event_open requires
        cpu >= 0 with PERF_FLAG_PID_CGROUP), summed on read — the
        reference's per-container collector layout."""
        lib = _load()
        handles = []
        err = ctypes.c_int(0)
        for cpu in cpus:
            h = lib.kp_open(
                cgroup_dir_fd, int(cpu), _PERF_FLAG_PID_CGROUP,
                ctypes.byref(err),
            )
            if not h:
                for held in handles:
                    lib.kp_close(held)
                raise PerfUnavailable(
                    f"perf_event_open(cgroup) failed (errno {err.value})"
                )
            handles.append(h)
        return cls(handles)

    @classmethod
    def fake(cls, cycles_step: int, instr_step: int) -> "PerfGroup":
        lib = _load()
        return cls([lib.kp_open_fake(cycles_step, instr_step)])

    # -- reading ------------------------------------------------------------

    def read(self) -> Tuple[int, int]:
        """(cumulative cycles, cumulative instructions)."""
        lib = _load()
        cycles = instr = 0
        for h in self._handles:
            c = ctypes.c_ulonglong(0)
            i = ctypes.c_ulonglong(0)
            rc = lib.kp_read_counters(h, ctypes.byref(c), ctypes.byref(i))
            if rc != 0:
                raise PerfUnavailable(f"perf read failed (errno {rc})")
            cycles += c.value
            instr += i.value
        return cycles, instr

    def close(self) -> None:
        lib = _load()
        for h in self._handles:
            lib.kp_close(h)
        self._handles = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
