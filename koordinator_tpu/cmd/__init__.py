"""Component entry points: each binary-equivalent is constructible from a
typed config object, with feature gates toggling subsystems.

Reference: cmd/{koord-scheduler,koord-descheduler,koord-manager,koordlet}
— cobra commands with component configs and --feature-gates. Here each
module exposes ``*Config`` + ``build_*(config)`` (the Setup function) and
a ``main(argv)`` flag parser; run as
``python -m koordinator_tpu.cmd.<component> --help``.
"""

from koordinator_tpu.cmd.scheduler import SchedulerConfig, build_scheduler  # noqa: F401
from koordinator_tpu.cmd.koordlet import KoordletConfig, build_koordlet  # noqa: F401
from koordinator_tpu.cmd.manager import ManagerConfig, build_manager  # noqa: F401
from koordinator_tpu.cmd.descheduler import (  # noqa: F401
    DeschedulerConfig,
    build_descheduler,
)
