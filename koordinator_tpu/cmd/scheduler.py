"""koord-scheduler entry point.

Reference: cmd/koord-scheduler/app/server.go (NewSchedulerCommand :81,
Setup :337) — the component config carries the plugin/solver knobs and a
--feature-gates spec; Setup builds the wired Scheduler.
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

from koordinator_tpu.features import SCHEDULER_GATES, FeatureGate


@dataclasses.dataclass
class SchedulerConfig:
    """KubeSchedulerConfiguration-equivalent component config."""

    feature_gates: str = ""
    #: batched solve cadence (the churn loop period)
    schedule_interval_seconds: float = 1.0
    fit_weight: int = 1
    loadaware_weight: int = 1
    score_according_prod: bool = False
    cluster_total: Optional[dict] = None


def build_scheduler(config: SchedulerConfig, gates: Optional[FeatureGate] = None):
    """Setup: a fully wired Scheduler (server.go:337)."""
    from koordinator_tpu.models.placement import PlacementModel
    from koordinator_tpu.ops.binpack import SolverConfig
    from koordinator_tpu.scheduler import Scheduler

    gates = gates or SCHEDULER_GATES.copy()
    gates.set_from_spec(config.feature_gates)
    model = PlacementModel(
        config=SolverConfig(
            fit_weight=config.fit_weight,
            loadaware_weight=config.loadaware_weight,
            score_according_prod=config.score_according_prod,
        )
    )
    scheduler = Scheduler(
        model=model,
        cluster_total=config.cluster_total,
        enable_preemption=gates.enabled("ElasticQuotaPreemption"),
    )
    #: gate off the batched device path: schedule_pending falls back to
    #: per-pod incremental cycles
    scheduler.batched_placement = gates.enabled("BatchedPlacement")
    return scheduler


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("koord-scheduler")
    parser.add_argument("--feature-gates", default="",
                        help="A=true,B=false gate overrides")
    parser.add_argument("--schedule-interval", type=float, default=1.0)
    parser.add_argument("--once", action="store_true",
                        help="run a single scheduling round and exit")
    args = parser.parse_args(argv)
    config = SchedulerConfig(
        feature_gates=args.feature_gates,
        schedule_interval_seconds=args.schedule_interval,
    )
    scheduler = build_scheduler(config)
    while True:
        out = scheduler.schedule_pending()
        placed = sum(1 for v in out.values() if v is not None)
        print(f"round: {placed}/{len(out)} placed, {len(out.waiting)} waiting")
        if args.once:
            return 0
        time.sleep(config.schedule_interval_seconds)


if __name__ == "__main__":
    raise SystemExit(main())
