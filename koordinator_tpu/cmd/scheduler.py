"""koord-scheduler entry point.

Reference: cmd/koord-scheduler/app/server.go (NewSchedulerCommand :81,
Setup :337) — the component config carries the plugin/solver knobs and a
--feature-gates spec; Setup builds the wired Scheduler.
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

from koordinator_tpu.features import SCHEDULER_GATES, FeatureGate


@dataclasses.dataclass
class SchedulerConfig:
    """KubeSchedulerConfiguration-equivalent component config."""

    feature_gates: str = ""
    #: batched solve cadence (the churn loop period)
    schedule_interval_seconds: float = 1.0
    fit_weight: int = 1
    loadaware_weight: int = 1
    score_according_prod: bool = False
    #: LoadAware aggregated (percentile) mode — mirrors the reference's
    #: LoadAwareSchedulingAggregatedArgs: filter substitutes the
    #: percentile usage + these thresholds when both are set; score
    #: substitutes the percentile base when aggregated_score_pct is set
    aggregated_usage_thresholds: Optional[dict] = None
    aggregated_usage_pct: Optional[int] = None
    aggregated_usage_duration_seconds: Optional[float] = None
    aggregated_score_pct: Optional[int] = None
    aggregated_score_duration_seconds: Optional[float] = None
    cluster_total: Optional[dict] = None
    #: the north-star backend selector (reference: the plugin-factory
    #: wiring at cmd/koord-scheduler/app/server.go:331-398):
    #: ``inprocess`` solves in this process; ``sidecar`` routes every
    #: batched solve through a koord-solver process at solver_address.
    placement_backend: str = "inprocess"
    solver_address: str = "/tmp/koord-solver.sock"
    solver_secret: Optional[bytes] = None
    #: degraded-mode failover (service/failover.py): wrap the sidecar
    #: backend so a solver outage is answered by the in-process solve
    #: instead of a skipped round. K consecutive failures flip to
    #: degraded; M consecutive healthy probes (hysteresis) flip back
    #: with a full-restage epoch reset.
    solver_failover: bool = False
    solver_failover_threshold: int = 3
    solver_failover_recovery_probes: int = 2
    #: plain solves with pods*nodes under this run on the host sequential
    #: path — a device round trip costs more than the whole solve there.
    #: -1 = MEASURE at startup (models/placement.py
    #: measure_host_fallback_cells: host per-cell cost vs device round
    #: latency on THIS backend/link, ~1 s probe)
    host_fallback_cells: int = -1
    #: scan unroll (ops/binpack.SolverConfig.unroll): 32 is the measured
    #: throughput optimum on v5e; the library default (8) favors compile
    #: time instead
    solver_unroll: int = 32
    #: anti-entropy auditor (scheduler/auditor.py): run a budgeted sweep
    #: every N scheduling rounds. 0 disables the auditor ENTIRELY —
    #: including the promotion sweep on lease acquisition (main() wires
    #: no auditor at 0)
    audit_interval_rounds: int = 16
    #: staged rows the device<->host parity probe re-lowers and compares
    #: per sweep (round-robin: every row provably covered within
    #: ceil(n/probe_rows) sweeps)
    audit_probe_rows: int = 64
    #: pipelined tick path (scheduler/pipeline.py): overlap staging for
    #: round N+1 with round N's in-flight solve and move the read-back +
    #: epilogue + bus publish onto a bounded publisher worker.
    #: Placements stay bit-identical to the serial loop; the round's
    #: critical path drops to catch-up staging + dispatch
    #: (docs/DESIGN.md §15)
    pipelined_ticks: bool = False
    #: scheduling trace fabric (obs/trace.py): span recording into the
    #: bounded ring served at /debug/trace. On by default — the cost is
    #: one lock+append per span (bench leg 13's trace_overhead_ratio
    #: measures it every run); the stuck-cycle watchdog works even when
    #: this is off (open marks are always tracked)
    trace: bool = True
    #: anomaly flight-recorder dump directory (obs/flight.py). None =
    #: $KTPU_FLIGHT_DIR or <tmp>/koord-flight
    flight_dir: Optional[str] = None
    #: device-cost observatory (obs/device.py): directory for on-demand
    #: jax profiler windows (/debug/profile?rounds=K). None =
    #: $KTPU_PROFILE_DIR or <tmp>/koord-profile
    profile_dir: Optional[str] = None
    #: stuck-cycle watchdog threshold (scheduler/monitor.py): an open
    #: round/publish mark older than this reads as stuck. The mark now
    #: covers the WHOLE batched round — including a first-round
    #: cold-start jit compile, which legitimately runs multi-second on
    #: big clusters — so raise it on deployments where a false
    #: scheduler_stuck_cycles_total alert is worse than slow detection
    monitor_timeout_seconds: float = 10.0
    #: node-axis shard count (docs/DESIGN.md §19): >1 splits the staged
    #: world over a ``nodes × pods`` mesh of that many devices and
    #: turns on sharded delta staging (dirty rows scattered into their
    #: owning shard of a live NamedSharding'd world). 1 = unsharded.
    #: Requires >= node_shards attached devices; in-process backend
    #: only (the sidecar stages its own world)
    node_shards: int = 1
    #: streaming serving mode (scheduler/streaming.py, docs/DESIGN.md
    #: §22): pods arrive on an open-loop stream into the QoS-laned
    #: intake and rounds fire ADAPTIVELY — batch-size watermark OR
    #: oldest-pod lane deadline, whichever comes first — instead of on
    #: the fixed schedule_interval cadence. The headline metric becomes
    #: per-pod submit→bind p50/p99 at a sustained arrival rate.
    streaming: bool = False
    #: batch-size trigger: a round fires as soon as this many arrivals
    #: are queued (a burst amortizes into one dispatch)
    stream_watermark: int = 64
    #: per-lane queue-wait targets (system, ls, be) in seconds: the
    #: oldest queued pod's submit + lane deadline is the other trigger
    stream_deadline_system_s: float = 0.002
    stream_deadline_ls_s: float = 0.010
    stream_deadline_be_s: float = 0.050
    #: intake bound: arrivals past this shed (BE first, typed + counted)
    stream_capacity: int = 4096
    #: floor between adaptively-fired rounds (0 = none): bounds the
    #: dispatch rate a trickle of deadline-armed singletons can drive
    stream_min_interval_s: float = 0.0
    #: per-lane serving SLOs (control/slo.py, docs/DESIGN.md §25):
    #: ``p99=<seconds>`` (or a bare float) per lane. Any set target
    #: turns on the ServingSLOController in streaming mode — the
    #: static stream_* knobs above become its STARTING point, and the
    #: reconcile loop walks them toward the declared target (bounded,
    #: hysteretic, one knob per reconcile, every decision recorded)
    slo_system: Optional[str] = None
    slo_ls: Optional[str] = None
    slo_be: Optional[str] = None
    #: controller cadence: rolling-stats window the lane p99 is read
    #: over, and the per-decision cooldown (hysteresis)
    slo_window_s: float = 5.0
    slo_cooldown_s: float = 1.0
    #: AOT warm pool (service/warmpool.py, docs/DESIGN.md §21):
    #: restore serialized executables for the hot solve signatures at
    #: startup and on leader promotion, and persist newly-observed
    #: signatures in the background — restart/failover/degraded-flip
    #: paths then skip the cold XLA compile. Rides the
    #: KTPU_COMPILATION_CACHE_DIR store (inert when that is empty);
    #: single-device processes only (AOT executables pin placement)
    warm_pool: bool = True
    #: HBM working-set budget (state/workingset.py, DESIGN §26): the
    #: byte line every staged tenant world is governed under — under
    #: pressure the least-valuable worlds demote host-pinned/cold
    #: instead of the process allocating past the line; 0 = unlimited
    hbm_budget_bytes: int = 0
    #: migration arbiter disruption budgets (control/migration.py,
    #: docs/DESIGN.md §27): every eviction source — preemption victims,
    #: defrag drains, rebalance sweeps, working-set demotion notes —
    #: passes through one arbiter. All-None caps + zero cooldown is the
    #: unlimited default: every path stays bit-identical to pre-arbiter
    #: behavior while still producing the typed decision ring.
    migration_max_per_round: Optional[int] = None
    migration_max_per_node: Optional[int] = None
    migration_max_per_tenant: Optional[int] = None
    migration_window_s: float = 60.0
    migration_node_cooldown_s: float = 0.0
    migration_dry_run: bool = False
    #: closed-loop defrag controller (control/migration.py): watches
    #: the fragmentation signal (a pending gang whose member shape fits
    #: no node though aggregate free capacity could hold it) and
    #: applies ONE arbitrated headroom repack per cooldown. Fixed-
    #: cadence loop only; off by default (defrag_headroom stays an
    #: operator-called API).
    defrag_loop: bool = False
    defrag_interval_s: float = 5.0
    defrag_cooldown_s: float = 30.0
    defrag_confirm: int = 2
    defrag_dry_run: bool = False
    #: periodic LoadAware Balance sweep inside the scheduling loop
    #: (scheduler.rebalance_sweep): 0 = no sweep. Backend picks the
    #: eviction-walk implementation: host (reference-shaped oracle),
    #: device (one lax.scan over the flattened candidates), verify
    #: (device + host replica, bit-equality asserted before applying)
    rebalance_interval_s: float = 0.0
    rebalance_backend: str = "host"


def build_scheduler(config: SchedulerConfig, gates: Optional[FeatureGate] = None):
    """Setup: a fully wired Scheduler (server.go:337)."""
    from koordinator_tpu.models.placement import PlacementModel
    from koordinator_tpu.ops.binpack import SolverConfig
    from koordinator_tpu.scheduler import Scheduler

    gates = gates or SCHEDULER_GATES.copy()
    gates.set_from_spec(config.feature_gates)
    if config.warm_pool:
        # the AOT warm pool (DESIGN §21): configured AND boot-restored
        # before the model and backends construct — the restore needs
        # no registrations (the executable map is program-keyed);
        # bindings then adopt into the already-warm pool and the
        # failover twin prewarms at construction iff the pool is
        # active. main() above restores even EARLIER (before this
        # module's heavy imports: measured ~0.5 s there vs ~1.0 s
        # here vs a background thread racing the build 5-8x slower) —
        # restore() is idempotent, so this call is the embedder
        # fallback and costs only a manifest re-scan when main
        # already ran. Loads only; a bad store degrades that shape to
        # cold compile, never to a crash (the rejection ladder, §21).
        from koordinator_tpu.service.warmpool import WARM_POOL

        WARM_POOL.configure()
        if WARM_POOL.active:
            WARM_POOL.restore(compile_missing=False)
    backend = None
    if config.placement_backend == "sidecar":
        from koordinator_tpu.cmd.solver import parse_address
        from koordinator_tpu.service.client import RemoteSolver

        backend = RemoteSolver(
            parse_address(config.solver_address), secret=config.solver_secret
        )
        if config.solver_failover:
            from koordinator_tpu.service.failover import FailoverSolver

            backend = FailoverSolver(
                backend,
                failure_threshold=config.solver_failover_threshold,
                recovery_probes=config.solver_failover_recovery_probes,
            )
    elif config.placement_backend != "inprocess":
        raise ValueError(
            f"unknown placement backend: {config.placement_backend!r}"
        )
    aggregated = None
    if (
        config.aggregated_usage_pct is not None
        or config.aggregated_score_pct is not None
    ):
        from koordinator_tpu.state.cluster import AggregatedArgs

        aggregated = AggregatedArgs(
            usage_thresholds=config.aggregated_usage_thresholds,
            usage_pct=config.aggregated_usage_pct,
            usage_duration_seconds=config.aggregated_usage_duration_seconds,
            score_pct=config.aggregated_score_pct,
            score_duration_seconds=config.aggregated_score_duration_seconds,
        )
    solver_config = SolverConfig(
        fit_weight=config.fit_weight,
        loadaware_weight=config.loadaware_weight,
        score_according_prod=config.score_according_prod,
        unroll=config.solver_unroll,
    )
    sharding = None
    if config.node_shards > 1:
        if backend is not None:
            raise ValueError(
                "--node-shards applies to the in-process solver only — "
                "the sidecar backend stages its own world"
            )
        from koordinator_tpu.parallel.mesh import (
            make_mesh2d,
            node_sharding,
        )

        # raises loudly when fewer devices are attached than shards
        sharding = node_sharding(
            make_mesh2d(node_shards=config.node_shards)
        )
    if backend is not None or not gates.enabled("BatchedPlacement") \
            or sharding is not None:
        # the sidecar routes everything remote; gated-off batched
        # placement never consults the cutoff; a sharded world must
        # not fall back to the host sequential path (it would sync the
        # whole mesh per tiny solve) — don't pay the probe
        fallback_cells = 0
    elif config.host_fallback_cells < 0:
        from koordinator_tpu.models.placement import (
            measure_host_fallback_cells,
        )

        fallback_cells = measure_host_fallback_cells(solver_config)
    else:
        fallback_cells = config.host_fallback_cells
    model = PlacementModel(
        config=solver_config,
        aggregated=aggregated,
        backend=backend,
        host_fallback_cells=fallback_cells,
        sharding=sharding,
    )
    if backend is not None and hasattr(backend, "on_flip_back"):
        # failover flip-back forces a full relower+restage so the
        # recovered sidecar's delta base is re-established from scratch
        backend.on_flip_back = model.reset_staging
    scheduler = Scheduler(
        model=model,
        cluster_total=config.cluster_total,
        enable_preemption=gates.enabled("ElasticQuotaPreemption"),
    )
    #: gate off the batched device path: schedule_pending falls back to
    #: per-pod incremental cycles
    scheduler.batched_placement = gates.enabled("BatchedPlacement")
    scheduler.monitor.timeout = config.monitor_timeout_seconds
    # the observability knobs apply at THIS layer, not only in main():
    # an embedder calling build_scheduler()+run_loop() with
    # trace=False / flight_dir=... must get what the config says
    from koordinator_tpu.obs.device import DEVICE_OBS
    from koordinator_tpu.obs.flight import FLIGHT
    from koordinator_tpu.obs.trace import TRACER

    TRACER.set_enabled(config.trace)
    if config.flight_dir is not None:
        FLIGHT.configure(dump_dir=config.flight_dir)
    if config.profile_dir is not None:
        DEVICE_OBS.configure(profile_dir=config.profile_dir)
    # the HBM working-set ledger (DESIGN §26): budget applied before
    # the first staging, the residency/demotion census on the debug
    # mux beside the other per-subsystem status services
    from koordinator_tpu.state.workingset import WORKING_SET

    if config.hbm_budget_bytes:
        WORKING_SET.set_budget(config.hbm_budget_bytes)
    scheduler.services.register("workingset", WORKING_SET.status)
    # the migration arbiter (control/migration.py, DESIGN §27): ALWAYS
    # constructed — with no --migration-* caps it is the unlimited
    # budget, which admits everything bit-identically to the legacy
    # paths while keeping the typed decision ring, the debug-mux
    # service, and the flight payload live
    from koordinator_tpu.control.migration import (
        MigrationArbiter,
        MigrationBudget,
    )

    arbiter = MigrationArbiter(MigrationBudget(
        max_per_round=config.migration_max_per_round,
        max_per_node=config.migration_max_per_node,
        max_per_tenant=config.migration_max_per_tenant,
        window_s=config.migration_window_s,
        node_cooldown_s=config.migration_node_cooldown_s,
        dry_run=config.migration_dry_run,
    ))
    scheduler.migration_arbiter = arbiter
    # working-set demotions are the fourth eviction source: recorded
    # against the same windows, undeferrable (the memory safety valve)
    WORKING_SET.migration_hook = lambda key, lane, reason: arbiter.note(
        "workingset", None, [key], lanes=[lane]
    )
    scheduler.services.register("migration", arbiter.status)
    FLIGHT.register_payload("migration", arbiter.flight_payload)
    return scheduler


def stream_config(config: SchedulerConfig):
    """The SchedulerConfig's streaming knobs as a StreamingConfig."""
    from koordinator_tpu.scheduler.streaming import StreamingConfig

    return StreamingConfig(
        watermark=config.stream_watermark,
        lane_deadline_s=(
            config.stream_deadline_system_s,
            config.stream_deadline_ls_s,
            config.stream_deadline_be_s,
        ),
        capacity=config.stream_capacity,
        min_round_interval_s=config.stream_min_interval_s,
    )


def build_streaming_loop(scheduler, bus, config: SchedulerConfig,
                         auditor=None, log=print):
    """Wire a :class:`~koordinator_tpu.scheduler.streaming.
    StreamingLoop` over the bus: admitted arrivals land as Pod applies,
    shed victims / expired pods are bus-deleted (typed, observed by
    every wired component), and pending pods applied by OTHER
    components enter the intake through a watch — so the open-loop
    stream and ordinary informer traffic share one trigger."""
    from koordinator_tpu.client.bus import EventType, Kind
    from koordinator_tpu.scheduler.streaming import StreamingLoop

    loop = StreamingLoop(
        scheduler,
        apply_fn=lambda pod: bus.apply(Kind.POD, pod.uid, pod),
        delete_fn=lambda uid: bus.delete(Kind.POD, uid),
        config=stream_config(config),
        pipelined=config.pipelined_ticks,
        auditor=auditor,
        log=log,
    )

    def on_pod(event, name, pod):
        # externally-applied pending pods join the intake (their lane
        # deadline arms the trigger); loop.submit()'s own applies are
        # already tracked and skipped, bound/assigned pods are not
        # arrivals, DELETEs are handled by the remove_pod chain
        if event is EventType.DELETED:
            return
        if getattr(pod, "node_name", None) is not None:
            # a bind — possibly published by ANOTHER seat (HA
            # streaming, DESIGN §25): resolve the intake's tracked
            # submit→bind span so a standby's timelines and depth
            # gauges stay true without it ever firing a round
            loop.observe_bound(pod)
            return
        loop.observe(pod)

    bus.watch(Kind.POD, on_pod)
    scheduler.services.register("streaming", loop.status)
    return loop


def build_slo_controller(streaming, bus, config: SchedulerConfig,
                         elector=None, log=print):
    """Close the loop on the streaming knobs (docs/DESIGN.md §25):
    when any ``--slo-*`` lane target is declared, a
    :class:`~koordinator_tpu.control.slo.ServingSLOController` rides
    the StreamingLoop's trigger loop and walks
    watermark/deadline/capacity toward the target — bounded,
    hysteretic, one knob per reconcile, every decision a typed record
    on the debug mux and stamped into flight-recorder dumps. Returns
    None when no target is set (the static flags stay in charge)."""
    from koordinator_tpu.control.slo import ServingSLOController, SLOSpec
    from koordinator_tpu.obs.flight import FLIGHT

    spec = SLOSpec.parse(config.slo_system, config.slo_ls, config.slo_be)
    if not spec.any():
        return None
    controller = ServingSLOController(
        streaming, spec, bus=bus, elector=elector,
        window_s=config.slo_window_s,
        cooldown_s=config.slo_cooldown_s,
        log=log,
    )
    streaming.attach_controller(controller)
    streaming.scheduler.services.register("slo", controller.status)
    # the decision-ring tail lands in every anomaly dump: "what was
    # the controller doing to the knobs before this?" answered from
    # the dump alone
    FLIGHT.register_payload("slo", controller.flight_payload)
    return controller


def build_defrag_controller(scheduler, config: SchedulerConfig, log=print):
    """Close the loop on ``defrag_headroom`` (docs/DESIGN.md §27):
    with ``--defrag-loop``, a
    :class:`~koordinator_tpu.control.migration.DefragController` rides
    the fixed-cadence scheduling loop — reconcile-on-the-pump like the
    SLO controller — watching the fragmentation signal and applying one
    arbitrated repack per cooldown. Returns None when the loop is off
    (the API stays operator-called)."""
    from koordinator_tpu.control.migration import (
        DefragController,
        DefragPolicy,
    )
    from koordinator_tpu.obs.flight import FLIGHT

    if not config.defrag_loop:
        return None
    controller = DefragController(scheduler, DefragPolicy(
        interval_s=config.defrag_interval_s,
        cooldown_s=config.defrag_cooldown_s,
        confirm=config.defrag_confirm,
        dry_run=config.defrag_dry_run,
    ))
    scheduler.services.register("defrag", controller.status)
    FLIGHT.register_payload("defrag", controller.flight_payload)
    return controller


def build_rebalance_plugin(config: SchedulerConfig):
    """The in-loop LoadAware Balance sweep's plugin: built when
    ``--rebalance-interval`` is set, run by the loop through
    ``scheduler.rebalance_sweep`` (arbitrated sink, delta-path
    evictions). Returns None when the sweep is off."""
    from koordinator_tpu.descheduler.loadaware import (
        LowNodeLoad,
        LowNodeLoadArgs,
    )

    if config.rebalance_interval_s <= 0:
        return None
    return LowNodeLoad(LowNodeLoadArgs(backend=config.rebalance_backend))


def run_loop(scheduler, config: SchedulerConfig, once: bool = False,
             log=print, elector=None, now_fn=time.time,
             max_rounds: Optional[int] = None, auditor=None,
             pipeline=None, sleep_fn=time.sleep, streaming=None,
             defrag=None, rebalance=None) -> int:
    """The scheduling loop over a wired bus: solve the pending queue
    every interval. A sidecar outage without failover skips the round —
    COUNTED and logged, never silent (``scheduler_rounds_skipped_total``
    carries the running total; with the failover backend wired,
    ``--solver-failover``, outages are solved in-process and no round
    skips). With ``elector``, rounds run only while holding the lease
    (the reference gates sched.Run on OnStartedLeading,
    server.go:226-252); losing the lease mid-round surfaces as
    FencingError, demotes to standby, and immediately FORGETS the
    aborted round's assumed-but-unbound pods — they were never
    published, and left in place they would linger until assume expiry
    and poison a later re-election's first snapshot. With ``auditor``
    (a scheduler.auditor.StateAuditor), an anti-entropy sweep runs
    before the round every ``audit_interval_rounds`` rounds, plus a
    mandatory promotion sweep right after this instance acquires the
    lease (wired through the elector's ``on_started_leading``).
    ``max_rounds`` bounds the loop for regression tests: after that
    many attempted rounds the loop returns the number of skipped rounds
    (0 = every round placed).

    Cadence: rounds fire on an ABSOLUTE deadline grid — the sleep is
    computed from round start, not from end-of-work — so a slow round
    (or an overlapped one) does not push every later round back.

    Pipelined mode (``config.pipelined_ticks`` or an explicit
    ``pipeline``): rounds run through a
    :class:`~koordinator_tpu.scheduler.pipeline.TickPipeline` — the
    round's critical path is catch-up staging + async dispatch, while
    the read-back, epilogue, and bus publish retire on the publisher
    worker during the cadence gap. A publish-side failure surfaces at
    the next round boundary and is handled by the SAME handlers below
    (a deferred FencingError still triggers the fencing forget);
    auditor sweeps drain the pipeline first so they never read a
    half-retired round, and failover mode flips quiesce it through the
    flip hooks wired here."""
    from koordinator_tpu.client.leaderelection import FencingError
    from koordinator_tpu.metrics.components import ROUNDS_SKIPPED
    from koordinator_tpu.obs.flight import FLIGHT
    from koordinator_tpu.obs.trace import TRACER
    from koordinator_tpu.service.client import (
        SolverOverloaded,
        SolverUnavailable,
    )

    if config.streaming or streaming is not None:
        # streaming serving mode (DESIGN §22): the adaptive trigger
        # replaces the fixed cadence entirely — the StreamingLoop owns
        # its own pipeline, auditor cadence, and watchdog polls
        if streaming is None:
            raise ValueError(
                "streaming mode needs a bus-wired StreamingLoop — "
                "build one with build_streaming_loop(scheduler, bus, "
                "config) and pass it as streaming="
            )
        if elector is not None:
            # HA streaming (DESIGN §25): the lease gates the trigger
            # loop itself — a standby seat drains its pipeline and
            # watch-feeds the intake without firing rounds; promotion
            # adopts the deposed leader's knob state FIRST, then
            # sweeps the pending cache into the gate (intake handoff)
            streaming.attach_elector(elector)
        if once:
            raise ValueError("--once is a fixed-cadence concept; "
                             "streaming mode serves continuously")
        try:
            streaming.run()  # blocks until streaming.stop()
        finally:
            streaming.stop()
        return 0

    if pipeline is None and config.pipelined_ticks:
        from koordinator_tpu.scheduler.pipeline import TickPipeline

        pipeline = TickPipeline(scheduler, log=log)
    hooked_backend = None
    prev_flip = prev_degraded = None
    if pipeline is not None:
        scheduler.services.register("tick-pipeline", pipeline.status)
        backend = getattr(scheduler.model, "backend", None)
        if backend is not None and hasattr(backend, "on_flip_back"):
            # degraded-mode flips quiesce the pipeline: the epoch reset
            # (full restage) must never race an in-flight tick's retire.
            # The originals are restored on exit — a re-invoked
            # run_loop must not chain wrappers over stopped pipelines.
            hooked_backend = backend
            prev_flip = backend.on_flip_back

            def _flip_back(prev=prev_flip, p=pipeline):
                p.drain("failover-flip", raise_deferred=False)
                if prev is not None:
                    prev()

            backend.on_flip_back = _flip_back
            if hasattr(backend, "on_flip_degraded"):
                prev_degraded = backend.on_flip_degraded

                def _flip_degraded(prev=prev_degraded, p=pipeline):
                    p.drain("failover-flip", raise_deferred=False)
                    if prev is not None:
                        prev()

                backend.on_flip_degraded = _flip_degraded

    skipped = 0
    rounds = 0
    # in-loop rebalance cadence: first sweep one full interval after
    # loop start (a sweep before any metric lands would be noise)
    last_rebalance = now_fn()

    def on_round_error(e):
        """The one round-failure handler — shared by the main loop's
        except blocks and the standby-branch drain so the skip count,
        metric reasons, fencing forget, and log lines cannot drift
        apart. A FencingError's aborted round placed nothing: it counts
        as skipped (metric AND max_rounds' return value) exactly like a
        solver outage, and the forget releases the aborted round's
        assumed-but-unbound pods — they were never published, and left
        in place they would linger until assume expiry."""
        nonlocal skipped
        skipped += 1
        if isinstance(e, FencingError):
            ROUNDS_SKIPPED.inc({"reason": "leadership-lost"})
            TRACER.instant("fencing-abort", cat="round")
            # anomaly: preserve the rounds that led up to the aborted
            # publish before the forget rewrites the cache
            FLIGHT.trigger("fencing-abort", detail=str(e))
            forgotten = scheduler.forget_assumed_unbound()
            log(f"leadership lost mid-round ({skipped} skipped so "
                f"far): {e}; forgot {len(forgotten)} "
                f"assumed-but-unbound pod(s)")
        else:
            # overloaded past the client's retry budget is an outage
            # from this seat: skip (counted), retry next round
            reason = ("solver-overloaded"
                      if isinstance(e, SolverOverloaded)
                      else "solver-unavailable")
            ROUNDS_SKIPPED.inc({"reason": reason})
            log(f"round skipped ({skipped} skipped so far): {e}")

    monitor = getattr(scheduler, "monitor", None)
    try:
        while True:
            round_start = now_fn()
            deadline = round_start + config.schedule_interval_seconds
            if monitor is not None:
                # span-fed watchdog: flags (and counts) rounds/publishes
                # whose tracer mark is stuck open past the timeout
                monitor.check_stuck()
            if elector is not None and not elector.tick(round_start):
                if pipeline is not None:
                    # a deferred publish-side failure from the round
                    # that deposed us must surface NOW, not after
                    # re-election: until the fencing forget runs, the
                    # aborted round's assumed-but-unbound pods hold
                    # quota/gang/reservation credit that standby
                    # metrics, status, and manual audits all read as
                    # live state
                    st = pipeline.status()
                    if st["inflight"] or st["pending_error"]:
                        try:
                            pipeline.drain("standby")
                        except (FencingError, SolverUnavailable,
                                SolverOverloaded) as e:
                            on_round_error(e)
                log("standby: lease held elsewhere")
                if once:
                    return 3  # distinct from success: no round ran
                sleep_fn(elector.retry_period)
                continue
            rounds += 1
            last = max_rounds is not None and rounds >= max_rounds
            try:
                if auditor is not None:
                    if pipeline is not None and auditor.sweep_due():
                        # quiesce BEFORE the sweep: an unretired tick's
                        # assumed-but-unpublished decisions would read
                        # as drift (deferred errors surface here too,
                        # into the handlers below)
                        pipeline.drain("auditor-sweep")
                    # repairs land BEFORE the solve so a drifted cache
                    # never feeds a round (the promotion sweep
                    # especially: audit the deposed leader's leavings
                    # before the first decision)
                    report = auditor.on_round(now=now_fn())
                    if report is not None and report["detections"]:
                        log(f"audit[{report['kind']}]: "
                            f"{sum(report['detections'].values())} "
                            f"drift(s) detected, "
                            f"repairs: {report['repairs']}")
                if pipeline is not None:
                    pipeline.submit_round(now=now_fn())
                    # the overlap window: warm next round's staging
                    # while this round's solve is in flight
                    pipeline.prestage(now=now_fn())
                    if once or last:
                        # surface this round's own publish-side fate
                        # before returning/stopping
                        pipeline.drain("once" if once else "shutdown")
                    out = None
                else:
                    out = scheduler.schedule_pending()
                # post-round control plane (DESIGN §27): the defrag
                # controller reconciles on the pump (interval-gated
                # internally, one arbitrated repack per cooldown), and
                # the LoadAware Balance sweep fires on its own cadence
                # through the arbitrated sink
                if defrag is not None:
                    defrag.maybe_reconcile(now=now_fn())
                if rebalance is not None and (
                    round_start - last_rebalance
                    >= config.rebalance_interval_s
                ):
                    last_rebalance = round_start
                    swept = scheduler.rebalance_sweep(
                        rebalance, now=now_fn()
                    )
                    if swept:
                        log(f"rebalance: evicted {len(swept)} pod(s)")
            except (FencingError, SolverUnavailable,
                    SolverOverloaded) as e:
                # in pipelined mode this may be a DEFERRED abort from
                # the previous round's publish — the handler is the
                # same safety net either way, and the already-staged
                # next round re-lowers any forgotten rows from truth
                on_round_error(e)
                if once:
                    return 1
            else:
                if out is not None:
                    placed = sum(1 for v in out.values() if v is not None)
                    # the serial loop's flight-recorder feed (the
                    # pipelined loop records from the publisher worker)
                    model = getattr(scheduler, "model", None)
                    FLIGHT.record_round({
                        # this scheduler's round, not the shared
                        # process-global counter (leader + standby)
                        "round": getattr(scheduler, "last_round_id",
                                         None),
                        "at": round_start,
                        "placed": placed,
                        "total": len(out),
                        "waiting": len(out.waiting),
                        "solver": getattr(model, "last_solver", None),
                        **(getattr(model, "last_timings", None) or {}),
                    })
                    log(f"round: {placed}/{len(out)} placed, "
                        f"{len(out.waiting)} waiting")
                if once:
                    return 0
            if last:
                return skipped
            sleep_fn(max(0.0, deadline - now_fn()))
    finally:
        if hooked_backend is not None:
            hooked_backend.on_flip_back = prev_flip
            if hasattr(hooked_backend, "on_flip_degraded"):
                hooked_backend.on_flip_degraded = prev_degraded
        if pipeline is not None:
            pipeline.stop()


def seed_bus_from_json(bus, path: str) -> None:
    """Populate the bus from a simple cluster-spec JSON file:
    ``{"nodes": [{"name", "cpu", "memory"}],
    "pods": [{"name", "cpu", "memory", "node"?}]}`` (cpu in millicores,
    memory in MiB) — the in-process stand-in for a kubeconfig."""
    import json

    from koordinator_tpu.apis.extension import ResourceName
    from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
    from koordinator_tpu.client.bus import Kind

    with open(path) as f:
        spec = json.load(f)
    now = time.time()
    for n in spec.get("nodes", ()):
        bus.apply(Kind.NODE, n["name"], NodeSpec(
            name=n["name"],
            allocatable={
                ResourceName.CPU: int(n.get("cpu", 0)),
                ResourceName.MEMORY: int(n.get("memory", 0)),
            },
        ))
        bus.apply(Kind.NODE_METRIC, n["name"], NodeMetric(
            node_name=n["name"], node_usage={}, update_time=now,
        ))
    for p in spec.get("pods", ()):
        pod = PodSpec(
            name=p["name"],
            requests={
                ResourceName.CPU: int(p.get("cpu", 0)),
                ResourceName.MEMORY: int(p.get("memory", 0)),
            },
            node_name=p.get("node"),
        )
        bus.apply(Kind.POD, pod.uid, pod)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("koord-scheduler")
    parser.add_argument("--feature-gates", default="",
                        help="A=true,B=false gate overrides")
    parser.add_argument("--schedule-interval", type=float, default=1.0)
    parser.add_argument("--once", action="store_true",
                        help="run a single scheduling round and exit")
    parser.add_argument(
        "--placement-backend", choices=("inprocess", "sidecar"),
        default="inprocess",
        help="where batched solves run (north star: the solver sidecar)",
    )
    parser.add_argument("--solver-address", default="/tmp/koord-solver.sock")
    parser.add_argument("--solver-secret-file", default=None)
    parser.add_argument(
        "--solver-failover", action="store_true",
        help="degraded-mode failover: a sidecar outage is answered by "
             "the in-process solver (bit-identical, cold compile) "
             "instead of skipping rounds; flips back with hysteresis",
    )
    parser.add_argument(
        "--solver-supervise", action="store_true",
        help="spawn the koord-solver sidecar at --solver-address and "
             "supervise it (liveness probes, backoff restarts, a "
             "restart-storm circuit breaker)",
    )
    parser.add_argument(
        "--pipelined-ticks", action="store_true",
        help="overlapped tick path: stage round N+1 while round N's "
             "solve is in flight and publish off the critical path "
             "(bit-identical placements; sub-10ms round critical path)",
    )
    parser.add_argument(
        "--streaming", action="store_true",
        help="continuous-arrival serving mode: rounds fire adaptively "
             "(batch-size watermark OR oldest-pod lane deadline) "
             "instead of on the fixed --schedule-interval cadence "
             "(docs/DESIGN.md §22)",
    )
    parser.add_argument(
        "--stream-watermark", type=int, default=64,
        help="streaming batch-size trigger: fire a round once this "
             "many arrivals are queued",
    )
    parser.add_argument(
        "--stream-deadline-system", type=float, default=0.002,
        help="system-lane queue-wait target in seconds (the deadline "
             "trigger for the highest-priority lane)",
    )
    parser.add_argument(
        "--stream-deadline-ls", type=float, default=0.010,
        help="latency-sensitive-lane queue-wait target in seconds",
    )
    parser.add_argument(
        "--stream-deadline-be", type=float, default=0.050,
        help="best-effort-lane queue-wait target in seconds",
    )
    parser.add_argument(
        "--stream-capacity", type=int, default=4096,
        help="streaming intake bound: arrivals past this shed (BE "
             "first, typed + counted — never silence)",
    )
    parser.add_argument(
        "--stream-min-interval", type=float, default=0.0,
        help="floor between adaptively-fired rounds in seconds (0 = "
             "none): bounds the dispatch rate a trickle of urgent "
             "singletons can drive",
    )
    parser.add_argument(
        "--slo-system", default=None,
        help="system-lane serving SLO, e.g. 'p99=0.002' (seconds; a "
             "bare float also parses). Any --slo-* target turns on "
             "the self-tuning SLO controller in --streaming mode: the "
             "static --stream-* knobs become its starting point and a "
             "reconcile loop walks them toward the target "
             "(docs/DESIGN.md §25)",
    )
    parser.add_argument(
        "--slo-ls", default=None,
        help="latency-sensitive-lane serving SLO (see --slo-system)",
    )
    parser.add_argument(
        "--slo-be", default=None,
        help="best-effort-lane serving SLO (see --slo-system)",
    )
    parser.add_argument(
        "--slo-window", type=float, default=5.0,
        help="SLO controller rolling-stats window in seconds (the "
             "lane p99 the reconcile loop reads)",
    )
    parser.add_argument(
        "--slo-cooldown", type=float, default=1.0,
        help="SLO controller per-decision cooldown in seconds "
             "(hysteresis: at most one knob adjustment per cooldown)",
    )
    parser.add_argument(
        "--hbm-budget-bytes", type=int, default=0,
        help="device-memory line for staged tenant worlds "
             "(docs/DESIGN.md §26): under pressure the least-valuable "
             "staged bases demote host-pinned/cold instead of the "
             "process allocating past the line; 0 = unlimited",
    )
    parser.add_argument(
        "--migration-max-per-round", type=int, default=None,
        help="disruption budget: admitted evictions per scheduling "
             "round, all sources combined (control/migration.py, "
             "docs/DESIGN.md §27); unset = unlimited",
    )
    parser.add_argument(
        "--migration-max-per-node", type=int, default=None,
        help="disruption budget: admitted evictions per node within "
             "--migration-window; unset = unlimited",
    )
    parser.add_argument(
        "--migration-max-per-tenant", type=int, default=None,
        help="disruption budget: admitted evictions per QoS lane "
             "(system/ls/be) within --migration-window; unset = "
             "unlimited",
    )
    parser.add_argument(
        "--migration-window", type=float, default=60.0,
        help="rolling window in seconds the per-node/per-tenant "
             "budgets are counted over",
    )
    parser.add_argument(
        "--migration-node-cooldown", type=float, default=0.0,
        help="per-node quiet period in seconds after an admitted "
             "eviction on that node (0 = none)",
    )
    parser.add_argument(
        "--migration-dry-run", action="store_true",
        help="classify-only arbitration: every eviction request is "
             "judged and recorded in the decision ring but NOTHING is "
             "evicted — audit what the budgets would do before "
             "enforcing them",
    )
    parser.add_argument(
        "--defrag-loop", action="store_true",
        help="closed-loop defrag (docs/DESIGN.md §27): watch the "
             "fragmentation signal (a pending gang that fits nowhere "
             "though aggregate free capacity could hold it) and apply "
             "one arbitrated headroom repack per cooldown; "
             "fixed-cadence loop only",
    )
    parser.add_argument(
        "--defrag-interval", type=float, default=5.0,
        help="defrag controller reconcile cadence in seconds",
    )
    parser.add_argument(
        "--defrag-cooldown", type=float, default=30.0,
        help="quiet period in seconds between applied repacks (one "
             "bounded action per cooldown)",
    )
    parser.add_argument(
        "--defrag-confirm", type=int, default=2,
        help="hysteresis: consecutive fragmented observations before "
             "the controller acts",
    )
    parser.add_argument(
        "--defrag-dry-run", action="store_true",
        help="defrag decisions are recorded (ring + metric) but "
             "defrag_headroom is never called",
    )
    parser.add_argument(
        "--rebalance-interval", type=float, default=0.0,
        help="run the LoadAware Balance sweep inside the scheduling "
             "loop every this many seconds, evictions routed through "
             "the migration arbiter (0 = no in-loop sweep)",
    )
    parser.add_argument(
        "--rebalance-backend", choices=("host", "device", "verify"),
        default="host",
        help="eviction-walk backend for the Balance sweep: host "
             "(reference-shaped oracle), device (one lax.scan over "
             "the flattened candidate list), verify (both, "
             "bit-equality asserted before applying)",
    )
    parser.add_argument(
        "--cluster-json", default=None,
        help="seed the bus from a cluster-spec JSON file",
    )
    parser.add_argument(
        "--audit-interval-rounds", type=int, default=16,
        help="anti-entropy sweep cadence in scheduling rounds (0 "
             "disables the auditor entirely); a mandatory promotion "
             "sweep also runs whenever this instance acquires the lease",
    )
    parser.add_argument(
        "--audit-probe-rows", type=int, default=64,
        help="staged rows the device<->host parity probe re-lowers and "
             "compares bit-for-bit per sweep (round-robin coverage)",
    )
    parser.add_argument(
        "--no-trace", action="store_true",
        help="disable span recording (obs/trace.py); the stuck-cycle "
             "watchdog keeps working, /debug/trace serves an empty ring",
    )
    parser.add_argument(
        "--flight-dir", default=None,
        help="anomaly flight-recorder dump directory (default: "
             "$KTPU_FLIGHT_DIR or <tmp>/koord-flight)",
    )
    parser.add_argument(
        "--profile-dir", default=None,
        help="on-demand jax profiler window directory "
             "(/debug/profile?rounds=K arms a window over the next K "
             "rounds; default: $KTPU_PROFILE_DIR or <tmp>/koord-profile)",
    )
    parser.add_argument(
        "--node-shards", type=int, default=1,
        help="split the staged node axis over this many devices "
             "(nodes x pods mesh, sharded delta staging — "
             "docs/DESIGN.md §19); 1 = unsharded, requires that many "
             "attached devices and the in-process backend",
    )
    parser.add_argument(
        "--no-warm-pool", action="store_true",
        help="disable the AOT warm pool (service/warmpool.py): "
             "restarts, leader promotions, and degraded-mode flips "
             "then pay the cold XLA compile again",
    )
    parser.add_argument(
        "--monitor-timeout", type=float, default=10.0,
        help="stuck-cycle watchdog threshold in seconds: an open "
             "round/publish mark older than this counts into "
             "scheduler_stuck_cycles_total; raise it where a cold-start "
             "compile legitimately holds a round open for longer",
    )
    parser.add_argument(
        "--leader-elect", action="store_true",
        help="gate scheduling rounds on holding the koord-scheduler "
             "lease (reference: --leader-elect on every binary)",
    )
    parser.add_argument("--leader-elect-identity", default=None)
    parser.add_argument(
        "--debug-port", type=int, default=None,
        help="serve /healthz /metrics /apis/v1/plugins /debug on this "
             "port (reference: the secure-serving mux on every binary)",
    )
    args = parser.parse_args(argv)

    # persistent XLA cache: a failed-over leader's in-process solver
    # warms from disk instead of recompiling
    from koordinator_tpu.utils.compilation_cache import (
        enable_persistent_cache,
    )

    enable_persistent_cache()
    if not args.no_warm_pool:
        # boot restore FIRST, before the heavy scheduler-stack imports
        # below: executable deserialization right after interpreter
        # start measures ~0.5 s on this box vs ~1.0 s for the same
        # entry once the full stack is imported (allocator state) —
        # and restore() is idempotent, so build_scheduler's own
        # restore (kept for embedders that never run this main)
        # re-scans the already-installed rows in milliseconds
        from koordinator_tpu.service.warmpool import WARM_POOL

        WARM_POOL.configure()
        if WARM_POOL.active:
            WARM_POOL.restore(compile_missing=False)
    secret = None
    if args.solver_secret_file:
        with open(args.solver_secret_file, "rb") as f:
            secret = f.read().strip()
    config = SchedulerConfig(
        feature_gates=args.feature_gates,
        schedule_interval_seconds=args.schedule_interval,
        placement_backend=args.placement_backend,
        solver_address=args.solver_address,
        solver_secret=secret,
        solver_failover=args.solver_failover,
        audit_interval_rounds=args.audit_interval_rounds,
        audit_probe_rows=args.audit_probe_rows,
        pipelined_ticks=args.pipelined_ticks,
        trace=not args.no_trace,
        flight_dir=args.flight_dir,
        profile_dir=args.profile_dir,
        monitor_timeout_seconds=args.monitor_timeout,
        node_shards=args.node_shards,
        warm_pool=not args.no_warm_pool,
        streaming=args.streaming,
        stream_watermark=args.stream_watermark,
        stream_deadline_system_s=args.stream_deadline_system,
        stream_deadline_ls_s=args.stream_deadline_ls,
        stream_deadline_be_s=args.stream_deadline_be,
        stream_capacity=args.stream_capacity,
        stream_min_interval_s=args.stream_min_interval,
        slo_system=args.slo_system,
        slo_ls=args.slo_ls,
        slo_be=args.slo_be,
        slo_window_s=args.slo_window,
        slo_cooldown_s=args.slo_cooldown,
        hbm_budget_bytes=args.hbm_budget_bytes,
        migration_max_per_round=args.migration_max_per_round,
        migration_max_per_node=args.migration_max_per_node,
        migration_max_per_tenant=args.migration_max_per_tenant,
        migration_window_s=args.migration_window,
        migration_node_cooldown_s=args.migration_node_cooldown,
        migration_dry_run=args.migration_dry_run,
        defrag_loop=args.defrag_loop,
        defrag_interval_s=args.defrag_interval,
        defrag_cooldown_s=args.defrag_cooldown,
        defrag_confirm=args.defrag_confirm,
        defrag_dry_run=args.defrag_dry_run,
        rebalance_interval_s=args.rebalance_interval,
        rebalance_backend=args.rebalance_backend,
    )
    from koordinator_tpu.client.bus import APIServer
    from koordinator_tpu.client.wiring import wire_scheduler
    from koordinator_tpu.obs.flight import FLIGHT
    from koordinator_tpu.obs.trace import TRACER

    supervisor = None
    http_server = None
    warm_pool = None
    # everything after the supervisor spawn runs under its finally: a
    # wiring/readiness failure must never strand an orphaned solver
    # child holding the solve socket
    try:
        if args.solver_supervise and args.placement_backend == "sidecar":
            from koordinator_tpu.cmd.solver import parse_address
            from koordinator_tpu.service.supervisor import SolverSupervisor

            extra = ()
            if args.solver_secret_file:
                extra = ("--secret-file", args.solver_secret_file)
            supervisor = SolverSupervisor(
                parse_address(args.solver_address),
                listen_spec=args.solver_address,
                extra_argv=extra,
            )
            supervisor.start()
        scheduler = build_scheduler(config)
        if config.warm_pool:
            from koordinator_tpu.service.warmpool import WARM_POOL

            if WARM_POOL.active:
                warm_pool = WARM_POOL
                # keep the store covering the hot signature set: newly
                # observed solve shapes are AOT-persisted off-path
                WARM_POOL.start_background()
        bus = APIServer()
        elector = None
        if args.leader_elect:
            import os

            from koordinator_tpu.client.leaderelection import LeaderElector

            elector = LeaderElector(
                bus, "koord-scheduler",
                args.leader_elect_identity
                or f"koord-scheduler-{os.getpid()}",
            )
        wire_scheduler(bus, scheduler, elector=elector)
        auditor = None
        if config.audit_interval_rounds > 0:
            from koordinator_tpu.scheduler.auditor import StateAuditor

            auditor = StateAuditor(
                scheduler, bus,
                interval_rounds=config.audit_interval_rounds,
                probe_rows=config.audit_probe_rows,
                # promotion sweeps then restore the warm pool + staged
                # world before the new leader's first solve (DESIGN §21)
                warm_pool=warm_pool,
            )
            scheduler.services.register("state-auditor", auditor.status)
            if elector is not None:
                # promotion sweep: audit the deposed leader's leavings
                # exactly once per acquisition, before the first round
                prev_started = elector.on_started_leading

                def _on_started(prev=prev_started, aud=auditor):
                    aud.note_promotion()
                    if prev is not None:
                        prev()

                elector.on_started_leading = _on_started
        streaming = None
        if config.streaming:
            # the continuous-arrival front end (DESIGN §22): wired
            # over the bus so open-loop submissions and ordinary
            # informer traffic share one adaptive trigger
            streaming = build_streaming_loop(
                scheduler, bus, config, auditor=auditor,
            )
            # declared SLO targets turn on the closed loop over the
            # streaming knobs (no targets = static flags stay in
            # charge, controller not built)
            build_slo_controller(
                streaming, bus, config, elector=elector,
            )
        defrag = None
        rebalance = None
        if not config.streaming:
            defrag = build_defrag_controller(scheduler, config)
            rebalance = build_rebalance_plugin(config)
        elif config.defrag_loop or config.rebalance_interval_s > 0:
            print("defrag loop / in-loop rebalance ride the "
                  "fixed-cadence scheduling loop; ignored in "
                  "--streaming mode")
        if args.cluster_json:
            seed_bus_from_json(bus, args.cluster_json)
        if args.debug_port is not None:
            from koordinator_tpu.metrics.components import SCHEDULER_METRICS
            from koordinator_tpu.utils.debug_http import DebugHTTPServer

            if supervisor is not None:
                # the supervisor's state machine beside the scheduler's
                # own debug surfaces: one GET answers "why is my solver
                # down?"
                scheduler.services.register(
                    "solver-supervisor", supervisor.status
                )
            if hasattr(scheduler.model.backend, "status"):
                scheduler.services.register(
                    "solver-failover", scheduler.model.backend.status
                )
            from koordinator_tpu.metrics.registry import MergedGatherer
            from koordinator_tpu.obs.device import DEVICE_OBS
            from koordinator_tpu.metrics.components import (
                DEVICE_METRICS,
                WORKINGSET_METRICS,
            )
            from koordinator_tpu.obs.explain import PlacementExplainer

            scheduler.services.register("flight-recorder", FLIGHT.status)
            scheduler.services.register("trace", TRACER.status)
            # the device observatory rides the same mux: its registry
            # merges into /metrics, its ring at /debug/device, and
            # /debug/profile arms profiler windows over coming rounds
            scheduler.services.register(
                "device-observatory", DEVICE_OBS.status
            )
            if warm_pool is not None:
                # the warm pool's hit/miss/quarantine counters and
                # last restore report: "did this failover skip its
                # compiles" answered from one GET (DESIGN §21)
                scheduler.services.register("warm-pool", warm_pool.status)
            http_server = DebugHTTPServer(
                services=scheduler.services, debug=scheduler.debug,
                metrics=MergedGatherer(
                    [SCHEDULER_METRICS, DEVICE_METRICS, WORKINGSET_METRICS]
                ),
                port=args.debug_port,
                tracer=TRACER,
                explain=PlacementExplainer(scheduler).explain,
                device=DEVICE_OBS.debug_payload,
                profile=DEVICE_OBS.request_profile,
            ).start()
            print(f"debug http on 127.0.0.1:{http_server.port}")
        return run_loop(scheduler, config, once=args.once, elector=elector,
                        auditor=auditor, streaming=streaming,
                        defrag=defrag, rebalance=rebalance)
    finally:
        if http_server is not None:
            http_server.stop()
        if supervisor is not None:
            supervisor.stop()
        if warm_pool is not None:
            warm_pool.stop_background()


if __name__ == "__main__":
    raise SystemExit(main())
