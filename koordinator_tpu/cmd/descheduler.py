"""koord-descheduler entry point.

Reference: cmd/koord-descheduler + pkg/descheduler/descheduler.go:46 —
profiles of Deschedule/Balance plugins run on the descheduling interval;
the LowNodeLoad balance plugin and the migration-evictor mode are the
component config's knobs.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional

from koordinator_tpu.features import FeatureGate

#: descheduler gates (the reference reuses the scheduler registry; the
#: meaningful toggles here are the eviction mode and anomaly debounce)
DESCHEDULER_GATES = FeatureGate({
    "MigrationController": True,
    "AnomalyDetection": True,
})


@dataclasses.dataclass
class DeschedulerConfig:
    feature_gates: str = ""
    descheduling_interval_seconds: float = 120.0
    #: LowNodeLoad thresholds (percent)
    low_cpu_percent: int = 45
    high_cpu_percent: int = 65
    #: consecutive abnormal observations before eviction
    anomaly_condition_count: int = 3
    max_migrating_per_node: int = 2


def build_descheduler(
    config: DeschedulerConfig, gates: Optional[FeatureGate] = None
):
    from koordinator_tpu.apis.extension import ResourceName
    from koordinator_tpu.descheduler.framework import (
        Descheduler,
        DirectEvictor,
        MigrationEvictor,
        Profile,
    )
    from koordinator_tpu.descheduler.loadaware import (
        LowNodeLoad,
        LowNodeLoadArgs,
    )

    from koordinator_tpu.descheduler.framework import EvictionLimiter
    from koordinator_tpu.descheduler.loadaware import NodePool

    gates = gates or DESCHEDULER_GATES.copy()
    gates.set_from_spec(config.feature_gates)
    pool = NodePool(
        low_thresholds={ResourceName.CPU: config.low_cpu_percent},
        high_thresholds={ResourceName.CPU: config.high_cpu_percent},
        consecutive_abnormalities=(
            config.anomaly_condition_count
            if gates.enabled("AnomalyDetection")
            else 1
        ),
    )
    plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[pool]))
    limiter = EvictionLimiter(max_per_node=config.max_migrating_per_node)
    evictor = (
        MigrationEvictor(limiter)
        if gates.enabled("MigrationController")
        else DirectEvictor(limiter)
    )
    return Descheduler(
        profiles=[Profile(name="default", balance_plugins=[plugin])],
        evictor=evictor,
        descheduling_interval=config.descheduling_interval_seconds,
    )


def main(argv=None) -> int:
    import time

    parser = argparse.ArgumentParser("koord-descheduler")
    parser.add_argument("--feature-gates", default="")
    parser.add_argument("--descheduling-interval", type=float, default=120.0)
    parser.add_argument("--once", action="store_true")
    parser.add_argument("--cluster-json", default=None)
    parser.add_argument("--leader-elect", action="store_true")
    parser.add_argument("--leader-elect-identity", default=None)
    args = parser.parse_args(argv)
    descheduler = build_descheduler(
        DeschedulerConfig(
            feature_gates=args.feature_gates,
            descheduling_interval_seconds=args.descheduling_interval,
        )
    )
    from koordinator_tpu.client.bus import APIServer
    from koordinator_tpu.client.wiring import wire_descheduler

    bus = APIServer()
    elector = None
    if args.leader_elect:
        import os

        from koordinator_tpu.client.leaderelection import LeaderElector

        elector = LeaderElector(
            bus, "koord-descheduler",
            args.leader_elect_identity or f"koord-descheduler-{os.getpid()}",
        )
    loop = wire_descheduler(bus, descheduler, elector=elector)
    if args.cluster_json:
        from koordinator_tpu.cmd.scheduler import seed_bus_from_json

        seed_bus_from_json(bus, args.cluster_json)
    print(
        "koord-descheduler: profiles="
        f"{[p.name for p in descheduler.profiles]}, "
        f"interval={descheduler.descheduling_interval}s"
    )
    from koordinator_tpu.client.leaderelection import FencingError

    def wait(seconds: float) -> None:
        """Sleep while renewing: the descheduling interval (120s) far
        exceeds the lease renew deadline (10s)."""
        if elector is None:
            time.sleep(seconds)
            return
        deadline = time.time() + seconds
        while time.time() < deadline:
            time.sleep(min(elector.retry_period,
                           max(deadline - time.time(), 0)))
            if not elector.tick(time.time()):
                return

    while True:
        if elector is not None and not elector.tick(time.time()):
            print("standby: lease held elsewhere")
            if args.once:
                return 3
            time.sleep(elector.retry_period)
            continue
        try:
            migrated = loop.run_once(now=time.time())
        except FencingError as e:
            print(f"leadership lost mid-cycle: {e}")
            if args.once:
                return 1
        else:
            print(f"descheduling cycle: migrated {len(migrated)} pods")
            if args.once:
                return 0
        wait(descheduler.descheduling_interval)


if __name__ == "__main__":
    raise SystemExit(main())
