"""koord-solver entry point: the placement-solver sidecar.

The north-star deployment splits the control plane from the compiled
solver (SURVEY.md §5.8): the scheduler speaks the framed-npz protocol to
this process, which keeps its jit cache warm across control-plane
restarts. Reference boundary: the plugin-backend selection at
cmd/koord-scheduler/app/server.go:331-398 — here the backend selection
is ``--placement-backend=sidecar`` on the scheduler side, and this is
the process it talks to.
"""

from __future__ import annotations

import argparse
import time
from typing import Optional, Tuple, Union


def parse_address(spec: str) -> Union[str, Tuple[str, int]]:
    """``host:port`` -> TCP tuple; anything else is a UDS path."""
    if ":" in spec and not spec.startswith("/"):
        host, _, port = spec.rpartition(":")
        return (host or "127.0.0.1", int(port))
    return spec


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("koord-solver")
    parser.add_argument(
        "--listen", default="/tmp/koord-solver.sock",
        help="UDS path or host:port to serve the solve protocol on",
    )
    parser.add_argument(
        "--secret-file", default=None,
        help="path to a shared secret required from TCP peers",
    )
    parser.add_argument("--once", action="store_true",
                        help="start, report readiness, and exit (smoke)")
    parser.add_argument(
        "--ready-file", default=None,
        help="write this file (containing the pid) once the solve "
             "socket is accepting — a race-free readiness signal for "
             "supervisors that don't want to poll the socket",
    )
    parser.add_argument(
        "--debug-port", type=int, default=None,
        help="serve /apis/v1/plugins/solver (routing + kernel-breaker "
             "+ admission-gate state), /metrics (admission queue/shed/"
             "latency series + device-observatory compile/padding/"
             "live-buffer series), /debug/trace (the sidecar's span "
             "ring — queue-wait + solve spans tagged with the "
             "scheduler's wire trace context), /debug/device, "
             "/debug/profile?rounds=K (a profiler window over the next "
             "K solves) and /healthz on this port",
    )
    parser.add_argument(
        "--profile-dir", default=None,
        help="on-demand jax profiler window directory (default: "
             "$KTPU_PROFILE_DIR or <tmp>/koord-profile)",
    )
    parser.add_argument(
        "--no-warm-pool", action="store_true",
        help="disable the AOT warm pool: a respawned sidecar then "
             "pays the cold trace + compile on its first solve again",
    )
    parser.add_argument(
        "--hbm-budget-bytes", type=int, default=0,
        help="device-memory line for staged tenant worlds "
             "(docs/DESIGN.md §26): staying under it demotes "
             "least-valuable bases host-pinned/cold instead of "
             "allocating past it; 0 = unlimited",
    )
    args = parser.parse_args(argv)

    # before the first jit: a restarted sidecar deserializes its
    # compiled programs instead of recompiling (cold-start blackout)
    from koordinator_tpu.utils.compilation_cache import (
        enable_persistent_cache,
    )

    enable_persistent_cache()

    warm_pool = None
    if not args.no_warm_pool:
        # the AOT warm pool (docs/DESIGN.md §21): a supervisor-respawned
        # sidecar restores the manifest's executables SEQUENTIALLY,
        # BEFORE the server stack imports and before the listen socket
        # opens — (a) deserialization right after interpreter start
        # measures ~2x cheaper than after the full stack is imported
        # (cmd/scheduler.py main's ordering), and (b) a restore racing
        # the first reconnecting client's solve would cold-compile the
        # very request the warm respawn exists to answer. The
        # supervisor's ready grace covers the extra boot second; the
        # background persister then keeps the store covering newly
        # observed signatures so the NEXT respawn (and the scheduler's
        # failover twin, which shares the store) stays warm. Inert
        # when the cache dir is disabled.
        from koordinator_tpu.service.warmpool import WARM_POOL

        WARM_POOL.configure()
        if WARM_POOL.active:
            warm_pool = WARM_POOL
            WARM_POOL.restore(compile_missing=False)
            WARM_POOL.start_background()

    from koordinator_tpu.service.server import PlacementService

    if args.hbm_budget_bytes:
        from koordinator_tpu.state.workingset import WORKING_SET

        WORKING_SET.set_budget(args.hbm_budget_bytes)

    secret: Optional[bytes] = None
    if args.secret_file:
        with open(args.secret_file, "rb") as f:
            secret = f.read().strip()
    service = PlacementService(parse_address(args.listen), secret=secret)
    service.start()
    if args.ready_file:
        import os

        with open(args.ready_file, "w") as f:
            f.write(str(os.getpid()))
    from koordinator_tpu.obs.device import DEVICE_OBS

    if args.profile_dir:
        DEVICE_OBS.configure(profile_dir=args.profile_dir)
    debug_server = None
    if args.debug_port is not None:
        from koordinator_tpu.metrics.components import (
            DEVICE_METRICS,
            SOLVER_METRICS,
            WORKINGSET_METRICS,
        )
        from koordinator_tpu.metrics.registry import MergedGatherer
        from koordinator_tpu.obs.trace import TRACER
        from koordinator_tpu.scheduler.monitor import DebugServices
        from koordinator_tpu.utils.debug_http import DebugHTTPServer

        services = DebugServices()
        # the solver's operational state — the kernel-routing breaker
        # ("why is this sidecar riding the scan?") and the admission
        # gate (lane depths, coalesce ratio, shed counts) in one GET;
        # /metrics serves the same gate as prometheus series (plus the
        # device observatory's compile/padding/live-buffer series), and
        # /debug/trace the sidecar-side spans (queue wait + solve,
        # joined to the scheduler's trace via the wire trace context)
        services.register("solver", service.status)
        services.register("trace", TRACER.status)
        services.register("device-observatory", DEVICE_OBS.status)
        # the HBM working-set ledger (§26): budget/rung census, who got
        # demoted and why, beside the gate and breaker state
        from koordinator_tpu.state.workingset import WORKING_SET

        services.register("workingset", WORKING_SET.status)
        if warm_pool is not None:
            # warm-pool health beside the breaker/gate state: did this
            # respawn skip its compiles, is the store clean (§21)
            services.register("warm-pool", warm_pool.status)
        debug_server = DebugHTTPServer(
            services=services,
            metrics=MergedGatherer(
                [SOLVER_METRICS, DEVICE_METRICS, WORKINGSET_METRICS]
            ),
            tracer=TRACER, port=args.debug_port,
            device=DEVICE_OBS.debug_payload,
            profile=DEVICE_OBS.request_profile,
        ).start()
    print(f"koord-solver: serving on {args.listen}")
    try:
        if args.once:
            return 0
        while True:
            time.sleep(60)
    except KeyboardInterrupt:
        return 0
    finally:
        service.stop()
        if debug_server is not None:
            debug_server.stop()
        if warm_pool is not None:
            warm_pool.stop_background()


if __name__ == "__main__":
    raise SystemExit(main())
