"""koord-manager entry point: slo controllers + webhooks + quota profiles.

Reference: cmd/koord-manager/main.go:119-160 — controller-runtime manager
registering the noderesource/nodemetric/nodeslo/quota-profile controllers
and the webhook server, gated by the manager feature gates
(pkg/features/features.go).
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional

from koordinator_tpu.features import MANAGER_GATES, FeatureGate


@dataclasses.dataclass
class ManagerConfig:
    feature_gates: str = ""
    #: noderesource sync cadence
    sync_interval_seconds: float = 60.0


@dataclasses.dataclass
class Manager:
    """The wired central controllers (main.go's mgr)."""

    noderesource: object
    nodeslo: object
    mutating_webhook: Optional[object]
    validating_webhook: Optional[object]
    quota_guard: Optional[object]
    profile_controller_factory: object  # scheduler -> QuotaProfileController
    node_mutating_webhook: Optional[object] = None
    node_validating_webhook: Optional[object] = None
    slo_config_webhook: Optional[object] = None

    def admit_pod(self, pod, old_pod=None):
        """The webhook chain every pod passes (mutate → validate);
        returns (pod, violations)."""
        if self.mutating_webhook is not None:
            pod = self.mutating_webhook.mutate(pod)
        violations = []
        if self.validating_webhook is not None:
            violations = self.validating_webhook.validate(pod, old_pod)
        return pod, violations

    def admit_node(self, node, old_node=None):
        """Node admission (amplification mutate → validate)."""
        if self.node_mutating_webhook is not None:
            node = self.node_mutating_webhook.mutate(node, old_node)
        violations = []
        if self.node_validating_webhook is not None:
            violations = self.node_validating_webhook.validate(node, old_node)
        return node, violations


def build_manager(config: ManagerConfig, gates: Optional[FeatureGate] = None) -> Manager:
    from koordinator_tpu.manager.noderesource import NodeResourceController
    from koordinator_tpu.manager.nodeslo import NodeSLOController
    from koordinator_tpu.quota.profile import QuotaProfileController
    from koordinator_tpu.webhook import (
        NodeMutatingWebhook,
        NodeValidatingWebhook,
        PodMutatingWebhook,
        PodValidatingWebhook,
        QuotaTopologyGuard,
        SLOConfigValidatingWebhook,
    )

    gates = gates or MANAGER_GATES.copy()
    gates.set_from_spec(config.feature_gates)
    return Manager(
        noderesource=NodeResourceController(),
        nodeslo=NodeSLOController(),
        mutating_webhook=(
            PodMutatingWebhook() if gates.enabled("PodMutatingWebhook") else None
        ),
        validating_webhook=(
            PodValidatingWebhook()
            if gates.enabled("PodValidatingWebhook")
            else None
        ),
        quota_guard=(
            QuotaTopologyGuard()
            if gates.enabled("ElasticValidatingWebhook")
            else None
        ),
        profile_controller_factory=QuotaProfileController,
        node_mutating_webhook=(
            NodeMutatingWebhook()
            if gates.enabled("NodeMutatingWebhook")
            else None
        ),
        node_validating_webhook=(
            NodeValidatingWebhook()
            if gates.enabled("NodeValidatingWebhook")
            else None
        ),
        slo_config_webhook=(
            SLOConfigValidatingWebhook()
            if gates.enabled("ConfigMapValidatingWebhook")
            else None
        ),
    )


def main(argv=None) -> int:
    import time

    parser = argparse.ArgumentParser("koord-manager")
    parser.add_argument("--feature-gates", default="")
    parser.add_argument("--sync-interval", type=float, default=60.0)
    parser.add_argument("--once", action="store_true")
    parser.add_argument("--cluster-json", default=None)
    parser.add_argument("--leader-elect", action="store_true")
    parser.add_argument("--leader-elect-identity", default=None)
    args = parser.parse_args(argv)
    config = ManagerConfig(feature_gates=args.feature_gates,
                           sync_interval_seconds=args.sync_interval)
    manager = build_manager(config)
    enabled = [
        name
        for name, component in (
            ("noderesource", manager.noderesource),
            ("nodeslo", manager.nodeslo),
            ("pod-mutating-webhook", manager.mutating_webhook),
            ("pod-validating-webhook", manager.validating_webhook),
            ("quota-topology-guard", manager.quota_guard),
        )
        if component is not None
    ]
    from koordinator_tpu.client.bus import APIServer
    from koordinator_tpu.client.leaderelection import FencingError
    from koordinator_tpu.client.wiring import wire_manager

    bus = APIServer()
    elector = None
    if args.leader_elect:
        import os

        from koordinator_tpu.client.leaderelection import LeaderElector

        elector = LeaderElector(
            bus, "koord-manager",
            args.leader_elect_identity or f"koord-manager-{os.getpid()}",
        )
    loop = wire_manager(bus, manager.noderesource, elector=elector)
    from koordinator_tpu.manager.recommendation import wire_recommendation

    recommender = wire_recommendation(bus, manager.mutating_webhook,
                                      elector=elector)
    if args.cluster_json:
        from koordinator_tpu.cmd.scheduler import seed_bus_from_json

        seed_bus_from_json(bus, args.cluster_json)
    print("koord-manager components:", ", ".join(enabled))

    def wait(seconds: float) -> bool:
        """Sleep ``seconds`` while keeping the lease renewed: the sync
        interval (60s) far exceeds renew_deadline (10s), so a leader
        must tick at retry_period cadence between reconciles. Returns
        False as soon as leadership is lost."""
        if elector is None:
            time.sleep(seconds)
            return True
        deadline = time.time() + seconds
        while time.time() < deadline:
            time.sleep(min(elector.retry_period, max(deadline - time.time(), 0)))
            if not elector.tick(time.time()):
                return False
        return True

    while True:
        if elector is not None and not elector.tick(time.time()):
            # standby: keep the recommendation histograms warm so a
            # failover doesn't start from an empty bank
            recommender.observe(now=time.time())
            print("standby: lease held elsewhere")
            if args.once:
                return 3  # distinct from success: no reconcile ran
            time.sleep(elector.retry_period)
            continue
        try:
            synced = loop.reconcile(now=time.time())
            recommender.run_once(now=time.time())
        except FencingError as e:
            # deposed mid-reconcile: demote to standby, don't crash
            # (the scheduler run_loop handles the same exception)
            print(f"leadership lost mid-reconcile: {e}")
            if args.once:
                return 1
        else:
            print(f"noderesource reconcile: {synced} nodes synced")
            if args.once:
                return 0
        wait(config.sync_interval_seconds)


if __name__ == "__main__":
    raise SystemExit(main())
