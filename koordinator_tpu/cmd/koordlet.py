"""koordlet entry point: the node agent daemon assembly.

Reference: cmd/koordlet/main.go + pkg/koordlet/koordlet.go:70-126
(NewDaemon wiring: executor → metriccache → statesinformer →
metricsadvisor → predictServer → qosManager → runtimeHooks) with the
koordlet feature gates (pkg/features/koordlet_features.go) toggling each
collector/strategy.
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import List, Optional

from koordinator_tpu.features import KOORDLET_GATES, FeatureGate


@dataclasses.dataclass
class KoordletConfig:
    feature_gates: str = ""
    cgroup_root: str = "/sys/fs/cgroup"
    proc_root: str = "/proc"
    use_cgroup_v2: bool = False
    collect_interval_seconds: float = 1.0
    reconcile_interval_seconds: float = 10.0
    node_capacity_mcpu: int = 0
    node_capacity_mem_mib: int = 0
    #: runtimehooks actuation mode (reference --runtime-hooks-mode):
    #: ``reconciler`` heals periodically off informer state; ``nri``
    #: additionally dispatches hook stages from the PLEG event stream
    runtime_hooks_mode: str = "reconciler"
    #: local checkpoint dir (reference §5.4: prediction histograms +
    #: TSDB survive restarts); empty = no persistence
    checkpoint_dir: str = ""
    checkpoint_interval_seconds: float = 60.0
    #: PV name -> block device "MAJ:MIN" (the host's volume-attachment
    #: view; the reference walks /var/lib/kubelet + sysfs — here the CSI
    #: layer/operator supplies the map). Feeds blkio pod-volume throttles.
    volume_devices: Optional[dict] = None


@dataclasses.dataclass
class KoordletDaemon:
    """The wired node agent (koordlet.go Daemon)."""

    states_informer: object
    metric_cache: object
    metrics_advisor: object
    qos_manager: object
    predict_server: object
    auditor: object
    executor: object
    collector_ctx: object = None
    runtime_hooks: object = None
    pleg: object = None
    nri_server: object = None
    reconcile_interval_seconds: float = 10.0
    checkpoint_dir: str = ""
    checkpoint_interval_seconds: float = 60.0
    _last_reconcile: float = 0.0
    _last_checkpoint: float = 0.0

    def tick(self, now: Optional[float] = None) -> None:
        """One daemon step: collect → predict → actuate → hooks (the
        run order of koordlet.go:127-188)."""
        now = time.time() if now is None else now
        self.metrics_advisor.tick(now)
        self._feed_predictor(now)
        self.qos_manager.tick(now)
        if self.pleg is not None:
            # NRI mode: lifecycle events dispatch hook stages directly
            self.pleg.poll()
        if self.runtime_hooks is not None and (
            now - self._last_reconcile >= self.reconcile_interval_seconds
        ):
            self._last_reconcile = now
            self.runtime_hooks.reconcile()
        if self.checkpoint_dir and (
            now - self._last_checkpoint >= self.checkpoint_interval_seconds
        ):
            self._last_checkpoint = now
            self.checkpoint()

    def checkpoint(self) -> None:
        """Persist restart state (§5.4): the metric TSDB + the
        prediction histograms."""
        import os

        os.makedirs(self.checkpoint_dir, exist_ok=True)
        self.metric_cache.save(
            os.path.join(self.checkpoint_dir, "metriccache.npz")
        )
        self.predict_server.save_checkpoint(
            os.path.join(self.checkpoint_dir, "prediction.json")
        )

    def _feed_predictor(self, now: float) -> None:
        """Stream the latest usage samples into the peak predictor
        (predict_server.go's informer subscription)."""
        ctx = self.collector_ctx
        if ctx is None:
            return
        from koordinator_tpu.koordlet.prediction.predict_server import (
            NODE_KEY,
            pod_key,
        )

        node_usage = ctx.latest_node_usage
        if node_usage:
            self.predict_server.update(
                NODE_KEY,
                node_usage.get("cpu", 0.0),
                node_usage.get("memory", 0.0),
                now,
            )
        live_keys = []
        for uid, usage in ctx.latest_pod_usage.items():
            key = pod_key(uid)
            live_keys.append(key)
            self.predict_server.update(
                key,
                usage.get("cpu", 0.0),
                usage.get("memory", 0.0),
                now,
            )
        # forget churned pods so predictor state stays bounded
        self.predict_server.gc(live_keys)


def _be_allocatable(states_informer) -> Optional[int]:
    """BE tier allocatable (node batch-cpu) from the informer's node
    view — the cpu-evict evictByAllocatable denominator."""
    from koordinator_tpu.apis.extension import ResourceName

    node = states_informer.get_node()
    if node is None:
        return None
    value = node.allocatable.get(ResourceName.BATCH_CPU)
    return int(value) if value else None


def build_koordlet(
    config: KoordletConfig, gates: Optional[FeatureGate] = None
) -> KoordletDaemon:
    """NewDaemon (koordlet.go:70): every subsystem built, gates deciding
    which collectors/strategies register."""
    from koordinator_tpu.koordlet.audit import Auditor
    from koordinator_tpu.koordlet.metriccache import MetricCache
    from koordinator_tpu.koordlet.metricsadvisor.collectors import (
        BEResourceCollector,
        ColdMemoryCollector,
        HostApplicationCollector,
        NodeResourceCollector,
        PageCacheCollector,
        PodResourceCollector,
        PSICollector,
        SysResourceCollector,
    )
    from koordinator_tpu.koordlet.metricsadvisor.framework import (
        CollectorContext,
        MetricsAdvisor,
    )
    from koordinator_tpu.koordlet.metricsadvisor.performance import (
        PerformanceCollector,
    )
    from koordinator_tpu.koordlet.prediction import (
        PeakPredictServer,
        PredictionConfig,
    )
    from koordinator_tpu.koordlet.qosmanager import (
        BlkIOReconcile,
        CgroupResourcesReconcile,
        CPUBurst,
        CPUEvictor,
        CPUSuppress,
        MemoryEvictor,
        QoSContext,
        QoSManager,
        ResctrlReconcile,
        SystemConfigReconcile,
    )
    from koordinator_tpu.koordlet.resourceexecutor import (
        ResourceUpdateExecutor,
    )
    from koordinator_tpu.koordlet.statesinformer import StatesInformer
    from koordinator_tpu.koordlet.system.cgroup import SystemConfig

    gates = gates or KOORDLET_GATES.copy()
    gates.set_from_spec(config.feature_gates)

    system_config = SystemConfig(
        cgroup_root=config.cgroup_root,
        proc_root=config.proc_root,
        use_cgroup_v2=config.use_cgroup_v2,
    )
    auditor = Auditor() if gates.enabled("AuditEvents") else None
    executor = ResourceUpdateExecutor(system_config, auditor=auditor)
    metric_cache = MetricCache()
    # the informer IS the PodProvider (running_pods) for every subsystem
    states_informer = StatesInformer()
    pod_provider = states_informer
    collector_ctx = CollectorContext(
        metric_cache=metric_cache,
        system_config=system_config,
        pod_provider=pod_provider,
    )
    collectors: List[object] = [
        NodeResourceCollector(),
        PodResourceCollector(),
        BEResourceCollector(),
        SysResourceCollector(),
        HostApplicationCollector(slo_provider=states_informer.get_node_slo),
    ]
    if gates.enabled("PSICollector"):
        collectors.append(PSICollector())
    if gates.enabled("CPICollector"):
        collectors.append(PerformanceCollector())
    if gates.enabled("ColdPageCollector"):
        collectors.append(ColdMemoryCollector())
        collectors.append(PageCacheCollector())
    from koordinator_tpu.koordlet.metricsadvisor.devices import (
        DeviceCollector,
        NodeStorageInfoCollector,
        PodThrottledCollector,
    )

    collectors.append(PodThrottledCollector())
    collectors.append(NodeStorageInfoCollector())
    if gates.enabled("Accelerators"):
        collectors.append(DeviceCollector())
    metrics_advisor = MetricsAdvisor(
        collector_ctx, collectors,
        interval_seconds=config.collect_interval_seconds,
    )

    predict_server = PeakPredictServer(PredictionConfig())

    qos_ctx = QoSContext(
        metric_cache=metric_cache,
        executor=executor,
        pod_provider=pod_provider,
        system_config=system_config,
        auditor=auditor,
        node_capacity_mcpu=config.node_capacity_mcpu,
        node_capacity_mem_mib=config.node_capacity_mem_mib,
        # PVC claim -> bound PV -> device for blkio pod-volume throttles
        volume_name_fn=states_informer.get_volume_name,
        volume_devices=dict(config.volume_devices or {}),
        be_allocatable_fn=lambda: _be_allocatable(states_informer),
    )
    strategies: List[object] = []
    if gates.enabled("BECPUSuppress"):
        strategies.append(CPUSuppress())
    if gates.enabled("BECPUEvict"):
        strategies.append(CPUEvictor())
    if gates.enabled("BEMemoryEvict"):
        strategies.append(MemoryEvictor())
    if gates.enabled("CPUBurst"):
        strategies.append(CPUBurst())
    if gates.enabled("RdtResctrl"):
        strategies.append(ResctrlReconcile())
    if gates.enabled("CgroupReconcile"):
        strategies.append(CgroupResourcesReconcile())
    if gates.enabled("BlkIOReconcile"):
        strategies.append(BlkIOReconcile())
    if gates.enabled("SystemConfig"):
        strategies.append(SystemConfigReconcile())
    for strategy in strategies:
        if strategy.name in ("resctrl", "cgreconcile", "blkio", "sysreconcile"):
            strategy.interval_seconds = config.reconcile_interval_seconds
    qos_manager = QoSManager(qos_ctx, strategies)

    # NodeSLO changes flow from the informer into the QoS strategies
    from koordinator_tpu.koordlet.statesinformer.states_informer import (
        StateKind,
    )

    states_informer.register_callback(
        StateKind.NODE_SLO,
        lambda kind, slo: setattr(qos_ctx, "node_slo", slo),
    )
    # the cpu-normalization ratio (node annotation) feeds quota-burst
    # bases so burst scaling floors at the normalized quota
    from koordinator_tpu.koordlet.runtimehooks.cpunormalization import (
        parse_ratio_from_annotations,
    )

    states_informer.register_callback(
        StateKind.NODE,
        lambda kind, node: setattr(
            qos_ctx, "cpu_normalization_ratio",
            parse_ratio_from_annotations(getattr(node, "annotations", None)),
        ),
    )

    # runtimehooks: bvt/cpuset/batchresource actuation (koordlet.go runs
    # runtimeHooks last); reconciler mode is always armed, NRI mode
    # additionally streams PLEG lifecycle events into the hook server
    from koordinator_tpu.koordlet.pleg import PLEG
    from koordinator_tpu.koordlet.runtimehooks import RuntimeHooks

    runtime_hooks = RuntimeHooks(states_informer, executor)
    pleg = nri_server = None
    if config.runtime_hooks_mode == "nri":
        pleg = PLEG(system_config)
        nri_server = runtime_hooks.attach_nri(pleg)
        pleg.poll()  # primer
    elif config.runtime_hooks_mode != "reconciler":
        raise ValueError(
            f"unknown runtime hooks mode: {config.runtime_hooks_mode!r}"
        )

    if config.checkpoint_dir:
        # resume from the previous incarnation's state (§5.4)
        import os

        metric_cache.load(
            os.path.join(config.checkpoint_dir, "metriccache.npz")
        )
        predict_server.load_checkpoint(
            os.path.join(config.checkpoint_dir, "prediction.json")
        )

    return KoordletDaemon(
        states_informer=states_informer,
        metric_cache=metric_cache,
        metrics_advisor=metrics_advisor,
        qos_manager=qos_manager,
        predict_server=predict_server,
        auditor=auditor,
        executor=executor,
        collector_ctx=collector_ctx,
        runtime_hooks=runtime_hooks,
        pleg=pleg,
        nri_server=nri_server,
        reconcile_interval_seconds=config.reconcile_interval_seconds,
        checkpoint_dir=config.checkpoint_dir,
        checkpoint_interval_seconds=config.checkpoint_interval_seconds,
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("koordlet")
    parser.add_argument("--feature-gates", default="")
    parser.add_argument("--cgroup-root", default="/sys/fs/cgroup")
    parser.add_argument("--proc-root", default="/proc")
    parser.add_argument("--cgroup-v2", action="store_true")
    parser.add_argument("--collect-interval", type=float, default=1.0)
    parser.add_argument("--runtime-hooks-mode",
                        choices=("reconciler", "nri"), default="reconciler")
    parser.add_argument("--checkpoint-dir", default="",
                        help="persist TSDB + prediction state across "
                             "restarts (empty = off)")
    parser.add_argument("--debug-port", type=int, default=None,
                        help="serve /healthz /metrics /audit on this port")
    parser.add_argument("--once", action="store_true")
    args = parser.parse_args(argv)
    daemon = build_koordlet(
        KoordletConfig(
            feature_gates=args.feature_gates,
            cgroup_root=args.cgroup_root,
            proc_root=args.proc_root,
            use_cgroup_v2=args.cgroup_v2,
            collect_interval_seconds=args.collect_interval,
            runtime_hooks_mode=args.runtime_hooks_mode,
            checkpoint_dir=args.checkpoint_dir,
        )
    )
    http_server = None
    if args.debug_port is not None:
        from koordinator_tpu.metrics.components import (
            KOORDLET_EXTERNAL_METRICS,
            KOORDLET_INTERNAL_METRICS,
        )
        from koordinator_tpu.metrics.registry import MergedGatherer
        from koordinator_tpu.utils.debug_http import DebugHTTPServer

        # internal + external sets on one endpoint (merged_gather.go)
        http_server = DebugHTTPServer(
            metrics=MergedGatherer([KOORDLET_INTERNAL_METRICS,
                                    KOORDLET_EXTERNAL_METRICS]),
            auditor=daemon.auditor,
            port=args.debug_port,
        ).start()
        print(f"debug http on 127.0.0.1:{http_server.port}")
    try:
        while True:
            daemon.tick()
            if args.once:
                return 0
            time.sleep(args.collect_interval)
    finally:
        if http_server is not None:
            http_server.stop()


if __name__ == "__main__":
    raise SystemExit(main())
