"""koord-runtime-proxy entry point: the CRI interposer daemon.

Reference: cmd/koord-runtime-proxy/main.go — flags for the proxy
endpoint, the real runtime endpoint, and the failure policy; the server
interposes kubelet↔containerd CRI calls and dispatches the hook server
pre/post (pkg/runtimeproxy/server/cri/criserver.go:44,90-102).

The in-process transport serves the interposer over a framed-JSON UDS
socket: each line is a CRIRequest
``{"method", "pod_uid", "container"?, "payload"?}`` — ``container``
names the container for container-level methods; the reply carries the
hook-merged resources. A kubelet stand-in (tests,
demos) connects instead of gRPC — the interception/merge/failover logic
is the same `RuntimeManagerCriServer` the library exposes.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import socketserver
from typing import Optional

from koordinator_tpu.runtimeproxy.criserver import (
    BackendRuntime,
    CRIRequest,
    RuntimeManagerCriServer,
)


@dataclasses.dataclass
class RuntimeProxyConfig:
    """Component config (main.go flag surface)."""

    listen: str = "/tmp/koord-runtimeproxy.sock"
    failure_policy: str = "ignore"  # ignore | fail


class NullBackend:
    """Stands in for the real container runtime when none is attached
    (the reference requires containerd; demos run hook dispatch only)."""

    def handle(self, request: CRIRequest) -> object:
        return {"ok": True, "method": request.method}

    def list_pods(self):
        return []


def build_proxy(config: RuntimeProxyConfig, hook_server=None,
                backend: Optional[BackendRuntime] = None):
    from koordinator_tpu.koordlet.runtimehooks import (
        FailurePolicy,
        HookRegistry,
        RuntimeHookServer,
    )

    if hook_server is None:
        hook_server = RuntimeHookServer(HookRegistry(), executor=None)
    policy = (
        FailurePolicy.FAIL if config.failure_policy == "fail"
        else FailurePolicy.IGNORE
    )
    proxy = RuntimeManagerCriServer(
        hook_server, backend or NullBackend(), failure_policy=policy
    )
    proxy.fail_over()
    return proxy


def serve(proxy: RuntimeManagerCriServer, listen: str, once: bool = False,
          log=print) -> int:
    """Line-framed JSON request loop over UDS."""
    import socket

    if os.path.exists(listen):
        # a dead predecessor leaves its socket behind; unlink it iff
        # nothing is accepting — never hijack a live proxy's endpoint
        # (same restart-in-place flow as service/server.py)
        probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            try:
                probe.connect(listen)
            except OSError:
                os.unlink(listen)
            else:
                raise OSError(f"address in use: {listen}")
        finally:
            probe.close()

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            for line in self.rfile:
                try:
                    req = json.loads(line)
                    payload = dict(req.get("payload", {}))
                    # the documented frame carries pod_uid at top level;
                    # intercept() resolves it from the payload
                    if "pod_uid" in req:
                        payload.setdefault("pod_uid", req["pod_uid"])
                    request = CRIRequest(
                        method=req["method"],
                        container=req.get("container"),
                        payload=payload,
                    )
                    response = proxy.intercept(request)
                    out = {
                        "backend": response.backend_response,
                        "hook": (
                            dataclasses.asdict(response.hook_response)
                            if response.hook_response is not None else None
                        ),
                    }
                    # serialize INSIDE the guard: an un-JSONable backend
                    # response must yield an error frame, not a dead
                    # connection
                    frame = json.dumps(out)
                except Exception as e:  # a bad frame must not kill the proxy
                    frame = json.dumps({"error": f"{type(e).__name__}: {e}"})
                self.wfile.write((frame + "\n").encode())
                self.wfile.flush()

    if once:
        # single-connection smoke: serve it SYNCHRONOUSLY so the process
        # doesn't exit (killing daemon threads) while replies are in
        # flight
        with socketserver.UnixStreamServer(listen, Handler) as server:
            log(f"koord-runtime-proxy listening on {listen}")
            server.handle_request()
        return 0

    class Server(socketserver.ThreadingUnixStreamServer):
        daemon_threads = True

    with Server(listen, Handler) as server:
        log(f"koord-runtime-proxy listening on {listen}")
        server.serve_forever()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("koord-runtime-proxy")
    parser.add_argument("--listen", default="/tmp/koord-runtimeproxy.sock",
                        help="UDS path for the interposed CRI endpoint")
    parser.add_argument("--failure-policy", choices=("ignore", "fail"),
                        default="ignore")
    parser.add_argument("--once", action="store_true",
                        help="serve a single connection and exit (smoke)")
    args = parser.parse_args(argv)
    config = RuntimeProxyConfig(listen=args.listen,
                                failure_policy=args.failure_policy)
    proxy = build_proxy(config)
    return serve(proxy, config.listen, once=args.once)


if __name__ == "__main__":
    raise SystemExit(main())
