"""Typed protocol layer: QoS classes, priority bands, resources, CRD-equivalents.

Mirrors the reference's ``apis/`` module (the annotation/label protocol that
is the de-facto API of the system) as plain Python types.
"""

from koordinator_tpu.apis.extension import (  # noqa: F401
    QoSClass,
    PriorityClass,
    ResourceName,
    PRIORITY_BANDS,
    priority_class_of,
    qos_class_of,
)
