"""analysis.koordinator.sh API group: Recommendation.

Reference: apis/analysis/v1alpha1/recommendation_types.go:55 — a
Recommendation targets a workload (CrossVersionObjectReference) or a
pod label selector (:34-42), and its status carries the most recently
computed recommended resources plus update time and conditions (:77-85).
The reference granularity is per-container; the typed model here is
per-pod (PodSpec is the pod-level scheduling unit throughout this
framework), which is the same information the webhook right-sizer and
noderesource consumers need.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from koordinator_tpu.apis.types import PodSpec, Resources, selector_matches


#: status condition types (metav1.Condition analogue)
CONDITION_READY = "RecommendationProvided"
CONDITION_NO_SAMPLES = "NoObservedSamples"


@dataclasses.dataclass
class RecommendationTarget:
    """What the analysis covers (reference: RecommendationTarget,
    types ``workload`` | ``podSelector``).

    ``workload`` uses the same "Kind/namespace/name" controller-owner
    string as :class:`PodSpec.owner`.
    """

    workload: Optional[str] = None
    pod_selector: Optional[Dict[str, str]] = None

    def matches(self, pod: PodSpec) -> bool:
        if self.workload is not None:
            return pod.owner == self.workload
        if self.pod_selector is not None:
            return selector_matches(self.pod_selector, pod.labels)
        return False


@dataclasses.dataclass
class Recommendation:
    """The Recommendation object: user-created spec (target), controller
    -filled status (recommended resources)."""

    name: str
    target: RecommendationTarget
    #: status: recommended per-pod requests (empty until first compute)
    recommended: Resources = dataclasses.field(default_factory=dict)
    update_time: float = 0.0
    #: condition type -> status (True/False)
    conditions: Dict[str, bool] = dataclasses.field(default_factory=dict)

    @property
    def ready(self) -> bool:
        return bool(self.recommended) and self.conditions.get(
            CONDITION_READY, False
        )
