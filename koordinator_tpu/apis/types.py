"""CRD-equivalent typed objects.

The reference defines its API as Kubernetes CRDs plus an annotation protocol
(reference: apis/slo/v1alpha1/nodemetric_types.go, apis/scheduling/v1alpha1/
{reservation,pod_migration_job}_types.go, scheduler-plugins PodGroup /
ElasticQuota). Here they are plain Python dataclasses: the control plane of
this framework is in-process (or gRPC-fronted, see ``runtimeproxy``), and
the hot state is immediately lowered onto the array substrate
(``koordinator_tpu.state``).

All quantities are canonical integer units (see apis/extension.py):
CPU in millicores, memory in MiB.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from koordinator_tpu.apis.extension import (
    NUM_RESOURCES,
    PriorityClass,
    QoSClass,
    ResourceName,
    priority_class_of,
)

#: Sparse resource mapping in canonical units.
Resources = Dict[ResourceName, int]


def resources_to_vector(res: Optional[Mapping[ResourceName, int]]) -> np.ndarray:
    """Densify a sparse resource mapping into an int64 ``[R]`` vector."""
    vec = np.zeros(NUM_RESOURCES, dtype=np.int64)
    if res:
        for name, qty in res.items():
            vec[int(name)] = int(qty)
    return vec


def vector_to_resources(vec: np.ndarray) -> Resources:
    """Sparsify an ``[R]`` vector back into a mapping (drops zeros)."""
    return {ResourceName(i): int(v) for i, v in enumerate(vec) if v != 0}


def selector_matches(
    selector: Optional[Mapping[str, str]], labels: Mapping[str, str]
) -> bool:
    """k8s equality-based label selector: every selector key/value must
    appear in ``labels``. Empty/None selector matches everything."""
    if not selector:
        return True
    return all(labels.get(k) == v for k, v in selector.items())


def add_resources(a: Resources, b: Resources) -> Resources:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) + v
    return out


@dataclasses.dataclass
class PodSpec:
    """A pod as the scheduler sees it.

    Combines corev1.Pod fields with the Koordinator label protocol already
    resolved (QoS class, priority class/band value, quota, gang).
    """

    name: str
    namespace: str = "default"
    uid: str = ""
    requests: Resources = dataclasses.field(default_factory=dict)
    limits: Resources = dataclasses.field(default_factory=dict)
    qos: QoSClass = QoSClass.NONE
    priority: int = 0           # numeric k8s priority
    sub_priority: int = 0       # koordinator.tpu/priority within the band
    priority_class: Optional[PriorityClass] = None  # derived if None
    quota: Optional[str] = None
    gang: Optional[str] = None
    node_name: Optional[str] = None   # set once assigned
    # device resource requests keyed by raw device resource name
    # (reference: extended resources like nvidia.com/gpu in pod spec)
    device_requests: Dict[str, int] = dataclasses.field(default_factory=dict)
    is_daemonset: bool = False
    preemptible: bool = True
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    # wall-clock seconds when this pod was assigned (for loadaware estimation
    # staleness rules, reference: load_aware.go:337-376)
    assign_time: float = 0.0
    #: controller owner reference, "Kind/namespace/name" (metav1
    #: OwnerReference with controller=true) — workload grouping for the
    #: descheduler arbitrator and duplicate detection
    owner: Optional[str] = None
    #: required node selector (spec.nodeSelector) — the node-affinity
    #: slice the compat descheduler plugin enforces
    node_selector: Optional[Dict[str, str]] = None
    #: requested host ports (containers[].ports[].hostPort): ints (TCP
    #: implied) or "<proto>:<port>" strings — the NodePorts filter input
    host_ports: Optional[List] = None
    #: Σ container restart counts (status) — TooManyRestarts input
    restart_count: int = 0
    #: volume name -> PVC claim key "namespace/name" (spec.volumes[] with
    #: persistentVolumeClaim) — blkio pod-volume throttle resolution
    volumes: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: assumed on a node behind a gang Permit barrier, NOT yet bound —
    #: the scheduler holds capacity but the placement is not observable
    #: (the reference keeps WaitOnPermit assumptions out of the API
    #: server; node agents must not treat such a pod as running)
    waiting_permit: bool = False
    #: metadata.creationTimestamp (wall-clock seconds) — eviction-order
    #: final tiebreak (descheduler sorter PodCreationTimestamp: newer
    #: pods evict first) and lifetime/arbitrator inputs
    creation_time: float = 0.0

    def __post_init__(self) -> None:
        if self.priority_class is None:
            self.priority_class = priority_class_of(value=self.priority)
        if not self.uid:
            self.uid = f"{self.namespace}/{self.name}"


@dataclasses.dataclass
class NodeSpec:
    """A node: allocatable capacity plus scheduling-relevant attributes."""

    name: str
    allocatable: Resources = dataclasses.field(default_factory=dict)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    unschedulable: bool = False
    # raw (pre-amplification) allocatable if cpu-normalization applies
    raw_allocatable: Optional[Resources] = None


@dataclasses.dataclass
class NodeMetric:
    """Reported node/pod utilization (reference: NodeMetric CRD,
    apis/slo/v1alpha1/nodemetric_types.go).

    ``update_time`` drives staleness (filter skip at 180s default,
    degrade-to-zero in the manager's batch calculator).
    """

    node_name: str
    node_usage: Resources = dataclasses.field(default_factory=dict)
    # pod uid -> usage
    pod_usages: Dict[str, Resources] = dataclasses.field(default_factory=dict)
    # priority-class aggregated usage (prod usage mode)
    prod_usage: Resources = dataclasses.field(default_factory=dict)
    sys_usage: Resources = dataclasses.field(default_factory=dict)
    # predictor output: reclaimable prod resources (feeds mid-tier calc;
    # reference: NodeMetric.Status.ProdReclaimableMetric)
    prod_reclaimable: Resources = dataclasses.field(default_factory=dict)
    # pod uid -> priority class recorded with the metric (used for pods
    # reported in the metric but absent from the pod list)
    pod_priority_class: Dict[str, PriorityClass] = dataclasses.field(
        default_factory=dict
    )
    # percentile -> usage, for aggregated usage mode (p50/p90/p95/p99)
    aggregated_usage: Dict[int, Resources] = dataclasses.field(default_factory=dict)
    # the aggregation window (seconds) the percentiles above were computed
    # over (the collect policy's primary aggregate duration)
    aggregated_duration: Optional[float] = None
    # additional windows: duration seconds -> percentile -> usage
    # (reference: AggregatedNodeUsages[] — one entry per
    # AggregatePolicy.Durations window)
    aggregated_windows: Dict[float, Dict[int, Resources]] = dataclasses.field(
        default_factory=dict
    )
    # system-usage percentiles per window (reference:
    # AggregatedSystemUsages — reported, no in-tree consumer)
    aggregated_system_usage: Dict[float, Dict[int, Resources]] = (
        dataclasses.field(default_factory=dict)
    )
    # host application name -> usage (reference: NodeMetric
    # HostApplicationMetric list, which also carries the app's QoS)
    host_app_usages: Dict[str, Resources] = dataclasses.field(
        default_factory=dict
    )
    host_app_qos: Dict[str, QoSClass] = dataclasses.field(default_factory=dict)
    # device name -> disk throughput/utilization over the window
    # (storage accounting from the nodestorageinfo collector)
    disk_usages: Dict[str, "DiskUsage"] = dataclasses.field(
        default_factory=dict
    )
    update_time: float = 0.0
    report_interval: float = 60.0


@dataclasses.dataclass
class DiskUsage:
    """One block device's throughput/utilization over the report window."""

    read_bps: int = 0
    write_bps: int = 0
    io_util_pct: int = 0


@dataclasses.dataclass
class PVCSpec:
    """A PersistentVolumeClaim as the node agent needs it (reference:
    statesinformer/impl/states_pvc.go — the informer keeps only the
    claim -> bound-PV mapping the blkio reconciler resolves through).

    ``name`` is the namespaced claim key ("namespace/name")."""

    name: str
    volume_name: str = ""       # bound PV name ("" = unbound)
    capacity_mib: int = 0


class GangMode(enum.Enum):
    """Gang failure handling (reference: core/gang.go ScheduleStrategy)."""

    STRICT = "Strict"
    NON_STRICT = "NonStrict"


@dataclasses.dataclass
class GangSpec:
    """A gang / PodGroup: all-or-nothing co-scheduling unit.

    Reference: scheduler-plugins PodGroup CRD + annotation fallback
    (pkg/scheduler/plugins/coscheduling/core/gang.go:43-95).
    """

    name: str
    min_member: int
    #: declared child count. The reference feeds this into its
    #: schedule-cycle validity machinery (ganggroup.go:110-127: a cycle
    #: only advances once every child attempted), which exists because
    #: its per-pod scheduler interleaves gangs across cycles. The
    #: batched solver places a whole pending queue per solve and
    #: resolves gangs at batch end — one batch IS one cycle — so the
    #: field is carried for API parity and surfaced in summaries, not
    #: consumed by admission logic.
    total_member: int = 0
    wait_time: float = 600.0
    mode: GangMode = GangMode.STRICT
    # gangs that must be admitted together (gang group)
    gang_group: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class QuotaSpec:
    """An elastic quota node in the hierarchical quota tree.

    Reference: scheduler-plugins ElasticQuota CRD + koordinator extensions
    (shared weight, allow-lent, guaranteed; pkg/scheduler/plugins/
    elasticquota/core/quota_info.go).

    NOTE: a resource dimension absent from ``max`` admits nothing on that
    dimension — pods requesting it are rejected, matching the reference's
    quota ``LessThanOrEqual`` semantics (missing key in the bound = not
    satisfiable). Define ``max`` for every resource your pods request.
    """

    name: str
    parent: Optional[str] = None
    min: Resources = dataclasses.field(default_factory=dict)
    max: Resources = dataclasses.field(default_factory=dict)
    shared_weight: Optional[Resources] = None  # defaults to max
    is_parent: bool = False
    allow_lent_resource: bool = True
    guaranteed: Resources = dataclasses.field(default_factory=dict)
    tree_id: str = ""
    #: opt into proportional min scaling when sibling mins oversubscribe
    #: the parent total (reference: enable-scale-min-quota annotation,
    #: core/scale_minquota_when_over_root_res.go)
    enable_min_quota_scale: bool = False
    #: tree roots: the node-pool total backing this tree (reference:
    #: AnnotationTotalResource set by the quota-profile controller)
    total_resource: Optional[Resources] = None


class ReservationState(enum.Enum):
    PENDING = "Pending"
    AVAILABLE = "Available"
    SUCCEEDED = "Succeeded"
    EXPIRED = "Expired"
    FAILED = "Failed"


@dataclasses.dataclass
class ReservationSpec:
    """A resource reservation (reference: apis/scheduling/v1alpha1/
    reservation_types.go).

    Reserves capacity on a node; owner pods matching ``owner_labels`` may
    allocate from it instead of from raw node capacity.
    """

    name: str
    requests: Resources = dataclasses.field(default_factory=dict)
    owner_labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    node_name: Optional[str] = None        # set once the reservation is bound
    state: ReservationState = ReservationState.PENDING
    allocatable: Resources = dataclasses.field(default_factory=dict)
    allocated: Resources = dataclasses.field(default_factory=dict)
    #: absolute expiry (spec.expires); checked before ttl
    expiration_time: Optional[float] = None
    #: relative expiry from create_time (spec.TTL); 0 disables expiration
    ttl: Optional[float] = None
    create_time: float = 0.0
    allocate_once: bool = True
    #: explicit pod owners (migration reservations; reference:
    #: ReservationOwner.Object) — when set, only these pods match
    owner_pod_uids: List[str] = dataclasses.field(default_factory=list)
    #: pods currently allocated from this reservation (reference:
    #: Reservation.Status current owners) — bookkeeping, not matching
    allocated_pod_uids: List[str] = dataclasses.field(default_factory=list)


class MigrationPhase(enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclasses.dataclass
class PodMigrationJob:
    """Descheduler migration job (reference: apis/scheduling/v1alpha1/
    pod_migration_job_types.go): reservation-first eviction state machine.
    """

    name: str
    pod_uid: str
    phase: MigrationPhase = MigrationPhase.PENDING
    reservation_name: Optional[str] = None
    reason: str = ""
    ttl: float = 300.0
    create_time: float = 0.0
    paused: bool = False


@dataclasses.dataclass
class DeviceInfo:
    """One allocatable device on a node (reference: apis/scheduling/
    v1alpha1/device_types.go DeviceInfo)."""

    minor: int                      # device index on the node
    device_type: str = "gpu"        # gpu | rdma | fpga
    resources: Resources = dataclasses.field(default_factory=dict)
    numa_node: int = 0
    pcie_id: int = 0
    health: bool = True


@dataclasses.dataclass
class NodeDevice:
    """Per-node device inventory + topology (Device CRD equivalent)."""

    node_name: str
    devices: List[DeviceInfo] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ClusterSnapshot:
    """Everything the placement solver needs for one solve.

    This is the host-side, typed view; ``koordinator_tpu.state`` lowers it
    to arrays. Components (informers in the reference) incrementally update
    it; solves see a consistent copy.
    """

    nodes: List[NodeSpec] = dataclasses.field(default_factory=list)
    pods: List[PodSpec] = dataclasses.field(default_factory=list)  # assigned pods
    pending_pods: List[PodSpec] = dataclasses.field(default_factory=list)
    node_metrics: Dict[str, NodeMetric] = dataclasses.field(default_factory=dict)
    gangs: Dict[str, GangSpec] = dataclasses.field(default_factory=dict)
    quotas: Dict[str, QuotaSpec] = dataclasses.field(default_factory=dict)
    reservations: List[ReservationSpec] = dataclasses.field(default_factory=list)
    devices: Dict[str, NodeDevice] = dataclasses.field(default_factory=dict)
    now: float = 0.0
    #: optional state.cluster.ClusterDeltaTracker the snapshot producer
    #: maintains — lets the model's staging cache re-lower only the node
    #: rows events touched instead of the world (None = full relower)
    delta_tracker: Optional[object] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    #: the tracker's epoch AT SNAPSHOT TIME (captured under the
    #: producer's lock): the staging cache syncs to this, not to the
    #: live epoch, so a mutation racing between snapshot() and the
    #: solve is re-lowered next tick instead of silently lost
    delta_epoch: Optional[int] = dataclasses.field(
        default=None, repr=False, compare=False
    )
