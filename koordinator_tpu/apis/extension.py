"""The core protocol: QoS classes, priority bands, resource names and units.

This is the TPU-native rebuild of the reference's annotation/label protocol
(reference: apis/extension/qos.go:19-28, apis/extension/priority.go:25-58,
apis/extension/resource.go:26-29). Because the array substrate encodes every
pod/node attribute as integers, this module also defines the *canonical
integer encodings* used on device:

Canonical units (chosen so all score math fits int32 on TPU without x64):

- CPU:    millicores (int32; 2^31 mCPU ≈ 2.1M cores — beyond any node/quota)
- Memory: MiB        (int32; 2^31 MiB = 2 PiB per node — beyond any node)
- Other scalar resources (batch-cpu, batch-memory, GPU shares, ...) follow
  the same convention as their base resource.

Percent math rounds via ``floor((200*used + alloc) / (2*alloc))``, which
needs ``200*used <= 2^31`` i.e. ``used <= 10.7M`` canonical units
(10.7k cores / 10 TiB) — safe for any single node.
Cluster-wide aggregations (quota trees) run host-side in Python ints (exact,
arbitrary precision, matching the reference's int64 semantics).
"""

from __future__ import annotations

import enum
from typing import Mapping, Optional


class QoSClass(enum.IntEnum):
    """Koordinator QoS classes, integer-encoded for the array substrate.

    Reference: apis/extension/qos.go:22-29. Order matters for array masks:
    colocation logic mostly branches on "is BE" and "is latency sensitive".
    """

    NONE = 0
    SYSTEM = 1
    LSE = 2  # latency-sensitive exclusive (pinned cpus, no sharing)
    LSR = 3  # latency-sensitive reserved (pinned cpus, reclaimable)
    LS = 4   # latency-sensitive (shared pool)
    BE = 5   # best effort (reclaimed resources)

    @property
    def is_latency_sensitive(self) -> bool:
        return self in (QoSClass.LSE, QoSClass.LSR, QoSClass.LS)


_QOS_BY_NAME = {
    "LSE": QoSClass.LSE,
    "LSR": QoSClass.LSR,
    "LS": QoSClass.LS,
    "BE": QoSClass.BE,
    "SYSTEM": QoSClass.SYSTEM,
}


def qos_class_of(name: Optional[str]) -> QoSClass:
    """Parse a QoS class name; unknown names map to NONE.

    Reference semantics: apis/extension/qos.go:31-40 (GetPodQoSClassByName).
    """
    if not name:
        return QoSClass.NONE
    return _QOS_BY_NAME.get(name, QoSClass.NONE)


class PriorityClass(enum.IntEnum):
    """Koordinator priority classes (bands of k8s priority values).

    Reference: apis/extension/priority.go:28-49.
    """

    NONE = 0
    FREE = 1
    BATCH = 2
    MID = 3
    PROD = 4


#: (min, max) inclusive k8s priority value band per class
#: (reference: apis/extension/priority.go:37-49).
PRIORITY_BANDS: Mapping[PriorityClass, tuple] = {
    PriorityClass.PROD: (9000, 9999),
    PriorityClass.MID: (7000, 7999),
    PriorityClass.BATCH: (5000, 5999),
    PriorityClass.FREE: (3000, 3999),
}

_PRIORITY_BY_NAME = {
    "koord-prod": PriorityClass.PROD,
    "koord-mid": PriorityClass.MID,
    "koord-batch": PriorityClass.BATCH,
    "koord-free": PriorityClass.FREE,
}


def priority_class_of(
    name: Optional[str] = None, value: Optional[int] = None
) -> PriorityClass:
    """Resolve the priority class from a class name or a numeric priority.

    Name takes precedence over value, matching the reference's label-first
    lookup (apis/extension/priority.go:71-101 GetPodPriorityClassRaw /
    getPriorityClassByPriority).
    """
    if name:
        p = _PRIORITY_BY_NAME.get(name)
        if p is not None:
            return p
    if value is None:
        return PriorityClass.NONE
    for cls, (lo, hi) in PRIORITY_BANDS.items():
        if lo <= value <= hi:
            return cls
    return PriorityClass.NONE


class ResourceName(enum.IntEnum):
    """Resource dimensions of the array substrate, in fixed column order.

    The first two columns (CPU, MEMORY) are the native resources; the rest
    are Koordinator extended resources (reference: apis/extension/
    resource.go:26-29 batch-cpu/batch-memory, mid-cpu/mid-memory and
    apis/extension/device_share.go GPU resources). Arrays of shape
    ``[..., R]`` index this enum on the last axis.
    """

    CPU = 0          # millicores
    MEMORY = 1       # MiB
    BATCH_CPU = 2    # millicores, dynamically reclaimed for BE pods
    BATCH_MEMORY = 3  # MiB, dynamically reclaimed for BE pods
    MID_CPU = 4      # millicores, reclaimed for MID pods
    MID_MEMORY = 5   # MiB, reclaimed for MID pods
    GPU = 6          # GPU shares in per-cent of a device (100 == 1 GPU)
    GPU_MEMORY = 7   # MiB of device memory


#: Number of resource columns in substrate arrays.
NUM_RESOURCES = len(ResourceName)

#: Which resource columns are "native" (exist on every node).
NATIVE_RESOURCES = (ResourceName.CPU, ResourceName.MEMORY)

#: Batch/Mid column → the native column its quantity is denominated in.
#: Used when translating extended resources by priority class
#: (reference: pkg/scheduler/plugins/loadaware/load_aware.go:66
#: TranslateResourceNameByPriorityClass).
EXTENDED_TO_NATIVE = {
    ResourceName.BATCH_CPU: ResourceName.CPU,
    ResourceName.BATCH_MEMORY: ResourceName.MEMORY,
    ResourceName.MID_CPU: ResourceName.CPU,
    ResourceName.MID_MEMORY: ResourceName.MEMORY,
}

#: Priority class → (cpu column, memory column) a pod of that class consumes.
PRIORITY_RESOURCES = {
    PriorityClass.PROD: (ResourceName.CPU, ResourceName.MEMORY),
    PriorityClass.NONE: (ResourceName.CPU, ResourceName.MEMORY),
    PriorityClass.MID: (ResourceName.MID_CPU, ResourceName.MID_MEMORY),
    PriorityClass.BATCH: (ResourceName.BATCH_CPU, ResourceName.BATCH_MEMORY),
    PriorityClass.FREE: (ResourceName.CPU, ResourceName.MEMORY),
}


# ---------------------------------------------------------------------------
# Well-known annotation/label keys (string protocol kept for interop with
# tooling that speaks the reference's protocol; the array substrate is the
# real API). Reference: apis/extension/*.go constants.
# ---------------------------------------------------------------------------

DOMAIN = "koordinator.tpu"

LABEL_QOS_CLASS = f"{DOMAIN}/qosClass"
LABEL_PRIORITY_CLASS = f"{DOMAIN}/priorityClass"
LABEL_POD_PRIORITY = f"{DOMAIN}/priority"  # sub-priority within a band
LABEL_GANG_NAME = f"{DOMAIN}/gang-name"
LABEL_GANG_MIN_MEMBER = f"{DOMAIN}/gang-min-available"
LABEL_QUOTA_NAME = f"{DOMAIN}/quota-name"
LABEL_QUOTA_PARENT = f"{DOMAIN}/quota-parent"
LABEL_QUOTA_IS_PARENT = f"{DOMAIN}/quota-is-parent"
ANNOTATION_RESOURCE_SPEC = f"{DOMAIN}/resource-spec"
ANNOTATION_RESOURCE_STATUS = f"{DOMAIN}/resource-status"
ANNOTATION_RESERVATION_ALLOCATED = f"{DOMAIN}/reservation-allocated"
ANNOTATION_DEVICE_ALLOCATED = f"{DOMAIN}/device-allocated"
ANNOTATION_DEVICE_ALLOCATE_HINTS = f"{DOMAIN}/device-allocate-hints"
ANNOTATION_DEVICE_JOINT_ALLOCATE = f"{DOMAIN}/device-joint-allocate"
ANNOTATION_SOFT_EVICTION = f"{DOMAIN}/soft-eviction"
ANNOTATION_EVICTION_COST = f"{DOMAIN}/eviction-cost"
# node-level colocation protocol (reference: apis/extension/node.go,
# node_colocation.go): reserved resources, cpu normalization/amplification
ANNOTATION_NODE_RESERVATION = f"{DOMAIN}/node-reservation"
ANNOTATION_CPU_NORMALIZATION_RATIO = f"{DOMAIN}/cpu-normalization-ratio"
ANNOTATION_RESOURCE_AMPLIFICATION_RATIO = (
    f"{DOMAIN}/node-resource-amplification-ratio"
)
ANNOTATION_NODE_RAW_ALLOCATABLE = f"{DOMAIN}/node-raw-allocatable"


def parse_node_reservation(
    annotations: Optional[Mapping[str, str]],
) -> Optional[dict]:
    """The node-reservation annotation, parsed once for every consumer.

    Reference: apis/extension/node_reservation.go GetNodeReservation +
    util.GetNodeReservationResources. Accepts the reference's nested form
    ``{"resources": {"cpu": N, "memory": N}, "applyPolicy": "..."}`` and
    the flat legacy form ``{"cpu": N, "memory": N}``. Returns
    ``{"cpu": mcpu, "memory": mib, "apply_policy": str}`` (canonical
    units, zeros for absent dims) or None for absent/malformed — the two
    consumers must agree on what a reservation says:

    - the scheduler-side node transform (client/wiring.transform_node)
      trims allocatable only under the Default policy
      (TrimNodeAllocatableByNodeReservation, node.go:130);
    - the manager's batch-overcommit inputs subtract it regardless of
      policy (GetNodeReservationFromAnnotation, node.go:85-100).
    """
    import json

    raw = (annotations or {}).get(ANNOTATION_NODE_RESERVATION)
    if not raw:
        return None
    try:
        spec = json.loads(raw)
    except (ValueError, TypeError):
        return None
    if not isinstance(spec, dict):
        return None
    res = spec.get("resources", spec)
    if not isinstance(res, dict):
        return None
    try:
        cpu = int(res.get("cpu", 0))
        mem = int(res.get("memory", 0))
    except (ValueError, TypeError):
        return None
    return {
        "cpu": max(cpu, 0),
        "memory": max(mem, 0),
        "apply_policy": str(spec.get("applyPolicy", "Default") or "Default"),
    }
