"""kidled cold-page accounting (Alibaba Cloud-kernel idle-page tracking).

Reference: pkg/koordlet/util/system/kidled_util.go — the kernel module
exposes ``/sys/kernel/mm/kidled/{scan_period_in_seconds,use_hierarchy}``
and per-cgroup ``memory.idle_page_stats`` histograms: one row per page
class (cfei/dfei/cfui/dfui/... = clean/dirty × file/slab × evictable/
unevictable × idle), bucketed by idle age. Cold bytes = Σ of the four
file-backed idle classes from the cold boundary bucket onward
(GetColdPageTotalBytes :138-141, kidledColdBoundary).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

from koordinator_tpu.koordlet.system.cgroup import CONFIG, SystemConfig

#: the page classes counted as reclaimable cold pages (:138)
COLD_PAGE_CLASSES = ("cfei", "dfei", "cfui", "dfui")

#: default boundary bucket (idle >= 5 scan periods; kidled_util.go:34)
DEFAULT_COLD_BOUNDARY = 3


@dataclasses.dataclass
class IdlePageStats:
    """Parsed memory.idle_page_stats."""

    scan_period_seconds: int = 0
    use_hierarchy: int = 0
    buckets: List[int] = dataclasses.field(default_factory=list)
    #: page class -> per-bucket bytes
    classes: Dict[str, List[int]] = dataclasses.field(default_factory=dict)

    def cold_page_bytes(self, boundary: int = DEFAULT_COLD_BOUNDARY) -> int:
        total = 0
        for name in COLD_PAGE_CLASSES:
            total += sum(self.classes.get(name, [])[boundary:])
        return total


def parse_idle_page_stats(content: str) -> IdlePageStats:
    """Parse the kidled histogram file: header lines
    ``# key: value`` (version/scan period/use_hierarchy/buckets), then
    ``<class> v0 v1 ...`` rows per page class."""
    stats = IdlePageStats()
    for line in content.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            fields = line[1:].split()
            if len(fields) < 2:
                continue
            key = fields[0].rstrip(":")
            if key == "scan_period_in_seconds":
                stats.scan_period_seconds = int(fields[1])
            elif key == "use_hierarchy":
                stats.use_hierarchy = int(fields[1])
            elif key == "buckets":
                stats.buckets = [int(x) for x in fields[1].split(",") if x]
            continue
        fields = line.split()
        stats.classes[fields[0]] = [int(x) for x in fields[1:]]
    return stats


class Kidled:
    """The kidled control files + per-cgroup stats reader."""

    def __init__(self, cfg: Optional[SystemConfig] = None):
        self.cfg = cfg or CONFIG

    @property
    def root(self) -> str:
        sysfs = getattr(self.cfg, "sysfs_root", "/sys")
        return os.path.join(sysfs, "kernel", "mm", "kidled")

    def supported(self) -> bool:
        return os.path.exists(os.path.join(self.root, "scan_period_in_seconds"))

    def set_scan_period(self, seconds: int) -> None:
        with open(os.path.join(self.root, "scan_period_in_seconds"), "w") as f:
            f.write(str(int(seconds)))

    def set_use_hierarchy(self, use: bool) -> None:
        with open(os.path.join(self.root, "use_hierarchy"), "w") as f:
            f.write("1" if use else "0")

    def read_stats(self, cgroup_dir: str = "") -> Optional[IdlePageStats]:
        sub = "" if self.cfg.use_cgroup_v2 else "memory"
        path = os.path.join(
            self.cfg.cgroup_root, sub, cgroup_dir, "memory.idle_page_stats"
        )
        try:
            with open(path) as f:
                return parse_idle_page_stats(f.read())
        except (OSError, ValueError, IndexError):
            # unreadable or malformed stats must not crash the tick
            return None
