"""Core scheduling: prctl(PR_SCHED_CORE) cookie management.

Reference: pkg/koordlet/util/system/core_sched_linux.go — create/share
core-scheduling cookies so same-core SMT siblings never co-run distrusted
tasks (the groupidentity CPUQOS core-expeller). The raw syscall is
injectable so tests (and non-Linux hosts) use a fake kernel.
"""

from __future__ import annotations

import ctypes
import ctypes.util
from typing import Callable, Dict, Optional

PR_SCHED_CORE = 62
#: prctl sub-commands (include/uapi/linux/prctl.h)
PR_SCHED_CORE_GET = 0
PR_SCHED_CORE_CREATE = 1
PR_SCHED_CORE_SHARE_TO = 2
PR_SCHED_CORE_SHARE_FROM = 3

PIDTYPE_PID = 0
PIDTYPE_TGID = 1
PIDTYPE_PGID = 2

PrctlFn = Callable[[int, int, int, int, int], int]


def _libc_prctl() -> PrctlFn:
    libc = ctypes.CDLL(ctypes.util.find_library("c"), use_errno=True)

    def call(option, arg2, arg3, arg4, arg5):
        rc = libc.prctl(
            ctypes.c_int(option), ctypes.c_ulong(arg2), ctypes.c_ulong(arg3),
            ctypes.c_ulong(arg4), ctypes.c_ulong(arg5),
        )
        return rc if rc >= 0 else -ctypes.get_errno()

    return call


class CoreSched:
    """Cookie operations over an injectable prctl (core_sched_linux.go
    CoreSchedExtended)."""

    def __init__(self, prctl: Optional[PrctlFn] = None):
        self._prctl = prctl if prctl is not None else _libc_prctl()

    def supported(self) -> bool:
        """Probe PR_SCHED_CORE_GET on self (EINVAL => kernel lacks it)."""
        cookie = ctypes.c_ulonglong(0)
        rc = self._prctl(
            PR_SCHED_CORE, PR_SCHED_CORE_GET, 0, PIDTYPE_PID,
            ctypes.addressof(cookie),
        )
        return rc == 0

    def get(self, pid: int) -> Optional[int]:
        cookie = ctypes.c_ulonglong(0)
        rc = self._prctl(
            PR_SCHED_CORE, PR_SCHED_CORE_GET, pid, PIDTYPE_PID,
            ctypes.addressof(cookie),
        )
        return int(cookie.value) if rc == 0 else None

    def create(self, pid: int, pid_type: int = PIDTYPE_TGID) -> bool:
        """Assign a fresh cookie to the task (group)."""
        return self._prctl(
            PR_SCHED_CORE, PR_SCHED_CORE_CREATE, pid, pid_type, 0
        ) == 0

    def share_to(self, pid: int, pid_type: int = PIDTYPE_TGID) -> bool:
        """Push the caller's cookie onto ``pid``."""
        return self._prctl(
            PR_SCHED_CORE, PR_SCHED_CORE_SHARE_TO, pid, pid_type, 0
        ) == 0

    def share_from(self, pid: int) -> bool:
        """Pull ``pid``'s cookie onto the caller."""
        return self._prctl(
            PR_SCHED_CORE, PR_SCHED_CORE_SHARE_FROM, pid, PIDTYPE_PID, 0
        ) == 0

    def assign_group_cookie(self, leader_pid: int, member_pids) -> int:
        """Give a task group one shared cookie (the groupidentity
        core-expeller flow: create on the leader unless it already has a
        cookie, share to members); returns how many members were tagged."""
        if not self.get(leader_pid):
            if not self.create(leader_pid, PIDTYPE_PID):
                return 0
        tagged = 0
        for pid in member_pids:
            if pid == leader_pid:
                continue
            if self.share_from(leader_pid) and self.share_to(pid, PIDTYPE_PID):
                tagged += 1
        return tagged


class FakeKernel:
    """In-memory PR_SCHED_CORE (tests / unsupported hosts)."""

    def __init__(self, supported: bool = True):
        self.cookies: Dict[int, int] = {}
        self._next = 1
        self._supported = supported
        self._caller = 0  # the "current" task

    def prctl(self, option, arg2, pid, pid_type, arg5):
        if option != PR_SCHED_CORE or not self._supported:
            return -22  # EINVAL
        if arg2 == PR_SCHED_CORE_GET:
            ctypes.cast(arg5, ctypes.POINTER(ctypes.c_ulonglong))[0] = (
                self.cookies.get(pid, 0)
            )
            return 0
        if arg2 == PR_SCHED_CORE_CREATE:
            self.cookies[pid] = self._next
            self._next += 1
            return 0
        if arg2 == PR_SCHED_CORE_SHARE_TO:
            self.cookies[pid] = self.cookies.get(self._caller, 0)
            return 0
        if arg2 == PR_SCHED_CORE_SHARE_FROM:
            self.cookies[self._caller] = self.cookies.get(pid, 0)
            return 0
        return -22
