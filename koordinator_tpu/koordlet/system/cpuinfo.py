"""CPU topology discovery from /proc + /sys.

Reference: pkg/koordlet/util/system/{cpuinfo.go,lscpu.go} — logical
processor → (core, socket, NUMA node) mapping. Parsed from
``/proc/cpuinfo`` (processor / physical id / core id) and
``/sys/devices/system/node/node*/cpulist``; both roots go through
``SystemConfig`` so tests point at a fake tree.
"""

from __future__ import annotations

import dataclasses
import glob
import os
import re
from typing import Dict, List, Optional

from koordinator_tpu.koordlet.system.cgroup import CONFIG, SystemConfig


@dataclasses.dataclass(frozen=True)
class ProcessorInfo:
    """One logical cpu (reference: koordletutil.ProcessorInfo)."""

    cpu_id: int
    core_id: int
    socket_id: int
    node_id: int


def parse_cpulist(text: str) -> List[int]:
    """"0-3,8,10-11" → [0,1,2,3,8,10,11] (kernel cpulist format)."""
    out: List[int] = []
    for part in text.strip().split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-", 1)
            out.extend(range(int(lo), int(hi) + 1))
        else:
            out.append(int(part))
    return out


def _numa_map(cfg: SystemConfig) -> Dict[int, int]:
    """cpu id -> NUMA node id from /sys/devices/system/node."""
    sysfs = getattr(cfg, "sysfs_root", "/sys")
    mapping: Dict[int, int] = {}
    for node_dir in glob.glob(
        os.path.join(sysfs, "devices", "system", "node", "node*")
    ):
        m = re.match(r".*node(\d+)$", node_dir)
        if m is None:
            continue
        node_id = int(m.group(1))
        cpulist = os.path.join(node_dir, "cpulist")
        try:
            with open(cpulist) as f:
                for cpu in parse_cpulist(f.read()):
                    mapping[cpu] = node_id
        except OSError:
            continue
    return mapping


def read_cpu_infos(cfg: Optional[SystemConfig] = None) -> List[ProcessorInfo]:
    """All logical processors with core/socket/NUMA placement."""
    cfg = cfg or CONFIG
    path = os.path.join(cfg.proc_root, "cpuinfo")
    numa = _numa_map(cfg)
    infos: List[ProcessorInfo] = []
    cpu_id = core_id = socket_id = None
    try:
        with open(path) as f:
            lines = list(f) + ["\n"]  # sentinel flush
    except OSError:
        return []
    for line in lines:
        line = line.strip()
        if not line:
            if cpu_id is not None:
                infos.append(
                    ProcessorInfo(
                        cpu_id=cpu_id,
                        core_id=core_id if core_id is not None else cpu_id,
                        socket_id=socket_id or 0,
                        node_id=numa.get(cpu_id, 0),
                    )
                )
            cpu_id = core_id = socket_id = None
            continue
        if ":" not in line:
            continue
        key, value = (x.strip() for x in line.split(":", 1))
        if key == "processor":
            cpu_id = int(value)
        elif key == "core id":
            core_id = int(value)
        elif key == "physical id":
            socket_id = int(value)
    return infos
