"""Typed cgroup v1/v2 resource registry with path redirection.

Reference: pkg/koordlet/util/system/cgroup_resource.go (the registry),
cgroup.go / cgroup2.go (v1/v2 read-write + conversions). A ``Resource``
knows its v1 subsystem+filename, its v2 filename, its value validator,
and — where the v2 file format differs (cpu.max packs quota+period;
cpu.weight rescales cpu.shares) — how to encode/decode values. Writers
go through ``resourceexecutor`` which adds caching/merging/audit.

Every path resolves under ``SystemConfig.cgroup_root`` so tests point the
whole stack at a fake cgroupfs in a temp dir (reference:
system.Conf.CgroupRootDir redirection + NewFileTestUtil).
"""

from __future__ import annotations

import dataclasses
import enum
import os
from typing import Callable, Dict, List, Optional, Tuple


class CgroupVersion(enum.Enum):
    V1 = 1
    V2 = 2


#: v1 hierarchies this framework manages.
V1_SUBSYSTEMS = ("cpu", "cpuacct", "cpuset", "memory", "blkio")

#: kernel cfs period in microseconds; quota and burst math in the
#: suppress/evict/burst strategies must all use the same value.
CFS_PERIOD_US = 100000


@dataclasses.dataclass
class SystemConfig:
    """Host paths + cgroup driver config (reference:
    pkg/koordlet/util/system/config.go system.Conf)."""

    cgroup_root: str = "/sys/fs/cgroup"
    proc_root: str = "/proc"
    sysfs_root: str = "/sys"
    use_cgroup_v2: bool = False
    #: cgroup path prefix for the kubepods hierarchy
    kubepods_dir: str = "kubepods"
    #: terway net-QoS dataplane config dir (reference:
    #: runtimehooks/hooks/terwayqos rootPath "/host-var-lib/terway/qos")
    terway_qos_root: str = "/host-var-lib/terway/qos"


#: Module-level active config; tests replace it (reference: system.Conf).
CONFIG = SystemConfig()


def set_config(cfg: SystemConfig) -> None:
    global CONFIG
    CONFIG = cfg


# -- validators -------------------------------------------------------------

Validator = Callable[[str], bool]


def _range_validator(lo: int, hi: int) -> Validator:
    def check(value: str) -> bool:
        try:
            v = int(value)
        except ValueError:
            return False
        return lo <= v <= hi

    return check


def _natural_int64(value: str) -> bool:
    try:
        v = int(value)
    except ValueError:
        return value == "max"  # v2 files accept "max"
    return 0 <= v <= 2**63 - 1


def _any_int(value: str) -> bool:
    try:
        int(value)
        return True
    except ValueError:
        return value == "max"


def _cpuset_validator(value: str) -> bool:
    # "0-3,8,10-11" or empty
    if value == "":
        return True
    try:
        for part in value.split(","):
            if "-" in part:
                lo, hi = part.split("-")
                if int(lo) > int(hi):
                    return False
            else:
                int(part)
        return True
    except ValueError:
        return False


#: cpu.shares bounds (reference: cgroup.go CPUSharesMinValue/MaxValue)
CPU_SHARES_MIN, CPU_SHARES_MAX = 2, 262144
#: cpu.weight bounds (reference: cgroup2.go CPUWeightMinValue/MaxValue)
CPU_WEIGHT_MIN, CPU_WEIGHT_MAX = 1, 10000


def convert_cpu_shares_to_weight(shares: int) -> int:
    """Kubelet's v1->v2 mapping: weight = 1 + (shares-2)*9999/262142
    (reference: cgroup2.go:302-315, KEP-2254)."""
    w = 1 + ((shares - 2) * 9999) // 262142
    return max(CPU_WEIGHT_MIN, min(CPU_WEIGHT_MAX, w))


def convert_cpu_weight_to_shares(weight: int) -> int:
    """Inverse mapping: shares = (weight-1)*262142/9999 + 2
    (reference: cgroup2.go:283-300)."""
    s = (weight - 1) * 262142 // 9999 + 2
    return max(CPU_SHARES_MIN, min(CPU_SHARES_MAX, s))


# -- resource ---------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CgroupResource:
    """One cgroup interface file, v1+v2 aware.

    ``resource_type`` is the canonical name (the v1 filename, as in the
    reference's ResourceType). ``v2_file=None`` means unsupported on v2.
    """

    resource_type: str
    v1_subfs: str                 # "cpu" | "cpuset" | "memory" | "blkio"
    v1_file: str
    v2_file: Optional[str] = None
    validator: Optional[Validator] = None
    #: v2 validator when the v2 value space differs (cpu.weight)
    v2_validator: Optional[Validator] = None
    #: encode a v1-convention value into the v2 file's format; receives
    #: (value, current_v2_content) for read-modify-write files (cpu.max)
    v2_encode: Optional[Callable[[str, str], str]] = None
    #: normalize a value for the v1 file (e.g. "max" -> "-1")
    v1_encode: Optional[Callable[[str], str]] = None
    #: decode raw v2 file content back into the v1-convention value space
    #: (cpu.weight -> shares, "max" -> "-1"); merge conditions compare in
    #: v1 conventions
    v2_decode: Optional[Callable[[str], str]] = None
    #: on v1 this file exists independently in EVERY hierarchy and a
    #: write must hit all of them (cgroup.procs: moving a task in only
    #: the cpu hierarchy leaves it in the old cpuset/memory cgroups)
    v1_all_subfs: bool = False

    def supported(self, version: CgroupVersion) -> bool:
        return version is CgroupVersion.V1 or self.v2_file is not None

    def path(self, parent_dir: str, cfg: Optional[SystemConfig] = None) -> str:
        """Absolute path of this file for the cgroup at ``parent_dir``
        (e.g. "kubepods/burstable/pod123"). v1 nests under the
        subsystem mount; v2 is unified."""
        cfg = cfg or CONFIG
        if cfg.use_cgroup_v2:
            if self.v2_file is None:
                raise FileNotFoundError(
                    f"{self.resource_type} unsupported on cgroup v2"
                )
            return os.path.join(cfg.cgroup_root, parent_dir, self.v2_file)
        return os.path.join(
            cfg.cgroup_root, self.v1_subfs, parent_dir, self.v1_file
        )

    def paths(self, parent_dir: str,
              cfg: Optional[SystemConfig] = None) -> List[str]:
        """All file paths a write must reach (one, except v1_all_subfs)."""
        cfg = cfg or CONFIG
        if cfg.use_cgroup_v2 or not self.v1_all_subfs:
            return [self.path(parent_dir, cfg)]
        return [
            os.path.join(cfg.cgroup_root, fs, parent_dir, self.v1_file)
            for fs in V1_SUBSYSTEMS
        ]

    def validate(self, value: str, cfg: Optional[SystemConfig] = None) -> bool:
        cfg = cfg or CONFIG
        v = (
            self.v2_validator
            if cfg.use_cgroup_v2 and self.v2_validator is not None
            else self.validator
        )
        return v is None or v(value)

    def encode(self, value: str, current: str,
               cfg: Optional[SystemConfig] = None) -> str:
        """Final file content for writing ``value`` (v1 conventions) given
        the file's ``current`` content (v2 packed files)."""
        cfg = cfg or CONFIG
        if cfg.use_cgroup_v2:
            if self.v2_encode is not None:
                return self.v2_encode(value, current)
            return value
        if self.v1_encode is not None:
            return self.v1_encode(value)
        return value

    def decode(self, content: str,
               cfg: Optional[SystemConfig] = None) -> str:
        """v1-convention value from raw file content (inverse of encode;
        identity on v1 and for files whose formats match)."""
        cfg = cfg or CONFIG
        if cfg.use_cgroup_v2 and self.v2_decode is not None:
            try:
                return self.v2_decode(content)
            except (ValueError, IndexError):
                return content
        return content

    def read(self, parent_dir: str, cfg: Optional[SystemConfig] = None) -> str:
        with open(self.path(parent_dir, cfg)) as f:
            return f.read().strip()

    def write(self, parent_dir: str, content: str,
              cfg: Optional[SystemConfig] = None) -> None:
        paths = self.paths(parent_dir, cfg)
        if len(paths) == 1:
            with open(paths[0], "w") as f:
                f.write(content)
            return
        # multi-hierarchy (cgroup.procs): a hierarchy that is not mounted
        # or lacks this cgroup dir is skipped — raising midway would leave
        # the task split across old/new cgroups with no way to converge
        first_err: Optional[OSError] = None
        wrote = False
        for p in paths:
            try:
                with open(p, "w") as f:
                    f.write(content)
                wrote = True
            except OSError as e:
                if first_err is None:
                    first_err = e
        if not wrote and first_err is not None:
            raise first_err


# -- v2 packed-file encoders -------------------------------------------------


def _cpu_max_parts(current: str) -> Tuple[str, str]:
    parts = current.split()
    quota = parts[0] if parts else "max"
    period = parts[1] if len(parts) > 1 else "100000"
    return quota, period


def _encode_cfs_quota(value: str, current: str) -> str:
    # v1 quota -1 means unlimited -> v2 "max" (reference: cgroup2.go cpu.max)
    quota, period = _cpu_max_parts(current)
    new_quota = "max" if value == "max" or int(value) < 0 else value
    return f"{new_quota} {period}"


def _encode_cfs_period(value: str, current: str) -> str:
    quota, _ = _cpu_max_parts(current)
    return f"{quota} {value}"


def _encode_cpu_shares(value: str, current: str) -> str:
    return str(convert_cpu_shares_to_weight(int(value)))


# -- the registry (reference: cgroup_resource.go:206-330) -------------------

CPU_SHARES = CgroupResource(
    "cpu.shares", "cpu", "cpu.shares", "cpu.weight",
    validator=_range_validator(CPU_SHARES_MIN, CPU_SHARES_MAX),
    v2_validator=_range_validator(CPU_SHARES_MIN, CPU_SHARES_MAX),
    v2_encode=_encode_cpu_shares,
    v2_decode=lambda c: str(convert_cpu_weight_to_shares(int(c))),
)
CPU_CFS_QUOTA = CgroupResource(
    "cpu.cfs_quota_us", "cpu", "cpu.cfs_quota_us", "cpu.max",
    validator=_any_int, v2_encode=_encode_cfs_quota,
    v1_encode=lambda v: "-1" if v == "max" else v,
    v2_decode=lambda c: c.split()[0].replace("max", "-1"),
)
CPU_CFS_PERIOD = CgroupResource(
    "cpu.cfs_period_us", "cpu", "cpu.cfs_period_us", "cpu.max",
    validator=_range_validator(1000, 1_000_000), v2_encode=_encode_cfs_period,
)
CPU_BURST = CgroupResource(
    "cpu.cfs_burst_us", "cpu", "cpu.cfs_burst_us", "cpu.max.burst",
    validator=_natural_int64,
)
#: group identity / bvt (Anolis kernel; reference: cgroup_resource.go:210)
CPU_BVT_WARP_NS = CgroupResource(
    "cpu.bvt_warp_ns", "cpu", "cpu.bvt_warp_ns", "cpu.bvt_warp_ns",
    validator=_range_validator(-1, 2),
)
CPU_IDLE = CgroupResource(
    "cpu.idle", "cpu", "cpu.idle", "cpu.idle",
    validator=_range_validator(0, 1),
)
CPU_SET = CgroupResource(
    "cpuset.cpus", "cpuset", "cpuset.cpus", "cpuset.cpus",
    validator=_cpuset_validator,
)
CPU_PROCS = CgroupResource(
    "cgroup.procs", "cpu", "cgroup.procs", "cgroup.procs",
    validator=_natural_int64, v1_all_subfs=True,
)
MEMORY_LIMIT = CgroupResource(
    "memory.limit_in_bytes", "memory", "memory.limit_in_bytes", "memory.max",
    validator=_any_int,
    v2_encode=lambda v, cur: "max" if v == "max" or int(v) < 0 else v,
    v1_encode=lambda v: "-1" if v == "max" else v,
    v2_decode=lambda c: "-1" if c == "max" else c,
)
MEMORY_MIN = CgroupResource(
    "memory.min", "memory", "memory.min", "memory.min",
    validator=_natural_int64,
)
MEMORY_LOW = CgroupResource(
    "memory.low", "memory", "memory.low", "memory.low",
    validator=_natural_int64,
)
MEMORY_HIGH = CgroupResource(
    "memory.high", "memory", "memory.high", "memory.high",
    validator=lambda v: v == "max" or _natural_int64(v),
)
MEMORY_WMARK_RATIO = CgroupResource(
    "memory.wmark_ratio", "memory", "memory.wmark_ratio",
    "memory.wmark_ratio", validator=_range_validator(0, 100),
)
MEMORY_WMARK_SCALE_FACTOR = CgroupResource(
    "memory.wmark_scale_factor", "memory", "memory.wmark_scale_factor",
    "memory.wmark_scale_factor", validator=_range_validator(1, 1000),
)
MEMORY_PRIORITY = CgroupResource(
    "memory.priority", "memory", "memory.priority", "memory.priority",
    validator=_range_validator(0, 12),
)
MEMORY_OOM_GROUP = CgroupResource(
    "memory.oom.group", "memory", "memory.oom.group", "memory.oom.group",
    validator=_range_validator(0, 1),
)
MEMORY_USAGE = CgroupResource(
    "memory.usage_in_bytes", "memory", "memory.usage_in_bytes",
    "memory.current",
)
#: cumulative cpu time: v1 cpuacct.usage is nanoseconds; v2 cpu.stat has
#: a "usage_usec N" line (callers parse per version)
CPU_ACCT_USAGE = CgroupResource(
    "cpuacct.usage", "cpuacct", "cpuacct.usage", "cpu.stat",
)
#: cfs throttling stats (nr_periods/nr_throttled/throttled_time) — the
#: podthrottled collector's source; same key/value format on v1 and v2
CPU_STAT = CgroupResource(
    "cpu.stat", "cpu", "cpu.stat", "cpu.stat",
)
BLKIO_IO_WEIGHT = CgroupResource(
    "blkio.cost.weight", "blkio", "blkio.cost.weight", "io.cost.weight",
    validator=_range_validator(1, 100),
)


def _device_value(value: str) -> bool:
    """"MAJ:MIN N" (or "MAJ:MIN max") device throttle entries."""
    parts = value.split()
    if len(parts) != 2 or ":" not in parts[0]:
        return False
    return parts[1] == "max" or parts[1].isdigit()


def _io_max_encode(key: str):
    """Pack a v1-style "MAJ:MIN N" throttle into the v2 ``io.max`` file,
    merging with the other keys already present for the device."""

    def enc(value: str, current: str) -> str:
        dev, val = value.split()
        entries: Dict[str, Dict[str, str]] = {}
        for line in current.splitlines():
            parts = line.split()
            if not parts:
                continue
            entries[parts[0]] = dict(
                kv.split("=", 1) for kv in parts[1:] if "=" in kv
            )
        entry = entries.setdefault(dev, {})
        entry[key] = "max" if val in ("max", "-1", "0") else val
        return "\n".join(
            f"{d} " + " ".join(f"{k}={v}" for k, v in sorted(e.items()))
            for d, e in sorted(entries.items())
        )

    return enc


#: blkio throttling (reference: blkio_reconcile.go throttle files;
#: cgroup v2 packs all four into io.max)
BLKIO_READ_BPS = CgroupResource(
    "blkio.throttle.read_bps_device", "blkio",
    "blkio.throttle.read_bps_device", "io.max",
    validator=_device_value, v2_encode=_io_max_encode("rbps"),
)
BLKIO_WRITE_BPS = CgroupResource(
    "blkio.throttle.write_bps_device", "blkio",
    "blkio.throttle.write_bps_device", "io.max",
    validator=_device_value, v2_encode=_io_max_encode("wbps"),
)
BLKIO_READ_IOPS = CgroupResource(
    "blkio.throttle.read_iops_device", "blkio",
    "blkio.throttle.read_iops_device", "io.max",
    validator=_device_value, v2_encode=_io_max_encode("riops"),
)
BLKIO_WRITE_IOPS = CgroupResource(
    "blkio.throttle.write_iops_device", "blkio",
    "blkio.throttle.write_iops_device", "io.max",
    validator=_device_value, v2_encode=_io_max_encode("wiops"),
)

_KNOWN: List[CgroupResource] = [
    CPU_SHARES, CPU_CFS_QUOTA, CPU_CFS_PERIOD, CPU_BURST, CPU_BVT_WARP_NS,
    CPU_IDLE, CPU_SET, CPU_PROCS, MEMORY_LIMIT, MEMORY_MIN, MEMORY_LOW,
    MEMORY_HIGH, MEMORY_WMARK_RATIO, MEMORY_WMARK_SCALE_FACTOR,
    MEMORY_PRIORITY, MEMORY_OOM_GROUP, MEMORY_USAGE, BLKIO_IO_WEIGHT,
    BLKIO_READ_BPS, BLKIO_WRITE_BPS, BLKIO_READ_IOPS, BLKIO_WRITE_IOPS,
    CPU_ACCT_USAGE, CPU_STAT,
]
_BY_TYPE: Dict[str, CgroupResource] = {r.resource_type: r for r in _KNOWN}


def get_resource(resource_type: str) -> CgroupResource:
    """Lookup by canonical name (reference: GetCgroupResource)."""
    r = _BY_TYPE.get(resource_type)
    if r is None:
        raise KeyError(f"unknown cgroup resource {resource_type!r}")
    return r


def known_resources() -> List[CgroupResource]:
    return list(_KNOWN)
