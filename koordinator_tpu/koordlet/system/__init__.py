"""OS abstraction: typed cgroup v1/v2 resource registry.

Reference: pkg/koordlet/util/system/ (cgroup_resource.go, cgroup.go,
cgroup2.go). All paths resolve under a configurable root so tests run
against a fake cgroupfs tree in a temp dir (the reference's testutil
path-redirection pattern).
"""

from koordinator_tpu.koordlet.system.cgroup import (
    CgroupResource,
    CgroupVersion,
    SystemConfig,
    convert_cpu_shares_to_weight,
    convert_cpu_weight_to_shares,
    get_resource,
    known_resources,
)

__all__ = [
    "CgroupResource",
    "CgroupVersion",
    "SystemConfig",
    "convert_cpu_shares_to_weight",
    "convert_cpu_weight_to_shares",
    "get_resource",
    "known_resources",
]
