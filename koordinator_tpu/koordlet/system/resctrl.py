"""resctrl (Intel RDT / AMD QoS) filesystem abstraction.

Reference: pkg/koordlet/util/system/resctrl.go + resctrl_linux.go —
schemata model (L3 cat + MBA per cache id), the contiguous-cache-way mask
math (CalculateCatL3MaskValue :576-605), vendor-specific MBA rendering
(qosmanager/plugins/resctrl/resctrl_reconcile.go:192-209), and control-
group directory/tasks management. Paths go through ``SystemConfig`` so
tests point at a fake resctrl tree (the reference's Conf redirection).
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Dict, List, Optional, Sequence

from koordinator_tpu.koordlet.system.cgroup import CONFIG, SystemConfig

#: resctrl control groups (reference: resctrl.go:36-41)
LSR_GROUP = "LSR"
LS_GROUP = "LS"
BE_GROUP = "BE"
RESCTRL_GROUPS = (LSR_GROUP, LS_GROUP, BE_GROUP)

#: AMD MBA is absolute GBps per CCD, not percent (resctrl.go)
AMD_CCD_MAX_MB_GBPS = 25 * 1024
AMD_CCD_UNLIMITED_MB = "2048000"


def detect_vendor(proc_root: str = "/proc") -> str:
    """"amd" | "intel" from /proc/cpuinfo vendor_id (GenuineIntel /
    AuthenticAMD); unknown vendors use Intel percent semantics."""
    try:
        with open(os.path.join(proc_root, "cpuinfo")) as f:
            for line in f:
                if line.startswith("vendor_id"):
                    return "amd" if "AuthenticAMD" in line else "intel"
    except OSError:
        pass
    return "intel"


def resctrl_root(cfg: Optional[SystemConfig] = None) -> str:
    cfg = cfg or CONFIG
    # tests place a fake resctrl tree next to the fake cgroup root
    root = getattr(cfg, "resctrl_root", None)
    if root:
        return root
    return os.path.join(os.path.dirname(cfg.cgroup_root.rstrip("/")), "resctrl")


def calculate_cat_l3_mask(cbm: int, start_percent: int, end_percent: int) -> str:
    """Contiguous cache-way mask covering [start%, end%) of the ways
    (reference: CalculateCatL3MaskValue, resctrl.go:576-605)."""
    if bin(cbm + 1).count("1") != 1:
        raise ValueError(f"illegal cbm {cbm:#x}")
    if start_percent < 0 or end_percent > 100 or end_percent <= start_percent:
        raise ValueError(
            f"illegal l3 cat percent: start {start_percent}, end {end_percent}"
        )
    ways = cbm.bit_length()
    start_way = math.ceil(ways * start_percent / 100)
    end_way = math.ceil(ways * end_percent / 100)
    mask = (1 << end_way) - (1 << start_way)
    return format(mask, "x")


def calculate_mba(mba_percent: int, vendor: str = "intel") -> str:
    """Render the MBA schemata value (resctrl_reconcile.go:172-209):
    Intel takes percent in multiples of 10 (rounded up); AMD takes
    absolute MBps per CCD, unlimited at 100%."""
    if vendor == "amd":
        if mba_percent == 100:
            return AMD_CCD_UNLIMITED_MB
        return str(int(AMD_CCD_MAX_MB_GBPS * mba_percent / 100))
    if mba_percent % 10 != 0:
        return str(mba_percent // 10 * 10 + 10)
    return str(mba_percent)


@dataclasses.dataclass
class ResctrlSchemata:
    """One group's schemata: per-cache-id L3 masks + MB values
    (reference: ResctrlSchemataRaw)."""

    l3: Dict[int, str] = dataclasses.field(default_factory=dict)
    mb: Dict[int, str] = dataclasses.field(default_factory=dict)

    def render(self) -> str:
        lines = []
        if self.l3:
            lines.append(
                "L3:" + ";".join(f"{i}={v}" for i, v in sorted(self.l3.items()))
            )
        if self.mb:
            lines.append(
                "MB:" + ";".join(f"{i}={v}" for i, v in sorted(self.mb.items()))
            )
        return "\n".join(lines) + ("\n" if lines else "")

    @classmethod
    def parse(cls, content: str) -> "ResctrlSchemata":
        out = cls()
        for line in content.splitlines():
            line = line.strip()
            if not line or ":" not in line:
                continue
            prefix, rest = line.split(":", 1)
            target = out.l3 if prefix.strip() == "L3" else (
                out.mb if prefix.strip() == "MB" else None
            )
            if target is None:
                continue
            for part in rest.split(";"):
                if "=" in part:
                    i, v = part.split("=", 1)
                    target[int(i)] = v.strip()
        return out


class ResctrlFS:
    """Reads/writes the (possibly fake) resctrl filesystem."""

    def __init__(self, cfg: Optional[SystemConfig] = None):
        self.cfg = cfg

    @property
    def root(self) -> str:
        return resctrl_root(self.cfg)

    def group_dir(self, group: str) -> str:
        return self.root if group == "" else os.path.join(self.root, group)

    def is_supported(self) -> bool:
        return os.path.isdir(self.root) and os.path.exists(
            os.path.join(self.root, "schemata")
        )

    def init_groups(self, groups: Sequence[str] = RESCTRL_GROUPS) -> List[str]:
        """Create missing control-group dirs (initCatResctrl :139-156);
        returns those created."""
        created = []
        for group in groups:
            d = self.group_dir(group)
            if not os.path.isdir(d):
                os.makedirs(d, exist_ok=True)
                created.append(group)
        return created

    def read_cbm(self) -> int:
        """Root L3 cbm mask (info/L3/cbm_mask)."""
        path = os.path.join(self.root, "info", "L3", "cbm_mask")
        with open(path) as f:
            return int(f.read().strip(), 16)

    def cache_ids(self) -> List[int]:
        """Cache ids present in the root schemata's L3 line."""
        schemata = self.read_schemata("")
        if schemata.l3:
            return sorted(schemata.l3)
        if schemata.mb:
            return sorted(schemata.mb)
        return [0]

    def read_schemata(self, group: str) -> ResctrlSchemata:
        path = os.path.join(self.group_dir(group), "schemata")
        if not os.path.exists(path):
            return ResctrlSchemata()
        with open(path) as f:
            return ResctrlSchemata.parse(f.read())

    def write_schemata_line(self, group: str, line: str) -> bool:
        """Write one schemata line (the kernel merges per-prefix lines);
        returns True when the value changed."""
        current = self.read_schemata(group)
        new = ResctrlSchemata.parse(line)
        changed = False
        for i, v in new.l3.items():
            if current.l3.get(i) != v:
                changed = True
        for i, v in new.mb.items():
            if current.mb.get(i) != v:
                changed = True
        if not changed:
            return False
        current.l3.update(new.l3)
        current.mb.update(new.mb)
        path = os.path.join(self.group_dir(group), "schemata")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(current.render())
        return True

    def read_tasks(self, group: str) -> List[int]:
        path = os.path.join(self.group_dir(group), "tasks")
        if not os.path.exists(path):
            return []
        with open(path) as f:
            return [int(x) for x in f.read().split() if x.strip()]

    def add_tasks(self, group: str, task_ids: Sequence[int]) -> None:
        """Append task ids (each write moves the task into the group)."""
        if not task_ids:
            return
        path = os.path.join(self.group_dir(group), "tasks")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        existing = set(self.read_tasks(group))
        with open(path, "a") as f:
            for tid in task_ids:
                if tid not in existing:
                    f.write(f"{tid}\n")
