"""koordlet: the node agent.

Reference layout: pkg/koordlet/ (SURVEY.md §2.4) — seven subsystems wired
together: statesinformer, metriccache, metricsadvisor, qosmanager,
runtimehooks, resourceexecutor, prediction (+ pleg, audit). This package
rebuilds them host-side (cgroup actuation is inherently a node/OS
concern); the math-heavy parts (metric aggregation, peak prediction,
suppress-target computation) lower onto the array substrate.
"""
