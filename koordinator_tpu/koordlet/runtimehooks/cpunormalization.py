"""CPU-normalization hook: scale cpu-share pods' cfs quota by the ratio.

Reference: pkg/koordlet/runtimehooks/hooks/cpunormalization/
cpu_normalization.go — the manager amplifies node CPU allocatable by the
normalization ratio (manager/noderesource.py CPUNormalizationPlugin), so
a pod's kubelet-derived cfs quota over-grants real cycles by the same
factor; this hook divides the quota back (``ceil(quota / ratio)`` when
ratio > 1, :122-131) for cpu-share pods:

- applies to QoS LS and None pods (podQOSConditions :42), but NOT to a
  None pod pinned via the cpuset annotation (isPodCPUShare :157-171 —
  such a pod is effectively LSR and its quota is unset by the cpuset
  hook);
- the ratio arrives with the node metadata (annotation
  ``koordinator.sh/cpu-normalization-ratio``, parseRule reading
  RegisterTypeNodeMetadata).

The original quota is derived from the pod/container CPU limit exactly
as the kubelet derives it (milli_cpu_to_quota); unlimited (<= 0) pods
are left alone (:118-121).
"""

from __future__ import annotations

import math
from typing import Optional

from koordinator_tpu.apis.extension import (
    ANNOTATION_CPU_NORMALIZATION_RATIO,
    QoSClass,
)
from koordinator_tpu.koordlet.runtimehooks.cpuset import cpuset_from_annotation
from koordinator_tpu.koordlet.runtimehooks.hooks import HookRegistry, Stage
from koordinator_tpu.koordlet.runtimehooks.protocol import (
    ContainerContext,
    PodContext,
    milli_cpu_to_quota,
)

NAME = "CPUNormalization"


def parse_ratio_from_annotations(annotations) -> Optional[float]:
    """extension.GetCPUNormalizationRatio: absent/malformed/<= 1 -> None
    (no scaling)."""
    raw = (annotations or {}).get(ANNOTATION_CPU_NORMALIZATION_RATIO)
    if raw is None:
        return None
    try:
        ratio = float(raw)
    except (TypeError, ValueError):
        return None
    if not ratio > 1.0:
        return None
    return ratio


def is_pod_cpu_share(qos: QoSClass, annotations) -> bool:
    """isPodCPUShare (cpu_normalization.go:157-171): LS or None; a pod
    with a scheduler-pinned cpuset is excluded. (The reference excludes
    pinned pods only for QoS None and still *calls* the hook for pinned
    LS pods — but their cfs quota was unset to -1 by the cpuset hook, so
    its ``originalCFSQuota <= 0`` guard skips them anyway, :118-121.
    This framework derives the quota from the limit rather than the live
    cgroup value, so the exclusion must be explicit to preserve the same
    net behavior.)"""
    if qos not in (QoSClass.LS, QoSClass.NONE):
        return False
    return cpuset_from_annotation(annotations or {}) is None


class CPUNormalizationPlugin:
    name = NAME

    def __init__(self):
        self.ratio: Optional[float] = None  # None/<=1 = disabled
        #: one-shot restore: True between a ratio-removal rule change and
        #: the reconcile pass that writes spec quotas back
        self.restoring: bool = False

    def update_rule(self, node) -> bool:
        """parseRule from the node metadata; returns True on change."""
        new = parse_ratio_from_annotations(
            getattr(node, "annotations", None) if node is not None else None
        )
        changed = new != self.ratio
        if changed and new is None:
            self.restoring = True
        self.ratio = new
        return changed

    def finish_restore(self) -> None:
        """Called after the restore reconcile pass has run."""
        self.restoring = False

    def _scaled_quota(self, limit_mcpu: int) -> Optional[int]:
        """ceil(spec quota / ratio) when scaling; during the ONE restore
        pass after a ratio removal, the UNSCALED spec quota (no kubelet
        re-asserts spec quotas in this framework — without the one-shot
        write every LS pod would stay shrunk forever). Steady state
        without a ratio is inert so the hook never fights the
        cfs-quota-burst strategy's scale-ups (qosmanager/cpuburst.py)."""
        if limit_mcpu <= 0:
            return None
        quota = milli_cpu_to_quota(limit_mcpu)
        if quota <= 0:
            return None
        if self.ratio is None:
            return quota if self.restoring else None
        return math.ceil(quota / self.ratio)

    def adjust_pod_cfs_quota(self, proto) -> None:
        """AdjustPodCFSQuota (:79)."""
        if not isinstance(proto, PodContext):
            return
        req = proto.request
        if not is_pod_cpu_share(req.qos, req.annotations):
            return
        quota = self._scaled_quota(req.pod_meta.cpu_limit_mcpu)
        if quota is not None:
            proto.response.cfs_quota_us = quota

    def adjust_container_cfs_quota(self, proto) -> None:
        """AdjustContainerCFSQuota (:95). Container limits come from
        PodMeta.container_limits_mcpu when the informer reports them;
        a missing entry leaves the container alone."""
        if not isinstance(proto, ContainerContext):
            return
        req = proto.request
        if not is_pod_cpu_share(req.qos, req.annotations):
            return
        limit = req.pod_meta.container_limits_mcpu.get(req.container_name, 0)
        quota = self._scaled_quota(limit)
        if quota is not None:
            proto.response.cfs_quota_us = quota

    def register(self, registry: HookRegistry) -> None:
        registry.register(
            Stage.PRE_RUN_POD_SANDBOX, self.name,
            "scale pod cfs quota by cpu-normalization ratio",
            self.adjust_pod_cfs_quota,
        )
        registry.register(
            Stage.PRE_CREATE_CONTAINER, self.name,
            "scale container cfs quota by cpu-normalization ratio",
            self.adjust_container_cfs_quota,
        )
        registry.register(
            Stage.PRE_UPDATE_CONTAINER_RESOURCES, self.name,
            "re-scale container cfs quota on update",
            self.adjust_container_cfs_quota,
        )
