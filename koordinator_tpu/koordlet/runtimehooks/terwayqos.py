"""Terway network-QoS hook: render node/pod bandwidth config files.

Reference: pkg/koordlet/runtimehooks/hooks/terwayqos/terwayqos.go — the
terway CNI dataplane reads two files under ``/host-var-lib/terway/qos``:

- ``global_bps_config``: node-level three-tier (L0/L1/L2) bandwidth
  splits derived from the NodeSLO (SystemStrategy.TotalNetworkBandwidth
  + per-class NetworkQOS, :270-311 parseNetQoS, LS -> L1, BE -> L2);
- ``pod.json``: per-pod priority + ingress/egress limits from the pod
  net-QoS annotation (:373-395 getPodQoS) and QoS class (:397-409
  getPodPrio — koord QoS label first, then kube QoS tier).

The hook is enabled iff the NodeSLO's policy selector names terway
(``netQOSPolicy == "terway-qos"``, :95-99); disabling removes both files
(:200-203, :233-236). Writes are cached (skip-if-unchanged) and audited,
the same guarantees the reference gets by routing through its executor's
common updater.

Bandwidth quantities follow the reference: ints are percentages of the
node total, strings absolute bits/s; stored values are Bytes/s
(:337 BitsToBytes).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

from koordinator_tpu.apis.extension import LABEL_QOS_CLASS, QoSClass
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.koordlet.runtimehooks.protocol import (
    KubeQOS,
    kube_qos_by_cgroup_parent,
)
from koordinator_tpu.manager.sloconfig import NetworkQOS, NodeSLOSpec

NAME = "TerwayQoS"
POD_CONFIG = "pod.json"
NODE_CONFIG = "global_bps_config"
NET_QOS_POLICY_KEY = "netQOSPolicy"
NET_QOS_POLICY_TERWAY = "terway-qos"

#: pod net-QoS annotation (reference: extension.AnnotationNetworkQOS)
ANNOTATION_NET_QOS = "koordinator.sh/networkQOS"

#: LabelPodQoS -> terway priority (terwayqos.go prioMapping)
_PRIO_BY_QOS = {
    QoSClass.LSE.value: 0,
    QoSClass.LSR.value: 0,
    QoSClass.LS.value: 1,
    QoSClass.BE.value: 2,
}


def bits_to_bytes(bits: int) -> int:
    return int(bits) // 8


class QuantityError(ValueError):
    """An IntOrString bandwidth value the reference's parseQuantity
    rejects (malformed, or absolute value over the node total).  The
    rule update carrying it is discarded and the prior config kept."""


def _parse_quantity(value, total_bits: int) -> int:
    """IntOrString: int = percent of total, str = absolute bits/s;
    result Bytes/s (terwayqos.go:352-371). Malformed or over-total
    raises QuantityError — returning 0 would mean "no limit", silently
    removing the cap on a typo'd value."""
    if value is None:
        return 0
    if isinstance(value, str):
        try:
            bps = bits_to_bytes(int(float(value)))
        except ValueError as e:
            raise QuantityError(f"bad bandwidth quantity {value!r}") from e
        if bps < 0 or bps > bits_to_bytes(total_bits):
            raise QuantityError(
                f"bandwidth {value!r} outside [0, node total "
                f"{total_bits} bits/s]"
            )
        return bps
    if int(value) < 0:
        raise QuantityError(f"negative bandwidth percent {value!r}")
    return int(value) * bits_to_bytes(total_bits) // 100


def _class_tier(qos_cfg: Optional[NetworkQOS], total_bits: int) -> Dict[str, int]:
    if qos_cfg is None or not qos_cfg.enable:
        return {"rx_min": 0, "rx_max": 0, "tx_min": 0, "tx_max": 0}
    return {
        "rx_min": _parse_quantity(qos_cfg.ingress_request, total_bits),
        "rx_max": _parse_quantity(qos_cfg.ingress_limit, total_bits),
        "tx_min": _parse_quantity(qos_cfg.egress_request, total_bits),
        "tx_max": _parse_quantity(qos_cfg.egress_limit, total_bits),
    }


def parse_node_config(slo: NodeSLOSpec) -> Dict[str, int]:
    """Node tier config in Bytes/s (parseNetQoS :270-311): hardware max
    from SystemStrategy, L1 from the LS class, L2 from the BE class."""
    total = int(slo.system_strategy.total_network_bandwidth_bps)
    ls = _class_tier(slo.resource_qos_strategy.ls.network, total)
    be = _class_tier(slo.resource_qos_strategy.be.network, total)
    return {
        "hw_tx_bps_max": bits_to_bytes(total),
        "hw_rx_bps_max": bits_to_bytes(total),
        "l1_rx_bps_min": ls["rx_min"], "l1_rx_bps_max": ls["rx_max"],
        "l1_tx_bps_min": ls["tx_min"], "l1_tx_bps_max": ls["tx_max"],
        "l2_rx_bps_min": be["rx_min"], "l2_rx_bps_max": be["rx_max"],
        "l2_tx_bps_min": be["tx_min"], "l2_tx_bps_max": be["tx_max"],
    }


def pod_prio(pod: PodMeta) -> int:
    """getPodPrio (:397-409): koord QoS label first, kube tier fallback."""
    label = pod.labels.get(LABEL_QOS_CLASS)
    if label in _PRIO_BY_QOS:
        return _PRIO_BY_QOS[label]
    kube = kube_qos_by_cgroup_parent(pod.cgroup_dir)
    return 2 if kube is KubeQOS.BESTEFFORT else 1


def pod_bandwidth(pod: PodMeta) -> Dict[str, int]:
    """getPodQoS (:373-395): the pod annotation's ingress/egress limits,
    bits/s -> Bytes/s; absent/malformed -> 0 (unlimited)."""
    raw = pod.annotations.get(ANNOTATION_NET_QOS)
    if not raw:
        return {"ingress": 0, "egress": 0}
    try:
        cfg = json.loads(raw)
        return {
            "ingress": bits_to_bytes(int(float(cfg.get("ingressLimit", 0) or 0))),
            "egress": bits_to_bytes(int(float(cfg.get("egressLimit", 0) or 0))),
        }
    except (ValueError, AttributeError):
        return {"ingress": 0, "egress": 0}


class TerwayQosPlugin:
    """Config-file generator state machine (the Plugin struct)."""

    name = NAME

    def __init__(self, root_path: str, auditor: Optional[Auditor] = None):
        self.root_path = root_path
        self.auditor = auditor or Auditor()
        self.enabled: Optional[bool] = None  # None = no NodeSLO seen yet
        self.node_config: Dict[str, int] = {}
        self.pods: Dict[str, dict] = {}
        self._written: Dict[str, str] = {}  # path -> last content

    @property
    def pod_file(self) -> str:
        return os.path.join(self.root_path, POD_CONFIG)

    @property
    def node_file(self) -> str:
        return os.path.join(self.root_path, NODE_CONFIG)

    # -- rule parsing --------------------------------------------------------

    def update_node_slo(self, slo: NodeSLOSpec) -> None:
        """parseRuleForNodeSLO (:86-120) + syncNodeConfig."""
        policy = slo.resource_qos_strategy.policies.get(NET_QOS_POLICY_KEY)
        enabled = policy == NET_QOS_POLICY_TERWAY
        if enabled:
            try:
                node_config = parse_node_config(slo)
            except QuantityError as e:
                # reference parseQuantity errors reject the rule update
                # and keep the previous config (no sync)
                self.auditor.log("terwayqos", "nodeslo", "reject", str(e))
                return
            self.node_config = node_config
        self.enabled = enabled
        self.sync()

    def update_pods(self, pods) -> None:
        """The all-pods callback (:154-195) + syncPodConfig."""
        out = {}
        for pod in pods:
            bw = pod_bandwidth(pod)
            out[pod.uid] = {
                "pod_name": pod.name,
                "pod_uid": pod.uid,
                "prio": pod_prio(pod),
                "cgroup_dir": os.path.join("net_cls", pod.cgroup_dir),
                "ingress_bandwidth": bw["ingress"],
                "egress_bandwidth": bw["egress"],
            }
        self.pods = out
        self.sync()

    # -- file sync -----------------------------------------------------------

    def _write(self, path: str, content: str) -> bool:
        if self._written.get(path) == content and os.path.exists(path):
            return False
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(content)
        self._written[path] = content
        self.auditor.log("terwayqos", path, "update", f"-> {len(content)}B")
        return True

    def _remove(self, path: str) -> None:
        try:
            os.remove(path)
        except OSError:
            pass
        self._written.pop(path, None)

    def sync(self) -> int:
        """syncAll (:143-156): returns files written."""
        if self.enabled is None:
            return 0
        if not self.enabled:
            self._remove(self.node_file)
            self._remove(self.pod_file)
            return 0
        written = 0
        node_text = "".join(
            f"{k}={v}\n" for k, v in self.node_config.items()
        )
        if self._write(self.node_file, node_text):
            written += 1
        if self._write(self.pod_file, json.dumps(self.pods, sort_keys=True)):
            written += 1
        return written
