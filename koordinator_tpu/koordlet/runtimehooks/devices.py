"""Device hook: inject DeviceShare allocations into container env.

Reference: pkg/koordlet/runtimehooks/hooks/gpu/gpu.go — at
PreCreateContainer, parse the scheduler's device-allocation annotation
(``koordinator.sh/device-allocated``, written by the DeviceShare
plugin's PreBind — scheduler/plugins/deviceshare.py) and inject the
allocated device minors into the container's environment so the runtime
(device plugin / accelerator stack) actually confines the container to
its allocation. This is the actuation edge that makes the device
allocator's output land in a container.

TPU-first: the primary env is ``TPU_VISIBLE_CHIPS`` (the libtpu chip
confinement variable); ``NVIDIA_VISIBLE_DEVICES`` (gpu.go:32 GpuAllocEnv)
is kept for NVML-backed nodes, and RDMA VF bus ids ride
``KOORDINATOR_RDMA_VFS`` (the reference injects VFs through device
mounts; an env carrying bus ids is the runtime-agnostic equivalent).

Env injection is meaningful at container *creation* (NRI adjustment /
CRI-proxy request merge). In standalone reconcile mode the env response
is inert — a running container's environment cannot be changed — which
matches the reference (its gpu hook also only registers
PreCreateContainer).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from koordinator_tpu.apis.extension import ANNOTATION_DEVICE_ALLOCATED
from koordinator_tpu.device.cache import DeviceType
from koordinator_tpu.koordlet.runtimehooks.hooks import HookRegistry, Stage
from koordinator_tpu.koordlet.runtimehooks.protocol import ContainerContext

NAME = "DeviceEnvInject"

#: libtpu chip confinement (TPU-native primary)
TPU_ALLOC_ENV = "TPU_VISIBLE_CHIPS"
#: gpu.go:32 GpuAllocEnv (NVML variant, kept optional per SURVEY §2.9)
GPU_ALLOC_ENV = "NVIDIA_VISIBLE_DEVICES"
RDMA_VFS_ENV = "KOORDINATOR_RDMA_VFS"


def parse_device_allocations(
    annotations: Dict[str, str]
) -> Optional[Dict[str, List[dict]]]:
    """The PreBind allocation payload: {type: [{minor, resources, vfs?}]}
    (reference: ext.GetDeviceAllocations)."""
    raw = annotations.get(ANNOTATION_DEVICE_ALLOCATED)
    if not raw:
        return None
    try:
        alloc = json.loads(raw)
    except ValueError:
        return None
    return alloc if isinstance(alloc, dict) else None


class DeviceEnvPlugin:
    name = NAME

    def inject_container_device_env(self, proto) -> None:
        """gpu.go:51 InjectContainerGPUEnv, generalized per device type."""
        if not isinstance(proto, ContainerContext):
            return
        alloc = parse_device_allocations(proto.request.annotations)
        if not alloc:
            return
        # malformed annotation entries skip (error-and-continue, like the
        # JSON parse above) — raising here would fail container creation
        # on the proxy/NRI path
        minor_list = []
        gpu_entries = alloc.get(DeviceType.GPU.value) or []
        for d in gpu_entries if isinstance(gpu_entries, list) else []:
            try:
                minor_list.append(str(int(d.get("minor", 0))))
            except (TypeError, ValueError, AttributeError):
                continue
        minors = ",".join(minor_list)
        if minors:
            envs = proto.response.add_envs or {}
            envs[TPU_ALLOC_ENV] = minors
            envs[GPU_ALLOC_ENV] = minors
            proto.response.add_envs = envs
        vfs = []
        rdma_entries = alloc.get(DeviceType.RDMA.value) or []
        for d in rdma_entries if isinstance(rdma_entries, list) else []:
            try:
                entry_vfs = d.get("vfs") or []
            except AttributeError:
                continue
            if isinstance(entry_vfs, list):
                vfs.extend(str(v) for v in entry_vfs
                           if isinstance(v, (str, int)))
        if vfs:
            envs = proto.response.add_envs or {}
            envs[RDMA_VFS_ENV] = ",".join(vfs)
            proto.response.add_envs = envs

    def register(self, registry: HookRegistry) -> None:
        registry.register(
            Stage.PRE_CREATE_CONTAINER, self.name,
            "inject allocated device env into container",
            self.inject_container_device_env,
        )
