"""Hook protocol: typed request/response contexts for pod lifecycle.

Reference: pkg/koordlet/runtimehooks/protocol/{protocol.go,
pod_context.go, container_context.go, kubeqos_context.go} — each hook
invocation carries a request (pod/container identity + labels,
annotations, cgroup parent, extended resources) and fills a response of
cgroup-level resource values (protocol.go:76-82: CPUShares, CFSQuota,
CPUSet, MemoryLimit, CPUBvt). The context then turns the response into
executor updates (injectForOrder / ReconcilerDone).

Values are canonical cgroup units: cpu shares (v1 scale), cfs quota
microseconds (-1 unlimited), memory bytes (-1 unlimited), bvt in
[-1, 2], cpuset as a cpu-list string ("" allowed: clears the set).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.metricsadvisor.framework import (
    ContainerBatchResources,
    PodMeta,
)
from koordinator_tpu.koordlet.resourceexecutor import (
    CgroupUpdater,
    ResourceUpdateExecutor,
    merge_if_cfs_quota_larger,
    merge_if_value_larger,
)

#: v1 cpu shares bounds (util/system cgroup.go:236-248)
CPU_SHARES_MIN, CPU_SHARES_MAX = 2, 262144
CFS_BASE_PERIOD_US = 100_000
CFS_QUOTA_MIN_US = 1000


def milli_cpu_to_shares(milli: int) -> int:
    """Reference: sysutil.MilliCPUToShares (cgroup.go:236-248)."""
    if milli <= 0:
        return CPU_SHARES_MIN
    return max(CPU_SHARES_MIN, min(CPU_SHARES_MAX, milli * 1024 // 1000))


def milli_cpu_to_quota(milli: int) -> int:
    """Reference: sysutil.MilliCPUToQuota (cgroup.go:250-258): <= 0 is
    unlimited (-1); floor at 1000us."""
    quota = milli * CFS_BASE_PERIOD_US // 1000
    if quota <= 0:
        return -1
    return max(quota, CFS_QUOTA_MIN_US)


class KubeQOS(enum.Enum):
    """The k8s-native QoS tier (cgroup tree position)."""

    GUARANTEED = "guaranteed"
    BURSTABLE = "burstable"
    BESTEFFORT = "besteffort"


#: Reference: koordletutil.GetPodQoSRelativePath — guaranteed pods live
#: directly under the kubepods root.
KUBE_QOS_DIR = {
    KubeQOS.GUARANTEED: "kubepods",
    KubeQOS.BURSTABLE: "kubepods/burstable",
    KubeQOS.BESTEFFORT: "kubepods/besteffort",
}


def kube_qos_by_cgroup_parent(cgroup_dir: str) -> KubeQOS:
    """Reference: koordletutil.GetKubeQoSByCgroupParent."""
    if "besteffort" in cgroup_dir:
        return KubeQOS.BESTEFFORT
    if "burstable" in cgroup_dir:
        return KubeQOS.BURSTABLE
    return KubeQOS.GUARANTEED


@dataclasses.dataclass
class Resources:
    """The hook response payload (protocol.go:76-87). ``None`` = leave
    the current cgroup value alone."""

    cpu_shares: Optional[int] = None
    cfs_quota_us: Optional[int] = None
    cpuset: Optional[str] = None
    memory_limit_bytes: Optional[int] = None
    cpu_bvt: Optional[int] = None
    #: env vars to ADD to the container (reference: ContainerResponse
    #: AddContainerEnvs, used by the device hook). Only meaningful at
    #: container creation — NRI adjustment / CRI-proxy request merge;
    #: inert in standalone cgroup reconcile (no cgroup file to write).
    add_envs: Optional[Dict[str, str]] = None

    def is_origin_res_changed(self) -> bool:
        return (
            self.cpu_shares is not None
            or self.cfs_quota_us is not None
            or self.cpuset is not None
            or self.memory_limit_bytes is not None
        )

    def updaters(self, cgroup_dir: str) -> List[CgroupUpdater]:
        """Lower the response to executor updates against one cgroup dir
        (protocol.go:127-160 injectCPUShares/CPUSet/CPUQuota/Memory)."""
        out: List[CgroupUpdater] = []
        if self.cpu_shares is not None:
            out.append(CgroupUpdater(
                "cpu.shares", cgroup_dir, str(self.cpu_shares),
                merge_if_value_larger,
            ))
        if self.cfs_quota_us is not None:
            out.append(CgroupUpdater(
                "cpu.cfs_quota_us", cgroup_dir, str(self.cfs_quota_us),
                merge_if_cfs_quota_larger,
            ))
        if self.memory_limit_bytes is not None:
            out.append(CgroupUpdater(
                "memory.limit_in_bytes", cgroup_dir,
                str(self.memory_limit_bytes), merge_if_value_larger,
            ))
        if self.cpuset is not None and self.cpuset != "":
            # an empty cpuset response means "clear": cpuset.cpus cannot
            # be written empty, so the reconciler simply leaves the file
            # (the kubelet/cpu-suppress owns it then)
            out.append(CgroupUpdater("cpuset.cpus", cgroup_dir, self.cpuset))
        if self.cpu_bvt is not None:
            out.append(CgroupUpdater(
                "cpu.bvt_warp_ns", cgroup_dir, str(self.cpu_bvt)
            ))
        return out


@dataclasses.dataclass
class PodRequest:
    """pod_context.go PodRequest: identity + attrs + cgroup parent."""

    pod_meta: PodMeta

    @property
    def labels(self) -> Dict[str, str]:
        return self.pod_meta.labels

    @property
    def annotations(self) -> Dict[str, str]:
        return self.pod_meta.annotations

    @property
    def cgroup_parent(self) -> str:
        return self.pod_meta.cgroup_dir

    @property
    def qos(self) -> QoSClass:
        return self.pod_meta.qos

    @property
    def kube_qos(self) -> KubeQOS:
        return kube_qos_by_cgroup_parent(self.pod_meta.cgroup_dir)

    @property
    def batch_resources(self) -> Dict[str, ContainerBatchResources]:
        return self.pod_meta.batch_resources


@dataclasses.dataclass
class ContainerRequest:
    pod_meta: PodMeta
    container_name: str
    cgroup_parent: str

    @property
    def labels(self) -> Dict[str, str]:
        return self.pod_meta.labels

    @property
    def annotations(self) -> Dict[str, str]:
        return self.pod_meta.annotations

    @property
    def qos(self) -> QoSClass:
        return self.pod_meta.qos

    @property
    def kube_qos(self) -> KubeQOS:
        return kube_qos_by_cgroup_parent(self.cgroup_parent)

    @property
    def batch(self) -> Optional[ContainerBatchResources]:
        return self.pod_meta.batch_resources.get(self.container_name)


class HooksProtocol:
    """Base context: request + response + apply (protocol.go:32-36)."""

    def updaters(self) -> List[CgroupUpdater]:
        raise NotImplementedError

    def reconciler_done(self, executor: ResourceUpdateExecutor) -> int:
        """Apply the response through the shared executor; returns the
        number of files written."""
        return executor.update_batch(True, self.updaters())


@dataclasses.dataclass
class PodContext(HooksProtocol):
    request: PodRequest
    response: Resources = dataclasses.field(default_factory=Resources)

    @classmethod
    def from_meta(cls, pod: PodMeta) -> "PodContext":
        return cls(request=PodRequest(pod_meta=pod))

    def updaters(self) -> List[CgroupUpdater]:
        return self.response.updaters(self.request.cgroup_parent)


@dataclasses.dataclass
class ContainerContext(HooksProtocol):
    request: ContainerRequest
    response: Resources = dataclasses.field(default_factory=Resources)

    @classmethod
    def from_meta(cls, pod: PodMeta, container: str) -> "ContainerContext":
        return cls(request=ContainerRequest(
            pod_meta=pod,
            container_name=container,
            cgroup_parent=pod.containers.get(
                container, f"{pod.cgroup_dir}/{container}"
            ),
        ))

    def updaters(self) -> List[CgroupUpdater]:
        return self.response.updaters(self.request.cgroup_parent)


@dataclasses.dataclass
class KubeQOSContext(HooksProtocol):
    """kubeqos_context.go: reconcile target for a QoS tier root dir."""

    kube_qos: KubeQOS
    response: Resources = dataclasses.field(default_factory=Resources)

    @property
    def cgroup_parent(self) -> str:
        return KUBE_QOS_DIR[self.kube_qos]

    def updaters(self) -> List[CgroupUpdater]:
        return self.response.updaters(self.cgroup_parent)
