"""Batch-resource hook: cgroup limits for BE pods on reclaimed resources.

Reference: pkg/koordlet/runtimehooks/hooks/batchresource/
{batch_resource.go,rule.go} — BE pods request ``kubernetes.io/batch-cpu``
/ ``batch-memory`` (the dynamically reclaimed overcommit computed by the
manager); the kubelet knows nothing about those extended resources, so
this hook translates them into real cgroup values:

- pod/container cpu.shares from summed batch-cpu *requests*
  (batch_resource.go:122 SetPodCPUShares, MilliCPUToShares);
- pod/container cfs quota from summed batch-cpu *limits*
  (:156 SetPodCFSQuota; any unlimited container -> -1; divided by the
  cpu-normalization ratio, ceil, when ratio > 1, rule.go:55);
- pod/container memory limit from batch-memory limits
  (:209 SetPodMemoryLimit; any unlimited container -> -1).

Non-BE pods and pods without batch resources are left untouched.
"""

from __future__ import annotations

import math
from typing import Optional

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.metricsadvisor.framework import (
    ContainerBatchResources,
)
from koordinator_tpu.koordlet.runtimehooks.hooks import HookRegistry, Stage
from koordinator_tpu.koordlet.runtimehooks.protocol import (
    ContainerContext,
    PodContext,
    milli_cpu_to_quota,
    milli_cpu_to_shares,
)

NAME = "BatchResource"


class BatchResourcePlugin:
    name = NAME

    def __init__(self):
        #: cpu-normalization ratio (rule.go:86; > 1 shrinks cfs quota)
        self.cpu_normalization_ratio: float = 1.0
        #: rule.go:55 GetCFSQuotaScaleRatio: disabled -> quota unset (-1)
        self.cfs_quota_enabled: bool = True

    def update_rule(self, cpu_normalization_ratio: Optional[float] = None,
                    cfs_quota_enabled: Optional[bool] = None) -> bool:
        changed = False
        if (cpu_normalization_ratio is not None
                and cpu_normalization_ratio != self.cpu_normalization_ratio):
            self.cpu_normalization_ratio = cpu_normalization_ratio
            changed = True
        if (cfs_quota_enabled is not None
                and cfs_quota_enabled != self.cfs_quota_enabled):
            self.cfs_quota_enabled = cfs_quota_enabled
            changed = True
        return changed

    # -- math ----------------------------------------------------------------

    def _scale_quota(self, quota_us: int) -> int:
        if quota_us > 0 and self.cpu_normalization_ratio > 1.0:
            return math.ceil(quota_us / self.cpu_normalization_ratio)
        return quota_us

    @staticmethod
    def _pod_batch_request_mcpu(batch) -> int:
        return sum(
            c.request_mcpu for c in batch.values() if c.request_mcpu > 0
        )

    @staticmethod
    def _pod_batch_limit_mcpu(batch) -> int:
        """Sum of limits; any unlimited container makes the pod
        unlimited (-1) (batch_resource.go:183-196)."""
        total = 0
        for c in batch.values():
            if c.limit_mcpu is None or c.limit_mcpu <= 0:
                return -1
            total += c.limit_mcpu
        return total

    @staticmethod
    def _pod_batch_memory_limit(batch) -> int:
        total = 0
        for c in batch.values():
            if c.memory_limit_bytes is None or c.memory_limit_bytes <= 0:
                return -1
            total += c.memory_limit_bytes
        return total

    # -- hook fns ------------------------------------------------------------

    def set_pod_resources(self, proto) -> None:
        """batch_resource.go:95 SetPodResources."""
        if not isinstance(proto, PodContext):
            return
        req = proto.request
        if req.qos is not QoSClass.BE or not req.batch_resources:
            return
        batch = req.batch_resources
        proto.response.cpu_shares = milli_cpu_to_shares(
            self._pod_batch_request_mcpu(batch)
        )
        if self.cfs_quota_enabled:
            proto.response.cfs_quota_us = self._scale_quota(
                milli_cpu_to_quota(self._pod_batch_limit_mcpu(batch))
            )
        else:
            proto.response.cfs_quota_us = -1
        proto.response.memory_limit_bytes = self._pod_batch_memory_limit(
            batch
        )

    def set_container_resources(self, proto) -> None:
        """batch_resource.go:244 SetContainerResources."""
        if not isinstance(proto, ContainerContext):
            return
        req = proto.request
        if req.qos is not QoSClass.BE:
            return
        c = req.batch
        if c is None:
            return
        proto.response.cpu_shares = milli_cpu_to_shares(c.request_mcpu)
        limit = (
            c.limit_mcpu
            if c.limit_mcpu is not None and c.limit_mcpu > 0
            else -1
        )
        if self.cfs_quota_enabled:
            proto.response.cfs_quota_us = self._scale_quota(
                milli_cpu_to_quota(limit)
            )
        else:
            proto.response.cfs_quota_us = -1
        proto.response.memory_limit_bytes = (
            c.memory_limit_bytes
            if c.memory_limit_bytes is not None and c.memory_limit_bytes > 0
            else -1
        )

    def register(self, registry: HookRegistry) -> None:
        registry.register(
            Stage.PRE_RUN_POD_SANDBOX, self.name,
            "set batch resource limits for BE pod cgroup",
            self.set_pod_resources,
        )
        registry.register(
            Stage.PRE_CREATE_CONTAINER, self.name,
            "set batch resource limits for BE container cgroup",
            self.set_container_resources,
        )
        registry.register(
            Stage.PRE_UPDATE_CONTAINER_RESOURCES, self.name,
            "re-apply batch resource limits on update",
            self.set_container_resources,
        )
