"""Runtime hook server: the NRI/proxy-equivalent event seam.

Reference: pkg/koordlet/runtimehooks/nri/server.go (containerd NRI v0.3)
and proxyserver/ (UDS gRPC for koord-runtime-proxy) — a runtime delivers
pod/container lifecycle events; the server runs the stage's hooks and
returns (and in standalone mode applies) the cgroup mutations.

The transport here is an in-process call surface: the CRI-interposer
component (``koordinator_tpu.runtimeproxy``) and the PLEG both drive it.
``apply=True`` ("standalone" reconciler-backed mode) writes the response
through the executor immediately; ``apply=False`` returns the mutation
for the interposer to merge into the runtime request (the NRI
adjustment path).
"""

from __future__ import annotations

from typing import Optional

from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.runtimehooks.hooks import (
    FailurePolicy,
    HookRegistry,
    Stage,
)
from koordinator_tpu.koordlet.runtimehooks.protocol import (
    ContainerContext,
    PodContext,
    Resources,
)


class RuntimeHookServer:
    """Dispatches lifecycle events to hooks (nri/server.go:
    RunPodSandbox/CreateContainer/UpdateContainer handlers)."""

    def __init__(
        self,
        registry: HookRegistry,
        executor: Optional[ResourceUpdateExecutor] = None,
        fail_policy: FailurePolicy = FailurePolicy.IGNORE,
    ):
        self.registry = registry
        self.executor = executor
        self.fail_policy = fail_policy

    def _finish(self, ctx, apply: bool) -> Resources:
        if apply and self.executor is not None:
            ctx.reconciler_done(self.executor)
        return ctx.response

    # -- pod events ----------------------------------------------------------

    def run_pod_sandbox(self, pod: PodMeta, apply: bool = True,
                        policy: Optional[FailurePolicy] = None) -> Resources:
        ctx = PodContext.from_meta(pod)
        self.registry.run_hooks(
            Stage.PRE_RUN_POD_SANDBOX, ctx, policy or self.fail_policy
        )
        return self._finish(ctx, apply)

    def stop_pod_sandbox(self, pod: PodMeta, apply: bool = True,
                         policy: Optional[FailurePolicy] = None) -> Resources:
        ctx = PodContext.from_meta(pod)
        self.registry.run_hooks(
            Stage.POST_STOP_POD_SANDBOX, ctx, policy or self.fail_policy
        )
        return self._finish(ctx, apply)

    # -- container events ----------------------------------------------------

    def create_container(
        self, pod: PodMeta, container: str, apply: bool = True,
        policy: Optional[FailurePolicy] = None,
    ) -> Resources:
        ctx = ContainerContext.from_meta(pod, container)
        self.registry.run_hooks(
            Stage.PRE_CREATE_CONTAINER, ctx, policy or self.fail_policy
        )
        return self._finish(ctx, apply)

    def start_container(
        self, pod: PodMeta, container: str, apply: bool = True,
        policy: Optional[FailurePolicy] = None,
    ) -> Resources:
        ctx = ContainerContext.from_meta(pod, container)
        self.registry.run_hooks(
            Stage.PRE_START_CONTAINER, ctx, policy or self.fail_policy
        )
        return self._finish(ctx, apply)

    def update_container_resources(
        self, pod: PodMeta, container: str, apply: bool = True,
        policy: Optional[FailurePolicy] = None,
    ) -> Resources:
        ctx = ContainerContext.from_meta(pod, container)
        self.registry.run_hooks(
            Stage.PRE_UPDATE_CONTAINER_RESOURCES, ctx, policy or self.fail_policy
        )
        return self._finish(ctx, apply)

    def post_start_container(
        self, pod: PodMeta, container: str, apply: bool = True,
        policy: Optional[FailurePolicy] = None,
    ) -> Resources:
        ctx = ContainerContext.from_meta(pod, container)
        self.registry.run_hooks(
            Stage.POST_START_CONTAINER, ctx, policy or self.fail_policy
        )
        return self._finish(ctx, apply)

    def stop_container(
        self, pod: PodMeta, container: str, apply: bool = True,
        policy: Optional[FailurePolicy] = None,
    ) -> Resources:
        ctx = ContainerContext.from_meta(pod, container)
        self.registry.run_hooks(
            Stage.POST_STOP_CONTAINER, ctx, policy or self.fail_policy
        )
        return self._finish(ctx, apply)
