"""NRI-mode server: event-driven hook invocation from the PLEG stream.

Reference: pkg/koordlet/runtimehooks/nri/server.go — the NRI plugin
subscribes to the container runtime's lifecycle event stream (containerd
NRI v0.3 stub), runs the registered hooks per event, and applies the
resulting adjustments. The reference's three modes map here as:

- **proxy** → ``runtimeproxy.criserver`` (interpose runtime requests),
- **reconciler** → ``reconciler.Reconciler`` (periodic drift heal),
- **NRI** → THIS: *push* events. The runtime's event feed analogue in
  this framework is the PLEG cgroupfs stream (``pleg/pleg.py``); events
  are resolved to PodMeta through the statesinformer's pod provider and
  dispatched to :class:`RuntimeHookServer` stages with standalone
  application (``apply=True`` — the NRI adjustment is written through
  the executor, since there is no runtime request to merge into).

Like the reference stub it supports an event subscription list
(``nriConfig.Events``), a plugin failure policy, disabled stages
(``Options.DisableStages``), and a Synchronize pass on registration
(server.go Synchronize: re-run hooks over every already-running pod so
a restarted koordlet converges immediately instead of waiting for the
next lifecycle event).
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, Optional, Set, Tuple

from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.koordlet.pleg.pleg import EventType, PodLifecycleEvent
from koordinator_tpu.koordlet.runtimehooks.hooks import Stage
from koordinator_tpu.koordlet.runtimehooks.server import RuntimeHookServer

#: reference event names (nriConfig.Events) keyed by PLEG event type
EVENT_NAMES = {
    EventType.POD_ADDED: "RunPodSandbox",
    EventType.POD_DELETED: "StopPodSandbox",
    EventType.CONTAINER_ADDED: "CreateContainer",
    EventType.CONTAINER_DELETED: "StopContainer",
}
ALL_EVENTS = frozenset(EVENT_NAMES.values())


class NriServer:
    """Dispatches PLEG lifecycle events to hook stages.

    ``pod_provider`` is any object with ``pods() -> Sequence[PodMeta]``
    (the statesinformer); events whose cgroup dir resolves to no known
    pod are dropped — the reconciler mode heals any gap on its next
    pass, matching the reference's layered NRI+reconciler deployment.
    """

    def __init__(
        self,
        server: RuntimeHookServer,
        pod_provider,
        events: Optional[Iterable[str]] = None,
        disable_stages: Optional[Set[str]] = None,
    ):
        self.server = server
        # statesinformer exposes running_pods(); any pods() sequence
        # source (tests, custom informers) works too
        self._pods_fn = getattr(pod_provider, "running_pods", None) or getattr(
            pod_provider, "pods"
        )
        # cgroup-dir index, rebuilt only when the pod set changes — a
        # PLEG burst after downtime must not do O(pods) work per event.
        # With an informer we invalidate on its PODS callback; without
        # one (plain pods() source) every event rebuilds. The previous
        # index is retained so DELETE events still resolve after the
        # informer drops the pod (the reference NRI event carries pod
        # info in-band; PLEG only carries the cgroup dir).
        self._index: Optional[Dict[str, Tuple[PodMeta, Optional[str]]]] = None
        self._prev: Dict[str, Tuple[PodMeta, Optional[str]]] = {}
        self._index_tracked = False
        register = getattr(pod_provider, "register_callback", None)
        if register is not None:
            from koordinator_tpu.koordlet.statesinformer import StateKind

            register(StateKind.PODS, lambda _kind, _pods: self._invalidate())
            self._index_tracked = True
            # eager build: the retained-previous-index guarantee for
            # stop events needs a snapshot from BEFORE the pod drops
            self._index = self._build_index()
        self.events = frozenset(events) if events is not None else ALL_EVENTS
        unknown = self.events - ALL_EVENTS
        if unknown:
            raise ValueError(f"unknown NRI events: {sorted(unknown)}; "
                             f"valid: {sorted(ALL_EVENTS)}")
        self.disable_stages = set(disable_stages or ())
        valid_stages = {s.value for s in Stage}
        unknown = self.disable_stages - valid_stages
        if unknown:
            raise ValueError(f"unknown stages: {sorted(unknown)}; "
                             f"valid: {sorted(valid_stages)}")
        #: counters per event name (observability parity with the
        #: reference's klog'd handlers)
        self.handled: Dict[str, int] = {}
        self.dropped = 0

    # -- wiring --------------------------------------------------------------

    def attach(self, pleg) -> "NriServer":
        """Subscribe to a PLEG instance and run the Synchronize pass."""
        pleg.register(self.handle_event)
        self.synchronize()
        return self

    def synchronize(self) -> int:
        """Re-apply hook outputs over every running pod (the NRI stub's
        Synchronize callback); returns how many contexts ran."""
        ran = 0
        for pod in self._pods_fn():
            if "RunPodSandbox" in self.events and not self._disabled(
                "PreRunPodSandbox"
            ):
                self.server.run_pod_sandbox(pod, apply=True)
                ran += 1
            if "CreateContainer" in self.events and not self._disabled(
                "PreCreateContainer"
            ):
                for name in pod.containers:
                    self.server.create_container(pod, name, apply=True)
                    ran += 1
        return ran

    # -- event dispatch ------------------------------------------------------

    def _disabled(self, stage_name: str) -> bool:
        return stage_name in self.disable_stages

    def _invalidate(self) -> None:
        if self._index is not None:
            self._prev = self._index
        self._index = self._build_index()

    def _build_index(self) -> Dict[str, Tuple[PodMeta, Optional[str]]]:
        index: Dict[str, Tuple[PodMeta, Optional[str]]] = {}
        for pod in self._pods_fn():
            index[pod.cgroup_dir] = (pod, None)
            for name, cdir in pod.containers.items():
                index[cdir] = (pod, name)
        return index

    def _resolve(self, cgroup_dir: str, include_retired: bool = False
                 ) -> Tuple[Optional[PodMeta], Optional[str]]:
        """(pod, container_name) for a PLEG cgroup dir; container_name
        is None for pod-level dirs. ``include_retired`` also consults
        the previous index so stop events resolve after the informer
        already dropped the pod."""
        if self._index is None or not self._index_tracked:
            if self._index is not None:
                self._prev = self._index
            self._index = self._build_index()
        hit = self._index.get(cgroup_dir)
        if hit is None and include_retired:
            hit = self._prev.get(cgroup_dir)
        return hit if hit is not None else (None, None)

    def handle_event(self, event: PodLifecycleEvent) -> bool:
        """PLEG handler: returns True if a hook stage ran."""
        name = EVENT_NAMES[event.event]
        if name not in self.events:
            return False
        is_stop = event.event in (
            EventType.POD_DELETED, EventType.CONTAINER_DELETED
        )
        pod, container = self._resolve(event.cgroup_dir,
                                       include_retired=is_stop)
        if pod is None:
            self.dropped += 1
            return False
        if event.event is EventType.POD_ADDED:
            if self._disabled("PreRunPodSandbox"):
                return False
            self.server.run_pod_sandbox(pod, apply=True)
        elif event.event is EventType.POD_DELETED:
            if self._disabled("PostStopPodSandbox"):
                return False
            self.server.stop_pod_sandbox(pod, apply=True)
        elif event.event is EventType.CONTAINER_ADDED:
            if container is None or self._disabled("PreCreateContainer"):
                return False
            self.server.create_container(pod, container, apply=True)
        else:  # CONTAINER_DELETED
            if container is None or self._disabled("PostStopContainer"):
                return False
            self.server.stop_container(pod, container, apply=True)
        self.handled[name] = self.handled.get(name, 0) + 1
        return True
