"""runtimehooks: pod-lifecycle QoS actuation (the decision->cgroup path).

Reference: pkg/koordlet/runtimehooks/ — the koordlet subsystem that turns
scheduler decisions (QoS class labels, cpuset annotations, batch
resources) into cgroup state at pod/container lifecycle events, via
three delivery modes: NRI server, runtime-proxy gRPC, and a reconciler.

Here: an instance-based hook registry (hooks.py), typed protocol
contexts (protocol.py), the three core hook plugins (groupidentity bvt,
cpuset pinning, batchresource limits), a reconciler that heals cgroup
drift from informer state, and an in-process server seam for the CRI
interposer. ``RuntimeHooks`` wires them against a states informer +
executor.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from koordinator_tpu.koordlet.metricsadvisor.framework import (
    ContainerBatchResources,
    PodMeta,
)
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.runtimehooks.batchresource import (
    BatchResourcePlugin,
)
from koordinator_tpu.koordlet.runtimehooks.cpunormalization import (
    CPUNormalizationPlugin,
)
from koordinator_tpu.koordlet.runtimehooks.cpuset import (
    CpusetPlugin,
    NodeTopoInfo,
)
from koordinator_tpu.koordlet.runtimehooks.devices import DeviceEnvPlugin
from koordinator_tpu.koordlet.runtimehooks.terwayqos import TerwayQosPlugin
from koordinator_tpu.koordlet.runtimehooks.groupidentity import (
    BvtPlugin,
    BvtRule,
    parse_rule,
)
from koordinator_tpu.koordlet.runtimehooks.hooks import (
    DEFAULT_REGISTRY,
    FailurePolicy,
    Hook,
    HookRegistry,
    Stage,
)
from koordinator_tpu.koordlet.runtimehooks.protocol import (
    ContainerContext,
    HooksProtocol,
    KUBE_QOS_DIR,
    KubeQOS,
    KubeQOSContext,
    PodContext,
    Resources,
    kube_qos_by_cgroup_parent,
    milli_cpu_to_quota,
    milli_cpu_to_shares,
)
from koordinator_tpu.koordlet.runtimehooks.reconciler import Reconciler
from koordinator_tpu.koordlet.runtimehooks.nri import NriServer
from koordinator_tpu.koordlet.runtimehooks.server import RuntimeHookServer
from koordinator_tpu.koordlet.statesinformer.states_informer import (
    StateKind,
    StatesInformer,
)
from koordinator_tpu.manager.sloconfig import NodeSLOSpec

__all__ = [
    "BatchResourcePlugin",
    "BvtPlugin",
    "BvtRule",
    "CPUNormalizationPlugin",
    "DeviceEnvPlugin",
    "TerwayQosPlugin",
    "ContainerBatchResources",
    "ContainerContext",
    "CpusetPlugin",
    "DEFAULT_REGISTRY",
    "FailurePolicy",
    "Hook",
    "HookRegistry",
    "HooksProtocol",
    "KUBE_QOS_DIR",
    "KubeQOS",
    "KubeQOSContext",
    "NodeTopoInfo",
    "PodContext",
    "Reconciler",
    "Resources",
    "NriServer",
    "RuntimeHookServer",
    "RuntimeHooks",
    "Stage",
    "kube_qos_by_cgroup_parent",
    "milli_cpu_to_quota",
    "milli_cpu_to_shares",
    "parse_rule",
]


class RuntimeHooks:
    """Top-level wiring (reference: runtimehooks.go NewRuntimeHook):
    registers the standard plugins on a fresh registry, subscribes to
    informer NodeSLO/pod changes, exposes the server + reconciler."""

    def __init__(
        self,
        informer: StatesInformer,
        executor: ResourceUpdateExecutor,
        registry: Optional[HookRegistry] = None,
    ):
        self.registry = registry or HookRegistry()
        self.executor = executor
        self.informer = informer

        self.groupidentity = BvtPlugin()
        self.cpuset = CpusetPlugin()
        self.batchresource = BatchResourcePlugin()
        self.devices = DeviceEnvPlugin()
        self.cpunormalization = CPUNormalizationPlugin()
        self.terwayqos = TerwayQosPlugin(
            root_path=executor.config.terway_qos_root,
            auditor=executor.auditor,
        )
        self.groupidentity.register(self.registry)
        self.cpuset.register(self.registry)
        self.batchresource.register(self.registry)
        self.devices.register(self.registry)
        self.cpunormalization.register(self.registry)

        self.reconciler = Reconciler(
            self.registry, executor, bvt_plugin=self.groupidentity
        )
        self.server = RuntimeHookServer(self.registry, executor)

        informer.register_callback(StateKind.NODE_SLO, self._on_node_slo)
        informer.register_callback(StateKind.PODS, self._on_pods)
        informer.register_callback(StateKind.NODE, self._on_node)
        # arm the rules from whatever the informer already holds
        self.groupidentity.update_rule(informer.get_node_slo())
        self.cpunormalization.update_rule(informer.get_node())
        self.terwayqos.update_node_slo(informer.get_node_slo())
        self.terwayqos.update_pods(informer.running_pods())

    # -- informer callbacks --------------------------------------------------

    def _on_node_slo(self, kind: StateKind, slo: NodeSLOSpec) -> None:
        if self.groupidentity.update_rule(slo):
            # rule changed: re-actuate every kube-QoS dir + pod
            # (rule.go:148 ruleUpdateCb)
            self.groupidentity.rule_update(
                self.informer.running_pods(), self.executor
            )
        self.terwayqos.update_node_slo(slo)

    def _on_pods(self, kind: StateKind, pods: Sequence[PodMeta]) -> None:
        self.terwayqos.update_pods(pods)
        self.reconcile()
        self._finish_restore_if_settled(pods)

    def _on_node(self, kind: StateKind, node) -> None:
        # cpu-normalization ratio rides the node annotation (the rule's
        # RegisterTypeNodeMetadata parse); a change re-actuates quotas,
        # and a removal restores spec quotas (one-shot, but kept armed
        # while the informer's pod view is empty so a pod missing during
        # the rule change still gets restored on its next PODS update)
        if self.cpunormalization.update_rule(node):
            self.reconcile()
            self._finish_restore_if_settled(self.informer.running_pods())

    def _finish_restore_if_settled(self, pods) -> None:
        if self.cpunormalization.restoring and len(pods) > 0:
            self.cpunormalization.finish_restore()

    # -- public surface ------------------------------------------------------

    def set_node_topo(self, topo: NodeTopoInfo) -> None:
        """Feed share pools / kubelet policy (reference: cpuset rule from
        the NodeResourceTopology CR). A changed rule re-actuates every
        pod immediately (cpuset/rule.go:205 ruleUpdateCb)."""
        if self.cpuset.update_rule(topo):
            self.reconcile()

    def reconcile(self) -> int:
        return self.reconciler.reconcile(self.informer.running_pods())

    def attach_nri(self, pleg, events=None, disable_stages=None):
        """Enable NRI mode: subscribe the hook server to a PLEG event
        stream (nri/server.go); returns the attached NriServer."""
        return NriServer(
            self.server, self.informer, events=events,
            disable_stages=disable_stages,
        ).attach(pleg)
