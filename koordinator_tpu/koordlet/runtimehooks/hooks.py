"""Hook registry: named hooks per lifecycle stage.

Reference: pkg/koordlet/runtimehooks/hooks/hooks.go — Register(stage,
name, description, fn) builds a per-stage hook list (:47), RunHooks
(:82) invokes them in registration order with a failure policy (Ignore
continues, Fail aborts). The registry here is instance-based so tests
and multiple agents compose; a module-level default mirrors the
reference's global.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Dict, List, Optional

from koordinator_tpu.koordlet.runtimehooks.protocol import HooksProtocol


class Stage(enum.Enum):
    """runtimeproxy/config RuntimeHookType (hooks.go:104-112)."""

    PRE_RUN_POD_SANDBOX = "PreRunPodSandbox"
    PRE_CREATE_CONTAINER = "PreCreateContainer"
    PRE_START_CONTAINER = "PreStartContainer"
    POST_START_CONTAINER = "PostStartContainer"
    POST_STOP_CONTAINER = "PostStopContainer"
    POST_STOP_POD_SANDBOX = "PostStopPodSandbox"
    PRE_UPDATE_CONTAINER_RESOURCES = "PreUpdateContainerResources"


class FailurePolicy(enum.Enum):
    IGNORE = "Ignore"   # log and continue (default)
    FAIL = "Fail"       # abort the stage on first error


HookFn = Callable[[HooksProtocol], None]


@dataclasses.dataclass
class Hook:
    name: str
    stage: Stage
    description: str
    fn: HookFn


class HookRegistry:
    """Per-stage ordered hook lists (hooks.go:47-100)."""

    def __init__(self):
        self._stages: Dict[Stage, List[Hook]] = {s: [] for s in Stage}

    def register(self, stage: Stage, name: str, description: str,
                 fn: HookFn) -> Hook:
        for hook in self._stages[stage]:
            if hook.name == name:
                raise ValueError(
                    f"hook {name} already registered at stage {stage.value}"
                )
        hook = Hook(name=name, stage=stage, description=description, fn=fn)
        self._stages[stage].append(hook)
        return hook

    def hooks_by_stage(self, stage: Stage) -> List[Hook]:
        return list(self._stages[stage])

    def stages_with_hooks(self) -> List[Stage]:
        """hooks.go:117 GetStages: stages that have registered hooks."""
        return [s for s, hooks in self._stages.items() if hooks]

    def run_hooks(
        self,
        stage: Stage,
        proto: HooksProtocol,
        fail_policy: FailurePolicy = FailurePolicy.IGNORE,
        errors: Optional[List[Exception]] = None,
    ) -> None:
        """hooks.go:82 RunHooks: invoke the stage's hooks in order; on
        error either collect-and-continue (Ignore) or re-raise (Fail)."""
        for hook in self._stages[stage]:
            try:
                hook.fn(proto)
            except Exception as e:  # noqa: BLE001 - hook isolation
                if fail_policy is FailurePolicy.FAIL:
                    raise
                if errors is not None:
                    errors.append(e)


#: module default, mirroring the reference's global registry
DEFAULT_REGISTRY = HookRegistry()
