"""Group identity (bvt) hook: CPU scheduling priority per QoS class.

Reference: pkg/koordlet/runtimehooks/hooks/groupidentity/{rule.go,
interceptor.go} — derives a bvt rule from the merged NodeSLO's
ResourceQOSStrategy (rule.go:78-146 parseRule):

- per-koord-QoS pod values: LSE/LSR -> lsr value, LS -> ls, BE -> be
  (a class's value is its GroupIdentity when its CPUQOS is enabled and
  the cluster CPU policy is groupIdentity, else 0);
- per-kube-QoS *dir* values: besteffort -> be, burstable -> ls,
  guaranteed -> 0 (kernel constraint: guaranteed root stays 0);
- per-kube-QoS *pod fallback* values (pods without koord QoS label):
  guaranteed -> lsr else ls else 0, burstable -> ls, besteffort -> be.

The hook (interceptor.go:29 SetPodBvtValue) resolves a pod's bvt from
its koord QoS first, falling back to its kube QoS tier. The rule-update
callback (rule.go:148-222 ruleUpdateCb) writes the three kube-QoS dir
values and every pod's value through the executor.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.koordlet.resourceexecutor import (
    CgroupUpdater,
    ResourceUpdateExecutor,
)
from koordinator_tpu.koordlet.runtimehooks.hooks import HookRegistry, Stage
from koordinator_tpu.koordlet.runtimehooks.protocol import (
    KUBE_QOS_DIR,
    KubeQOS,
    PodContext,
    kube_qos_by_cgroup_parent,
)
from koordinator_tpu.manager.sloconfig import NodeSLOSpec

NAME = "GroupIdentity"
#: the disabled / none value (sloconfig.NoneCPUQOS().GroupIdentity)
BVT_NONE = 0


@dataclasses.dataclass
class BvtRule:
    enable: bool
    pod_qos_params: Dict[QoSClass, int]
    kube_qos_dir_params: Dict[KubeQOS, int]
    kube_qos_pod_params: Dict[KubeQOS, int]
    #: QoS classes whose pods get a shared core-scheduling cookie so SMT
    #: siblings never co-run others' tasks (CPUQOS.core_expeller;
    #: reference: the coresched hook driven by the same rule)
    core_expeller_qos: frozenset = frozenset()

    def pod_bvt(self, qos: QoSClass, kube_qos: KubeQOS) -> int:
        """interceptor.go getPodBvtValue: koord QoS first, kube QoS
        fallback."""
        if qos in self.pod_qos_params:
            return self.pod_qos_params[qos]
        return self.kube_qos_pod_params.get(kube_qos, BVT_NONE)

    def kube_qos_dir_bvt(self, kube_qos: KubeQOS) -> int:
        return self.kube_qos_dir_params.get(kube_qos, BVT_NONE)


def parse_rule(slo: NodeSLOSpec) -> BvtRule:
    """rule.go:78-146 parseRule over the merged NodeSLO spec."""
    strategy = slo.resource_qos_strategy
    lsr_enabled = strategy.lsr.enable
    ls_enabled = strategy.ls.enable
    be_enabled = strategy.be.enable

    lsr_value = strategy.lsr.cpu.group_identity if lsr_enabled else BVT_NONE
    ls_value = strategy.ls.cpu.group_identity if ls_enabled else BVT_NONE
    be_value = strategy.be.cpu.group_identity if be_enabled else BVT_NONE

    # guaranteed pod fallback: lsr if enabled, else ls, else none
    guaranteed_pod = BVT_NONE
    if lsr_enabled:
        guaranteed_pod = lsr_value
    elif ls_enabled:
        guaranteed_pod = ls_value

    return BvtRule(
        enable=lsr_enabled or ls_enabled or be_enabled,
        pod_qos_params={
            QoSClass.LSE: lsr_value,
            QoSClass.LSR: lsr_value,
            QoSClass.LS: ls_value,
            QoSClass.BE: be_value,
        },
        kube_qos_dir_params={
            # guaranteed root dir must stay 0 (kernel constraint)
            KubeQOS.GUARANTEED: BVT_NONE,
            KubeQOS.BURSTABLE: ls_value,
            KubeQOS.BESTEFFORT: be_value,
        },
        kube_qos_pod_params={
            KubeQOS.GUARANTEED: guaranteed_pod,
            KubeQOS.BURSTABLE: ls_value,
            KubeQOS.BESTEFFORT: be_value,
        },
        core_expeller_qos=frozenset(
            qos
            for qos, cfg in (
                (QoSClass.LSE, strategy.lsr),
                (QoSClass.LSR, strategy.lsr),
                (QoSClass.LS, strategy.ls),
            )
            if cfg.enable and cfg.cpu.core_expeller
        ),
    )


class BvtPlugin:
    """The groupidentity hook plugin."""

    name = NAME

    def __init__(self, core_sched=None):
        self._rule: Optional[BvtRule] = None
        #: optional CoreSched (system/core_sched.py) for the expeller
        self.core_sched = core_sched

    # -- rule lifecycle ------------------------------------------------------

    def update_rule(self, slo: NodeSLOSpec) -> bool:
        new = parse_rule(slo)
        changed = new != self._rule
        self._rule = new
        return changed

    @property
    def rule(self) -> Optional[BvtRule]:
        return self._rule

    # -- hook fn -------------------------------------------------------------

    def set_pod_bvt(self, proto) -> None:
        """interceptor.go:29 SetPodBvtValue."""
        if not isinstance(proto, PodContext):
            return
        r = self._rule
        if r is None or not r.enable:
            return
        req = proto.request
        proto.response.cpu_bvt = r.pod_bvt(req.qos, req.kube_qos)

    def register(self, registry: HookRegistry) -> None:
        registry.register(
            Stage.PRE_RUN_POD_SANDBOX, self.name,
            "set bvt value for pod cgroup", self.set_pod_bvt,
        )

    def apply_core_expeller(self, pods: List[PodMeta], pids_of) -> int:
        """Tag each expeller-class pod's tasks with one shared
        core-scheduling cookie (reference: the coresched hook applying the
        CPUQOS core-expeller over PR_SCHED_CORE). ``pids_of(pod)`` reads
        the pod's live pids; returns how many pods were tagged."""
        r = self._rule
        if (
            r is None
            or not r.core_expeller_qos
            or self.core_sched is None
            or not self.core_sched.supported()
        ):
            return 0
        tagged = 0
        for pod in pods:
            if pod.qos not in r.core_expeller_qos:
                continue
            pids = list(pids_of(pod))
            if not pids:
                continue
            self.core_sched.assign_group_cookie(pids[0], pids)
            tagged += 1
        return tagged

    # -- rule-update actuation (rule.go:148-222) -----------------------------

    def rule_update_levels(
        self, pods: List[PodMeta]
    ) -> List[List[CgroupUpdater]]:
        """Leveled bvt writes: kube-QoS dirs first, then pod dirs, then
        container dirs (container values inherit the pod's; written
        explicitly so a disable propagates, rule.go:240-260)."""
        r = self._rule
        if r is None:
            return []
        qos_level = [
            CgroupUpdater(
                "cpu.bvt_warp_ns", KUBE_QOS_DIR[kq],
                str(r.kube_qos_dir_bvt(kq)),
            )
            for kq in (KubeQOS.GUARANTEED, KubeQOS.BURSTABLE,
                       KubeQOS.BESTEFFORT)
        ]
        pod_level = []
        container_level = []
        for pod in pods:
            kube_qos = kube_qos_by_cgroup_parent(pod.cgroup_dir)
            bvt = r.pod_bvt(pod.qos, kube_qos)
            pod_level.append(
                CgroupUpdater("cpu.bvt_warp_ns", pod.cgroup_dir, str(bvt))
            )
            for cdir in pod.containers.values():
                container_level.append(
                    CgroupUpdater("cpu.bvt_warp_ns", cdir, str(bvt))
                )
        return [qos_level, pod_level, container_level]

    def rule_update(self, pods: List[PodMeta],
                    executor: ResourceUpdateExecutor) -> int:
        levels = self.rule_update_levels(pods)
        if not levels:
            return 0
        return executor.leveled_update_batch(levels)
