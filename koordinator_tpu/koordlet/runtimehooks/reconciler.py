"""Reconciler mode: periodically (re-)apply hook outputs to cgroupfs.

Reference: pkg/koordlet/runtimehooks/reconciler/reconciler.go — where no
NRI/proxy interposition is available (or to heal drift), the reconciler
walks kube-QoS dirs, every pod, and every container on informer events
and applies the same hook-derived cgroup values through the shared
executor (:244 Run, :272 reconcileKubeQOSCgroup, :313
reconcilePodCgroup).

Writes go through ``leveled_update_batch`` so the cgroup hierarchy stays
consistent mid-transition (parents loosened before children tighten).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.koordlet.resourceexecutor import (
    CgroupUpdater,
    ResourceUpdateExecutor,
)
from koordinator_tpu.koordlet.runtimehooks.groupidentity import BvtPlugin
from koordinator_tpu.koordlet.runtimehooks.hooks import (
    FailurePolicy,
    HookRegistry,
    Stage,
)
from koordinator_tpu.koordlet.runtimehooks.protocol import (
    ContainerContext,
    KubeQOS,
    KubeQOSContext,
    PodContext,
)


class Reconciler:
    """Drives hook stages over the current pod set."""

    def __init__(
        self,
        registry: HookRegistry,
        executor: ResourceUpdateExecutor,
        bvt_plugin: Optional[BvtPlugin] = None,
    ):
        self.registry = registry
        self.executor = executor
        self.bvt_plugin = bvt_plugin

    def reconcile(self, pods: Sequence[PodMeta]) -> int:
        """One reconcile pass; returns the number of cgroup writes.

        Levels: kube-QoS dirs -> pods -> containers (reconciler.go
        KubeQOSLevel/PodLevel/ContainerLevel ordering).
        """
        qos_level: List[CgroupUpdater] = []
        pod_level: List[CgroupUpdater] = []
        container_level: List[CgroupUpdater] = []

        if self.bvt_plugin is not None and self.bvt_plugin.rule is not None:
            for kq in KubeQOS:
                ctx = KubeQOSContext(kube_qos=kq)
                ctx.response.cpu_bvt = self.bvt_plugin.rule.kube_qos_dir_bvt(
                    kq
                )
                qos_level.extend(ctx.updaters())

        for pod in pods:
            pod_ctx = PodContext.from_meta(pod)
            self.registry.run_hooks(
                Stage.PRE_RUN_POD_SANDBOX, pod_ctx, FailurePolicy.IGNORE
            )
            pod_level.extend(pod_ctx.updaters())
            for container in pod.containers:
                c_ctx = ContainerContext.from_meta(pod, container)
                self.registry.run_hooks(
                    Stage.PRE_CREATE_CONTAINER, c_ctx, FailurePolicy.IGNORE
                )
                container_level.extend(c_ctx.updaters())

        return self.executor.leveled_update_batch(
            [qos_level, pod_level, container_level]
        )
