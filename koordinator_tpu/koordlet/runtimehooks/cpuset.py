"""CPUSet hook: pin containers to scheduler-allocated cpus/share pools.

Reference: pkg/koordlet/runtimehooks/hooks/cpuset/{cpuset.go,rule.go} —
the container cpuset resolves in priority order (rule.go:46-146
getContainerCPUSet):

1. pod annotation ``koordinator.sh/resource-status`` carrying an explicit
   cpuset (LSE/LSR pods pinned by the scheduler's NodeNUMAResource
   PreBind) -> use it verbatim (cpuset.go:114 GetCPUSetFromPod);
2. NUMA-aware allocation (numaNodeResources with cpu) -> join the share
   pools of the allocated NUMA nodes (BE pods use the BE share pools);
3. QoS=SYSTEM -> the system QoS cpuset if configured;
4. QoS=LS -> all share pools;
5. kube besteffort tier -> empty string (cpu-suppress owns the BE dirs);
6. kubelet static policy -> leave alone (None); none policy -> all
   share pools.

Pods pinned via annotation also get their cfs quota unset
(cpuset.go:171-214 UnsetPodCPUQuota: avoid throttling a pinned pod,
issue #489).

Share pools come from the node topology the agent reports (reference:
NodeResourceTopology CR annotations); here `NodeTopoInfo` carries them
(statesinformer Device/NRT reporting).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

from koordinator_tpu.apis.extension import (
    ANNOTATION_RESOURCE_STATUS,
    QoSClass,
    ResourceName,
)
from koordinator_tpu.koordlet.runtimehooks.hooks import HookRegistry, Stage
from koordinator_tpu.koordlet.runtimehooks.protocol import (
    ContainerContext,
    KubeQOS,
    PodContext,
)

NAME = "CPUSetAllocator"

#: kubelet cpu-manager policies (extension.KubeletCPUManagerPolicy)
KUBELET_POLICY_NONE = "none"
KUBELET_POLICY_STATIC = "static"


def parse_resource_status(annotations: Dict[str, str]) -> Optional[dict]:
    raw = annotations.get(ANNOTATION_RESOURCE_STATUS)
    if not raw:
        return None
    try:
        status = json.loads(raw)
    except ValueError:
        return None
    return status if isinstance(status, dict) else None


def cpuset_from_status(status: Optional[dict]) -> Optional[str]:
    """The scheduler-pinned cpuset, as a cpu-list string (reference:
    util.GetCPUSetFromPod). None when the pod carries no allocation."""
    if not status:
        return None
    cpus = status.get("cpuset")
    if not cpus:
        return None
    if isinstance(cpus, str):
        return cpus
    return ",".join(str(int(c)) for c in cpus)


def cpuset_from_annotation(annotations: Dict[str, str]) -> Optional[str]:
    return cpuset_from_status(parse_resource_status(annotations))


def numa_nodes_from_status(status: Optional[dict]) -> List[int]:
    """NUMA nodes the scheduler allocated cpu (or batch-cpu) on
    (rule.go:66-78 isNUMAAware check)."""
    if not status:
        return []
    out = []
    for entry in status.get("numaNodeResources", []) or []:
        res = entry.get("resources") or {}
        cpu = res.get(str(int(ResourceName.CPU)), res.get(int(ResourceName.CPU), 0))
        batch = res.get(
            str(int(ResourceName.BATCH_CPU)),
            res.get(int(ResourceName.BATCH_CPU), 0),
        )
        if cpu or batch:
            out.append(int(entry.get("node", 0)))
    return out


@dataclasses.dataclass
class NodeTopoInfo:
    """What the cpuset rule needs from the node topology report."""

    #: NUMA node -> shared-pool cpuset string (LS pods / default)
    share_pools: Dict[int, str] = dataclasses.field(default_factory=dict)
    #: NUMA node -> BE shared-pool cpuset string
    be_share_pools: Dict[int, str] = dataclasses.field(default_factory=dict)
    system_qos_cpuset: str = ""
    kubelet_policy: str = KUBELET_POLICY_NONE


@dataclasses.dataclass
class CpusetRule:
    share_pools: Dict[int, str]
    be_share_pools: Dict[int, str]
    system_qos_cpuset: str
    kubelet_policy: str

    def all_share_pools(self) -> str:
        return ",".join(
            self.share_pools[n] for n in sorted(self.share_pools)
        )

    def container_cpuset(self, req, status: Optional[dict] = None) -> Optional[str]:
        """rule.go:46-146; None = leave alone, "" = clear. ``status`` is
        the pre-parsed resource-status annotation (parsed once per hook
        invocation)."""
        if status is None:
            status = parse_resource_status(req.annotations)
        pinned = cpuset_from_status(status)
        if pinned is not None:
            return pinned

        numa_nodes = numa_nodes_from_status(status)
        if numa_nodes:
            pools = (
                self.be_share_pools if req.qos is QoSClass.BE
                else self.share_pools
            )
            return ",".join(
                pools[n] for n in numa_nodes if n in pools
            )

        if req.qos is QoSClass.SYSTEM and self.system_qos_cpuset:
            return self.system_qos_cpuset

        if req.qos is QoSClass.LS:
            return self.all_share_pools()

        if req.kube_qos is KubeQOS.BESTEFFORT:
            # BE dirs are owned by cpu-suppress; clear container pins
            return ""

        if self.kubelet_policy == KUBELET_POLICY_STATIC:
            return None
        return self.all_share_pools()


class CpusetPlugin:
    name = NAME

    def __init__(self):
        self._rule: Optional[CpusetRule] = None

    def update_rule(self, topo: NodeTopoInfo) -> bool:
        new = CpusetRule(
            share_pools=dict(topo.share_pools),
            be_share_pools=dict(topo.be_share_pools),
            system_qos_cpuset=topo.system_qos_cpuset,
            kubelet_policy=topo.kubelet_policy,
        )
        changed = new != self._rule
        self._rule = new
        return changed

    @property
    def rule(self) -> Optional[CpusetRule]:
        return self._rule

    # -- hook fns ------------------------------------------------------------

    def set_container_cpuset(self, proto) -> None:
        """cpuset.go:105 SetContainerCPUSet (+ :94 unset CFS)."""
        if not isinstance(proto, ContainerContext):
            return
        req = proto.request
        status = parse_resource_status(req.annotations)
        pinned = cpuset_from_status(status)
        if pinned is not None:
            proto.response.cpuset = pinned
            proto.response.cfs_quota_us = -1  # UnsetContainerCPUQuota
            return
        if self._rule is None:
            return
        proto.response.cpuset = self._rule.container_cpuset(req, status)

    def unset_pod_cpu_quota(self, proto) -> None:
        """cpuset.go:171 UnsetPodCPUQuota for annotation-pinned pods."""
        if not isinstance(proto, PodContext):
            return
        if cpuset_from_annotation(proto.request.annotations) is not None:
            proto.response.cfs_quota_us = -1

    def register(self, registry: HookRegistry) -> None:
        registry.register(
            Stage.PRE_CREATE_CONTAINER, self.name,
            "set container cpuset from annotation/share pools",
            self.set_container_cpuset,
        )
        registry.register(
            Stage.PRE_RUN_POD_SANDBOX, self.name,
            "unset cfs quota for cpuset-pinned pods",
            self.unset_pod_cpu_quota,
        )
        registry.register(
            Stage.PRE_UPDATE_CONTAINER_RESOURCES, self.name,
            "re-apply container cpuset on update",
            self.set_container_cpuset,
        )
