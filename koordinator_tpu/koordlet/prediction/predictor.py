"""Prod-reclaimable predictor: how much prod-requested capacity is idle.

Reference: pkg/koordlet/prediction/peak_predictor.go — the result that
feeds NodeMetric.ProdReclaimableMetric and, through the manager, the
MID-tier resources:

- podReclaimablePredictor (:128-210): per reclaimable prod pod,
  ``reclaimable += max(request - peak, 0)`` where peak = p95 cpu /
  p98 memory x safety margin; pods in cold start contribute 0.
- priorityReclaimablePredictor (:221-305): per reclaim-supported
  priority class, ``max(Σ request - peak(class usage) - peak(sys), 0)``.
- minPredictor (:307-340): the min of both, per resource.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from koordinator_tpu.koordlet.prediction.predict_server import (
    PeakPredictServer,
    SYS_KEY,
    pod_key,
    priority_key,
)


def prod_reclaimable(
    server: PeakPredictServer,
    pods: Sequence[Tuple[str, int, int]],
    now: float,
) -> Dict[str, int]:
    """``pods`` rows: (uid, cpu_request_mcpu, mem_request_mib) for
    reclaimable prod pods. Returns {"cpu": mCPU, "memory": MiB}."""
    # pod-level view — batch percentile over every pod at once
    keys = [pod_key(uid) for uid, _, _ in pods]
    peaks = server.peaks_batch(keys)
    pod_cpu = 0.0
    pod_mem = 0.0
    prod_cpu_req = 0
    prod_mem_req = 0
    for (uid, cpu_req, mem_req), key, peak in zip(pods, keys, peaks):
        prod_cpu_req += cpu_req
        prod_mem_req += mem_req
        if server.in_cold_start(key, now):
            continue  # cold-start pods reclaim nothing
        if peak["cpu"] is not None:
            pod_cpu += max(cpu_req - peak["cpu"], 0.0)
        if peak["memory"] is not None:
            pod_mem += max(mem_req - peak["memory"], 0.0)

    # priority-class view: requests minus peak class usage minus sys peak
    cls_peak = server.peak(priority_key("prod"))
    sys_peak = server.peak(SYS_KEY)
    pri_cpu = pri_mem = None
    if cls_peak["cpu"] is not None:
        pri_cpu = max(
            prod_cpu_req - cls_peak["cpu"] - (sys_peak["cpu"] or 0.0), 0.0
        )
    if cls_peak["memory"] is not None:
        pri_mem = max(
            prod_mem_req - cls_peak["memory"] - (sys_peak["memory"] or 0.0),
            0.0,
        )

    # min of the two views (minPredictor)
    cpu = pod_cpu if pri_cpu is None else min(pod_cpu, pri_cpu)
    mem = pod_mem if pri_mem is None else min(pod_mem, pri_mem)
    return {"cpu": int(cpu), "memory": int(mem)}
