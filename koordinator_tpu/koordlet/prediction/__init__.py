from koordinator_tpu.koordlet.prediction.histogram import HistogramBank
from koordinator_tpu.koordlet.prediction.predict_server import (
    PeakPredictServer,
    PredictionConfig,
)
from koordinator_tpu.koordlet.prediction.predictor import (
    prod_reclaimable,
)

__all__ = [
    "HistogramBank",
    "PeakPredictServer",
    "PredictionConfig",
    "prod_reclaimable",
]
