"""Peak prediction server: ingests usage samples, serves peak estimates.

Reference: pkg/koordlet/prediction/predict_server.go — one decaying
histogram per (subject, resource); subjects are pods (uid), priority
classes, and the system residual. Checkpointed to disk
(checkpoint.go) so restarts keep history.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence

from koordinator_tpu.koordlet.prediction.histogram import HistogramBank


@dataclasses.dataclass
class PredictionConfig:
    """Reference: prediction/config.go:40-42."""

    safety_margin_percent: int = 10
    cpu_half_life_seconds: float = 12 * 3600
    memory_half_life_seconds: float = 24 * 3600
    cold_start_seconds: float = 15 * 60
    checkpoint_path: str = ""


#: subject key helpers (reference: UIDType / UIDGenerator)
def pod_key(uid: str) -> str:
    return f"pod/{uid}"


def priority_key(priority_class: str) -> str:
    return f"priority/{priority_class}"


SYS_KEY = "sys"
NODE_KEY = "node"


class PeakPredictServer:
    """Histogram banks + checkpoint (reference: predict_server.go:65)."""

    def __init__(self, config: Optional[PredictionConfig] = None):
        self.config = config or PredictionConfig()
        self.cpu = HistogramBank(
            first_bucket=25.0,  # mCPU (reference: 0.025 cores)
            half_life_seconds=self.config.cpu_half_life_seconds,
        )
        self.memory = HistogramBank(
            first_bucket=5.0,  # MiB (reference: 5 MiB)
            half_life_seconds=self.config.memory_half_life_seconds,
        )

    def update(self, key: str, cpu_mcpu: float, mem_mib: float,
               now: float) -> None:
        self.cpu.add(key, cpu_mcpu, now)
        self.memory.add(key, mem_mib, now)

    def peak(self, key: str, cpu_p: float = 0.95,
             mem_p: float = 0.98) -> Dict[str, Optional[float]]:
        """Peak estimate with the safety margin applied (reference:
        peak_predictor.go:176-193: p95 cpu / p98 memory, each scaled by
        (100 + margin)/100)."""
        ratio = (100 + self.config.safety_margin_percent) / 100.0
        cpu = self.cpu.percentile(key, cpu_p)
        mem = self.memory.percentile(key, mem_p)
        return {
            "cpu": cpu * ratio if cpu is not None else None,
            "memory": mem * ratio if mem is not None else None,
        }

    def peaks_batch(self, keys: Sequence[str], cpu_p: float = 0.95,
                    mem_p: float = 0.98) -> List[Dict[str, Optional[float]]]:
        ratio = (100 + self.config.safety_margin_percent) / 100.0
        cpus = self.cpu.percentiles_batch(keys, [cpu_p])
        mems = self.memory.percentiles_batch(keys, [mem_p])
        return [
            {
                "cpu": c[0] * ratio if c[0] is not None else None,
                "memory": m[0] * ratio if m[0] is not None else None,
            }
            for c, m in zip(cpus, mems)
        ]

    def in_cold_start(self, key: str, now: float) -> bool:
        """Pods younger than the cold-start window are not reclaimable
        (peak_predictor.go coldStartDuration check)."""
        first = self.cpu.first_seen(key)
        return first is None or now - first < self.config.cold_start_seconds

    def gc(self, live_keys: Sequence[str]) -> None:
        keep = set(live_keys) | {SYS_KEY, NODE_KEY}
        keep |= {k for k in (priority_key("prod"), priority_key("mid"),
                             priority_key("batch"), priority_key("free"))}
        self.cpu.forget(keep)
        self.memory.forget(keep)

    # -- checkpoint (reference: prediction/checkpoint.go) --------------------

    def save_checkpoint(self, path: Optional[str] = None) -> None:
        path = path or self.config.checkpoint_path
        if not path:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"cpu": self.cpu.state(),
                       "memory": self.memory.state()}, f)
        os.replace(tmp, path)

    def load_checkpoint(self, path: Optional[str] = None) -> bool:
        path = path or self.config.checkpoint_path
        if not path or not os.path.exists(path):
            return False
        try:
            with open(path) as f:
                state = json.load(f)
            self.cpu.load_state(state["cpu"])
            self.memory.load_state(state["memory"])
            return True
        except (ValueError, KeyError, OSError):
            return False
