"""Vectorized decaying exponential histograms.

Reference: pkg/koordlet/prediction/predict_server.go:205-222 — the
reference keeps one VPA-style decaying exponential histogram per subject
(pod/priority/node) per resource. TPU-native design: a *bank* holds every
subject's histogram as one ``[N, B]`` weight matrix over shared
exponential bucket boundaries, so decay is one elementwise multiply,
sample ingest is a row scatter-add, and percentiles for ALL subjects are
one cumulative-sum pass — the whole node's predictor state updates in a
few fused array ops instead of N object updates.

VPA bucket semantics: bucket 0 spans ``[0, first)``; bucket b >= 1 spans
``[first*growth^(b-1), first*growth^b)``, and percentile queries return
the crossing bucket's *start* (vpa histogram.Percentile). Growth 1.05
(DefaultHistogramBucketSizeGrowth 0.05), first bucket 25 mCPU for CPU /
5 MiB for memory (predict_server.go:208,217 scaled to canonical units).
Decay halves a sample's weight every half-life (cpu 12h, mem 24h,
config.go:40-42), applied lazily per row.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np


class HistogramBank:
    """N decaying histograms over shared exponential buckets."""

    def __init__(self, first_bucket: float, growth: float = 1.05,
                 num_buckets: int = 256, half_life_seconds: float = 12 * 3600):
        self.first_bucket = first_bucket
        self.growth = growth
        self.num_buckets = num_buckets
        self.half_life = half_life_seconds
        #: start of each bucket (VPA GetBucketStart): 0 for bucket 0,
        #: first*growth^(b-1) for b >= 1
        self.bounds = np.concatenate(
            [[0.0], first_bucket * growth ** np.arange(num_buckets - 1)]
        )
        self._rows: Dict[str, int] = {}
        self._weights = np.zeros((0, num_buckets), np.float64)
        self._last_decay = np.zeros(0, np.float64)
        self._first_seen: Dict[str, float] = {}

    # -- rows ---------------------------------------------------------------

    def _row(self, key: str, now: float) -> int:
        idx = self._rows.get(key)
        if idx is None:
            idx = len(self._rows)
            self._rows[key] = idx
            if idx >= self._weights.shape[0]:
                grow = max(16, self._weights.shape[0])
                self._weights = np.vstack(
                    [self._weights, np.zeros((grow, self.num_buckets))]
                )
                self._last_decay = np.concatenate(
                    [self._last_decay, np.zeros(grow)]
                )
            self._last_decay[idx] = now
            self._first_seen[key] = now
        return idx

    def first_seen(self, key: str) -> Optional[float]:
        return self._first_seen.get(key)

    def _bucket(self, value: float) -> int:
        if value < self.first_bucket:
            return 0
        b = (
            int(math.log(value / self.first_bucket) / math.log(self.growth))
            + 1
        )
        return min(b, self.num_buckets - 1)

    def _decay_row(self, idx: int, now: float) -> None:
        dt = now - self._last_decay[idx]
        if dt > 0:
            self._weights[idx] *= 0.5 ** (dt / self.half_life)
            self._last_decay[idx] = now

    # -- ingest -------------------------------------------------------------

    def add(self, key: str, value: float, now: float,
            weight: float = 1.0) -> None:
        idx = self._row(key, now)
        self._decay_row(idx, now)
        self._weights[idx, self._bucket(value)] += weight

    # -- query --------------------------------------------------------------

    def percentile(self, key: str, p: float) -> Optional[float]:
        got = self.percentiles_batch([key], [p])
        return got[0][0]

    def percentiles_batch(
        self, keys: Sequence[str], ps: Sequence[float]
    ) -> List[List[Optional[float]]]:
        """[K, P] percentile matrix in one cumsum pass (the bank-wide
        analogue of histogram.Percentile)."""
        idxs = [self._rows.get(k, -1) for k in keys]
        out: List[List[Optional[float]]] = []
        valid = [i for i in idxs if i >= 0]
        if valid:
            w = self._weights[valid]
            total = w.sum(axis=1)
            cum = np.cumsum(w, axis=1)
        pos = 0
        for i in idxs:
            if i < 0:
                out.append([None] * len(ps))
                continue
            t = total[pos]
            if t <= 0:
                out.append([None] * len(ps))
                pos += 1
                continue
            row = cum[pos]
            vals: List[Optional[float]] = []
            for p in ps:
                b = int(np.searchsorted(row, p * t, side="left"))
                b = min(b, self.num_buckets - 1)
                vals.append(float(self.bounds[b]))
            out.append(vals)
            pos += 1
        return out

    def forget(self, live_keys: Iterable[str]) -> None:
        """Drop rows for departed subjects (compaction)."""
        live = set(live_keys)
        dead = [k for k in self._rows if k not in live]
        if not dead:
            return
        keep = [k for k in self._rows if k in live]
        new_weights = np.zeros((max(len(keep), 16), self.num_buckets))
        new_decay = np.zeros(max(len(keep), 16))
        new_rows = {}
        for j, k in enumerate(keep):
            new_weights[j] = self._weights[self._rows[k]]
            new_decay[j] = self._last_decay[self._rows[k]]
            new_rows[k] = j
        for k in dead:
            self._first_seen.pop(k, None)
        self._rows = new_rows
        self._weights = new_weights
        self._last_decay = new_decay

    # -- checkpoint ---------------------------------------------------------

    #: checkpoint format version; bumped when bucket semantics change so
    #: stale checkpoints are discarded instead of silently reinterpreted
    STATE_VERSION = 2

    def state(self) -> dict:
        keys = list(self._rows)
        idxs = [self._rows[k] for k in keys]
        return {
            "version": self.STATE_VERSION,
            "keys": keys,
            "weights": self._weights[idxs].tolist(),
            "last_decay": self._last_decay[idxs].tolist(),
            "first_seen": [self._first_seen.get(k, 0.0) for k in keys],
        }

    def load_state(self, state: dict) -> None:
        if state.get("version") != self.STATE_VERSION:
            return  # stale format: cold-start rather than misread buckets
        keys = state["keys"]
        n = len(keys)
        self._rows = {k: i for i, k in enumerate(keys)}
        self._weights = np.array(state["weights"], np.float64).reshape(
            n, self.num_buckets
        ) if n else np.zeros((0, self.num_buckets))
        self._last_decay = np.array(state["last_decay"], np.float64)
        self._first_seen = dict(zip(keys, state["first_seen"]))
