from koordinator_tpu.koordlet.pleg.pleg import PLEG, PodLifecycleEvent

__all__ = ["PLEG", "PodLifecycleEvent"]
