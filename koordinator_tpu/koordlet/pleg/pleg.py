"""Pod lifecycle event generator: watches the cgroupfs tree.

Reference: pkg/koordlet/pleg/{pleg.go,watcher.go} — inotify on the
kubepods cgroup directories emits pod/container create/delete events as
the fallback where NRI isn't available. Here the watcher is a poll-diff
over the directory tree (works on any filesystem, no inotify binding),
with the same event surface.
"""

from __future__ import annotations

import dataclasses
import enum
import os
from typing import Callable, Dict, List, Optional, Set

from koordinator_tpu.koordlet.system.cgroup import SystemConfig


class EventType(enum.Enum):
    POD_ADDED = "pod_added"
    POD_DELETED = "pod_deleted"
    CONTAINER_ADDED = "container_added"
    CONTAINER_DELETED = "container_deleted"


@dataclasses.dataclass(frozen=True)
class PodLifecycleEvent:
    event: EventType
    cgroup_dir: str  # pod or container cgroup dir relative to root


Handler = Callable[[PodLifecycleEvent], None]


class PLEG:
    """Poll-diff lifecycle watcher over kubepods cgroup dirs."""

    def __init__(self, config: SystemConfig,
                 kubepods_dir: Optional[str] = None):
        self.config = config
        self.kubepods_dir = kubepods_dir or config.kubepods_dir
        self._handlers: List[Handler] = []
        self._known_pods: Set[str] = set()
        self._known_containers: Set[str] = set()
        self._primed = False

    def register(self, handler: Handler) -> None:
        self._handlers.append(handler)

    def _root(self) -> str:
        if self.config.use_cgroup_v2:
            return os.path.join(self.config.cgroup_root, self.kubepods_dir)
        return os.path.join(
            self.config.cgroup_root, "cpu", self.kubepods_dir
        )

    def _scan(self) -> tuple:
        """(pods, containers) as cgroup dirs relative to the cgroup root.

        Layout: kubepods[/<qos tier>]/<pod>/<container>; QoS tier dirs
        (besteffort/burstable) hold pods, pod dirs hold containers.
        """
        pods: Set[str] = set()
        containers: Set[str] = set()
        root = self._root()
        tiers = [""]
        try:
            for entry in sorted(os.listdir(root)):
                if not os.path.isdir(os.path.join(root, entry)):
                    continue
                if entry in ("besteffort", "burstable", "guaranteed"):
                    tiers.append(entry)
                else:
                    pods.add(os.path.join(self.kubepods_dir, entry))
        except OSError:
            return pods, containers
        for tier in tiers[1:]:
            try:
                for entry in sorted(os.listdir(os.path.join(root, tier))):
                    full = os.path.join(root, tier, entry)
                    if os.path.isdir(full):
                        pods.add(os.path.join(self.kubepods_dir, tier, entry))
            except OSError:
                continue
        base = os.path.dirname(root)  # the dir containing kubepods/
        for pod in pods:
            pod_abs = os.path.join(base, pod)
            try:
                for entry in sorted(os.listdir(pod_abs)):
                    if os.path.isdir(os.path.join(pod_abs, entry)):
                        containers.add(os.path.join(pod, entry))
            except OSError:
                continue
        return pods, containers

    def poll(self) -> List[PodLifecycleEvent]:
        """Diff against the last scan; fire handlers; return events. The
        first poll primes without events (reference: the watcher only
        reports changes after the initial walk)."""
        pods, containers = self._scan()
        events: List[PodLifecycleEvent] = []
        if self._primed:
            for p in sorted(pods - self._known_pods):
                events.append(PodLifecycleEvent(EventType.POD_ADDED, p))
            for p in sorted(self._known_pods - pods):
                events.append(PodLifecycleEvent(EventType.POD_DELETED, p))
            for c in sorted(containers - self._known_containers):
                events.append(
                    PodLifecycleEvent(EventType.CONTAINER_ADDED, c)
                )
            for c in sorted(self._known_containers - containers):
                events.append(
                    PodLifecycleEvent(EventType.CONTAINER_DELETED, c)
                )
        self._known_pods = pods
        self._known_containers = containers
        self._primed = True
        for e in events:
            for h in self._handlers:
                h(e)
        return events
