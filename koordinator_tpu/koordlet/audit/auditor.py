"""Ring-buffer audit log of every node-level QoS action.

Reference: pkg/koordlet/audit/{auditor.go,event_logger.go} — every cgroup
write / eviction / suppress action is recorded with subject + operation +
detail, bounded in memory, queryable (the reference also tails to disk
and serves HTTP; here the query API is a method).
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import time
from typing import Deque, Iterator, List, Optional


@dataclasses.dataclass(frozen=True)
class AuditEvent:
    timestamp: float
    #: what acted: "qosmanager/cpusuppress", "resourceexecutor", ...
    group: str
    #: object acted on: a cgroup dir, a pod uid, a node resource
    subject: str
    #: verb: "update", "evict", "suppress", ...
    operation: str
    detail: str = ""


class Auditor:
    """Bounded in-memory event log (reference: auditor.go LogEvent +
    ring buffer)."""

    def __init__(self, capacity: int = 2048, clock=time.time):
        self._events: Deque[AuditEvent] = collections.deque(maxlen=capacity)
        self._clock = clock

    def log(self, group: str, subject: str, operation: str,
            detail: str = "") -> None:
        self._events.append(
            AuditEvent(self._clock(), group, subject, operation, detail)
        )

    def query(
        self,
        group: Optional[str] = None,
        subject: Optional[str] = None,
        operation: Optional[str] = None,
        since: Optional[float] = None,
        limit: Optional[int] = None,
    ) -> List[AuditEvent]:
        """Newest-first filtered view (reference: the HTTP query endpoint
        pkg/koordlet/audit/logger.go)."""

        def match(e: AuditEvent) -> bool:
            return (
                (group is None or e.group == group)
                and (subject is None or e.subject == subject)
                and (operation is None or e.operation == operation)
                and (since is None or e.timestamp >= since)
            )

        it: Iterator[AuditEvent] = filter(match, reversed(self._events))
        if limit is not None:
            it = itertools.islice(it, limit)
        return list(it)

    def __len__(self) -> int:
        return len(self._events)
