from koordinator_tpu.koordlet.audit.auditor import AuditEvent, Auditor

__all__ = ["AuditEvent", "Auditor"]
