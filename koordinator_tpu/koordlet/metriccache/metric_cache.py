"""In-memory time-series store for node/pod/container metrics.

Reference: pkg/koordlet/metriccache/ — the reference embeds a Prometheus
TSDB (tsdb_storage.go) plus an in-memory KV (kv_storage.go) and exposes
typed metric resources with aggregate queries (avg/p50/p90/p95/p99/last/
count, metric_result.go:75-175).

TPU-native design: series are fixed-capacity numpy ring buffers (no
external TSDB dependency, no disk); aggregation is vectorized — a batch
query stacks every requested series into one [S, T] matrix and reduces
along time in one shot (sort for the percentile family), which is the
shape the NodeMetric reporter wants (all pods aggregated at once).

Values are float64 in canonical units (mCPU / MiB) so downstream
consumers round into the int32 array substrate.

Aggregation semantics match the reference exactly
(util.go:55-100): percentile = ascending sort, index
``max(int(n*p) - 1, 0)``; avg = arithmetic mean; last = latest by
timestamp; count = number of points.
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np


class MetricKind(str, enum.Enum):
    """Typed metric resources (reference: metric_resources.go)."""

    NODE_CPU_USAGE = "node_cpu_usage"            # mCPU
    NODE_MEMORY_USAGE = "node_memory_usage"      # MiB
    POD_CPU_USAGE = "pod_cpu_usage"              # mCPU, label pod=<uid>
    POD_MEMORY_USAGE = "pod_memory_usage"        # MiB, label pod=<uid>
    CONTAINER_CPU_USAGE = "container_cpu_usage"  # mCPU, label container=
    CONTAINER_MEMORY_USAGE = "container_memory_usage"
    BE_CPU_USAGE = "be_cpu_usage"                # mCPU (all BE pods)
    SYS_CPU_USAGE = "sys_cpu_usage"              # mCPU (node - pods)
    SYS_MEMORY_USAGE = "sys_memory_usage"        # MiB
    PSI_CPU_SOME_AVG10 = "psi_cpu_some_avg10"    # percent
    PSI_MEM_SOME_AVG10 = "psi_mem_some_avg10"
    PSI_MEM_FULL_AVG10 = "psi_mem_full_avg10"
    PSI_IO_SOME_AVG10 = "psi_io_some_avg10"
    CONTAINER_CPI = "container_cpi"              # cycles/instruction
    HOST_APP_CPU_USAGE = "host_app_cpu_usage"    # mCPU, label app=
    HOST_APP_MEMORY_USAGE = "host_app_memory_usage"
    NODE_COLD_PAGE_BYTES = "node_cold_page_bytes"    # kidled cold file pages
    NODE_PAGE_CACHE_MIB = "node_page_cache_mib"      # meminfo Cached
    DEVICE_UTIL = "device_util"                  # percent, label minor=
    DEVICE_MEMORY_USED = "device_memory_used"    # MiB, label minor=
    POD_CPU_THROTTLED_RATIO = "pod_cpu_throttled_ratio"  # 0..1, label pod=
    NODE_DISK_READ_BPS = "node_disk_read_bps"    # bytes/s, label dev=
    NODE_DISK_WRITE_BPS = "node_disk_write_bps"  # bytes/s, label dev=
    NODE_DISK_IO_UTIL = "node_disk_io_util"      # percent, label dev=


class AggregationType(str, enum.Enum):
    AVG = "avg"
    P99 = "p99"
    P95 = "p95"
    P90 = "p90"
    P50 = "p50"
    LAST = "last"
    COUNT = "count"


_PERCENTILE = {
    AggregationType.P99: 0.99,
    AggregationType.P95: 0.95,
    AggregationType.P90: 0.90,
    AggregationType.P50: 0.50,
}

#: series key: (kind, sorted label items)
SeriesKey = Tuple[str, Tuple[Tuple[str, str], ...]]


def _key(kind: MetricKind, labels: Optional[Mapping[str, str]]) -> SeriesKey:
    return (kind.value, tuple(sorted((labels or {}).items())))


class _Ring:
    """Fixed-capacity (time, value) ring buffer."""

    __slots__ = ("ts", "vals", "head", "size")

    def __init__(self, capacity: int):
        self.ts = np.zeros(capacity, np.float64)
        self.vals = np.zeros(capacity, np.float64)
        self.head = 0  # next write slot
        self.size = 0

    def append(self, t: float, v: float) -> None:
        cap = len(self.ts)
        self.ts[self.head] = t
        self.vals[self.head] = v
        self.head = (self.head + 1) % cap
        self.size = min(self.size + 1, cap)

    def window(self, start: float, end: float) -> Tuple[np.ndarray, np.ndarray]:
        """Chronological points with start <= t <= end."""
        cap = len(self.ts)
        if self.size < cap:
            ts, vals = self.ts[: self.size], self.vals[: self.size]
        else:
            idx = np.arange(self.head, self.head + cap) % cap
            ts, vals = self.ts[idx], self.vals[idx]
        mask = (ts >= start) & (ts <= end)
        return ts[mask], vals[mask]


def aggregate_points(
    vals: np.ndarray, agg: AggregationType
) -> Optional[float]:
    """Reference semantics (util.go): None on empty input."""
    n = len(vals)
    if n == 0:
        return None
    if agg is AggregationType.COUNT:
        return float(n)
    if agg is AggregationType.LAST:
        return float(vals[-1])
    if agg is AggregationType.AVG:
        return float(vals.mean())
    p = _PERCENTILE[agg]
    idx = max(int(n * p) - 1, 0)
    return float(np.sort(vals)[idx])


class MetricCache:
    """Typed series store + KV (reference: metric_cache.go:56)."""

    def __init__(self, capacity_per_series: int = 4096,
                 retention_seconds: float = 30 * 60):
        self._capacity = capacity_per_series
        self._series: Dict[SeriesKey, _Ring] = {}
        self._kv: Dict[str, object] = {}
        self.retention_seconds = retention_seconds

    # -- KV (reference: kv_storage.go) --------------------------------------

    def set(self, key: str, value: object) -> None:
        self._kv[key] = value

    def get(self, key: str) -> Optional[object]:
        return self._kv.get(key)

    # -- time series --------------------------------------------------------

    def append(self, kind: MetricKind, labels: Optional[Mapping[str, str]],
               timestamp: float, value: float) -> None:
        key = _key(kind, labels)
        ring = self._series.get(key)
        if ring is None:
            ring = self._series[key] = _Ring(self._capacity)
        ring.append(timestamp, float(value))

    def query(self, kind: MetricKind,
              labels: Optional[Mapping[str, str]] = None,
              start: float = -math.inf,
              end: float = math.inf) -> Tuple[np.ndarray, np.ndarray]:
        ring = self._series.get(_key(kind, labels))
        if ring is None:
            return np.zeros(0), np.zeros(0)
        return ring.window(start, end)

    def label_values(self, kind: MetricKind, label: str) -> List[str]:
        """Distinct values of one label across a kind's series (e.g. the
        block devices the storage collector has reported)."""
        out = set()
        for key_kind, labels in self._series:
            if key_kind != kind.value:
                continue
            for name, value in labels:
                if name == label:
                    out.add(value)
        return sorted(out)

    def aggregate(self, kind: MetricKind,
                  labels: Optional[Mapping[str, str]] = None,
                  start: float = -math.inf, end: float = math.inf,
                  agg: AggregationType = AggregationType.AVG
                  ) -> Optional[float]:
        _, vals = self.query(kind, labels, start, end)
        return aggregate_points(vals, agg)

    # -- persistence (reference: the TSDB lives on disk,
    # metriccache/tsdb_storage.go — a koordlet restart keeps its
    # aggregation window instead of reporting from empty) -------------------

    def save(self, path: str) -> None:
        """Atomic npz snapshot of every series (chronological points)."""
        import json
        import math as _math
        import os

        arrays = {}
        meta = []
        for i, (key, ring) in enumerate(self._series.items()):
            ts, vals = ring.window(-_math.inf, _math.inf)
            arrays[f"ts_{i}"] = ts
            arrays[f"v_{i}"] = vals
            meta.append(list(key))
        arrays["meta"] = np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8
        )
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)

    def load(self, path: str) -> bool:
        """Restore a snapshot; returns False if absent/corrupt."""
        import json
        import os

        if not os.path.exists(path):
            return False
        # restore into a LOCAL dict and commit only on full success: a
        # corrupt snapshot (zipfile.BadZipFile, truncated arrays, missing
        # keys — anything) must leave the cache untouched, not
        # half-populated
        restored: Dict[SeriesKey, _Ring] = {}
        try:
            with np.load(path) as data:
                meta = json.loads(bytes(data["meta"]).decode())
                for i, key in enumerate(meta):
                    kind, labels = key
                    ring = _Ring(self._capacity)
                    ts, vals = data[f"ts_{i}"], data[f"v_{i}"]
                    order = np.argsort(ts, kind="stable")
                    for t, v in zip(ts[order], vals[order]):
                        ring.append(float(t), float(v))
                    restored[
                        (kind, tuple(tuple(kv) for kv in labels))
                    ] = ring
        except Exception:
            return False
        self._series.update(restored)
        return True

    def aggregate_batch(
        self,
        requests: Sequence[Tuple[MetricKind, Optional[Mapping[str, str]]]],
        start: float, end: float,
        aggs: Sequence[AggregationType],
    ) -> List[Dict[AggregationType, Optional[float]]]:
        """Aggregate many series x many types in one vectorized pass.

        The NodeMetric reporter calls this with every pod's cpu+memory
        series; windows are stacked into a padded [S, T] matrix and each
        reduction runs matrix-at-once instead of per-series loops
        (the batched analogue of states_nodemetric.go:332 collectMetric).
        """
        windows = [self.query(kind, labels, start, end)[1]
                   for kind, labels in requests]
        s = len(windows)
        if s == 0:
            return []
        maxt = max((len(w) for w in windows), default=0)
        out: List[Dict[AggregationType, Optional[float]]] = [
            {} for _ in range(s)
        ]
        if maxt == 0:
            for d in out:
                for a in aggs:
                    d[a] = None
            return out
        mat = np.full((s, maxt), np.nan)
        for i, w in enumerate(windows):
            mat[i, : len(w)] = w
        counts = np.sum(~np.isnan(mat), axis=1)
        # O(S*T log T) sort only when a percentile was actually requested
        sorted_mat = (
            np.sort(mat, axis=1)  # NaNs sort to the end
            if any(a in _PERCENTILE for a in aggs) else None
        )
        for a in aggs:
            if a is AggregationType.COUNT:
                vals = counts.astype(float)
            elif a is AggregationType.AVG:
                vals = np.nansum(mat, axis=1) / np.maximum(counts, 1)
            elif a is AggregationType.LAST:
                last_idx = np.maximum(counts - 1, 0)
                vals = mat[np.arange(s), last_idx]
            else:
                p = _PERCENTILE[a]
                idx = np.maximum((counts * p).astype(int) - 1, 0)
                vals = sorted_mat[np.arange(s), idx]
            for i in range(s):
                out[i][a] = float(vals[i]) if counts[i] > 0 else None
        return out

    def gc(self, now: float) -> int:
        """Drop series with no point in the retention window (reference:
        tsdb head GC / recycleDB)."""
        dead = [
            k for k, ring in self._series.items()
            if ring.size == 0
            or ring.window(now - self.retention_seconds, math.inf)[0].size == 0
        ]
        for k in dead:
            del self._series[k]
        return len(dead)
