from koordinator_tpu.koordlet.metriccache.metric_cache import (
    AggregationType,
    MetricCache,
    MetricKind,
)

__all__ = ["AggregationType", "MetricCache", "MetricKind"]
