from koordinator_tpu.koordlet.statesinformer.states_informer import (
    StatesInformer,
)
from koordinator_tpu.koordlet.statesinformer.nodemetric_reporter import (
    NodeMetricReporter,
)

__all__ = ["StatesInformer", "NodeMetricReporter"]
