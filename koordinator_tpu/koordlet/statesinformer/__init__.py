from koordinator_tpu.koordlet.statesinformer.states_informer import (
    StateKind,
    StatesInformer,
)
from koordinator_tpu.koordlet.statesinformer.nodemetric_reporter import (
    NodeMetricReporter,
)
from koordinator_tpu.koordlet.statesinformer.reporters import (
    DeviceReporter,
    NodeTopologyReporter,
    PodsInformer,
    pod_meta_from_spec,
)

__all__ = [
    "StateKind",
    "StatesInformer",
    "NodeMetricReporter",
    "DeviceReporter",
    "NodeTopologyReporter",
    "PodsInformer",
    "pod_meta_from_spec",
]
