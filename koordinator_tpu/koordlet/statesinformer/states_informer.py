"""States informer: the node agent's view of node / pods / NodeSLO.

Reference: pkg/koordlet/statesinformer/impl/{states_informer.go,
registry.go, callback_runner.go} — a registry of informer plugins keeps
node, pod list, NodeSLO, NodeMetric policy in sync and fans callbacks out
to subscribers (qosmanager strategies re-arm on NodeSLO changes, the
metric reporter on collect-policy changes).

In this framework the control plane is in-process: setters stand in for
the apiserver watch; the callback fan-out and the typed getters keep the
same surface the subsystems program against.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Dict, List, Optional, Sequence

from koordinator_tpu.apis.types import NodeSpec
from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.manager.nodemetric import NodeMetricCollectPolicy
from koordinator_tpu.manager.sloconfig import NodeSLOSpec


class StateKind(enum.Enum):
    NODE = "node"
    PODS = "pods"
    NODE_SLO = "nodeslo"
    COLLECT_POLICY = "collect_policy"
    PVCS = "pvcs"


Callback = Callable[[StateKind, object], None]


class StatesInformer:
    """Typed state + callback fan-out (reference: callback_runner.go)."""

    def __init__(self):
        self._node: Optional[NodeSpec] = None
        self._pods: List[PodMeta] = []
        self._node_slo: NodeSLOSpec = NodeSLOSpec()
        self._collect_policy: Optional[NodeMetricCollectPolicy] = None
        #: claim key ("namespace/name") -> bound PV name (reference:
        #: states_pvc.go volumeNameMap)
        self._volume_names: Dict[str, str] = {}
        self._callbacks: Dict[StateKind, List[Callback]] = {
            k: [] for k in StateKind
        }

    # -- subscribe ----------------------------------------------------------

    def register_callback(self, kind: StateKind, cb: Callback) -> None:
        self._callbacks[kind].append(cb)

    def _fire(self, kind: StateKind, value: object) -> None:
        for cb in self._callbacks[kind]:
            cb(kind, value)

    # -- setters (the "watch" side) -----------------------------------------

    def set_node(self, node: NodeSpec) -> None:
        self._node = node
        self._fire(StateKind.NODE, node)

    def set_pods(self, pods: Sequence[PodMeta]) -> None:
        self._pods = list(pods)
        self._fire(StateKind.PODS, self._pods)

    def set_node_slo(self, slo: NodeSLOSpec) -> None:
        self._node_slo = slo
        self._fire(StateKind.NODE_SLO, slo)

    def set_collect_policy(self, policy: NodeMetricCollectPolicy) -> None:
        self._collect_policy = policy
        self._fire(StateKind.COLLECT_POLICY, policy)

    def upsert_pvc(self, pvc) -> None:
        """PVC add/update (states_pvc.go updateVolumeNameMap)."""
        self._volume_names[pvc.name] = pvc.volume_name
        self._fire(StateKind.PVCS, dict(self._volume_names))

    def remove_pvc(self, claim_key: str) -> None:
        if self._volume_names.pop(claim_key, None) is not None:
            self._fire(StateKind.PVCS, dict(self._volume_names))

    # -- getters (what subsystems consume) ----------------------------------

    def get_node(self) -> Optional[NodeSpec]:
        return self._node

    def running_pods(self) -> List[PodMeta]:
        """PodProvider protocol for the advisor/qosmanager."""
        return self._pods

    def get_node_slo(self) -> NodeSLOSpec:
        return self._node_slo

    def get_collect_policy(self) -> Optional[NodeMetricCollectPolicy]:
        return self._collect_policy

    def get_volume_name(self, claim_key: str) -> str:
        """Bound PV for a "namespace/name" claim key; "" when unknown
        (reference: states_pvc.go GetVolumeName)."""
        return self._volume_names.get(claim_key, "")
