"""NodeMetric reporter: aggregates the metric cache into a NodeMetric.

Reference: pkg/koordlet/statesinformer/impl/states_nodemetric.go —
``collectMetric`` (:332) queries the TSDB for the collect-policy window,
aggregates node + per-pod usage (avg), percentile stats for aggregated-
usage mode, the system residual, prod-tier usage, and the predictor's
prod-reclaimable, then updates the NodeMetric CR status (:244 sync).

Here the produced object is the scheduler-facing
``apis.types.NodeMetric``, so the report loop closes the colocation
cycle in-process: koordlet reports -> manager computes batch resources ->
scheduler places BE pods. Pod aggregation uses the cache's batched
matrix path — all pods reduce in one pass.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from koordinator_tpu.apis.extension import (
    PriorityClass,
    QoSClass,
    ResourceName,
    priority_class_of,
)
from koordinator_tpu.apis.types import NodeMetric
from koordinator_tpu.koordlet.metriccache import (
    AggregationType,
    MetricCache,
    MetricKind,
)
from koordinator_tpu.koordlet.prediction import (
    PeakPredictServer,
    prod_reclaimable,
)
from koordinator_tpu.koordlet.statesinformer.states_informer import (
    StatesInformer,
)

#: percentile -> aggregation type for aggregated-usage mode
_PCTS = {
    50: AggregationType.P50,
    90: AggregationType.P90,
    95: AggregationType.P95,
    99: AggregationType.P99,
}


def _percentile_usages(cpu_row, mem_row) -> Dict[int, dict]:
    """percentile -> sparse usage map from one window's aggregate rows."""
    out: Dict[int, dict] = {}
    for pct, agg in _PCTS.items():
        usage = {}
        if cpu_row[agg] is not None:
            usage[ResourceName.CPU] = int(cpu_row[agg])
        if mem_row[agg] is not None:
            usage[ResourceName.MEMORY] = int(mem_row[agg])
        if usage:
            out[pct] = usage
    return out


class NodeMetricReporter:
    def __init__(self, metric_cache: MetricCache, informer: StatesInformer,
                 predict_server: Optional[PeakPredictServer] = None):
        self.metric_cache = metric_cache
        self.informer = informer
        self.predict_server = predict_server
        self.last_report: Optional[NodeMetric] = None

    def _primary_duration(self) -> float:
        """The declared policy window — the SINGLE site defining both
        the query window and the reported aggregated_duration key."""
        policy = self.informer.get_collect_policy()
        return float(policy.aggregate_duration_seconds if policy else 300)

    def _window(self, now: float) -> float:
        return now - self._primary_duration()

    def report(self, now: float) -> Optional[NodeMetric]:
        node = self.informer.get_node()
        if node is None:
            return None
        mc = self.metric_cache
        primary_dur = self._primary_duration()
        start = now - primary_dur
        A = AggregationType

        metric = NodeMetric(node_name=node.name, update_time=now)
        policy = self.informer.get_collect_policy()
        if policy is not None:
            metric.report_interval = float(policy.report_interval_seconds)

        # node + system usage (avg over the window) + aggregated
        # percentiles — one batched pass for the primary window
        node_aggs = mc.aggregate_batch(
            [(MetricKind.NODE_CPU_USAGE, None),
             (MetricKind.NODE_MEMORY_USAGE, None),
             (MetricKind.SYS_CPU_USAGE, None),
             (MetricKind.SYS_MEMORY_USAGE, None)],
            start, now, [A.AVG] + list(_PCTS.values()),
        )
        cpu_row, mem_row, sys_cpu_row, sys_mem_row = node_aggs
        if cpu_row[A.AVG] is not None:
            metric.node_usage[ResourceName.CPU] = int(cpu_row[A.AVG])
        if mem_row[A.AVG] is not None:
            metric.node_usage[ResourceName.MEMORY] = int(mem_row[A.AVG])
        metric.aggregated_usage = _percentile_usages(cpu_row, mem_row)
        if metric.aggregated_usage:
            metric.aggregated_duration = primary_dur
        # extra aggregation windows (reference: AggregatePolicy.Durations
        # -> one AggregatedNodeUsages + AggregatedSystemUsages entry
        # each); node + system series reduce in ONE batched pass per
        # window
        for dur in getattr(policy, "aggregate_durations", ()) or ():
            dur = float(dur)
            if dur == primary_dur:
                continue
            w_cpu, w_mem, ws_cpu, ws_mem = mc.aggregate_batch(
                [(MetricKind.NODE_CPU_USAGE, None),
                 (MetricKind.NODE_MEMORY_USAGE, None),
                 (MetricKind.SYS_CPU_USAGE, None),
                 (MetricKind.SYS_MEMORY_USAGE, None)],
                now - dur, now, list(_PCTS.values()),
            )
            by_pct = _percentile_usages(w_cpu, w_mem)
            if by_pct:
                metric.aggregated_windows[dur] = by_pct
            sys_pct = _percentile_usages(ws_cpu, ws_mem)
            if sys_pct:
                metric.aggregated_system_usage[dur] = sys_pct

        # per-pod usage: ONE batched matrix reduction for all pods
        pods = self.informer.running_pods()
        reqs = []
        for pod in pods:
            reqs.append((MetricKind.POD_CPU_USAGE, {"pod": pod.uid}))
            reqs.append((MetricKind.POD_MEMORY_USAGE, {"pod": pod.uid}))
        pod_aggs = mc.aggregate_batch(reqs, start, now, [A.AVG])
        prod_cpu = prod_mem = 0
        for i, pod in enumerate(pods):
            cpu = pod_aggs[2 * i][A.AVG]
            mem = pod_aggs[2 * i + 1][A.AVG]
            usage = {}
            if cpu is not None:
                usage[ResourceName.CPU] = int(cpu)
            if mem is not None:
                usage[ResourceName.MEMORY] = int(mem)
            if usage:
                metric.pod_usages[pod.uid] = usage
                # Reference GetPodPriorityClassWithDefault (slo-controller
                # plugin.go:297): resolve from the priority band, default
                # unlabeled/priority-0 pods to PROD (BE qos -> BATCH) so
                # ordinary k8s pods' usage stays in the HP sums.
                cls = priority_class_of(value=pod.priority or None)
                if cls == PriorityClass.NONE:
                    cls = (
                        PriorityClass.BATCH
                        if pod.qos == QoSClass.BE
                        else PriorityClass.PROD
                    )
                metric.pod_priority_class[pod.uid] = cls
                is_prod = cls == PriorityClass.PROD
                if is_prod:
                    prod_cpu += usage.get(ResourceName.CPU, 0)
                    prod_mem += usage.get(ResourceName.MEMORY, 0)
        metric.prod_usage = {
            ResourceName.CPU: prod_cpu, ResourceName.MEMORY: prod_mem
        }

        # storage accounting: per-device disk throughput/io-util from the
        # nodestorageinfo collector (VERDICT r3 #6 — volume usage rides
        # the NodeMetric onto the bus)
        for dev in mc.label_values(MetricKind.NODE_DISK_READ_BPS, "dev"):
            labels = {"dev": dev}
            rd = mc.aggregate(
                MetricKind.NODE_DISK_READ_BPS, labels, start, now, A.AVG
            )
            wr = mc.aggregate(
                MetricKind.NODE_DISK_WRITE_BPS, labels, start, now, A.AVG
            )
            util = mc.aggregate(
                MetricKind.NODE_DISK_IO_UTIL, labels, start, now, A.AVG
            )
            if rd is None and wr is None and util is None:
                continue
            from koordinator_tpu.apis.types import DiskUsage

            metric.disk_usages[dev] = DiskUsage(
                read_bps=int(rd or 0),
                write_bps=int(wr or 0),
                io_util_pct=int(util or 0),
            )

        # system residual: avg + primary-window percentiles (reference:
        # AggregatedSystemUsages, states_nodemetric.go:342), from the
        # rows the primary batch already produced
        if sys_cpu_row[A.AVG] is not None:
            metric.sys_usage[ResourceName.CPU] = int(sys_cpu_row[A.AVG])
        if sys_mem_row[A.AVG] is not None:
            metric.sys_usage[ResourceName.MEMORY] = int(sys_mem_row[A.AVG])
        sys_pct = _percentile_usages(sys_cpu_row, sys_mem_row)
        if sys_pct:
            metric.aggregated_system_usage[primary_dur] = sys_pct

        # host applications (reference: NodeMetric HostApplicationMetric)
        apps = self.informer.get_node_slo().host_applications
        if apps:
            app_reqs = []
            for app in apps:
                app_reqs.append(
                    (MetricKind.HOST_APP_CPU_USAGE, {"app": app.name})
                )
                app_reqs.append(
                    (MetricKind.HOST_APP_MEMORY_USAGE, {"app": app.name})
                )
            app_aggs = mc.aggregate_batch(app_reqs, start, now, [A.AVG])
            for i, app in enumerate(apps):
                usage = {}
                cpu = app_aggs[2 * i][A.AVG]
                mem = app_aggs[2 * i + 1][A.AVG]
                if cpu is not None:
                    usage[ResourceName.CPU] = int(cpu)
                if mem is not None:
                    usage[ResourceName.MEMORY] = int(mem)
                if usage:
                    metric.host_app_usages[app.name] = usage
                    metric.host_app_qos[app.name] = app.qos

        # predictor: prod reclaimable (feeds MID resources)
        if self.predict_server is not None:
            rec = prod_reclaimable(
                self.predict_server,
                [(p.uid, p.cpu_request_mcpu, p.memory_request_mib)
                 for p in pods
                 if p.qos in (QoSClass.LS, QoSClass.LSR, QoSClass.LSE)],
                now,
            )
            metric.prod_reclaimable = {
                ResourceName.CPU: rec["cpu"],
                ResourceName.MEMORY: rec["memory"],
            }

        self.last_report = metric
        return metric
