"""Reporting informers: kubelet-style pod source, NodeResourceTopology,
and Device reporting — the koordlet side of the topology/device pipeline
the scheduler's NUMA/DeviceShare plugins consume.

Reference: pkg/koordlet/statesinformer/impl/
- ``kubelet_stub.go`` + pods informer: scrape the kubelet for the node's
  pod list and publish it into the informer;
- ``states_noderesourcetopology.go:243-320`` (calcNodeTopo /
  calTopologyZoneList): discover CPU topology + per-NUMA resources and
  report the NodeResourceTopology CR the scheduler's
  topology-options manager syncs;
- ``states_device_linux.go``: enumerate accelerator devices and report
  the Device CR for the deviceshare cache.

Here "reporting" is a callback (the in-process API-server bus): the
scheduler wires ``Scheduler.update_node_topology`` /
``Scheduler.update_node_devices`` as the sinks.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Protocol, Sequence

import numpy as np

from koordinator_tpu.apis.extension import ResourceName
from koordinator_tpu.device.cache import DeviceEntry
from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.koordlet.statesinformer.states_informer import (
    StatesInformer,
)
from koordinator_tpu.koordlet.system.cgroup import SystemConfig
from koordinator_tpu.koordlet.system.cpuinfo import (
    ProcessorInfo,
    read_cpu_infos,
)
from koordinator_tpu.numa.hints import NUMATopologyPolicy
from koordinator_tpu.numa.manager import TopologyOptions
from koordinator_tpu.numa.topology import CPUTopology


class KubeletStub(Protocol):
    """The kubelet scrape seam (kubelet_stub.go GetAllPods)."""

    def get_all_pods(self) -> Sequence[PodMeta]: ...


def pod_meta_from_spec(pod) -> PodMeta:
    """Scheduler-side PodSpec -> node-agent PodMeta (the projection a
    kubelet scrape would yield for a pod bound to this node): kube-QoS
    tier from the koordinator QoS class, one ``main`` container, batch
    resources populated for pods running on reclaimed batch-* columns."""
    from koordinator_tpu.apis.extension import QoSClass
    from koordinator_tpu.koordlet.metricsadvisor.framework import (
        ContainerBatchResources,
    )

    # kubelet layout: BE -> besteffort, LS -> burstable, LSR/LSE
    # (guaranteed) sit DIRECTLY under kubepods — cgreconcile's tier
    # rollups and memory.min protection depend on this nesting. Dirs
    # key by pod UID (like the kubelet), not name: same-named pods in
    # different namespaces must not share a cgroup.
    uid_dir = "pod" + pod.uid.replace("/", "_")
    if pod.qos == QoSClass.BE:
        base = f"kubepods/besteffort/{uid_dir}"
    elif pod.qos in (QoSClass.LSR, QoSClass.LSE):
        base = f"kubepods/{uid_dir}"
    else:
        base = f"kubepods/burstable/{uid_dir}"
    meta = PodMeta(
        pod.uid, base, pod.qos,
        containers={"main": f"{base}/main"},
        name=pod.name,
        priority=pod.priority,
        cpu_request_mcpu=pod.requests.get(ResourceName.CPU, 0),
        cpu_limit_mcpu=pod.limits.get(ResourceName.CPU, 0),
        memory_request_mib=pod.requests.get(ResourceName.MEMORY, 0),
        memory_limit_mib=pod.limits.get(ResourceName.MEMORY, 0),
        labels=dict(pod.labels),
        annotations=dict(pod.annotations),
        container_limits_mcpu={
            "main": pod.limits.get(ResourceName.CPU, 0)
        },
        volumes=dict(pod.volumes),
    )
    batch_cpu = pod.requests.get(ResourceName.BATCH_CPU, 0)
    if batch_cpu:
        limit_cpu = pod.limits.get(ResourceName.BATCH_CPU, batch_cpu)
        meta.batch_resources["main"] = ContainerBatchResources(
            request_mcpu=batch_cpu,
            limit_mcpu=limit_cpu,
            memory_limit_bytes=pod.limits.get(
                ResourceName.BATCH_MEMORY,
                pod.requests.get(ResourceName.BATCH_MEMORY, 0),
            ) * 1024 * 1024,
        )
    return meta


class PodsInformer:
    """Polls the kubelet stub and publishes the pod list (the reference's
    pods informer plugin; the poll interval is the caller's tick)."""

    def __init__(self, stub: KubeletStub, informer: StatesInformer):
        self.stub = stub
        self.informer = informer

    def sync(self) -> List[PodMeta]:
        pods = list(self.stub.get_all_pods())
        self.informer.set_pods(pods)
        return pods


@dataclasses.dataclass
class NodeTopologyReport:
    """What the NRT CR carries (zones: cpu topology + per-NUMA amounts)."""

    node_name: str
    options: TopologyOptions


class NodeTopologyReporter:
    """Builds TopologyOptions from the discovered CPU topology and
    per-NUMA memory, and reports through the sink
    (states_noderesourcetopology.go calcNodeTopo)."""

    def __init__(
        self,
        node_name: str,
        system_config: SystemConfig,
        report: Callable[[str, TopologyOptions], None],
        policy: NUMATopologyPolicy = NUMATopologyPolicy.NONE,
        numa_memory_mib: Optional[Dict[int, int]] = None,
        cpu_infos: Optional[Sequence[ProcessorInfo]] = None,
    ):
        self.node_name = node_name
        self.system_config = system_config
        self.report = report
        self.policy = policy
        #: per-NUMA memory; None = split evenly is impossible without a
        #: source, so memory is omitted from the zones
        self.numa_memory_mib = numa_memory_mib
        self._cpu_infos = cpu_infos
        self.last_report: Optional[NodeTopologyReport] = None

    def sync(self) -> Optional[NodeTopologyReport]:
        infos = (
            list(self._cpu_infos)
            if self._cpu_infos is not None
            else read_cpu_infos(self.system_config)
        )
        if not infos:
            return None
        infos.sort(key=lambda p: p.cpu_id)
        n = infos[-1].cpu_id + 1
        present = {p.cpu_id for p in infos}
        # offline / hot-removed cpus leave id holes: they must be neither
        # pinnable nor counted as capacity — reserve them out
        holes = [cpu for cpu in range(n) if cpu not in present]
        core = np.zeros(n, dtype=np.int64)
        node = np.zeros(n, dtype=np.int64)
        socket = np.zeros(n, dtype=np.int64)
        for p in infos:
            # cores are socket-local ids in /proc/cpuinfo; globalize
            core[p.cpu_id] = p.socket_id * 10_000 + p.core_id
            node[p.cpu_id] = p.node_id
            socket[p.cpu_id] = p.socket_id
        for cpu in holes:  # phantom slots get a unique non-colliding core
            core[cpu] = -1 - cpu
        # densify core ids
        _, core = np.unique(core, return_inverse=True)
        topology = CPUTopology(
            core_id=core, node_id=node, socket_id=socket
        )
        per_node_cpus: Dict[int, int] = {}
        for p in infos:  # count only PRESENT cpus toward capacity
            per_node_cpus[p.node_id] = per_node_cpus.get(p.node_id, 0) + 1
        numa_resources: Dict[int, Dict] = {}
        for numa_id in sorted(per_node_cpus):
            res = {ResourceName.CPU: per_node_cpus[numa_id] * 1000}
            if self.numa_memory_mib is not None:
                res[ResourceName.MEMORY] = self.numa_memory_mib.get(numa_id, 0)
            numa_resources[numa_id] = res
        options = TopologyOptions(
            cpu_topology=topology,
            policy=self.policy,
            numa_node_resources=numa_resources,
            reserved_cpus=tuple(holes),
        )
        self.last_report = NodeTopologyReport(self.node_name, options)
        self.report(self.node_name, options)
        return self.last_report


class DeviceSource(Protocol):
    """Accelerator inventory seam (states_device_linux.go enumerates via
    NVML; tests and TPU hosts provide typed inventories)."""

    def list_devices(self) -> Sequence[DeviceEntry]: ...


class DeviceReporter:
    """Reports the node's device inventory to the scheduler's device
    cache (the Device CR reporting path)."""

    def __init__(
        self,
        node_name: str,
        source: DeviceSource,
        report: Callable[[str, Sequence[DeviceEntry]], None],
    ):
        self.node_name = node_name
        self.source = source
        self.report = report

    def sync(self) -> List[DeviceEntry]:
        entries = list(self.source.list_devices())
        self.report(self.node_name, entries)
        return entries
