"""CPU burst: let latency-sensitive containers briefly exceed their cfs
quota to absorb spikes.

Reference: pkg/koordlet/qosmanager/plugins/cpuburst/cpu_burst.go — for
each non-BE container with a cpu limit, when the burst policy allows:

  cpu.cfs_burst_us = limit_cores * period * CPUBurstPercent / 100

(burst buffer the kernel may carry over between periods). The cfs-quota-
burst half (scaling quota up under throttling, bounded by
CFSQuotaBurstPercent and the node share-pool threshold) degrades back
when node utilization crosses SharePoolThresholdPercent.
"""

from __future__ import annotations

from typing import Optional

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.metriccache import AggregationType, MetricKind
from koordinator_tpu.koordlet.qosmanager.framework import QoSContext
from koordinator_tpu.koordlet.resourceexecutor import CgroupUpdater
from koordinator_tpu.koordlet.system.cgroup import CFS_PERIOD_US


class CPUBurst:
    name = "cpuburst"
    interval_seconds = 1.0

    def enabled(self, ctx: QoSContext) -> bool:
        return ctx.node_slo.cpu_burst_strategy.policy != "none"

    def _node_share_pool_overloaded(self, ctx: QoSContext,
                                    now: float) -> bool:
        """Degrade bursts when node cpu usage crosses the share-pool
        threshold (cpu_burst.go shared-pool check)."""
        strategy = ctx.node_slo.cpu_burst_strategy
        if ctx.node_capacity_mcpu <= 0:
            return False
        usage = ctx.metric_cache.aggregate(
            MetricKind.NODE_CPU_USAGE,
            start=now - ctx.metric_collect_interval, end=now,
            agg=AggregationType.LAST,
        )
        if usage is None:
            return False
        pct = usage / ctx.node_capacity_mcpu * 100.0
        return pct >= strategy.share_pool_threshold_percent

    def execute(self, ctx: QoSContext, now: float) -> None:
        strategy = ctx.node_slo.cpu_burst_strategy
        burst_allowed = strategy.policy in ("auto", "cpuBurstOnly") and (
            not self._node_share_pool_overloaded(ctx, now)
        )
        for pod in ctx.pod_provider.running_pods():
            if pod.qos is QoSClass.BE or pod.cpu_limit_mcpu <= 0:
                continue
            if burst_allowed:
                burst_us = (
                    pod.cpu_limit_mcpu * CFS_PERIOD_US
                    * strategy.cpu_burst_percent // 100 // 1000
                )
            else:
                burst_us = 0
            ctx.executor.update(True, CgroupUpdater(
                "cpu.cfs_burst_us", pod.cgroup_dir, str(burst_us)))
            for cdir in pod.containers.values():
                ctx.executor.update(True, CgroupUpdater(
                    "cpu.cfs_burst_us", cdir, str(burst_us)))
