"""CPU burst: let latency-sensitive containers briefly exceed their cfs
quota to absorb spikes.

Reference: pkg/koordlet/qosmanager/plugins/cpuburst/cpu_burst.go. Two
halves:

1. **Static burst buffer** (`applyCPUBurst` :561): for each non-BE pod
   with a cpu limit, ``cpu.cfs_burst_us = limit_cores * period *
   CPUBurstPercent / 100``. (Extension beyond the reference: the buffer
   degrades to 0 when the share pool crosses the threshold — the
   reference leaves the static value alone.)

2. **CFS quota burst** (`applyCFSQuotaBurst` :341): throttled pods get
   their cfs quota scaled UP in 1.2x steps, bounded by
   ``base * CFSQuotaBurstPercent / 100``; a token-bucket limiter over
   ``CFSQuotaBurstPeriodSeconds`` (:122-151: capacity =
   period * (maxScale-100) percent-seconds, consumed while usage > 100%
   of limit, refilled while < 60%) forces 0.8x scale-DOWN steps when
   exhausted; the node share-pool state overrides: overload -> scale
   down, cooling (>= 0.9x threshold, :52) -> hold (changeOperationByNode
   :701-709). Node share-pool accounting excludes LSE/LSR requests from
   the total and LSE/LSR/BE usage from the usage (:296-316).

Granularity: this framework's throttle/usage metrics are pod-level
(POD_CPU_THROTTLED_RATIO / POD_CPU_USAGE), so operations are generated
per pod and applied to the pod dir and every container dir. The limiter
seeds DETERMINISTICALLY at half capacity (the reference randomizes the
initial fill in [0, 0.5); determinism is a framework principle).
"""

from __future__ import annotations

import math
from typing import Dict, Optional

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.metriccache import AggregationType, MetricKind
from koordinator_tpu.koordlet.qosmanager.framework import QoSContext
from koordinator_tpu.koordlet.resourceexecutor import CgroupUpdater
from koordinator_tpu.koordlet.system.cgroup import (
    CFS_PERIOD_US,
    CPU_CFS_QUOTA,
)

#: cfs quota scale steps (cpu_burst.go:49-50)
CFS_INCREASE_STEP = 1.2
CFS_DECREASE_STEP = 0.8
#: cooling band starts at this fraction of the share-pool threshold (:52)
SHARE_POOL_COOLING_RATIO = 0.9
#: limiter consume/save usage thresholds, percent of limit (:54-55)
LIMITER_CONSUME_ABOVE_PCT = 100
LIMITER_SAVE_BELOW_PCT = 60

#: node share-pool states (cpu_burst.go:83-94)
OVERLOAD, COOLING, IDLE, UNKNOWN = "overload", "cooling", "idle", "unknown"


class BurstLimiter:
    """Token bucket in percent-seconds (cpu_burst.go burstLimiter)."""

    def __init__(self, period_sec: int, max_scale_pct: int):
        self.capacity = float(period_sec * (max_scale_pct - 100))
        # deterministic half-fill (reference: random in [0, 0.5)*cap)
        self.token = self.capacity / 2
        self.last: Optional[float] = None

    def update_if_changed(self, period_sec: int, max_scale_pct: int) -> None:
        new_capacity = float(period_sec * (max_scale_pct - 100))
        if new_capacity != self.capacity:
            self.__init__(period_sec, max_scale_pct)

    #: a gap longer than this means the plugin was not reconciling
    #: (disabled, or the daemon was down — the reference's limiter is
    #: in-memory so a restart starts fresh); integrating it as one dt
    #: would slam the bucket to +-capacity
    GAP_RESET_SEC = 30.0

    def allow(self, now: float, usage_scale_pct: int) -> bool:
        # float dt throughout: the reference truncates to whole seconds
        # (:142), which at a ~1s tick cadence would discard most of the
        # elapsed time and let the bucket never drain
        dt = 0.0 if self.last is None else max(now - self.last, 0.0)
        if dt > self.GAP_RESET_SEC:
            dt = 0.0
        if usage_scale_pct >= LIMITER_CONSUME_ABOVE_PCT:
            self.token -= (usage_scale_pct - 100) * dt
        elif usage_scale_pct < LIMITER_SAVE_BELOW_PCT:
            self.token += (100 - usage_scale_pct) * dt
        self.token = max(min(self.token, self.capacity), -self.capacity)
        self.last = now
        return self.token > 0


class CPUBurst:
    name = "cpuburst"
    interval_seconds = 1.0

    def __init__(self):
        #: pod uid -> BurstLimiter (containerLimiter analogue)
        self._limiters: Dict[str, BurstLimiter] = {}
        #: True once any burst/scale write happened: a policy flip to
        #: "none" must still run ONE cleanup pass resetting quotas and
        #: burst buffers, or disabling the feature would leave pods with
        #: a permanent 3x quota override
        self._dirty: bool = False

    def enabled(self, ctx: QoSContext) -> bool:
        return (
            ctx.node_slo.cpu_burst_strategy.policy != "none" or self._dirty
        )

    # -- node share-pool state ----------------------------------------------

    def _pod_usages_last(self, ctx: QoSContext, pods,
                         now: float) -> Dict[str, Optional[float]]:
        """One LAST aggregation per pod per tick, shared by the node
        share-pool accounting and the limiter."""
        return {
            pod.uid: ctx.metric_cache.aggregate(
                MetricKind.POD_CPU_USAGE, {"pod": pod.uid},
                start=now - ctx.metric_collect_interval, end=now,
                agg=AggregationType.LAST,
            )
            for pod in pods
        }

    def _base_quota_us(self, ctx: QoSContext, limit_mcpu: int) -> int:
        """The pod's steady-state quota: the SAME formula the
        cpu-normalization hook writes (milli_cpu_to_quota, then
        ceil(quota/ratio) when a ratio is active) so burst scaling floors
        at the normalized value instead of ping-ponging against it."""
        from koordinator_tpu.koordlet.runtimehooks.protocol import (
            milli_cpu_to_quota,
        )

        quota = milli_cpu_to_quota(limit_mcpu)
        if quota <= 0:
            return quota
        ratio = ctx.cpu_normalization_ratio
        if ratio and ratio > 1.0:
            quota = math.ceil(quota / ratio)
        return quota

    def _node_burst_state(self, ctx: QoSContext, usages, now: float) -> str:
        """cpu_burst.go:262-340 getNodeStateForBurst, pod-granular."""
        strategy = ctx.node_slo.cpu_burst_strategy
        if ctx.node_capacity_mcpu <= 0:
            return UNKNOWN
        node_usage = ctx.metric_cache.aggregate(
            MetricKind.NODE_CPU_USAGE,
            start=now - ctx.metric_collect_interval, end=now,
            agg=AggregationType.LAST,
        )
        if node_usage is None:
            return UNKNOWN
        pool_total = float(ctx.node_capacity_mcpu)
        pool_usage = float(node_usage)
        for pod in ctx.pod_provider.running_pods():
            if pod.qos in (QoSClass.LSE, QoSClass.LSR):
                pool_total -= pod.cpu_request_mcpu
            if pod.qos in (QoSClass.LSE, QoSClass.LSR, QoSClass.BE):
                usage = usages.get(pod.uid)
                if usage is not None:
                    pool_usage -= usage
        threshold = strategy.share_pool_threshold_percent / 100.0
        cooling = threshold * SHARE_POOL_COOLING_RATIO
        ratio = 1.0 if pool_total <= 0 else pool_usage / pool_total
        if ratio >= threshold:
            return OVERLOAD
        if ratio >= cooling:
            return COOLING
        return IDLE

    # -- cfs quota burst ----------------------------------------------------

    def _quota_operation(self, ctx: QoSContext, pod, strategy, usages,
                         now: float) -> str:
        """genOperationByContainer (:467-501), pod-granular: 'up',
        'down', 'remain', or 'reset'.

        The limiter ticks BEFORE the policy check — the reference runs
        cfsBurstAllowedByLimiter first — so across a disabled stretch
        the clock keeps advancing and tokens keep refilling while usage
        is low, instead of freezing and then integrating the whole gap
        as one dt on re-enable (ADVICE r4)."""
        allowed = True
        if (strategy.cfs_quota_burst_period_seconds >= 0
                and strategy.cfs_quota_burst_percent >= 100):
            limiter = self._limiters.get(pod.uid)
            if limiter is None:
                limiter = self._limiters[pod.uid] = BurstLimiter(
                    strategy.cfs_quota_burst_period_seconds,
                    strategy.cfs_quota_burst_percent,
                )
            else:
                limiter.update_if_changed(
                    strategy.cfs_quota_burst_period_seconds,
                    strategy.cfs_quota_burst_percent,
                )
            usage = usages.get(pod.uid)
            scale_pct = 100
            if usage is not None and pod.cpu_limit_mcpu > 0:
                scale_pct = int(usage / pod.cpu_limit_mcpu * 100)
            allowed = limiter.allow(now, scale_pct)
        if strategy.policy not in ("auto", "cfsQuotaBurstOnly"):
            return "reset"
        if strategy.cfs_quota_burst_period_seconds >= 0:
            if strategy.cfs_quota_burst_percent < 100:
                return "down"  # illegal config -> not allowed (:558-561)
            if not allowed:
                return "down"
        throttled = ctx.metric_cache.aggregate(
            MetricKind.POD_CPU_THROTTLED_RATIO, {"pod": pod.uid},
            start=now - ctx.metric_collect_interval, end=now,
            agg=AggregationType.LAST,
        )
        if throttled is None:
            return "remain"
        return "up" if throttled > 0 else "remain"

    @staticmethod
    def _apply_node_state(state: str, op: str) -> str:
        """changeOperationByNode (:701-709)."""
        if state == OVERLOAD and op in ("up", "remain"):
            return "down"
        if state in (COOLING, UNKNOWN) and op == "up":
            return "remain"
        return op

    def _scale_quota_dir(self, ctx: QoSContext, cgroup_dir: str,
                         base: int, ceil: int, op: str) -> str:
        """Scale one dir's cfs quota (applyCFSQuotaBurst :397-407):
        target = clamp(step(current), base, ceil). Returns "wrote",
        "unreadable" (dir not materialized — the cleanup pass must stay
        armed), or "noop"."""
        if base <= 0:
            return "noop"
        try:
            raw = CPU_CFS_QUOTA.read(cgroup_dir, ctx.system_config)
            current = int(raw)
        except (OSError, ValueError):
            return "unreadable"  # not materialized yet: skip this round
        if current <= 0:
            return "noop"  # unlimited: nothing to scale (:389-392)
        if op == "up":
            target = int(current * CFS_INCREASE_STEP)
        elif op == "down":
            target = int(current * CFS_DECREASE_STEP)
        elif op == "reset":
            target = base
        else:
            return "noop"
        target = max(base, min(target, ceil))
        if target == current:
            return "noop"
        ctx.executor.update(True, CgroupUpdater(
            "cpu.cfs_quota_us", cgroup_dir, str(target)))
        self._dirty = True
        ctx.log("cpuburst", cgroup_dir, "cfs_quota_burst",
                f"{op}: {current} -> {target}")
        return "wrote"

    # -- main ---------------------------------------------------------------

    def execute(self, ctx: QoSContext, now: float) -> None:
        strategy = ctx.node_slo.cpu_burst_strategy
        # policy flipped to "none" with scaled state outstanding: one
        # cleanup pass resets quota to base and the burst buffer to 0
        cleanup = strategy.policy == "none"
        pods = ctx.pod_provider.running_pods()
        usages = (
            {} if cleanup else self._pod_usages_last(ctx, pods, now)
        )
        node_state = (
            UNKNOWN if cleanup else self._node_burst_state(ctx, usages, now)
        )
        burst_allowed = strategy.policy in ("auto", "cpuBurstOnly") and (
            node_state != OVERLOAD
        )
        cleanup_incomplete = False
        live_uids = set()
        for pod in pods:
            if pod.qos is QoSClass.BE or pod.cpu_limit_mcpu <= 0:
                continue
            live_uids.add(pod.uid)
            # -- half 1: static burst buffer (applyCPUBurst) -------------
            if burst_allowed:
                burst_us = (
                    pod.cpu_limit_mcpu * CFS_PERIOD_US
                    * strategy.cpu_burst_percent // 100 // 1000
                )
            else:
                burst_us = 0
            for bdir in [pod.cgroup_dir, *pod.containers.values()]:
                if ctx.executor.update(True, CgroupUpdater(
                        "cpu.cfs_burst_us", bdir, str(burst_us))):
                    self._dirty = self._dirty or burst_us > 0

            # -- half 2: cfs quota burst (applyCFSQuotaBurst) ------------
            if cleanup:
                op = "reset"
            else:
                op = self._apply_node_state(
                    node_state,
                    self._quota_operation(ctx, pod, strategy, usages, now),
                )
            base = self._base_quota_us(ctx, pod.cpu_limit_mcpu)
            ceil = base
            if not cleanup and strategy.cfs_quota_burst_percent > 100:
                ceil = base * strategy.cfs_quota_burst_percent // 100
            unreadable = (
                self._scale_quota_dir(ctx, pod.cgroup_dir, base, ceil, op)
                == "unreadable"
            )
            for name, cdir in pod.containers.items():
                climit = pod.container_limits_mcpu.get(name, 0)
                if climit <= 0:
                    continue
                cbase = self._base_quota_us(ctx, climit)
                cceil = cbase
                if not cleanup and strategy.cfs_quota_burst_percent > 100:
                    cceil = cbase * strategy.cfs_quota_burst_percent // 100
                if self._scale_quota_dir(
                        ctx, cdir, cbase, cceil, op) == "unreadable":
                    unreadable = True
            if cleanup and unreadable:
                cleanup_incomplete = True
        if cleanup:
            # stay armed (dirty) while any scaled dir was unreadable this
            # pass, so the reset retries next tick instead of stranding a
            # burst quota override. (A pod absent from running_pods()
            # during the window is the residual gap — same exposure the
            # reference has when a pod vanishes mid-reconcile.)
            if not cleanup_incomplete:
                self._dirty = False
                self._limiters.clear()
            return
        # limiter recycle (Recycle :638-645)
        for uid in list(self._limiters):
            if uid not in live_uids:
                del self._limiters[uid]
