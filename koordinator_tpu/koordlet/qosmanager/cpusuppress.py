"""BE CPU suppression: dynamically shrink what best-effort pods may use.

Reference: pkg/koordlet/qosmanager/plugins/cpusuppress/cpu_suppress.go.
The invariant (cpu_suppress.go:151-163):

  suppress(BE) := node.Capacity * SLOPercent
                  - pod(non-BE).Used
                  - max(system.Used, node.reserved)

with ``system.Used = max(node.Used - Σ pod.Used, 0)``
(helpers/calculator.go:38-80). The budget is applied either as a cpuset
(scatter across NUMA nodes, paired by hyperthread core, never below 2
cpus, growth rate-limited to ceil(10%) of the node's cpus per round —
cpu_suppress.go:653 calculateBESuppressCPUSetPolicy, :392) or as a cfs
quota on the BE tier cgroup (quota = mCPU * period / 1000, min 2000us,
small deltas bypassed, increases capped at 10% of capacity per round —
cpu_suppress.go:589-628 adjustByCfsQuota).

Cpuset writes are hierarchy-safe: union first from upper to lower, then
the real target from lower to upper (applyCPUSetWithNonePolicy) — here
via the executor's leveled merge batch.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.metriccache import AggregationType, MetricKind
from koordinator_tpu.koordlet.qosmanager.framework import CPUInfo, QoSContext
from koordinator_tpu.koordlet.resourceexecutor import (
    CgroupUpdater,
    merge_if_cpuset_looser,
)
from koordinator_tpu.koordlet.resourceexecutor.executor import (
    _parse_cpuset,
    parse_cfs_quota,
)
from koordinator_tpu.koordlet.system.cgroup import (
    CFS_PERIOD_US,
    CPU_CFS_QUOTA,
    CPU_SET,
)

BE_MIN_QUOTA_US = 2000
SUPPRESS_BYPASS_QUOTA_DELTA_RATIO = 0.01
BE_MAX_INCREASE_CPU_PERCENT = 0.1


def calculate_be_suppress_mcpu(
    capacity_mcpu: int,
    threshold_percent: int,
    node_used_mcpu: float,
    pod_used_mcpu: Dict[str, float],
    non_be_uids: set,
    reserved_mcpu: int,
) -> int:
    """The suppress budget in mCPU (cpu_suppress.go:137-163)."""
    all_used = sum(pod_used_mcpu.values())
    non_be_used = sum(
        u for uid, u in pod_used_mcpu.items() if uid in non_be_uids
    )
    system_used = max(node_used_mcpu - all_used, 0.0)
    system_or_reserved = max(system_used, float(reserved_mcpu))
    budget = (
        capacity_mcpu * threshold_percent / 100.0
        - non_be_used
        - system_or_reserved
    )
    return int(budget)


def select_suppress_cpus(
    want_cpus: int, cpu_infos: List[CPUInfo], old_count: int
) -> List[int]:
    """Pick cpu ids for the BE cpuset: scattered across NUMA nodes,
    hyperthread-paired, at least 2, growth rate-limited
    (cpu_suppress.go:653 + :392 beMaxIncreaseCpuNum)."""
    n = len(cpu_infos)
    if n == 0:
        return []
    max_increase = math.ceil(n * BE_MAX_INCREASE_CPU_PERCENT)
    if old_count > 0 and want_cpus > old_count + max_increase:
        want_cpus = old_count + max_increase
    want_cpus = max(2, min(want_cpus, n))

    # bucket per (numa node, socket), each sorted by (core, cpu) so HT
    # siblings are adjacent
    buckets: Dict[Tuple[int, int], List[CPUInfo]] = {}
    for info in cpu_infos:
        buckets.setdefault((info.node_id, info.socket_id), []).append(info)
    ordered = sorted(
        (sorted(b, key=lambda c: (c.core_id, c.cpu_id))
         for b in buckets.values()),
        key=lambda b: (-len(b), b[0].cpu_id),
    )

    picked: List[int] = []
    picked_set = set()
    # round-robin: take a full HT core pair from each bucket in turn
    progress = True
    while len(picked) + 1 < want_cpus and progress:
        progress = False
        for bucket in ordered:
            if len(picked) + 1 >= want_cpus:
                break
            for i in range(len(bucket) - 1):
                a, b = bucket[i], bucket[i + 1]
                if a.cpu_id in picked_set or b.cpu_id in picked_set:
                    continue
                if a.core_id == b.core_id:
                    picked.extend([a.cpu_id, b.cpu_id])
                    picked_set.update([a.cpu_id, b.cpu_id])
                    progress = True
                    break
    if len(picked) < want_cpus:
        for bucket in ordered:
            for info in bucket:
                if len(picked) >= want_cpus:
                    break
                if info.cpu_id not in picked_set:
                    picked.append(info.cpu_id)
                    picked_set.add(info.cpu_id)
    return sorted(picked)


def cpuset_str(cpu_ids: List[int]) -> str:
    return ",".join(str(c) for c in sorted(cpu_ids))


class CPUSuppress:
    """The strategy plugin."""

    name = "cpusuppress"
    interval_seconds = 1.0

    def __init__(self):
        self._suppressed_policy: Dict[str, bool] = {}

    def enabled(self, ctx: QoSContext) -> bool:
        return True

    # -- helpers -------------------------------------------------------------

    def _be_cpuset_dirs(self, ctx: QoSContext) -> List[List[str]]:
        """BE cgroup dirs by level: [tier], [pods], [containers]."""
        tier = [ctx.be_cgroup_dir]
        pods, containers = [], []
        for pod in ctx.pod_provider.running_pods():
            if pod.qos is QoSClass.BE:
                pods.append(pod.cgroup_dir)
                containers.extend(pod.containers.values())
        return [lvl for lvl in (tier, pods, containers) if lvl]

    def _latest(self, ctx: QoSContext, kind: MetricKind,
                labels=None, now: float = 0.0) -> Optional[float]:
        return ctx.metric_cache.aggregate(
            kind, labels, start=now - ctx.metric_collect_interval, end=now,
            agg=AggregationType.LAST,
        )

    # -- main ----------------------------------------------------------------

    def execute(self, ctx: QoSContext, now: float) -> None:
        threshold = ctx.node_slo.resource_used_threshold_with_be
        if not threshold.enable:
            self._recover_cfs_quota(ctx)
            self._recover_cpuset(ctx)
            return

        node_used = self._latest(ctx, MetricKind.NODE_CPU_USAGE, now=now)
        if node_used is None:
            return
        pods = list(ctx.pod_provider.running_pods())
        pod_used: Dict[str, float] = {}
        non_be = set()
        for pod in pods:
            u = self._latest(
                ctx, MetricKind.POD_CPU_USAGE, {"pod": pod.uid}, now=now
            )
            if u is not None:
                pod_used[pod.uid] = u
            if pod.qos is not QoSClass.BE:
                non_be.add(pod.uid)

        budget_mcpu = calculate_be_suppress_mcpu(
            ctx.node_capacity_mcpu,
            threshold.cpu_suppress_threshold_percent,
            node_used, pod_used, non_be, ctx.node_reserved_mcpu,
        )

        if threshold.cpu_suppress_policy == "cfsQuota":
            self._adjust_by_cfs_quota(ctx, budget_mcpu)
            self._recover_cpuset(ctx)
        else:
            self._adjust_by_cpuset(ctx, budget_mcpu)
            self._recover_cfs_quota(ctx)

    # -- cpuset policy -------------------------------------------------------

    def _adjust_by_cpuset(self, ctx: QoSContext, budget_mcpu: int) -> None:
        try:
            old = CPU_SET.read(ctx.be_cgroup_dir, ctx.system_config)
        except OSError:
            old = ""
        # kernel normalizes cpuset to range syntax ("0-63"): parse, don't
        # count commas
        try:
            old_count = len(_parse_cpuset(old))
        except ValueError:
            old_count = 0
        # reference rounds the BE cpuset size UP (cpu_suppress.go:388
        # math.Ceil), so a non-integral budget still grants the extra CPU
        want = -(-budget_mcpu // 1000)
        cpus = select_suppress_cpus(want, ctx.cpu_infos, old_count)
        if not cpus:
            return
        target = cpuset_str(cpus)
        levels = [
            [CgroupUpdater("cpuset.cpus", d, target, merge_if_cpuset_looser)
             for d in level]
            for level in self._be_cpuset_dirs(ctx)
        ]
        ctx.executor.leveled_update_batch(levels)
        self._suppressed_policy["cpuset"] = True
        ctx.log("qosmanager/cpusuppress", ctx.be_cgroup_dir, "suppress",
                f"cpuset -> {target}")

    def _recover_cpuset(self, ctx: QoSContext) -> None:
        if not self._suppressed_policy.get("cpuset"):
            return
        all_cpus = cpuset_str([c.cpu_id for c in ctx.cpu_infos])
        if not all_cpus:
            return
        levels = [
            [CgroupUpdater("cpuset.cpus", d, all_cpus,
                           merge_if_cpuset_looser) for d in level]
            for level in self._be_cpuset_dirs(ctx)
        ]
        ctx.executor.leveled_update_batch(levels)
        self._suppressed_policy["cpuset"] = False
        ctx.log("qosmanager/cpusuppress", ctx.be_cgroup_dir, "recover",
                "cpuset restored")

    # -- cfs quota policy ----------------------------------------------------

    def _adjust_by_cfs_quota(self, ctx: QoSContext, budget_mcpu: int) -> None:
        new_quota = max(budget_mcpu * CFS_PERIOD_US // 1000, BE_MIN_QUOTA_US)
        try:
            raw = CPU_CFS_QUOTA.read(ctx.be_cgroup_dir, ctx.system_config)
        except OSError:
            raw = ""
        cur = parse_cfs_quota(raw)
        if cur is None:
            cur = -1

        capacity_cores = ctx.node_capacity_mcpu / 1000.0
        min_delta = capacity_cores * CFS_PERIOD_US * (
            SUPPRESS_BYPASS_QUOTA_DELTA_RATIO
        )
        if cur > 0 and abs(new_quota - cur) < min_delta and (
            new_quota != BE_MIN_QUOTA_US
        ):
            return
        max_increase = capacity_cores * CFS_PERIOD_US * (
            BE_MAX_INCREASE_CPU_PERCENT
        )
        if cur > 0 and new_quota - cur > max_increase:
            new_quota = cur + int(max_increase)
        ctx.executor.update(False, CgroupUpdater(
            "cpu.cfs_quota_us", ctx.be_cgroup_dir, str(new_quota)))
        self._suppressed_policy["cfsQuota"] = True
        ctx.log("qosmanager/cpusuppress", ctx.be_cgroup_dir, "suppress",
                f"cfs quota -> {new_quota}")

    def _recover_cfs_quota(self, ctx: QoSContext) -> None:
        if not self._suppressed_policy.get("cfsQuota"):
            return
        ctx.executor.update(False, CgroupUpdater(
            "cpu.cfs_quota_us", ctx.be_cgroup_dir, "-1"))
        self._suppressed_policy["cfsQuota"] = False
        ctx.log("qosmanager/cpusuppress", ctx.be_cgroup_dir, "recover",
                "cfs quota unlimited")
