"""SystemConfig reconcile: node-level /proc/sys memory knobs.

Reference: pkg/koordlet/qosmanager/plugins/sysreconcile/system_config.go
(:71-140): from the NodeSLO SystemStrategy,

    min_free_kbytes        = total_mem_kbytes * minFreeKbytesFactor / 10000
    watermark_scale_factor = strategy value (valid range 10..400)
    memcg reap background  = 0/1

written under /proc/sys/vm (path-redirected through SystemConfig for
fake trees), with last-written caching so steady state costs no I/O.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from koordinator_tpu.koordlet.qosmanager.framework import QoSContext

#: valid ranges (reference: sysutil.MinFreeKbytes/WatermarkScaleFactor
#: validators)
MIN_FREE_KBYTES_RANGE = (10 * 1024, 400 * 1024 * 1024)
WATERMARK_SCALE_RANGE = (10, 400)


class SystemConfigReconcile:
    name = "sysreconcile"
    interval_seconds = 10.0

    def __init__(self):
        self._written: Dict[str, str] = {}

    def enabled(self, ctx: QoSContext) -> bool:
        return ctx.node_slo.system_strategy is not None

    def _vm_path(self, ctx: QoSContext, name: str) -> str:
        return os.path.join(ctx.system_config.proc_root, "sys", "vm", name)

    def _write(self, ctx: QoSContext, name: str, value: int) -> None:
        path = self._vm_path(ctx, name)
        text = str(int(value))
        if self._written.get(path) == text:
            return
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                f.write(text)
        except OSError:
            return
        self._written[path] = text
        ctx.log("sysreconcile", name, "update", text)

    def execute(self, ctx: QoSContext, now: float) -> None:
        strategy = ctx.node_slo.system_strategy
        total_kbytes = ctx.node_capacity_mem_mib * 1024
        if strategy.min_free_kbytes_factor and total_kbytes > 0:
            value = total_kbytes * strategy.min_free_kbytes_factor // 10000
            if MIN_FREE_KBYTES_RANGE[0] <= value <= MIN_FREE_KBYTES_RANGE[1]:
                self._write(ctx, "min_free_kbytes", value)
        wsf = strategy.watermark_scale_factor
        if wsf and WATERMARK_SCALE_RANGE[0] <= wsf <= WATERMARK_SCALE_RANGE[1]:
            self._write(ctx, "watermark_scale_factor", wsf)
        if strategy.memcg_reap_background in (0, 1):
            self._write(
                ctx, "memcg_reap_background", strategy.memcg_reap_background
            )
