"""BlkIOReconcile: block-device throttling per QoS tier and pod.

Reference: pkg/koordlet/qosmanager/plugins/blkio/blkio_reconcile.go — the
NodeSLO's per-QoS BlkIOQOS block configs become
``blkio.throttle.{read,write}_{bps,iops}_device`` writes on the QoS tier
cgroup dir and every member pod's dir (:106-243, updateBlkIOConfig;
getBlkIOUpdaterFromBlockCfg :311-373). The reference resolves volume
groups/pod volumes to disk numbers on the host; the typed model addresses
devices by MAJ:MIN directly. A zero limit removes the throttle (writes
``MAJ:MIN 0`` → kernel clears, matching getBlkIORemoverFromDiskNumber).
"""

from __future__ import annotations

import dataclasses
from typing import List

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.qosmanager.framework import QoSContext
from koordinator_tpu.koordlet.resourceexecutor.executor import CgroupUpdater
from koordinator_tpu.manager.sloconfig import BlockCfg

_QOS_DIR = {
    QoSClass.BE: "kubepods/besteffort",
    QoSClass.LS: "kubepods/burstable",
}

_FILES = (
    ("blkio.throttle.read_bps_device", "read_bps"),
    ("blkio.throttle.write_bps_device", "write_bps"),
    ("blkio.throttle.read_iops_device", "read_iops"),
    ("blkio.throttle.write_iops_device", "write_iops"),
)


def block_updaters(parent_dir: str, block: BlockCfg) -> List[CgroupUpdater]:
    """The four throttle writes for one device on one cgroup dir."""
    out = []
    for resource_type, field_name in _FILES:
        value = getattr(block, field_name)
        out.append(
            CgroupUpdater(
                resource_type,
                parent_dir,
                f"{block.device} {value}",
                key_extra=block.device,  # one cache entry per device
            )
        )
    return out


class BlkIOReconcile:
    name = "blkio"
    interval_seconds = 10.0

    def __init__(self):
        #: cgroup dir -> devices throttled by a previous pass; a device
        #: that disappears from the config gets an explicit "dev 0"
        #: remover write (reference: getBlkIORemoverFromDiskNumber)
        self._applied: dict = {}

    def enabled(self, ctx: QoSContext) -> bool:
        strategy = ctx.node_slo.resource_qos_strategy
        return bool(self._applied) or any(
            strategy.for_qos(q).blkio for q in (QoSClass.LS, QoSClass.BE)
        )

    def execute(self, ctx: QoSContext, now: float) -> None:
        strategy = ctx.node_slo.resource_qos_strategy
        updates: List[CgroupUpdater] = []
        live: dict = {}

        def throttle(parent_dir: str, blocks) -> None:
            for block in blocks:
                updates.extend(block_updaters(parent_dir, block))
                live.setdefault(parent_dir, set()).add(block.device)

        def resolve_pod_volume(pod, block):
            """volume name -> PVC claim -> bound PV -> device
            (blkio_reconcile.go:387-411 BlockTypePodVolume); None when
            any link is missing — the throttle is skipped, matching the
            reference's error-and-continue."""
            claim = pod.volumes.get(block.name)
            if not claim or ctx.volume_name_fn is None:
                return None
            pv = ctx.volume_name_fn(claim)
            device = ctx.volume_devices.get(pv) if pv else None
            if not device:
                return None
            return dataclasses.replace(
                block, device=device, block_type="device", name=""
            )

        for qos, tier_dir in _QOS_DIR.items():
            blocks = strategy.for_qos(qos).blkio
            if not blocks:
                continue
            device_blocks = [
                b for b in blocks if b.block_type != "pod_volume"
            ]
            volume_blocks = [
                b for b in blocks if b.block_type == "pod_volume"
            ]
            throttle(tier_dir, device_blocks)
            for pod in ctx.pod_provider.running_pods():
                if pod.qos != qos:
                    continue
                throttle(pod.cgroup_dir, device_blocks)
                for block in volume_blocks:
                    resolved = resolve_pod_volume(pod, block)
                    if resolved is not None:
                        throttle(pod.cgroup_dir, [resolved])

        # stale devices: explicitly clear the kernel throttle
        for parent_dir, devices in self._applied.items():
            for device in devices - live.get(parent_dir, set()):
                updates.extend(
                    block_updaters(parent_dir, BlockCfg(device=device))
                )
        self._applied = live

        for up in updates:
            ctx.executor.update(True, up)
            ctx.log("blkio", up.parent_dir, up.resource_type, up.value)
