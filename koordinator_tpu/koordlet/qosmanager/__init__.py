from koordinator_tpu.koordlet.qosmanager.framework import (
    CPUInfo,
    QoSContext,
    QoSManager,
)
from koordinator_tpu.koordlet.qosmanager.cpusuppress import CPUSuppress
from koordinator_tpu.koordlet.qosmanager.evictors import CPUEvictor, MemoryEvictor
from koordinator_tpu.koordlet.qosmanager.cpuburst import CPUBurst

__all__ = [
    "CPUInfo",
    "QoSContext",
    "QoSManager",
    "CPUSuppress",
    "CPUEvictor",
    "MemoryEvictor",
    "CPUBurst",
]
