from koordinator_tpu.koordlet.qosmanager.framework import (
    CPUInfo,
    QoSContext,
    QoSManager,
)
from koordinator_tpu.koordlet.qosmanager.cpusuppress import CPUSuppress
from koordinator_tpu.koordlet.qosmanager.evictors import CPUEvictor, MemoryEvictor
from koordinator_tpu.koordlet.qosmanager.cpuburst import CPUBurst
from koordinator_tpu.koordlet.qosmanager.resctrl import ResctrlReconcile
from koordinator_tpu.koordlet.qosmanager.cgreconcile import (
    CgroupResourcesReconcile,
)
from koordinator_tpu.koordlet.qosmanager.blkio import BlkIOReconcile
from koordinator_tpu.koordlet.qosmanager.sysreconcile import (
    SystemConfigReconcile,
)

__all__ = [
    "CPUInfo",
    "QoSContext",
    "QoSManager",
    "CPUSuppress",
    "CPUEvictor",
    "MemoryEvictor",
    "CPUBurst",
    "ResctrlReconcile",
    "CgroupResourcesReconcile",
    "BlkIOReconcile",
    "SystemConfigReconcile",
]
