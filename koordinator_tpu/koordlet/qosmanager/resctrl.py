"""ResctrlReconcile: LLC (L3 CAT) + memory-bandwidth (MBA) isolation per
QoS tier.

Reference: pkg/koordlet/qosmanager/plugins/resctrl/resctrl_reconcile.go —
three resctrl control groups (LSR, LS, BE; :109-122 getPodResctrlGroup
maps LSE/LSR→LSR, LS→LS, BE→BE), each reconciled to its strategy's cache
way range (calculateAndApplyRDTL3PolicyForGroup :293) and MBA percent
(:329), then every pod's tasks are pulled into its group's tasks file
(:211-292).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.qosmanager.framework import QoSContext
from koordinator_tpu.koordlet.system.resctrl import (
    BE_GROUP,
    LS_GROUP,
    LSR_GROUP,
    RESCTRL_GROUPS,
    ResctrlFS,
    calculate_cat_l3_mask,
    calculate_mba,
    detect_vendor,
)

_QOS_TO_GROUP = {
    QoSClass.LSE: LSR_GROUP,
    QoSClass.LSR: LSR_GROUP,
    QoSClass.LS: LS_GROUP,
    QoSClass.BE: BE_GROUP,
}

_GROUP_TO_QOS = {
    LSR_GROUP: QoSClass.LSR,
    LS_GROUP: QoSClass.LS,
    BE_GROUP: QoSClass.BE,
}


def pod_resctrl_group(qos: QoSClass) -> str:
    """getPodResctrlGroup (:109-122); "" = unknown (left alone)."""
    return _QOS_TO_GROUP.get(qos, "")


class ResctrlReconcile:
    name = "resctrl"
    interval_seconds = 10.0

    def __init__(self, fs: Optional[ResctrlFS] = None,
                 vendor: Optional[str] = None):
        self._fs = fs
        #: None = detect from /proc/cpuinfo at first execute (AMD's MBA
        #: takes absolute MBps, Intel's takes percent — writing the wrong
        #: convention throttles drastically)
        self.vendor = vendor

    def _vendor_for(self, ctx: QoSContext) -> str:
        if self.vendor is None:
            self.vendor = detect_vendor(ctx.system_config.proc_root)
        return self.vendor

    def _fs_for(self, ctx: QoSContext) -> ResctrlFS:
        # bind to the context's SystemConfig unless explicitly injected,
        # so the resctrl tree and the cgroup tree stay consistent
        if self._fs is None:
            self._fs = ResctrlFS(ctx.system_config)
        return self._fs

    @property
    def fs(self) -> ResctrlFS:
        assert self._fs is not None
        return self._fs

    def enabled(self, ctx: QoSContext) -> bool:
        return self._fs_for(ctx).is_supported()

    def execute(self, ctx: QoSContext, now: float) -> None:
        fs = self._fs_for(ctx)
        try:
            fs.init_groups()
            cbm = fs.read_cbm()
            cache_ids = fs.cache_ids()
        except (OSError, ValueError):
            return
        strategy = ctx.node_slo.resource_qos_strategy
        for group in RESCTRL_GROUPS:
            qos_cfg = strategy.for_qos(_GROUP_TO_QOS[group])
            resctrl = qos_cfg.resctrl
            # a kernel rejection (e.g. CAT-only host refusing MB lines)
            # must not abort the reconcile pass or the manager tick
            try:
                self._apply_l3(ctx, group, cbm, cache_ids, resctrl)
            except OSError:
                pass
            try:
                self._apply_mb(ctx, group, cache_ids, resctrl)
            except OSError:
                pass
        self._move_tasks(ctx)

    # -- policy (:293-343) --------------------------------------------------

    def _apply_l3(self, ctx, group, cbm, cache_ids, resctrl) -> None:
        try:
            mask = calculate_cat_l3_mask(
                cbm,
                resctrl.cat_range_start_percent,
                resctrl.cat_range_end_percent,
            )
        except ValueError:
            return
        line = "L3:" + ";".join(f"{i}={mask}" for i in cache_ids)
        if self.fs.write_schemata_line(group, line):
            ctx.log("resctrl", group, "schemata", line)

    def _apply_mb(self, ctx, group, cache_ids, resctrl) -> None:
        value = calculate_mba(resctrl.mba_percent, self._vendor_for(ctx))
        line = "MB:" + ";".join(f"{i}={value}" for i in cache_ids)
        if self.fs.write_schemata_line(group, line):
            ctx.log("resctrl", group, "schemata", line)

    # -- task placement (:211-292) -----------------------------------------

    def _move_tasks(self, ctx: QoSContext) -> None:
        """Pull every pod's task ids into its QoS group's tasks file; ids
        come from the pod cgroup's cgroup.procs under the fake/real root."""
        for pod in ctx.pod_provider.running_pods():
            group = pod_resctrl_group(pod.qos)
            if not group:
                continue
            tids = self._pod_task_ids(ctx, pod)
            if tids:
                try:
                    self.fs.add_tasks(group, tids)
                except OSError:
                    # a task that exited mid-write (ESRCH) is retried on
                    # the next tick; don't abort the pass
                    continue

    def _pod_task_ids(self, ctx: QoSContext, pod) -> List[int]:
        """Thread-level task ids: the resctrl tasks file moves exactly the
        written TID, so worker threads must be moved individually — read
        the cgroup's thread-level files first (v1 ``tasks``, v2
        ``cgroup.threads``), falling back to ``cgroup.procs`` (leaders
        only) when absent."""
        tids: List[int] = []
        dirs = [pod.cgroup_dir] + list(pod.containers.values())
        root = ctx.system_config.cgroup_root
        if ctx.system_config.use_cgroup_v2:
            sub, names = "", ("cgroup.threads", "cgroup.procs")
        else:
            sub, names = "cpu", ("tasks", "cgroup.procs")
        for d in dirs:
            for name in names:
                path = os.path.join(root, sub, d, name)
                if not os.path.exists(path):
                    continue
                try:
                    with open(path) as f:
                        tids.extend(
                            int(x) for x in f.read().split() if x.strip()
                        )
                except (OSError, ValueError):
                    pass
                break  # thread-level file found: don't double-read procs
        return sorted(set(tids))
