"""CgroupResourcesReconcile: memcg QoS knobs per tier/pod/container.

Reference: pkg/koordlet/qosmanager/plugins/cgreconcile/cgroup_reconcile.go
— per reconcile pass it computes, from the NodeSLO ResourceQOSStrategy's
MemoryQOS, the container-level memcg values (:283-354):

    memory.min  = request * minLimitPercent / 100
    memory.low  = request * lowLimitPercent / 100
    memory.high = limit (or node total) * throttlingPercent / 100
    memory.wmark_ratio / wmark_scale_factor / priority / oom.group

pod level sums its containers (:237-281), and the QoS tier dir sums its
pods (:190-208, updateCgroupSummaryForQoS), written top-down through the
merging executor.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.qosmanager.framework import QoSContext
from koordinator_tpu.koordlet.resourceexecutor.executor import CgroupUpdater
from koordinator_tpu.koordlet.system.cgroup import CgroupVersion

MIB = 1024 * 1024

#: QoS tier cgroup dirs (kubelet layout)
_QOS_DIR = {
    QoSClass.BE: "kubepods/besteffort",
    QoSClass.LS: "kubepods/burstable",
}


@dataclasses.dataclass
class _Summary:
    """Per-tier rollup (cgroupResourceSummary)."""

    memory_min: int = 0
    memory_low: int = 0


class CgroupResourcesReconcile:
    name = "cgreconcile"
    interval_seconds = 10.0

    def enabled(self, ctx: QoSContext) -> bool:
        strategy = ctx.node_slo.resource_qos_strategy
        return any(
            strategy.for_qos(q).memory is not None
            and strategy.for_qos(q).enable
            for q in (QoSClass.LS, QoSClass.BE, QoSClass.LSR)
        )

    def execute(self, ctx: QoSContext, now: float) -> None:
        strategy = ctx.node_slo.resource_qos_strategy
        node_total_bytes = ctx.node_capacity_mem_mib * MIB
        summaries: Dict[QoSClass, _Summary] = {
            QoSClass.LS: _Summary(),
            QoSClass.BE: _Summary(),
        }
        updates: List[CgroupUpdater] = []
        for pod in ctx.pod_provider.running_pods():
            cfg = strategy.for_qos(pod.qos)
            if not cfg.enable or cfg.memory is None:
                continue
            mem = cfg.memory
            # PodMeta carries pod-level requests (the reference iterates
            # container specs); containers split the pod request evenly
            request = pod.memory_request_mib * MIB
            limit = (pod.memory_limit_mib or 0) * MIB or node_total_bytes
            pod_min = request * mem.min_limit_percent // 100
            pod_low = request * mem.low_limit_percent // 100
            pod_high = (
                limit * mem.throttling_percent // 100
                if mem.throttling_percent
                else 0
            )
            n_containers = max(len(pod.containers), 1)
            for cname, cdir in sorted(pod.containers.items()):
                updates += self._container_updates(
                    cdir,
                    mem,
                    pod_min // n_containers,
                    pod_low // n_containers,
                    pod_high // n_containers,
                )
            # only pods actually living under a managed tier dir roll up
            # into it (LSR/LSE guaranteed pods sit directly under
            # kubepods, not burstable)
            tier = summaries.get(pod.qos)
            if tier is not None:
                tier.memory_min += pod_min
                tier.memory_low += pod_low
            updates.append(CgroupUpdater("memory.min", pod.cgroup_dir, str(pod_min)))
            updates.append(CgroupUpdater("memory.low", pod.cgroup_dir, str(pod_low)))

        # tier dirs written first (top-down hierarchy constraint)
        tier_updates: List[CgroupUpdater] = []
        for qos, summary in summaries.items():
            d = _QOS_DIR[qos]
            tier_updates.append(
                CgroupUpdater("memory.min", d, str(summary.memory_min))
            )
            tier_updates.append(
                CgroupUpdater("memory.low", d, str(summary.memory_low))
            )
        for up in tier_updates + updates:
            ctx.executor.update(True, up)
            ctx.log("cgreconcile", up.parent_dir, up.resource_type, up.value)

    def _container_updates(self, cdir, mem, c_min, c_low, c_high) -> List[CgroupUpdater]:
        return [
            CgroupUpdater("memory.min", cdir, str(c_min)),
            CgroupUpdater("memory.low", cdir, str(c_low)),
            # disabled knobs reset to their neutral values so a config
            # rollback clears previously-applied limits
            CgroupUpdater(
                "memory.high", cdir, str(c_high) if c_high > 0 else "max"
            ),
            CgroupUpdater("memory.wmark_ratio", cdir, str(mem.wmark_ratio)),
            CgroupUpdater(
                "memory.wmark_scale_factor", cdir, str(mem.wmark_scale_permill)
            ),
            CgroupUpdater(
                "memory.priority",
                cdir,
                str(mem.priority) if mem.priority_enable else "0",
            ),
            CgroupUpdater("memory.oom.group", cdir, str(mem.oom_kill_group)),
        ]
