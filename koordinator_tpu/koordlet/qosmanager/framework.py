"""QoS strategy framework: timed plugins that actuate node QoS.

Reference: pkg/koordlet/qosmanager/{qosmanager.go,framework/strategy.go,
framework/context.go} — each strategy runs on its own interval with
access to the states informer, metric cache, and resource executor; the
helpers' eviction path is shared.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Protocol, Sequence

from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta, PodProvider
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.manager.sloconfig import NodeSLOSpec
from koordinator_tpu.koordlet.system.cgroup import SystemConfig


@dataclasses.dataclass(frozen=True)
class CPUInfo:
    """One logical processor (reference: koordletutil.ProcessorInfo)."""

    cpu_id: int
    core_id: int
    socket_id: int
    node_id: int  # NUMA node


#: Eviction callback: (pods, reason) -> uids actually evicted. The node
#: agent wires this to the apiserver eviction API (reference:
#: framework.Evictor.EvictPodsIfNotEvicted).
EvictFn = Callable[[List[PodMeta], str], List[str]]


@dataclasses.dataclass
class QoSContext:
    """Shared strategy dependencies (reference: framework/context.go)."""

    metric_cache: MetricCache
    executor: ResourceUpdateExecutor
    pod_provider: PodProvider
    system_config: SystemConfig
    node_slo: NodeSLOSpec = dataclasses.field(default_factory=NodeSLOSpec)
    node_capacity_mcpu: int = 0
    node_capacity_mem_mib: int = 0
    node_reserved_mcpu: int = 0
    cpu_infos: List[CPUInfo] = dataclasses.field(default_factory=list)
    evict: Optional[EvictFn] = None
    auditor: Optional[Auditor] = None
    #: cgroup parent of the best-effort QoS tier (reference:
    #: koordletutil.GetPodQoSRelativePath(PodQOSBestEffort))
    be_cgroup_dir: str = "kubepods/besteffort"
    #: PVC claim key ("namespace/name") -> bound PV name (the
    #: statesinformer's get_volume_name; states_pvc.go)
    volume_name_fn: Optional[Callable[[str], str]] = None
    #: active cpu-normalization ratio (node annotation, parsed by the
    #: informer wiring); quota-burst bases divide by it so the two
    #: features compose instead of fighting
    cpu_normalization_ratio: Optional[float] = None
    #: PV name -> block device "MAJ:MIN" (the host's volume attachment
    #: view; the reference walks /var/lib/kubelet + sysfs for this)
    volume_devices: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: how far back "latest" metric queries look
    metric_collect_interval: float = 60.0
    #: BE tier allocatable (node batch-cpu), for the cpu-evict
    #: evictByAllocatable policy (cpu_evict.go getBEMilliAllocatable);
    #: None = unknown, the policy falls back to the real-limit path
    be_allocatable_fn: Optional[Callable[[], Optional[int]]] = None

    def log(self, group: str, subject: str, op: str, detail: str = "") -> None:
        if self.auditor is not None:
            self.auditor.log(group, subject, op, detail)


class QoSStrategy(Protocol):
    name: str
    interval_seconds: float

    def enabled(self, ctx: QoSContext) -> bool: ...

    def execute(self, ctx: QoSContext, now: float) -> None: ...


class QoSManager:
    """Runs strategies on their intervals (reference: qosmanager.go:42-51
    registers cpusuppress, cpuevict, memoryevict, cpuburst, ...)."""

    def __init__(self, ctx: QoSContext, strategies: Sequence[QoSStrategy]):
        self.ctx = ctx
        self.strategies = list(strategies)
        self._last_run: Dict[str, float] = {}

    def tick(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        for s in self.strategies:
            last = self._last_run.get(s.name, -1e18)
            if now - last < s.interval_seconds:
                continue
            if s.enabled(self.ctx):
                s.execute(self.ctx, now)
            self._last_run[s.name] = now

    def run_all(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        for s in self.strategies:
            if s.enabled(self.ctx):
                s.execute(self.ctx, now)
            self._last_run[s.name] = now
