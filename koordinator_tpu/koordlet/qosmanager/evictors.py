"""Pressure-driven best-effort eviction strategies.

Reference: pkg/koordlet/qosmanager/plugins/{memoryevict/memory_evict.go,
cpuevict/cpu_evict.go}.

Memory: when node memory usage% exceeds MemoryEvictThresholdPercent,
evict BE pods (lowest priority first, then largest memory) until
``capacity * (usage% - lower%) / 100`` MiB is released; lower defaults to
threshold - 2 (memory_evict.go:101-160).

CPU: when the BE tier's real cfs limit falls below
CPUEvictBESatisfactionLowerPercent of BE requests while BE pods are
actually cpu-starved (usage/limit >= 90%), release
``(upper% - satisfaction) * request`` mCPU by evicting BE pods (lowest
priority first, then highest cpu usage) (cpu_evict.go:246-360).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.metriccache import AggregationType, MetricKind
from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.koordlet.qosmanager.framework import QoSContext
from koordinator_tpu.koordlet.resourceexecutor.executor import parse_cfs_quota
from koordinator_tpu.koordlet.system.cgroup import CFS_PERIOD_US, CPU_CFS_QUOTA

MEMORY_RELEASE_BUFFER_PERCENT = 2
BE_CPU_USAGE_THRESHOLD_PERCENT = 90


def _be_pods(ctx: QoSContext) -> List[PodMeta]:
    return [p for p in ctx.pod_provider.running_pods()
            if p.qos is QoSClass.BE]


def _pod_metric_last(ctx: QoSContext, kind: MetricKind, uid: str,
                     now: float) -> Optional[float]:
    return ctx.metric_cache.aggregate(
        kind, {"pod": uid},
        start=now - ctx.metric_collect_interval, end=now,
        agg=AggregationType.LAST,
    )


class MemoryEvictor:
    name = "memoryevict"
    interval_seconds = 1.0
    #: min seconds between eviction rounds (memory_evict.go cooldown)
    cooldown_seconds = 60.0

    def __init__(self):
        self._last_evict = -1e18

    def enabled(self, ctx: QoSContext) -> bool:
        return ctx.node_slo.resource_used_threshold_with_be.enable

    def execute(self, ctx: QoSContext, now: float) -> None:
        threshold = ctx.node_slo.resource_used_threshold_with_be
        pct = threshold.memory_evict_threshold_percent
        lower = threshold.memory_evict_lower_percent
        if lower is None:
            lower = pct - MEMORY_RELEASE_BUFFER_PERCENT
        if pct <= 0 or lower >= pct or ctx.node_capacity_mem_mib <= 0:
            return
        if now - self._last_evict < self.cooldown_seconds:
            return
        used = ctx.metric_cache.aggregate(
            MetricKind.NODE_MEMORY_USAGE,
            start=now - ctx.metric_collect_interval, end=now,
            agg=AggregationType.LAST,
        )
        if used is None:
            return
        usage_pct = used / ctx.node_capacity_mem_mib * 100.0
        if usage_pct < pct:
            return
        need_release_mib = ctx.node_capacity_mem_mib * (
            usage_pct - lower
        ) / 100.0

        infos = []
        for pod in _be_pods(ctx):
            mem = _pod_metric_last(
                ctx, MetricKind.POD_MEMORY_USAGE, pod.uid, now
            ) or 0.0
            infos.append((pod, mem))
        # priority asc; then mem desc; metric-less pods last by name desc
        # (memory_evict.go:203-215)
        infos.sort(key=lambda t: (
            t[0].priority,
            -t[1] if t[1] > 0 else float("inf"),
            tuple(-ord(c) for c in t[0].name),
        ))

        victims, released = [], 0.0
        for pod, mem in infos:
            if released >= need_release_mib:
                break
            victims.append(pod)
            released += mem
        if victims and ctx.evict is not None:
            ctx.evict(victims, "evict by node memory usage")
            self._last_evict = now
            ctx.log("qosmanager/memoryevict", "node", "evict",
                    f"{len(victims)} BE pods, ~{released:.0f} MiB")


class CPUEvictor:
    name = "cpuevict"
    interval_seconds = 1.0
    cooldown_seconds = 60.0

    def __init__(self):
        self._last_evict = -1e18

    def enabled(self, ctx: QoSContext) -> bool:
        t = ctx.node_slo.resource_used_threshold_with_be
        return (
            t.enable
            and t.cpu_evict_be_satisfaction_lower_percent is not None
            and t.cpu_evict_be_satisfaction_upper_percent is not None
        )

    def _be_real_limit_mcpu(self, ctx: QoSContext) -> float:
        """BE tier's effective cpu limit from its cfs quota
        (cpu_evict.go getBEMilliRealLimit)."""
        try:
            raw = CPU_CFS_QUOTA.read(ctx.be_cgroup_dir, ctx.system_config)
        except OSError:
            return float(ctx.node_capacity_mcpu)
        quota = parse_cfs_quota(raw)
        if quota is None or quota <= 0:
            return float(ctx.node_capacity_mcpu)
        return quota / CFS_PERIOD_US * 1000.0

    def _be_limit_mcpu(self, ctx: QoSContext, t) -> float:
        """The satisfaction denominator per CPUEvictPolicy
        (cpu_evict.go:148-151): evictByAllocatable uses the BE tier's
        allocatable (node batch-cpu), the default uses the cfs-quota
        real limit. An unknown allocatable falls back to the real
        limit rather than guessing."""
        if t.cpu_evict_policy == "evictByAllocatable":
            alloc = (
                ctx.be_allocatable_fn() if ctx.be_allocatable_fn else None
            )
            if alloc is not None and alloc > 0:
                return float(alloc)
        return self._be_real_limit_mcpu(ctx)

    def execute(self, ctx: QoSContext, now: float) -> None:
        t = ctx.node_slo.resource_used_threshold_with_be
        if now - self._last_evict < self.cooldown_seconds:
            return
        be_pods = _be_pods(ctx)
        be_request = float(sum(p.cpu_request_mcpu for p in be_pods))
        if be_request <= 0:
            return
        real_limit = self._be_limit_mcpu(ctx, t)
        satisfaction = real_limit / be_request
        lower = t.cpu_evict_be_satisfaction_lower_percent / 100.0
        upper = t.cpu_evict_be_satisfaction_upper_percent / 100.0
        if satisfaction > lower:
            return
        # only evict when BE is actually starved: avg usage near its
        # limit over the configured window (cpu_evict.go:111-114 —
        # the window applies when larger than the collect interval)
        window = max(
            2 * ctx.metric_collect_interval,
            float(t.cpu_evict_time_window_seconds or 0),
        )
        be_usage = ctx.metric_cache.aggregate(
            MetricKind.BE_CPU_USAGE,
            start=now - window, end=now,
            agg=AggregationType.AVG,
        )
        if be_usage is None or real_limit <= 0:
            return
        usage_threshold = t.cpu_evict_be_usage_threshold_percent or (
            BE_CPU_USAGE_THRESHOLD_PERCENT
        )
        if be_usage / real_limit * 100.0 < usage_threshold:
            return

        release_mcpu = (upper - satisfaction) * be_request

        infos = []
        for pod in be_pods:
            usage = _pod_metric_last(
                ctx, MetricKind.POD_CPU_USAGE, pod.uid, now
            ) or 0.0
            rel_usage = (
                usage / pod.cpu_request_mcpu if pod.cpu_request_mcpu else 0.0
            )
            infos.append((pod, rel_usage))
        # priority asc, then relative cpu usage desc (cpu_evict.go:354-360)
        infos.sort(key=lambda x: (x[0].priority, -x[1]))

        victims, released = [], 0.0
        for pod, _ in infos:
            if released >= release_mcpu:
                break
            victims.append(pod)
            released += pod.cpu_request_mcpu
        if victims and ctx.evict is not None:
            ctx.evict(victims, "evict by BE cpu satisfaction")
            self._last_evict = now
            ctx.log("qosmanager/cpuevict", "node", "evict",
                    f"{len(victims)} BE pods, ~{released:.0f} mCPU")
