from koordinator_tpu.koordlet.metricsadvisor.framework import (
    Collector,
    CollectorContext,
    MetricsAdvisor,
    PodMeta,
)

__all__ = ["Collector", "CollectorContext", "MetricsAdvisor", "PodMeta"]
