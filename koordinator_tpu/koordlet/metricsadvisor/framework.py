"""Collector plugin framework for the metrics advisor.

Reference: pkg/koordlet/metricsadvisor/framework/{plugin.go,context.go} —
a registry of collectors, each on its own timer, appending samples to the
metric cache; SharedState passes cross-collector values (e.g. pod usage
for the system-resource collector).

Here collectors are driven by explicit ``collect()`` ticks (the agent
main loop or tests call them; no goroutines), and the shared state is a
typed ``CollectorContext``.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Mapping, Optional, Protocol, Sequence

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.koordlet.system.cgroup import SystemConfig


@dataclasses.dataclass
class ContainerBatchResources:
    """One container's koordinator extended (batch) resources, in
    canonical units (reference: util.GetBatchMilliCPUFromResourceList /
    GetBatchMemoryFromResourceList over container requests/limits).
    ``None`` limit = unlimited."""

    request_mcpu: int = 0
    limit_mcpu: Optional[int] = None
    memory_limit_bytes: Optional[int] = None


@dataclasses.dataclass
class PodMeta:
    """What node-local subsystems need to know about a running pod
    (reference: statesinformer.PodMeta: pod + cgroup parent dir)."""

    uid: str
    cgroup_dir: str            # e.g. "kubepods/pod<uid>"
    qos: QoSClass = QoSClass.NONE
    containers: Dict[str, str] = dataclasses.field(default_factory=dict)
    # container name -> cgroup dir
    name: str = ""
    priority: int = 0          # k8s numeric priority (eviction order)
    cpu_request_mcpu: int = 0
    cpu_limit_mcpu: int = 0    # 0 = no limit
    memory_request_mib: int = 0
    memory_limit_mib: int = 0  # 0 = no limit
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: container name -> extended (batch) resources; populated for BE
    #: pods running on reclaimed batch-cpu/batch-memory
    batch_resources: Dict[str, "ContainerBatchResources"] = (
        dataclasses.field(default_factory=dict)
    )
    #: container name -> cpu limit (mCPU); feeds container-level cfs
    #: quota hooks (cpu-normalization). Absent entry = unknown/unlimited.
    container_limits_mcpu: Dict[str, int] = dataclasses.field(
        default_factory=dict
    )
    #: volume name -> PVC claim key ("namespace/name"); feeds the blkio
    #: pod-volume device resolution (pod.Spec.Volumes projection)
    volumes: Dict[str, str] = dataclasses.field(default_factory=dict)


class PodProvider(Protocol):
    """Source of the current pod list (the statesinformer)."""

    def running_pods(self) -> Sequence[PodMeta]: ...


@dataclasses.dataclass
class CollectorContext:
    """Shared collector state (reference: framework/context.go:63
    SharedState): latest per-source usages for cross-collector math."""

    metric_cache: MetricCache
    system_config: SystemConfig
    pod_provider: Optional[PodProvider] = None
    #: latest node usage sample {"cpu": mCPU, "memory": MiB}
    latest_node_usage: Dict[str, float] = dataclasses.field(
        default_factory=dict
    )
    #: latest per-pod usage {uid: {"cpu": mCPU, "memory": MiB}}
    latest_pod_usage: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict
    )


class Collector(Protocol):
    name: str

    def setup(self, ctx: CollectorContext) -> None: ...

    def collect(self, now: float) -> None: ...

    def enabled(self) -> bool: ...


class MetricsAdvisor:
    """Runs registered collectors (reference: metrics_advisor.go).

    ``tick`` invokes each enabled collector whose interval elapsed;
    ``collect_all`` forces one round (tests, initial sync).
    """

    def __init__(self, ctx: CollectorContext,
                 collectors: Sequence[Collector],
                 interval_seconds: float = 1.0):
        self.ctx = ctx
        self.collectors: List[Collector] = []
        self.interval_seconds = interval_seconds
        self._last_run: Dict[str, float] = {}
        for c in collectors:
            c.setup(ctx)
            self.collectors.append(c)

    def collect_all(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        for c in self.collectors:
            if c.enabled():
                c.collect(now)
                self._last_run[c.name] = now

    def tick(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        for c in self.collectors:
            if not c.enabled():
                continue
            if now - self._last_run.get(c.name, -1e18) >= self.interval_seconds:
                c.collect(now)
                self._last_run[c.name] = now
