"""Performance (CPI) collector: cycles-per-instruction via the native
perf-group module.

Reference: pkg/koordlet/metricsadvisor/collectors/performance/
performance_collector_linux.go — per running container it opens a
cycles+instructions perf group on the container cgroup (one fd per cpu),
reads the deltas each tick, and appends a CPI sample. The native source
here is koordinator_tpu/native (perf_group.cpp); the collector takes a
source *factory* so hosts without perf (locked-down
perf_event_paranoid) or tests can inject the deterministic fake backend.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional, Tuple

from koordinator_tpu.koordlet.metriccache import MetricKind
from koordinator_tpu.koordlet.metricsadvisor.framework import (
    CollectorContext,
)
from koordinator_tpu.native import PerfGroup, PerfUnavailable

#: factory: (container cgroup dir) -> PerfGroup
SourceFactory = Callable[[str], PerfGroup]


def cgroup_perf_factory(ctx: CollectorContext) -> SourceFactory:
    """Real source: perf groups on the container's (v2) cgroup dir across
    all online cpus (the reference's per-container layout)."""

    def open_source(container_dir: str) -> PerfGroup:
        cfg = ctx.system_config
        if cfg.use_cgroup_v2:
            path = os.path.join(cfg.cgroup_root, container_dir)
        else:
            # v1: perf cgroups live under the perf_event hierarchy
            path = os.path.join(cfg.cgroup_root, "perf_event", container_dir)
        fd = os.open(path, os.O_RDONLY)
        try:
            return PerfGroup.open_cgroup(fd, range(os.cpu_count() or 1))
        finally:
            os.close(fd)

    return open_source


class PerformanceCollector:
    """Appends CONTAINER_CPI samples (cycles/instruction per interval)."""

    name = "performance"

    def __init__(self, source_factory: Optional[SourceFactory] = None):
        self.ctx: Optional[CollectorContext] = None
        self._factory = source_factory
        self._sources: Dict[str, PerfGroup] = {}
        self._last: Dict[str, Tuple[int, int]] = {}
        self._failed = False

    def setup(self, ctx: CollectorContext) -> None:
        self.ctx = ctx
        if self._factory is None:
            self._factory = cgroup_perf_factory(ctx)

    def enabled(self) -> bool:
        return (
            self.ctx is not None
            and self.ctx.pod_provider is not None
            and not self._failed
        )

    def collect(self, now: float) -> None:
        ctx = self.ctx
        live = set()
        for pod in ctx.pod_provider.running_pods():
            if self._failed:
                break
            for cname, cdir in pod.containers.items():
                key = f"{pod.uid}/{cname}"
                live.add(key)
                source = self._sources.get(key)
                if source is None:
                    try:
                        source = self._factory(cdir)
                    except PerfUnavailable:
                        # no perf on this host: disable the collector
                        # rather than retrying every tick
                        self._failed = True
                        break
                    except OSError:
                        # transient: the container's cgroup vanished
                        # between listing and open — skip it this tick
                        continue
                    self._sources[key] = source
                try:
                    cycles, instr = source.read()
                except PerfUnavailable:
                    # dead fds (cgroup torn down & recreated): drop the
                    # source so the next tick reopens it fresh
                    self._sources.pop(key, None)
                    self._last.pop(key, None)
                    source.close()
                    continue
                prev = self._last.get(key)
                self._last[key] = (cycles, instr)
                if prev is None:
                    continue  # primer tick: no delta yet
                d_cycles = cycles - prev[0]
                d_instr = instr - prev[1]
                if d_instr <= 0:
                    continue
                ctx.metric_cache.append(
                    MetricKind.CONTAINER_CPI,
                    {"pod": pod.uid, "container": cname},
                    now,
                    d_cycles / d_instr,
                )
        # drop sources of containers that went away
        for key in list(self._sources):
            if key not in live:
                self._sources.pop(key).close()
                self._last.pop(key, None)
