"""The collectors: node/pod/BE/system resource usage + PSI.

Reference: pkg/koordlet/metricsadvisor/collectors/{noderesource,
podresource,beresource,sysresource}/ and util/system/psi.go. Each reads
/proc or cgroupfs (under the configurable roots, so tests use fake
trees), converts cumulative counters to rates between ticks, and appends
canonical-unit samples (mCPU / MiB) to the metric cache.

CPU usage derivation (reference: collectors/noderesource/
node_resource_collector.go): /proc/stat jiffy counters are cumulative;
usage_mcpu = delta(busy_jiffies) / USER_HZ / delta_t * 1000. Pod usage
uses the cgroup's cumulative cpu time (v1 cpuacct.usage ns; v2 cpu.stat
usage_usec).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.metriccache import MetricKind
from koordinator_tpu.koordlet.metricsadvisor.framework import CollectorContext
from koordinator_tpu.koordlet.system.cgroup import (
    CPU_ACCT_USAGE,
    MEMORY_USAGE,
    SystemConfig,
)

#: Linux USER_HZ (jiffies per second); constant on every mainstream arch.
USER_HZ = 100


def read_proc_stat_busy_jiffies(cfg: SystemConfig) -> Optional[int]:
    """Sum of non-idle jiffies from the aggregate "cpu " line of
    /proc/stat (user+nice+system+irq+softirq+steal; idle+iowait excluded,
    matching the reference's cpu usage collector)."""
    try:
        with open(os.path.join(cfg.proc_root, "stat")) as f:
            for line in f:
                if line.startswith("cpu "):
                    parts = [int(x) for x in line.split()[1:]]
                    # user nice system idle iowait irq softirq steal ...
                    idle = parts[3] + (parts[4] if len(parts) > 4 else 0)
                    return sum(parts[:8]) - idle
    except (OSError, ValueError, IndexError):
        return None
    return None


def read_meminfo_used_mib(cfg: SystemConfig) -> Optional[float]:
    """MemTotal - MemAvailable in MiB (reference: node memory collector
    uses the same definition)."""
    total = avail = None
    try:
        with open(os.path.join(cfg.proc_root, "meminfo")) as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1])  # kB
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1])
    except (OSError, ValueError, IndexError):
        return None
    if total is None or avail is None:
        return None
    return (total - avail) / 1024.0


def read_cgroup_cpu_ns(cgroup_dir: str, cfg: SystemConfig) -> Optional[int]:
    """Cumulative cpu nanoseconds of a cgroup (v1 cpuacct.usage;
    v2 cpu.stat usage_usec * 1000)."""
    try:
        raw = CPU_ACCT_USAGE.read(cgroup_dir, cfg)
    except OSError:
        return None
    if cfg.use_cgroup_v2:
        for line in raw.splitlines():
            if line.startswith("usage_usec"):
                try:
                    return int(line.split()[1]) * 1000
                except (ValueError, IndexError):
                    return None
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def read_cgroup_memory_mib(cgroup_dir: str, cfg: SystemConfig) -> Optional[float]:
    try:
        return int(MEMORY_USAGE.read(cgroup_dir, cfg)) / (1024.0 * 1024.0)
    except (OSError, ValueError):
        return None


class _RateTracker:
    """Cumulative-counter -> rate conversion between ticks."""

    def __init__(self):
        self._last: Dict[str, Tuple[float, float]] = {}  # key -> (t, value)

    def rate(self, key: str, now: float, value: float) -> Optional[float]:
        last = self._last.get(key)
        self._last[key] = (now, value)
        if last is None:
            return None
        dt = now - last[0]
        if dt <= 0:
            return None
        return max(value - last[1], 0.0) / dt

    def forget_missing(self, live_keys) -> None:
        live = set(live_keys)
        for k in list(self._last):
            if k not in live:
                del self._last[k]


class NodeResourceCollector:
    """Whole-node cpu/memory usage (reference: collectors/noderesource)."""

    name = "noderesource"

    def __init__(self):
        self._rates = _RateTracker()
        self.ctx: Optional[CollectorContext] = None

    def setup(self, ctx: CollectorContext) -> None:
        self.ctx = ctx

    def enabled(self) -> bool:
        return True

    def collect(self, now: float) -> None:
        ctx = self.ctx
        cfg = ctx.system_config
        busy = read_proc_stat_busy_jiffies(cfg)
        if busy is not None:
            jps = self._rates.rate("node_cpu", now, float(busy))
            if jps is not None:
                mcpu = jps / USER_HZ * 1000.0
                ctx.metric_cache.append(
                    MetricKind.NODE_CPU_USAGE, None, now, mcpu
                )
                ctx.latest_node_usage["cpu"] = mcpu
        mem = read_meminfo_used_mib(cfg)
        if mem is not None:
            ctx.metric_cache.append(
                MetricKind.NODE_MEMORY_USAGE, None, now, mem
            )
            ctx.latest_node_usage["memory"] = mem


class PodResourceCollector:
    """Per-pod (and per-container) usage from cgroupfs (reference:
    collectors/podresource)."""

    name = "podresource"

    def __init__(self):
        self._rates = _RateTracker()
        self.ctx: Optional[CollectorContext] = None

    def setup(self, ctx: CollectorContext) -> None:
        self.ctx = ctx

    def enabled(self) -> bool:
        return self.ctx.pod_provider is not None

    def collect(self, now: float) -> None:
        ctx = self.ctx
        cfg = ctx.system_config
        pods = list(ctx.pod_provider.running_pods())
        seen = {}
        for pod in pods:
            usage: Dict[str, float] = {}
            ns = read_cgroup_cpu_ns(pod.cgroup_dir, cfg)
            if ns is not None:
                nsps = self._rates.rate(f"pod:{pod.uid}", now, float(ns))
                if nsps is not None:
                    usage["cpu"] = nsps / 1e9 * 1000.0  # ns/s -> mCPU
                    ctx.metric_cache.append(
                        MetricKind.POD_CPU_USAGE, {"pod": pod.uid}, now,
                        usage["cpu"],
                    )
            mem = read_cgroup_memory_mib(pod.cgroup_dir, cfg)
            if mem is not None:
                usage["memory"] = mem
                ctx.metric_cache.append(
                    MetricKind.POD_MEMORY_USAGE, {"pod": pod.uid}, now, mem
                )
            for cname, cdir in pod.containers.items():
                cns = read_cgroup_cpu_ns(cdir, cfg)
                if cns is not None:
                    rate = self._rates.rate(
                        f"container:{pod.uid}/{cname}", now, float(cns)
                    )
                    if rate is not None:
                        ctx.metric_cache.append(
                            MetricKind.CONTAINER_CPU_USAGE,
                            {"pod": pod.uid, "container": cname},
                            now, rate / 1e9 * 1000.0,
                        )
            seen[pod.uid] = usage
        ctx.latest_pod_usage.clear()
        ctx.latest_pod_usage.update(seen)
        self._rates.forget_missing(
            [f"pod:{p.uid}" for p in pods]
            + [f"container:{p.uid}/{c}" for p in pods for c in p.containers]
        )


class BEResourceCollector:
    """Aggregate best-effort usage (reference: collectors/beresource):
    sum of BE pods' usage, for the cpusuppress strategy."""

    name = "beresource"

    def __init__(self):
        self.ctx: Optional[CollectorContext] = None

    def setup(self, ctx: CollectorContext) -> None:
        self.ctx = ctx

    def enabled(self) -> bool:
        return self.ctx.pod_provider is not None

    def collect(self, now: float) -> None:
        ctx = self.ctx
        be_cpu = 0.0
        have_rate = False
        for pod in ctx.pod_provider.running_pods():
            if pod.qos is not QoSClass.BE:
                continue
            usage = ctx.latest_pod_usage.get(pod.uid, {})
            # primer ticks have no cpu rate yet: no data is no sample,
            # not a zero that skews the suppress/evict aggregates
            if "cpu" in usage:
                have_rate = True
                be_cpu += usage["cpu"]
        if have_rate:
            ctx.metric_cache.append(
                MetricKind.BE_CPU_USAGE, None, now, be_cpu
            )


class SysResourceCollector:
    """System usage = node usage - Σ pod usage, clamped at zero
    (reference: collectors/sysresource — feeds the batch overcommit
    calculator's System.Used term)."""

    name = "sysresource"

    def __init__(self):
        self.ctx: Optional[CollectorContext] = None

    def setup(self, ctx: CollectorContext) -> None:
        self.ctx = ctx

    def enabled(self) -> bool:
        return True

    def collect(self, now: float) -> None:
        ctx = self.ctx
        node = ctx.latest_node_usage
        if not node:
            return
        pods_cpu = sum(u.get("cpu", 0.0) for u in ctx.latest_pod_usage.values())
        pods_mem = sum(
            u.get("memory", 0.0) for u in ctx.latest_pod_usage.values()
        )
        if "cpu" in node:
            ctx.metric_cache.append(
                MetricKind.SYS_CPU_USAGE, None, now,
                max(node["cpu"] - pods_cpu, 0.0),
            )
        if "memory" in node:
            ctx.metric_cache.append(
                MetricKind.SYS_MEMORY_USAGE, None, now,
                max(node["memory"] - pods_mem, 0.0),
            )


def read_psi_avg10(path: str, want_full: bool = False) -> Optional[float]:
    """Parse "some avg10=X ..." / "full avg10=X ..." from a PSI file
    (reference: util/system/psi.go)."""
    try:
        with open(path) as f:
            for line in f:
                kind, _, rest = line.partition(" ")
                if (kind == "full") == want_full:
                    for field in rest.split():
                        if field.startswith("avg10="):
                            return float(field[len("avg10="):])
    except (OSError, ValueError):
        return None
    return None


class PSICollector:
    """Node pressure-stall information from /proc/pressure (reference:
    PSICollector feature gate + collectors wiring psi into metriccache)."""

    name = "psi"

    _SOURCES = (
        ("cpu", False, MetricKind.PSI_CPU_SOME_AVG10),
        ("memory", False, MetricKind.PSI_MEM_SOME_AVG10),
        ("memory", True, MetricKind.PSI_MEM_FULL_AVG10),
        ("io", False, MetricKind.PSI_IO_SOME_AVG10),
    )

    def __init__(self):
        self.ctx: Optional[CollectorContext] = None

    def setup(self, ctx: CollectorContext) -> None:
        self.ctx = ctx

    def enabled(self) -> bool:
        return os.path.isdir(
            os.path.join(self.ctx.system_config.proc_root, "pressure")
        )

    def collect(self, now: float) -> None:
        ctx = self.ctx
        base = os.path.join(ctx.system_config.proc_root, "pressure")
        for res, full, kind in self._SOURCES:
            v = read_psi_avg10(os.path.join(base, res), full)
            if v is not None:
                ctx.metric_cache.append(kind, None, now, v)


def default_collectors():
    """The standard collector set (reference: metrics_advisor.go
    collector registry). Device/throttled/storage collectors self-gate
    via enabled() on their source trees."""
    from koordinator_tpu.koordlet.metricsadvisor.devices import (
        DeviceCollector,
        NodeStorageInfoCollector,
        PodThrottledCollector,
    )

    return [
        NodeResourceCollector(),
        PodResourceCollector(),
        BEResourceCollector(),
        SysResourceCollector(),
        PSICollector(),
        DeviceCollector(),
        PodThrottledCollector(),
        NodeStorageInfoCollector(),
    ]


class ColdMemoryCollector:
    """kidled cold-page collector (reference: metricsadvisor/collectors/
    coldmemoryresource, ColdPageCollector feature gate): reads the root
    cgroup's memory.idle_page_stats and appends the reclaimable cold-page
    bytes."""

    name = "coldmemory"

    def __init__(self, cold_boundary: Optional[int] = None):
        from koordinator_tpu.koordlet.system.kidled import (
            DEFAULT_COLD_BOUNDARY,
        )

        self.ctx: Optional[CollectorContext] = None
        self.cold_boundary = (
            cold_boundary if cold_boundary is not None else DEFAULT_COLD_BOUNDARY
        )
        self._kidled = None

    #: default scan cadence written at setup when kidled is idle
    #: (reference: kidled_util.go defaultKidledScanPeriodInSeconds)
    DEFAULT_SCAN_PERIOD_SECONDS = 120

    def setup(self, ctx: CollectorContext) -> None:
        from koordinator_tpu.koordlet.system.kidled import Kidled

        self.ctx = ctx
        self._kidled = Kidled(ctx.system_config)
        if self._kidled.supported():
            # the kernel default scan period is 0 (scanning off): start
            # scanning or idle_page_stats never accumulates (the
            # reference collector configures kidled at startup)
            try:
                self._kidled.set_scan_period(self.DEFAULT_SCAN_PERIOD_SECONDS)
                self._kidled.set_use_hierarchy(True)
            except OSError:
                self._kidled = None

    def enabled(self) -> bool:
        return self._kidled is not None and self._kidled.supported()

    def collect(self, now: float) -> None:
        stats = self._kidled.read_stats("")
        if stats is None:
            return
        self.ctx.metric_cache.append(
            MetricKind.NODE_COLD_PAGE_BYTES, None, now,
            float(stats.cold_page_bytes(self.cold_boundary)),
        )


class PageCacheCollector:
    """Node page-cache collector (reference: collectors/pagecache): the
    meminfo Cached amount, feeding cache-aware overcommit policies."""

    name = "pagecache"

    def __init__(self):
        self.ctx: Optional[CollectorContext] = None

    def setup(self, ctx: CollectorContext) -> None:
        self.ctx = ctx

    def enabled(self) -> bool:
        return os.path.exists(
            os.path.join(self.ctx.system_config.proc_root, "meminfo")
        )

    def collect(self, now: float) -> None:
        path = os.path.join(self.ctx.system_config.proc_root, "meminfo")
        try:
            with open(path) as f:
                for line in f:
                    if line.startswith("Cached:"):
                        kb = int(line.split()[1])
                        self.ctx.metric_cache.append(
                            MetricKind.NODE_PAGE_CACHE_MIB, None, now,
                            kb / 1024.0,
                        )
                        return
        except (OSError, ValueError, IndexError):
            return


class HostApplicationCollector:
    """Host-application usage collector (reference: collectors/
    hostapplication): per NodeSLO host app, read its cgroup cpu/memory
    and append HOST_APP_* samples with the app label. The informer's
    NodeSLO carries the app list (statesinformer.get_node_slo)."""

    name = "hostapplication"

    def __init__(self, slo_provider=None):
        #: callable returning the current NodeSLOSpec (the informer)
        self.slo_provider = slo_provider
        self.ctx: Optional[CollectorContext] = None
        self._rates = _RateTracker()

    def setup(self, ctx: CollectorContext) -> None:
        self.ctx = ctx

    def enabled(self) -> bool:
        return self.slo_provider is not None

    def collect(self, now: float) -> None:
        ctx = self.ctx
        cfg = ctx.system_config
        slo = self.slo_provider()
        raw = getattr(slo, "host_applications", None) or []
        # duplicate names would interleave unrelated cumulative counters
        # through one rate-tracker key (garbage rates) — first wins
        apps = list({app.name: app for app in reversed(raw)}.values())[::-1]
        for app in apps:
            if not app.cgroup_dir:
                continue
            ns = read_cgroup_cpu_ns(app.cgroup_dir, cfg)
            if ns is not None:
                rate = self._rates.rate(f"hostapp:{app.name}", now, float(ns))
                if rate is not None:
                    ctx.metric_cache.append(
                        MetricKind.HOST_APP_CPU_USAGE, {"app": app.name},
                        now, rate / 1e9 * 1000.0,
                    )
            mem = read_cgroup_memory_mib(app.cgroup_dir, cfg)
            if mem is not None:
                ctx.metric_cache.append(
                    MetricKind.HOST_APP_MEMORY_USAGE, {"app": app.name},
                    now, mem,
                )
        self._rates.forget_missing([f"hostapp:{a.name}" for a in apps])
