"""Device telemetry + podthrottled + nodestorageinfo collectors.

Reference: pkg/koordlet/metricsadvisor/devices/gpu/collector_gpu_linux.go
(NVML inventory/health/utilization feeding the Device CR), and
collectors/{podthrottled,nodestorageinfo}. The TPU-native analogue reads
a sysfs-style accelerator tree — the shape libtpu-metrics exports —
instead of binding NVML:

    <sysfs_root>/class/accel/accel<N>/
        device_type    ("tpu" | "gpu" | ...)
        healthy        ("1" | "0")
        mem_total_mib  (int)
        mem_used_mib   (int)
        utilization    (percent int)
        numa_node, socket_id, pcie_id

Tests point ``SystemConfig.sysfs_root`` at a fake tree (the same pattern
as the cgroupfs fakes). The collector is both a metricsadvisor plugin
(utilization/memory samples into the TSDB) and a
``statesinformer.DeviceSource`` (inventory for the DeviceReporter →
Device objects on the bus → DeviceShare).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

from koordinator_tpu.device.cache import (
    DeviceEntry,
    DeviceResourceName,
    DeviceType,
)
from koordinator_tpu.koordlet.metriccache import MetricKind
from koordinator_tpu.koordlet.metricsadvisor.collectors import _RateTracker
from koordinator_tpu.koordlet.metricsadvisor.framework import (
    CollectorContext,
)
from koordinator_tpu.koordlet.system.cgroup import CPU_STAT, SystemConfig


def _read(path: str) -> Optional[str]:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return None


def _read_int(path: str) -> Optional[int]:
    raw = _read(path)
    if raw is None:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


class DeviceCollector:
    """Accelerator inventory + telemetry from the sysfs accel tree."""

    name = "device"

    def __init__(self, cfg: Optional[SystemConfig] = None):
        self.ctx: Optional[CollectorContext] = None
        self._cfg = cfg

    def setup(self, ctx: CollectorContext) -> None:
        self.ctx = ctx
        if self._cfg is None:
            self._cfg = ctx.system_config

    def _accel_root(self) -> str:
        return os.path.join(self._cfg.sysfs_root, "class", "accel")

    def enabled(self) -> bool:
        return os.path.isdir(self._accel_root())

    def _minors(self) -> List[int]:
        try:
            names = os.listdir(self._accel_root())
        except OSError:
            return []
        minors = []
        for name in names:
            if name.startswith("accel"):
                try:
                    minors.append(int(name[len("accel"):]))
                except ValueError:
                    continue
        return sorted(minors)

    # -- statesinformer.DeviceSource -----------------------------------------

    def list_devices(self) -> List[DeviceEntry]:
        """Typed inventory for the Device reporting path (the NVML
        device-info read, collector_gpu_linux.go)."""
        entries = []
        for minor in self._minors():
            d = os.path.join(self._accel_root(), f"accel{minor}")
            mem_total = _read_int(os.path.join(d, "mem_total_mib")) or 0
            dtype = _read(os.path.join(d, "device_type")) or "gpu"
            entries.append(DeviceEntry(
                minor=minor,
                device_type=(
                    DeviceType(dtype)
                    if dtype in DeviceType._value2member_map_
                    else DeviceType.GPU
                ),
                resources={
                    DeviceResourceName.GPU_CORE: 100,
                    DeviceResourceName.GPU_MEMORY: mem_total,
                    DeviceResourceName.GPU_MEMORY_RATIO: 100,
                },
                socket_id=_read_int(os.path.join(d, "socket_id")) or 0,
                numa_node=_read_int(os.path.join(d, "numa_node")) or 0,
                pcie_id=_read(os.path.join(d, "pcie_id")) or "0",
                labels={"type": dtype},
                health=(_read(os.path.join(d, "healthy")) != "0"),
            ))
        return entries

    # -- metricsadvisor.Collector --------------------------------------------

    def collect(self, now: float) -> None:
        cache = self.ctx.metric_cache
        for minor in self._minors():
            d = os.path.join(self._accel_root(), f"accel{minor}")
            util = _read_int(os.path.join(d, "utilization"))
            if util is not None:
                cache.append(
                    MetricKind.DEVICE_UTIL, {"minor": str(minor)}, now,
                    float(util),
                )
            used = _read_int(os.path.join(d, "mem_used_mib"))
            if used is not None:
                cache.append(
                    MetricKind.DEVICE_MEMORY_USED, {"minor": str(minor)},
                    now, float(used),
                )


def read_cgroup_cpu_stat(cgroup_dir: str,
                         cfg: SystemConfig) -> Optional[Dict[str, int]]:
    """Parse cpu.stat's nr_periods/nr_throttled/throttled_time
    (v1 cpu/cpu.stat; v2 cpu.stat carries the same keys plus usage)."""
    try:
        raw = CPU_STAT.read(cgroup_dir, cfg)
    except OSError:
        return None
    out: Dict[str, int] = {}
    for line in raw.splitlines():
        parts = line.split()
        if len(parts) == 2:
            try:
                out[parts[0]] = int(parts[1])
            except ValueError:
                continue
    if "nr_periods" not in out:
        return None
    return out


class PodThrottledCollector:
    """Per-pod cfs throttling ratio (reference: collectors/podthrottled):
    Δnr_throttled / Δnr_periods between ticks."""

    name = "podthrottled"

    def __init__(self):
        self._periods = _RateTracker()
        self._throttled = _RateTracker()
        self.ctx: Optional[CollectorContext] = None

    def setup(self, ctx: CollectorContext) -> None:
        self.ctx = ctx

    def enabled(self) -> bool:
        return self.ctx.pod_provider is not None

    def collect(self, now: float) -> None:
        ctx = self.ctx
        cfg = ctx.system_config
        pods = list(ctx.pod_provider.running_pods())
        for pod in pods:
            stat = read_cgroup_cpu_stat(pod.cgroup_dir, cfg)
            if stat is None:
                continue
            dp = self._periods.rate(
                f"pod:{pod.uid}", now, float(stat["nr_periods"])
            )
            dt = self._throttled.rate(
                f"pod:{pod.uid}", now, float(stat.get("nr_throttled", 0))
            )
            if dp is None or dt is None or dp <= 0:
                continue
            ctx.metric_cache.append(
                MetricKind.POD_CPU_THROTTLED_RATIO, {"pod": pod.uid}, now,
                min(dt / dp, 1.0),
            )
        self._periods.forget_missing([f"pod:{p.uid}" for p in pods])
        self._throttled.forget_missing([f"pod:{p.uid}" for p in pods])


#: /proc/diskstats columns (0-indexed after the 3 id fields):
#: 0=reads completed, 2=sectors read, 4=writes completed,
#: 6=sectors written, 9=io_ticks (ms busy)
_SECTOR_BYTES = 512

#: partition device names (sda1, vdb2, nvme0n1p1, mmcblk0p2, xvda1) —
#: the kernel folds partition I/O into the parent disk's counters, so
#: counting both would double-count throughput
_PARTITION_RE = re.compile(
    r"^(?:nvme\d+n\d+p\d+|mmcblk\d+p\d+|(?:[hsv]d|xvd)[a-z]+\d+)$"
)


class NodeStorageInfoCollector:
    """Node disk throughput + io utilization from /proc/diskstats
    (reference: collectors/nodestorageinfo)."""

    name = "nodestorageinfo"

    def __init__(self):
        self._rates = _RateTracker()
        self.ctx: Optional[CollectorContext] = None

    def setup(self, ctx: CollectorContext) -> None:
        self.ctx = ctx

    def _path(self) -> str:
        return os.path.join(self.ctx.system_config.proc_root, "diskstats")

    def enabled(self) -> bool:
        return os.path.exists(self._path())

    def collect(self, now: float) -> None:
        ctx = self.ctx
        try:
            with open(self._path()) as f:
                lines = f.read().splitlines()
        except OSError:
            return
        live = []
        for line in lines:
            parts = line.split()
            if len(parts) < 14:
                continue
            dev = parts[2]
            if _PARTITION_RE.match(dev):
                continue  # whole disks only
            live.append(dev)
            fields = [int(x) for x in parts[3:]]
            read_bps = self._rates.rate(
                f"{dev}:read", now, float(fields[2] * _SECTOR_BYTES)
            )
            write_bps = self._rates.rate(
                f"{dev}:write", now, float(fields[6] * _SECTOR_BYTES)
            )
            util = self._rates.rate(f"{dev}:ticks", now, float(fields[9]))
            labels = {"dev": dev}
            if read_bps is not None:
                ctx.metric_cache.append(
                    MetricKind.NODE_DISK_READ_BPS, labels, now, read_bps
                )
            if write_bps is not None:
                ctx.metric_cache.append(
                    MetricKind.NODE_DISK_WRITE_BPS, labels, now, write_bps
                )
            if util is not None:
                # io_ticks is ms busy per wall second -> percent
                ctx.metric_cache.append(
                    MetricKind.NODE_DISK_IO_UTIL, labels, now,
                    min(util / 10.0, 100.0),
                )
        self._rates.forget_missing(
            [f"{d}:{k}" for d in live for k in ("read", "write", "ticks")]
        )
