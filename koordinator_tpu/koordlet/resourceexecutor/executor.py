"""Serialized, cached, audited cgroup writer.

Reference: pkg/koordlet/resourceexecutor/{executor.go,updater.go} — all
cgroup mutations in koordlet flow through one executor that:

- skips writes whose value already matches the cached last-written value
  (``cacheable`` updates, executor.go:240 updateByCache);
- supports *merge conditions* for files where an intermediate state must
  stay safe during top-down reconciliation (e.g. only shrink cfs quota
  after children shrank: updater.go:441 MergeConditionIfValueIsLarger,
  MergeConditionIfCFSQuotaIsLarger, MergeConditionIfCPUSetIsLooser);
- runs leveled batches: merge-update top->down, then final-update
  bottom->up (executor.go:114 LeveledUpdateBatch), so parent cgroup
  values are always >= their children's during the transition;
- audits every actual write (updater.go audit.V(3).Record calls).

The reference serializes through a singleton goroutine + cache GC; here
calls are direct (CPython's GIL + single reconcile loop) with the same
cache semantics — entries expire so external drift is re-written.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.system.cgroup import (
    CONFIG,
    CgroupResource,
    SystemConfig,
    V1_SUBSYSTEMS,
    get_resource,
)

#: (old_value, new_value) -> (value_to_write, need_write)
MergeCondition = Callable[[str, str], Tuple[str, bool]]


def merge_if_value_larger(old: str, new: str) -> Tuple[str, bool]:
    """Write only when the new integer value is larger (reference:
    updater.go:441 MergeConditionIfValueIsLarger)."""
    try:
        o, n = int(old), int(new)
    except ValueError:
        return new, True
    return new, n > o


def parse_cfs_quota(raw: str) -> Optional[int]:
    """Quota microseconds from a v1 cpu.cfs_quota_us or v2 cpu.max
    content; "max" and -1 both mean unlimited (-1). None if unparsable."""
    try:
        return int(raw.split()[0].replace("max", "-1"))
    except (ValueError, IndexError):
        return None


def merge_if_cfs_quota_larger(old: str, new: str) -> Tuple[str, bool]:
    """cfs_quota: -1 (unlimited) is the largest value (reference:
    updater.go MergeConditionIfCFSQuotaIsLarger)."""
    o = parse_cfs_quota(old)
    try:
        n = int(new)
    except ValueError:
        n = None
    if o is None or n is None:
        return new, True
    if o == -1:
        return new, False
    if n == -1:
        return new, True
    return new, n > o


def _parse_cpuset(value: str) -> frozenset:
    cpus = set()
    for part in value.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-")
            cpus.update(range(int(lo), int(hi) + 1))
        else:
            cpus.add(int(part))
    return frozenset(cpus)


def merge_if_cpuset_looser(old: str, new: str) -> Tuple[str, bool]:
    """cpuset: merge pass writes the union so children never lose their
    current cpus mid-transition (reference: updater.go
    MergeConditionIfCPUSetIsLooser)."""
    try:
        o, n = _parse_cpuset(old), _parse_cpuset(new)
    except ValueError:
        return new, True
    union = o | n
    if union == o:
        return old, False
    merged = ",".join(str(c) for c in sorted(union))
    return merged, True


@dataclasses.dataclass
class CgroupUpdater:
    """One pending write (reference: updater.go CgroupResourceUpdater)."""

    resource_type: str
    parent_dir: str
    value: str
    merge_condition: Optional[MergeCondition] = None
    #: extra cache-key component for files holding multiple independent
    #: entries (device-keyed blkio throttles: one key per device)
    key_extra: str = ""

    def resource(self) -> CgroupResource:
        return get_resource(self.resource_type)

    def key(self, cfg: SystemConfig) -> str:
        # keyed by resource type AND path: distinct resources can share a
        # packed v2 file (cpu.cfs_quota_us and cpu.cfs_period_us both map
        # to cpu.max) and must not collide in the cache
        base = f"{self.resource_type}:{self.resource().path(self.parent_dir, cfg)}"
        return f"{base}:{self.key_extra}" if self.key_extra else base


class ResourceUpdateExecutor:
    """The single write path to cgroupfs."""

    def __init__(
        self,
        config: Optional[SystemConfig] = None,
        auditor: Optional[Auditor] = None,
        cache_ttl: float = 300.0,
        clock=time.time,
    ):
        self.config = config or CONFIG
        self.auditor = auditor or Auditor()
        self.cache_ttl = cache_ttl
        self._clock = clock
        # path -> (value_written, expiry)
        self._cache: Dict[str, Tuple[str, float]] = {}

    # -- cache ---------------------------------------------------------------

    def _cached(self, key: str) -> Optional[str]:
        hit = self._cache.get(key)
        if hit is None:
            return None
        value, expiry = hit
        if self._clock() > expiry:
            del self._cache[key]
            return None
        return value

    def _remember(self, key: str, value: str) -> None:
        self._cache[key] = (value, self._clock() + self.cache_ttl)

    # -- update --------------------------------------------------------------

    def update(self, cacheable: bool, updater: CgroupUpdater,
               merge: bool = False) -> bool:
        """Apply one update; returns True when the file was written.

        ``merge=True`` applies the updater's merge condition against the
        current file content (the top-down pass of a leveled batch).
        """
        resource = updater.resource()
        if self.config.use_cgroup_v2 and resource.v2_file is None:
            return False
        if not resource.validate(updater.value, self.config):
            self.auditor.log(
                "resourceexecutor", updater.key(self.config), "reject",
                f"invalid value {updater.value!r}",
            )
            return False

        path = resource.path(updater.parent_dir, self.config)
        key = updater.key(self.config)
        value = updater.value

        current = None
        if merge and updater.merge_condition is not None:
            # the merge condition needs the live content, and the merged
            # value is what the cache must compare against; v2 content is
            # decoded into v1 conventions first (cpu.weight -> shares,
            # "max" -> -1) so the comparison happens in one value space
            try:
                current = resource.read(updater.parent_dir, self.config)
            except OSError:
                current = ""
            value, need = updater.merge_condition(
                resource.decode(current, self.config), value
            )
            if not need:
                return False
        if cacheable and self._cached(key) == value:
            # cache hit short-circuits BEFORE any read: steady-state
            # reconcile ticks cost zero cgroupfs I/O
            return False
        if current is None:
            # packed v2 files (cpu.max) need the live content to encode
            if self.config.use_cgroup_v2 and resource.v2_encode is not None:
                try:
                    current = resource.read(updater.parent_dir, self.config)
                except OSError:
                    current = ""
            else:
                current = ""

        try:
            content = resource.encode(value, current, self.config)
        except (ValueError, TypeError) as e:
            self.auditor.log(
                "resourceexecutor", path, "reject",
                f"cannot encode {value!r}: {e}",
            )
            return False
        try:
            resource.write(updater.parent_dir, content, self.config)
        except OSError as e:
            self.auditor.log(
                "resourceexecutor", path, "error", f"write failed: {e}"
            )
            return False
        self._remember(key, value)
        self.auditor.log(
            "resourceexecutor", path, "update", f"-> {content!r}"
        )
        from koordinator_tpu.metrics.components import CGROUP_WRITES

        CGROUP_WRITES.inc({"resource": updater.resource_type})
        return True

    def update_batch(self, cacheable: bool,
                     updaters: Sequence[CgroupUpdater]) -> int:
        return sum(
            1 for u in updaters if self.update(cacheable, u)
        )

    def leveled_update_batch(
        self, levels: Sequence[Sequence[CgroupUpdater]]
    ) -> int:
        """Two-phase hierarchy-safe reconcile (reference:
        executor.go:114-190): merge-update from the top level down (values
        only grow/loosen), then plain update from the bottom level up
        (values settle to their targets)."""
        written = 0
        for level in levels:
            for u in level:
                if self.update(True, u, merge=True):
                    written += 1
        for level in reversed(levels):
            for u in level:
                if self.update(True, u):
                    written += 1
        return written


def ensure_cgroup_dir(parent_dir: str, cfg: Optional[SystemConfig] = None,
                      subfs: Sequence[str] = V1_SUBSYSTEMS) -> None:
    """Create the fake-cgroupfs directories for tests (reference:
    testutil NewFileTestUtil.MkDirAll)."""
    cfg = cfg or CONFIG
    if cfg.use_cgroup_v2:
        os.makedirs(os.path.join(cfg.cgroup_root, parent_dir), exist_ok=True)
    else:
        for fs in subfs:
            os.makedirs(
                os.path.join(cfg.cgroup_root, fs, parent_dir), exist_ok=True
            )
