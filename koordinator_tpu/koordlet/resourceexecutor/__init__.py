from koordinator_tpu.koordlet.resourceexecutor.executor import (
    CgroupUpdater,
    ResourceUpdateExecutor,
    merge_if_cfs_quota_larger,
    merge_if_cpuset_looser,
    merge_if_value_larger,
)

__all__ = [
    "CgroupUpdater",
    "ResourceUpdateExecutor",
    "merge_if_cfs_quota_larger",
    "merge_if_cpuset_looser",
    "merge_if_value_larger",
]
