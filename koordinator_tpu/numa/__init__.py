"""NUMA-aware fine-grained CPU/resource allocation.

TPU-native rebuild of the reference's NodeNUMAResource plugin and
scheduler-level topology manager (reference:
pkg/scheduler/plugins/nodenumaresource/, pkg/scheduler/frameworkext/
topologymanager/). Per-node CPU topologies are small fixed arrays, so the
inherently sequential greedy take() runs host-side on NumPy arrays (the
batched node-level Filter/Score stays on device, see SURVEY.md §7 step 6);
NUMA-node resource hints are bitmask arithmetic over at most 8 NUMA nodes.
"""

from koordinator_tpu.numa.topology import (  # noqa: F401
    CPUBindPolicy,
    CPUExclusivePolicy,
    CPUTopology,
    NUMAAllocateStrategy,
)
from koordinator_tpu.numa.accumulator import take_cpus, take_preferred_cpus  # noqa: F401
from koordinator_tpu.numa.hints import (  # noqa: F401
    NUMATopologyHint,
    NUMATopologyPolicy,
    merge_hints,
)
from koordinator_tpu.numa.manager import (  # noqa: F401
    NodeAllocation,
    PodAllocation,
    ResourceManager,
    TopologyOptions,
)
