"""Topology-aligned CPU take(): the greedy cpuset bin-packer.

Semantics oracle: pkg/scheduler/plugins/nodenumaresource/cpu_accumulator.go
(takeCPUs :87, takePreferredCPUs :29, cpuAccumulator :234). The phase order
and every tie-breaking sort are preserved exactly; orderings are expressed
as ``np.lexsort`` keys over the topology arrays instead of Go sort.Slice
closures. This runs host-side per node: the candidate-node fan-out is the
batched device solver, the per-node take() is a ≤256-element greedy that
would not benefit from the MXU (SURVEY.md §7 hard part (b)).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from koordinator_tpu.numa.topology import (
    AllocatedCPUs,
    CPUBindPolicy,
    CPUExclusivePolicy,
    CPUTopology,
    NUMAAllocateStrategy,
)


class CPUAllocationError(Exception):
    pass


class _Accumulator:
    """Mutable take() state (reference: cpuAccumulator cpu_accumulator.go:234)."""

    def __init__(
        self,
        topology: CPUTopology,
        max_ref_count: int,
        available: np.ndarray,            # bool [C]
        allocated: AllocatedCPUs,
        num_needed: int,
        exclusive_policy: CPUExclusivePolicy,
        strategy: NUMAAllocateStrategy,
    ):
        self.topo = topology
        self.max_ref_count = max_ref_count
        self.avail = available.copy()
        self.needed = int(num_needed)
        self.exclusive_policy = exclusive_policy
        self.exclusive = exclusive_policy in (
            CPUExclusivePolicy.PCPU_LEVEL,
            CPUExclusivePolicy.NUMA_NODE_LEVEL,
        )
        self.excl_cores = set(allocated.exclusive_in_cores)
        self.excl_nodes = set(allocated.exclusive_in_numa_nodes)
        self.strategy = strategy
        # ref counts only matter when cpus may be shared (maxRefCount > 1,
        # reference: newCPUAccumulator :269-274)
        self.ref = (
            allocated.ref_count.astype(np.int64)
            if max_ref_count > 1
            else np.zeros(topology.num_cpus, dtype=np.int64)
        )
        self.result: List[int] = []

    # -- predicates (reference :306-330) ------------------------------------
    def needs(self, n: int) -> bool:
        return self.needed >= n

    @property
    def satisfied(self) -> bool:
        return self.needed < 1

    @property
    def failed(self) -> bool:
        return self.needed > int(self.avail.sum())

    def _core_excluded(self, core: int) -> bool:
        return (
            self.exclusive_policy == CPUExclusivePolicy.PCPU_LEVEL
            and core in self.excl_cores
        )

    def _node_excluded(self, node: int) -> bool:
        return (
            self.exclusive_policy == CPUExclusivePolicy.NUMA_NODE_LEVEL
            and node in self.excl_nodes
        )

    # -- mutation (reference take() :290-304) -------------------------------
    def take(self, cpus) -> None:
        cpus = [int(c) for c in cpus]
        self.result.extend(cpus)
        for c in cpus:
            self.avail[c] = False
            if self.exclusive:
                if self.exclusive_policy == CPUExclusivePolicy.PCPU_LEVEL:
                    self.excl_cores.add(int(self.topo.core_id[c]))
                elif self.exclusive_policy == CPUExclusivePolicy.NUMA_NODE_LEVEL:
                    self.excl_nodes.add(int(self.topo.node_id[c]))
        self.needed -= len(cpus)

    # -- orderings ----------------------------------------------------------
    def _strategy_key(self, free_score: int) -> int:
        """Ascending sort key: most-allocated prefers the *least* free."""
        if self.strategy == NUMAAllocateStrategy.MOST_ALLOCATED:
            return free_score
        return -free_score

    def _sorted_core_cpus(self, cores: List[int],
                          cpus_in_cores: Dict[int, np.ndarray]) -> List[int]:
        """Core order within a node/socket: cpu count desc, core ref count
        asc (shared mode), core id asc (reference sortCores :345-368);
        cpus within a core ascend."""
        def key(core):
            ref = int(self.ref[cpus_in_cores[core]].sum()) if self.max_ref_count > 1 else 0
            return (-len(cpus_in_cores[core]), ref, core)

        out: List[int] = []
        for core in sorted(cores, key=key):
            out.extend(sorted(int(c) for c in cpus_in_cores[core]))
        return out

    def _group_cores(self, cpu_ids: np.ndarray) -> Dict[int, np.ndarray]:
        groups: Dict[int, list] = {}
        for c in cpu_ids:
            groups.setdefault(int(self.topo.core_id[c]), []).append(int(c))
        return {k: np.asarray(v) for k, v in groups.items()}

    def _sort_cpus_by_ref(self, cpus: List[int]) -> List[int]:
        if self.max_ref_count > 1:
            return sorted(cpus, key=lambda c: (int(self.ref[c]), c))
        return cpus

    def _extract_one_per_core(self, cpus: List[int]) -> List[int]:
        """First cpu of each core in current order (reference extractCPU :332)."""
        seen, out = set(), []
        for c in cpus:
            core = int(self.topo.core_id[c])
            if core not in seen:
                seen.add(core)
                out.append(c)
        return out

    def free_cores_in_node(self, full_only: bool, filter_exclusive: bool) -> List[List[int]]:
        """Free-core cpu lists grouped by NUMA node, node-sorted by the NUMA
        strategy (reference freeCoresInNode :371-461)."""
        cpu_ids = np.flatnonzero(self.avail)
        if filter_exclusive:
            cpu_ids = np.asarray(
                [c for c in cpu_ids if not self._node_excluded(int(self.topo.node_id[c]))],
                dtype=np.int64,
            )
        if cpu_ids.size == 0:
            return []
        socket_free: Dict[int, int] = {}
        for c in cpu_ids:
            socket_free[int(self.topo.socket_id[c])] = (
                socket_free.get(int(self.topo.socket_id[c]), 0) + 1
            )
        cpus_in_cores = self._group_cores(cpu_ids)
        if full_only:
            cpus_in_cores = {
                k: v for k, v in cpus_in_cores.items()
                if len(v) == self.topo.cpus_per_core
            }
        cores_in_nodes: Dict[int, List[int]] = {}
        for core, cpus in cpus_in_cores.items():
            cores_in_nodes.setdefault(int(self.topo.node_id[cpus[0]]), []).append(core)

        cpus_in_nodes = {
            node: self._sorted_core_cpus(cores, cpus_in_cores)
            for node, cores in cores_in_nodes.items()
        }

        def node_key(node):
            some_cpu = cpus_in_nodes[node][0]
            socket = int(self.topo.socket_id[some_cpu])
            return (
                self._strategy_key(len(cpus_in_nodes[node])),
                self._strategy_key(socket_free.get(socket, 0)),
                node,
            )

        return [cpus_in_nodes[n] for n in sorted(cpus_in_nodes, key=node_key)]

    def free_cores_in_socket(self, full_only: bool) -> List[List[int]]:
        """Free-core cpu lists grouped by socket (reference freeCoresInSocket
        :464-527; note: no exclusive filtering, matching the reference)."""
        cpu_ids = np.flatnonzero(self.avail)
        if cpu_ids.size == 0:
            return []
        cpus_in_cores = self._group_cores(cpu_ids)
        if full_only:
            cpus_in_cores = {
                k: v for k, v in cpus_in_cores.items()
                if len(v) == self.topo.cpus_per_core
            }
        cores_in_sockets: Dict[int, List[int]] = {}
        for core, cpus in cpus_in_cores.items():
            cores_in_sockets.setdefault(int(self.topo.socket_id[cpus[0]]), []).append(core)
        cpus_in_sockets = {
            s: self._sorted_core_cpus(cores, cpus_in_cores)
            for s, cores in cores_in_sockets.items()
        }

        def socket_key(s):
            return (self._strategy_key(len(cpus_in_sockets[s])), s)

        return [cpus_in_sockets[s] for s in sorted(cpus_in_sockets, key=socket_key)]

    def free_cpus_in_node(self, filter_exclusive: bool) -> List[List[int]]:
        """All free cpus grouped by NUMA node (reference freeCPUsInNode
        :530-605): used by the SpreadByPCPUs path."""
        cpu_ids = [
            int(c) for c in np.flatnonzero(self.avail)
            if not (
                filter_exclusive
                and (
                    self._core_excluded(int(self.topo.core_id[c]))
                    or self._node_excluded(int(self.topo.node_id[c]))
                )
            )
        ]
        if not cpu_ids:
            return []
        node_free: Dict[int, int] = {}
        socket_free: Dict[int, int] = {}
        cpus_in_nodes: Dict[int, List[int]] = {}
        for c in cpu_ids:
            node = int(self.topo.node_id[c])
            socket = int(self.topo.socket_id[c])
            node_free[node] = node_free.get(node, 0) + 1
            socket_free[socket] = socket_free.get(socket, 0) + 1
            cpus_in_nodes.setdefault(node, []).append(c)
        for node, cpus in cpus_in_nodes.items():
            cpus = self._sort_cpus_by_ref(sorted(cpus))
            if filter_exclusive:
                cpus = self._extract_one_per_core(cpus)
            cpus_in_nodes[node] = cpus

        def node_key(node):
            socket = int(self.topo.socket_id[cpus_in_nodes[node][0]])
            return (
                self._strategy_key(node_free[node]),
                self._strategy_key(socket_free[socket]),
                node,
            )

        return [cpus_in_nodes[n] for n in sorted(cpus_in_nodes, key=node_key)]

    def free_cpus_in_socket(self, filter_exclusive: bool) -> List[List[int]]:
        """All free cpus grouped by socket (reference freeCPUsInSocket
        :608-656; PCPU-level exclusion only)."""
        cpu_ids = [
            int(c) for c in np.flatnonzero(self.avail)
            if not (filter_exclusive and self._core_excluded(int(self.topo.core_id[c])))
        ]
        if not cpu_ids:
            return []
        cpus_in_sockets: Dict[int, List[int]] = {}
        for c in cpu_ids:
            cpus_in_sockets.setdefault(int(self.topo.socket_id[c]), []).append(c)
        for s, cpus in cpus_in_sockets.items():
            cpus = self._sort_cpus_by_ref(sorted(cpus))
            if filter_exclusive:
                cpus = self._extract_one_per_core(cpus)
            cpus_in_sockets[s] = cpus

        def socket_key(s):
            return (self._strategy_key(len(cpus_in_sockets[s])), s)

        return [cpus_in_sockets[s] for s in sorted(cpus_in_sockets, key=socket_key)]

    def free_cpus(self, filter_exclusive: bool) -> List[int]:
        """Global core-major cpu ordering for the last-resort fill
        (reference freeCPUs :666-774): socket affinity with already-taken
        cpus first, then strategy scores, then core fill, stable ids."""
        cpu_ids = [
            int(c) for c in np.flatnonzero(self.avail)
            if not (
                filter_exclusive
                and (
                    self._core_excluded(int(self.topo.core_id[c]))
                    or self._node_excluded(int(self.topo.node_id[c]))
                )
            )
        ]
        if not cpu_ids:
            return []
        cpus_in_cores: Dict[int, List[int]] = {}
        node_free: Dict[int, int] = {}
        socket_free: Dict[int, int] = {}
        for c in cpu_ids:
            core = int(self.topo.core_id[c])
            cpus_in_cores.setdefault(core, []).append(c)
            node_free[int(self.topo.node_id[c])] = (
                node_free.get(int(self.topo.node_id[c]), 0) + 1
            )
            socket_free[int(self.topo.socket_id[c])] = (
                socket_free.get(int(self.topo.socket_id[c]), 0) + 1
            )
        result_sockets = [int(self.topo.socket_id[c]) for c in self.result]
        socket_colo = {
            s: result_sockets.count(s) for s in socket_free
        }

        def core_key(core):
            some_cpu = cpus_in_cores[core][0]
            socket = int(self.topo.socket_id[some_cpu])
            node = int(self.topo.node_id[some_cpu])
            ref = int(self.ref[cpus_in_cores[core]].sum()) if self.max_ref_count > 1 else 0
            return (
                -socket_colo.get(socket, 0),
                self._strategy_key(socket_free[socket]),
                self._strategy_key(node_free[node]),
                len(cpus_in_cores[core]),
                socket,
                ref,
                core,
            )

        out: List[int] = []
        for core in sorted(cpus_in_cores, key=core_key):
            out.extend(self._sort_cpus_by_ref(sorted(cpus_in_cores[core])))
        return out

    def spread(self, cpus: List[int]) -> List[int]:
        """Round-robin one cpu per core per pass (reference spreadCPUs :798)."""
        if len(cpus) <= self.topo.cpus_per_core:
            return cpus
        out: List[int] = []
        pending = list(cpus)
        while pending:
            seen, leftover = set(), []
            for c in pending:
                core = int(self.topo.core_id[c])
                if core in seen:
                    leftover.append(c)
                else:
                    seen.add(core)
                    out.append(c)
            pending = leftover
        return out


def take_cpus(
    topology: CPUTopology,
    max_ref_count: int,
    available: np.ndarray,
    allocated: AllocatedCPUs,
    num_needed: int,
    bind_policy: CPUBindPolicy = CPUBindPolicy.DEFAULT,
    exclusive_policy: CPUExclusivePolicy = CPUExclusivePolicy.NONE,
    strategy: NUMAAllocateStrategy = NUMAAllocateStrategy.MOST_ALLOCATED,
) -> np.ndarray:
    """Take ``num_needed`` logical cpus honoring topology + policies.

    Phase order mirrors reference takeCPUs (cpu_accumulator.go:87-232):
    full-core fit in one NUMA node → one socket → whole sockets desc →
    per-core fill asc; spread path node → socket; final single-cpu fill.
    """
    acc = _Accumulator(
        topology, max_ref_count, available, allocated, num_needed,
        exclusive_policy, strategy,
    )
    if acc.satisfied:
        return np.asarray(sorted(acc.result), dtype=np.int64)
    if acc.failed:
        raise CPUAllocationError("not enough cpus available to satisfy request")

    full_pcpus = bind_policy == CPUBindPolicy.FULL_PCPUS
    if full_pcpus or topology.cpus_per_core == 1:
        # whole request fits in the free full cores of one NUMA node
        if acc.needed <= topology.cpus_per_node:
            for filter_exclusive in (True, False):
                for cpus in acc.free_cores_in_node(True, filter_exclusive):
                    if len(cpus) >= acc.needed:
                        acc.take(cpus[: acc.needed])
                        return np.asarray(sorted(acc.result), dtype=np.int64)
        # ... or of one socket
        if acc.needed <= topology.cpus_per_socket:
            for cpus in acc.free_cores_in_socket(True):
                if len(cpus) >= acc.needed:
                    acc.take(cpus[: acc.needed])
                    return np.asarray(sorted(acc.result), dtype=np.int64)
        # take whole sockets' free cores, most-free first (reference :141-155)
        free = sorted(acc.free_cores_in_socket(True), key=len, reverse=True)
        unsatisfied = []
        for cpus in free:
            if not acc.needs(len(cpus)):
                unsatisfied.append(cpus)
            else:
                acc.take(cpus)
                if acc.satisfied:
                    return np.asarray(sorted(acc.result), dtype=np.int64)
        # fill from the least-free leftover lists, a full core at a time
        if acc.needs(topology.cpus_per_core):
            per_core = topology.cpus_per_core
            for cpus in sorted(unsatisfied, key=len):
                for i in range(0, len(cpus), per_core):
                    acc.take(cpus[i : i + per_core])
                    if acc.satisfied:
                        return np.asarray(sorted(acc.result), dtype=np.int64)
                    if not acc.needs(per_core):
                        break

    if not full_pcpus:
        # spread: same NUMA node first (reference :184-214)
        if acc.needed <= topology.cpus_per_node:
            for filter_exclusive in (True, False):
                for cpus in acc.free_cpus_in_node(filter_exclusive):
                    if len(cpus) >= acc.needed:
                        cpus = acc.spread(cpus)
                        acc.take(cpus[: acc.needed])
                        return np.asarray(sorted(acc.result), dtype=np.int64)
        if acc.needed <= topology.cpus_per_socket:
            for filter_exclusive in (True, False):
                for cpus in acc.free_cpus_in_socket(filter_exclusive):
                    if len(cpus) >= acc.needed:
                        cpus = acc.spread(cpus)
                        acc.take(cpus[: acc.needed])
                        return np.asarray(sorted(acc.result), dtype=np.int64)

    # last resort: single cpus near what's already taken (reference :217-229)
    for filter_exclusive in (True, False):
        for c in acc.spread(acc.free_cpus(filter_exclusive)):
            if acc.needs(1):
                acc.take([c])
            if acc.satisfied:
                return np.asarray(sorted(acc.result), dtype=np.int64)

    raise CPUAllocationError("failed to allocate cpus")


def take_preferred_cpus(
    topology: CPUTopology,
    max_ref_count: int,
    available: np.ndarray,
    preferred: np.ndarray,
    allocated: AllocatedCPUs,
    num_needed: int,
    bind_policy: CPUBindPolicy = CPUBindPolicy.DEFAULT,
    exclusive_policy: CPUExclusivePolicy = CPUExclusivePolicy.NONE,
    strategy: NUMAAllocateStrategy = NUMAAllocateStrategy.MOST_ALLOCATED,
) -> np.ndarray:
    """Drain preferred (reservation-reusable) cpus first, then the rest
    (reference takePreferredCPUs cpu_accumulator.go:29-85)."""
    available = available.copy()
    preferred = available & preferred
    result = np.asarray([], dtype=np.int64)
    needed = int(num_needed)
    if preferred.any():
        take_n = min(needed, int(preferred.sum()))
        result = take_cpus(
            topology, max_ref_count, preferred, allocated, take_n,
            bind_policy, exclusive_policy, strategy,
        )
        needed -= len(result)
        available &= ~preferred
    if needed > 0:
        rest = take_cpus(
            topology, max_ref_count, available, allocated, needed,
            bind_policy, exclusive_policy, strategy,
        )
        result = np.union1d(result, rest)
    return np.asarray(sorted(int(c) for c in result), dtype=np.int64)
