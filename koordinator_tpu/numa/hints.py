"""NUMA topology hints + the four topology-manager merge policies.

Semantics oracle: pkg/scheduler/frameworkext/topologymanager/policy.go
(mergePermutation :86, filterProvidersHints :99, mergeFilteredHints :129),
policy_{none,best_effort,restricted,single_numa_node}.go, and
pkg/util/bitmask/bitmask.go (IsNarrowerThan :146). Affinities are plain
Python ints used as bitmasks over NUMA node ids (≤64 nodes).
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


class NUMATopologyPolicy(str, enum.Enum):
    """Pod/node NUMA alignment requirement (reference: apis/extension/
    numa_aware.go NUMATopologyPolicy)."""

    NONE = ""
    BEST_EFFORT = "BestEffort"
    RESTRICTED = "Restricted"
    SINGLE_NUMA_NODE = "SingleNUMANode"


@dataclasses.dataclass(frozen=True)
class NUMATopologyHint:
    """One provider hint: a NUMA-node bitmask + preference + weight
    (reference: topologymanager/policy.go NUMATopologyHint)."""

    affinity: Optional[int]  # bitmask over node ids; None = no preference
    preferred: bool = False
    score: int = 0


def mask_of(nodes: Iterable[int]) -> int:
    mask = 0
    for n in nodes:
        mask |= 1 << int(n)
    return mask


def mask_bits(mask: int) -> List[int]:
    out, i = [], 0
    while mask >> i:
        if (mask >> i) & 1:
            out.append(i)
        i += 1
    return out


def mask_count(mask: int) -> int:
    return bin(mask).count("1")


def _is_narrower(a: int, b: int) -> bool:
    """Fewer bits set wins; ties go to more lower-numbered bits
    (reference: bitmask.go IsNarrowerThan :146-151)."""
    if mask_count(a) == mask_count(b):
        return a < b
    return mask_count(a) < mask_count(b)


#: provider hints: per provider, resource name → list of hints (or None)
ProviderHints = Dict[str, Optional[List[NUMATopologyHint]]]


def _filter_providers_hints(
    providers_hints: Sequence[ProviderHints],
) -> List[List[NUMATopologyHint]]:
    """Normalize provider hints into per-resource hint lists (reference:
    filterProvidersHints policy.go:99-127): no hints at all → one preferred
    don't-care; a nil resource entry → preferred don't-care; an *empty*
    resource entry → unpreferred don't-care (provider cannot satisfy)."""
    out: List[List[NUMATopologyHint]] = []
    for hints in providers_hints:
        if not hints:
            out.append([NUMATopologyHint(None, True)])
            continue
        for resource in hints:
            if hints[resource] is None:
                out.append([NUMATopologyHint(None, True)])
            elif len(hints[resource]) == 0:
                out.append([NUMATopologyHint(None, False)])
            else:
                out.append(list(hints[resource]))
    return out


def _merge_permutation(
    default_affinity: int, permutation: Sequence[NUMATopologyHint]
) -> NUMATopologyHint:
    """Bitwise-AND one hint per provider; preferred iff all preferred and
    all set affinities equal (reference mergePermutation policy.go:86-96)."""
    preferred = True
    affinities = [h.affinity for h in permutation if h.affinity is not None]
    for h in permutation:
        if h.affinity is not None and h.affinity != affinities[0]:
            preferred = False
        if not h.preferred:
            preferred = False
    merged = default_affinity
    for a in affinities:
        merged &= a
    return NUMATopologyHint(merged, preferred, 0)


def _merge_filtered_hints(
    numa_nodes: Sequence[int], filtered: List[List[NUMATopologyHint]]
) -> NUMATopologyHint:
    """Cross-product merge, keep the narrowest preferred result
    (reference mergeFilteredHints policy.go:129-186)."""
    default_affinity = mask_of(numa_nodes)
    best = NUMATopologyHint(default_affinity, False, 0)
    for permutation in itertools.product(*filtered):
        merged = _merge_permutation(default_affinity, permutation)
        if merged.affinity == 0:
            continue
        score = merged.score
        for h in permutation:
            if h.affinity is not None and merged.affinity == h.affinity:
                score = max(score, h.score)
        merged = dataclasses.replace(merged, score=score)

        if merged.preferred and not best.preferred:
            best = merged
            continue
        if not merged.preferred and best.preferred:
            continue
        if not _is_narrower(merged.affinity, best.affinity):
            if (
                mask_count(merged.affinity) == mask_count(best.affinity)
                and merged.score > best.score
            ):
                best = merged
            continue
        best = merged
    return best


def merge_hints(
    policy: NUMATopologyPolicy,
    numa_nodes: Sequence[int],
    providers_hints: Sequence[ProviderHints],
) -> Tuple[NUMATopologyHint, bool]:
    """Merge all providers' hints under a policy → (best hint, admit).

    - NONE: no alignment, always admit (policy_none.go).
    - BEST_EFFORT: merged hint, always admit (policy_best_effort.go).
    - RESTRICTED: admit only if the merged hint is preferred
      (policy_restricted.go:40).
    - SINGLE_NUMA_NODE: only single-node or don't-care preferred hints
      participate; a whole-machine result degrades to don't-care
      (policy_single_numa_node.go:47-74).
    """
    if policy == NUMATopologyPolicy.NONE:
        return NUMATopologyHint(None, False, 0), True

    filtered = _filter_providers_hints(providers_hints)
    if policy == NUMATopologyPolicy.SINGLE_NUMA_NODE:
        filtered = [
            [
                h
                for h in hints
                if (h.affinity is None and h.preferred)
                or (
                    h.affinity is not None
                    and mask_count(h.affinity) == 1
                    and h.preferred
                )
            ]
            for hints in filtered
        ]
        best = _merge_filtered_hints(numa_nodes, filtered)
        if best.affinity == mask_of(numa_nodes):
            best = NUMATopologyHint(None, best.preferred, 0)
        return best, best.preferred

    best = _merge_filtered_hints(numa_nodes, filtered)
    if policy == NUMATopologyPolicy.RESTRICTED:
        return best, best.preferred
    return best, True  # BEST_EFFORT
