"""CPU topology as dense arrays + allocation bookkeeping types.

Reference semantics: pkg/scheduler/plugins/nodenumaresource/cpu_topology.go
(CPUTopology / CPUDetails) and pkg/scheduler/apis/config (CPUBindPolicy,
CPUExclusivePolicy, NUMAAllocateStrategy). Instead of a map cpu→CPUInfo, the
topology is three parallel int arrays indexed by logical cpu id; allocation
state (ref counts, exclusive markers) are arrays of the same shape so the
accumulator's orderings are ``np.lexsort`` keys.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Iterable, Optional

import numpy as np


class CPUBindPolicy(str, enum.Enum):
    """How a cpuset pod wants its logical CPUs laid out
    (reference: pkg/scheduler/apis/config/types.go CPUBindPolicy)."""

    DEFAULT = "Default"
    FULL_PCPUS = "FullPCPUs"         # monopolize whole physical cores
    SPREAD_BY_PCPUS = "SpreadByPCPUs"  # one logical CPU per physical core
    CONSTRAINED_BURST = "ConstrainedBurst"


class CPUExclusivePolicy(str, enum.Enum):
    """Exclusion domain a cpuset allocation claims
    (reference: CPUExclusivePolicy{None,PCPULevel,NUMANodeLevel})."""

    NONE = "None"
    PCPU_LEVEL = "PCPULevel"
    NUMA_NODE_LEVEL = "NUMANodeLevel"


class NUMAAllocateStrategy(str, enum.Enum):
    """Prefer packing onto busy NUMA nodes or spreading onto free ones
    (reference: NUMAAllocateStrategy MostAllocated/LeastAllocated)."""

    MOST_ALLOCATED = "MostAllocated"
    LEAST_ALLOCATED = "LeastAllocated"


@dataclasses.dataclass(frozen=True)
class CPUTopology:
    """Static CPU topology of one node.

    Arrays are indexed by logical cpu id 0..C-1 (reference:
    cpu_topology.go CPUDetails keyed by CPUID).
    """

    core_id: np.ndarray    # [C] physical core of each logical cpu
    node_id: np.ndarray    # [C] NUMA node of each logical cpu
    socket_id: np.ndarray  # [C] socket of each logical cpu

    @staticmethod
    def build(
        sockets: int = 1,
        nodes_per_socket: int = 1,
        cores_per_node: int = 4,
        threads_per_core: int = 2,
    ) -> "CPUTopology":
        """Synthesize a regular topology (tests + defaults).

        CPU ids are laid out hyperthread-major like common x86 lscpu output
        is *not*; we use the simple contiguous layout (cpu = sequential
        within core) — the accumulator never relies on id layout, only on
        the id→core/node/socket maps.
        """
        n = sockets * nodes_per_socket * cores_per_node * threads_per_core
        cpu = np.arange(n)
        core = cpu // threads_per_core
        node = core // cores_per_node
        socket = node // nodes_per_socket
        return CPUTopology(core_id=core, node_id=node, socket_id=socket)

    @property
    def num_cpus(self) -> int:
        return len(self.core_id)

    @property
    def num_cores(self) -> int:
        return len(np.unique(self.core_id))

    @property
    def num_nodes(self) -> int:
        return len(np.unique(self.node_id))

    @property
    def num_sockets(self) -> int:
        return len(np.unique(self.socket_id))

    @property
    def cpus_per_core(self) -> int:
        return self.num_cpus // max(1, self.num_cores)

    @property
    def cpus_per_node(self) -> int:
        return self.num_cpus // max(1, self.num_nodes)

    @property
    def cpus_per_socket(self) -> int:
        return self.num_cpus // max(1, self.num_sockets)

    @property
    def numa_nodes(self) -> np.ndarray:
        return np.unique(self.node_id)

    def is_valid(self) -> bool:
        return self.num_cpus > 0

    def cpus_in_numa_node(self, node: int) -> np.ndarray:
        return np.flatnonzero(self.node_id == node)


@dataclasses.dataclass
class AllocatedCPUs:
    """Per-cpu allocation state of one node, accumulator input
    (reference: CPUDetails RefCount/ExclusivePolicy fields populated from
    existing PodAllocations, resource_manager.go:431 GetAvailableCPUs).
    """

    ref_count: np.ndarray          # [C] int, how many pods share each cpu
    exclusive_in_cores: set        # core ids with a PCPULevel allocation
    exclusive_in_numa_nodes: set   # NUMA node ids with a NUMANodeLevel alloc

    @staticmethod
    def empty(topology: CPUTopology) -> "AllocatedCPUs":
        return AllocatedCPUs(
            ref_count=np.zeros(topology.num_cpus, dtype=np.int32),
            exclusive_in_cores=set(),
            exclusive_in_numa_nodes=set(),
        )


def cpuset_mask(topology: CPUTopology, cpus: Optional[Iterable[int]]) -> np.ndarray:
    """Bool mask [C] from an iterable of cpu ids (None → empty)."""
    mask = np.zeros(topology.num_cpus, dtype=bool)
    if cpus is not None:
        ids = np.asarray(list(cpus), dtype=np.int64)
        if ids.size:
            mask[ids] = True
    return mask
