"""Per-node NUMA resource manager: hints, allocation, release.

Semantics oracle: pkg/scheduler/plugins/nodenumaresource/
{resource_manager.go, node_allocation.go, topology_options.go,
least_allocated.go, most_allocated.go}. Holds per-node allocation state
(pod → cpuset + per-NUMA-node resources), generates NUMA topology hints
for the scheduler-level topology manager, and performs the final
hint-constrained allocation (even distribution + cpuset take).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from koordinator_tpu.apis.extension import ResourceName
from koordinator_tpu.apis.types import Resources
from koordinator_tpu.numa.accumulator import (
    CPUAllocationError,
    take_preferred_cpus,
)
from koordinator_tpu.numa.hints import (
    NUMATopologyHint,
    NUMATopologyPolicy,
    mask_bits,
    mask_count,
    mask_of,
)
from koordinator_tpu.numa.topology import (
    AllocatedCPUs,
    CPUBindPolicy,
    CPUExclusivePolicy,
    CPUTopology,
    NUMAAllocateStrategy,
    cpuset_mask,
)

MAX_NODE_SCORE = 100


@dataclasses.dataclass
class TopologyOptions:
    """Per-node topology as synced from the NodeResourceTopology CRD
    (reference: topology_options.go TopologyOptions)."""

    cpu_topology: Optional[CPUTopology] = None
    max_ref_count: int = 1
    policy: NUMATopologyPolicy = NUMATopologyPolicy.NONE
    # NUMA node id -> allocatable resources on that node
    numa_node_resources: Dict[int, Resources] = dataclasses.field(default_factory=dict)
    reserved_cpus: Sequence[int] = ()
    # node CPU amplification ratio (cpu-normalization, reference:
    # topology_options.go AmplificationRatios)
    amplification_ratio: float = 1.0

    @property
    def numa_nodes(self) -> List[int]:
        return sorted(self.numa_node_resources)


@dataclasses.dataclass
class ResourceOptions:
    """One pod's allocation request against one node (reference:
    plugin.go getResourceOptions / ResourceOptions)."""

    requests: Resources
    original_requests: Optional[Resources] = None
    num_cpus_needed: int = 0
    request_cpu_bind: bool = False
    required_cpu_bind_policy: bool = False
    cpu_bind_policy: CPUBindPolicy = CPUBindPolicy.DEFAULT
    cpu_exclusive_policy: CPUExclusivePolicy = CPUExclusivePolicy.NONE
    preferred_cpus: Sequence[int] = ()
    hint: NUMATopologyHint = NUMATopologyHint(None, False, 0)
    # reusable (reservation-restored) resources per NUMA node
    reusable_resources: Dict[int, Resources] = dataclasses.field(default_factory=dict)
    numa_scorer: Optional[str] = None  # "LeastAllocated" | "MostAllocated"

    def __post_init__(self):
        if self.original_requests is None:
            self.original_requests = dict(self.requests)


@dataclasses.dataclass
class PodAllocation:
    """What one pod holds on one node (reference: node_allocation.go
    PodAllocation)."""

    pod_uid: str
    cpuset: np.ndarray = dataclasses.field(
        default_factory=lambda: np.asarray([], dtype=np.int64)
    )
    cpu_exclusive_policy: CPUExclusivePolicy = CPUExclusivePolicy.NONE
    # NUMA node id -> resources taken from that node
    numa_resources: Dict[int, Resources] = dataclasses.field(default_factory=dict)


class NodeAllocation:
    """All pod allocations on one node (reference: node_allocation.go
    NodeAllocation: allocatedPods/allocatedCPUs/allocatedResources)."""

    def __init__(self, node_name: str):
        self.node_name = node_name
        self.pods: Dict[str, PodAllocation] = {}

    def add(self, allocation: PodAllocation) -> None:
        if allocation.pod_uid in self.pods:
            return
        self.pods[allocation.pod_uid] = allocation

    def release(self, pod_uid: str) -> None:
        self.pods.pop(pod_uid, None)

    def allocated_cpus(self, topology: CPUTopology) -> AllocatedCPUs:
        state = AllocatedCPUs.empty(topology)
        for alloc in self.pods.values():
            for c in alloc.cpuset:
                state.ref_count[int(c)] += 1
                if alloc.cpu_exclusive_policy == CPUExclusivePolicy.PCPU_LEVEL:
                    state.exclusive_in_cores.add(int(topology.core_id[int(c)]))
                elif alloc.cpu_exclusive_policy == CPUExclusivePolicy.NUMA_NODE_LEVEL:
                    state.exclusive_in_numa_nodes.add(int(topology.node_id[int(c)]))
        return state

    def available_cpus(
        self,
        topology: CPUTopology,
        max_ref_count: int,
        reserved: Sequence[int] = (),
        preferred: Sequence[int] = (),
    ) -> Tuple[np.ndarray, AllocatedCPUs]:
        """Available mask + allocation detail; preferred (reservation)
        cpus get one refcount forgiven (reference: node_allocation.go:133
        getAvailableCPUs)."""
        state = self.allocated_cpus(topology)
        for c in preferred:
            if state.ref_count[int(c)] > 0:
                state.ref_count[int(c)] -= 1
        available = state.ref_count < max_ref_count
        available &= ~cpuset_mask(topology, reserved)
        return available, state

    def allocated_numa_resources(self) -> Dict[int, Resources]:
        out: Dict[int, Resources] = {}
        for alloc in self.pods.values():
            for node, res in alloc.numa_resources.items():
                acc = out.setdefault(node, {})
                for k, v in res.items():
                    acc[k] = acc.get(k, 0) + v
        return out


def _score_numa(
    scorer: Optional[str], requested: Resources, total: Resources, pod_requests: Resources
) -> int:
    """NUMA-set score used to weight hints (reference: least_allocated.go
    leastResourceScorer / most_allocated.go, weight 1 per requested
    resource)."""
    if scorer is None:
        return 0
    score_sum, weight_sum = 0, 0
    for r in pod_requests:
        cap = total.get(r, 0)
        req = requested.get(r, 0) + pod_requests[r]
        if scorer == "MostAllocated":
            s = 0 if cap == 0 or req > cap else req * MAX_NODE_SCORE // cap
        else:
            s = 0 if cap == 0 or req > cap else (cap - req) * MAX_NODE_SCORE // cap
        score_sum += s
        weight_sum += 1
    return score_sum // weight_sum if weight_sum else 0


def generate_resource_hints(
    numa_node_resources: Dict[int, Resources],
    pod_requests: Resources,
    total_available: Dict[int, Resources],
    scorer: Optional[str] = None,
) -> Dict[ResourceName, List[NUMATopologyHint]]:
    """Hints per resource over all NUMA-node subsets (reference:
    resource_manager.go:459 generateResourceHints): a mask yields a hint
    for a resource iff the mask's total capacity and free amount both cover
    the request and the mask avoids nodes with zero *available* amount of
    it (the reference builds the lack set from available, not capacity);
    preferred = the minimal feasible-by-capacity mask size. Memory-like
    resources are gated together, others independently."""
    numa_nodes = sorted(numa_node_resources)
    resource_names_by_numa = set()
    for res in numa_node_resources.values():
        resource_names_by_numa.update(res)

    lack_mask: Dict[ResourceName, int] = {}
    for r in resource_names_by_numa:
        for node, avail in total_available.items():
            if avail.get(r, 0) == 0:
                lack_mask[r] = lack_mask.get(r, 0) | (1 << node)

    min_affinity = {r: len(numa_nodes) for r in pod_requests}
    memory_names = [r for r in pod_requests if r == ResourceName.MEMORY]
    other_names = [r for r in pod_requests if r != ResourceName.MEMORY]
    hints: Dict[ResourceName, List[NUMATopologyHint]] = {}
    total_resource_names = set()

    def gen(mask: int, score: int, total: Resources, free: Resources,
            names: Sequence[ResourceName]) -> None:
        if not names:
            return
        for r in names:
            if total.get(r, 0) < pod_requests[r]:
                return
        for r in names:
            if mask & lack_mask.get(r, 0):
                return
        n = mask_count(mask)
        for r in names:
            if n < min_affinity[r]:
                min_affinity[r] = n
        for r in names:
            if free.get(r, 0) < pod_requests[r]:
                return
        for r in names:
            hints.setdefault(r, []).append(NUMATopologyHint(mask, False, score))

    for mask in range(1, 1 << len(numa_nodes)):
        bits = [numa_nodes[i] for i in range(len(numa_nodes)) if (mask >> i) & 1]
        real_mask = mask_of(bits)
        total: Resources = {}
        free: Resources = {}
        for node in bits:
            for k, v in total_available.get(node, {}).items():
                free[k] = free.get(k, 0) + v
            for k, v in numa_node_resources.get(node, {}).items():
                total[k] = total.get(k, 0) + v
        requested = {k: max(0, total.get(k, 0) - free.get(k, 0)) for k in total}
        score = _score_numa(scorer, requested, total, pod_requests)

        gen(real_mask, score, total, free, memory_names)
        for r in pod_requests:
            if r in total:
                total_resource_names.add(r)
        for r in other_names:
            gen(real_mask, score, total, free, [r])

    for r in pod_requests:
        for i, h in enumerate(hints.get(r, [])):
            hints[r][i] = dataclasses.replace(
                h, preferred=mask_count(h.affinity) == min_affinity[r]
            )
    for r in total_resource_names:
        hints.setdefault(r, [])
    return hints


class ResourceManager:
    """Cluster-wide NUMA allocation bookkeeping + the allocate entrypoints
    (reference: resource_manager.go resourceManager)."""

    def __init__(
        self,
        default_strategy: NUMAAllocateStrategy = NUMAAllocateStrategy.MOST_ALLOCATED,
    ):
        self.default_strategy = default_strategy
        self.topology_options: Dict[str, TopologyOptions] = {}
        self.node_allocations: Dict[str, NodeAllocation] = {}

    # -- topology options sync (reference: topology_options.go manager) ----
    def update_topology(self, node_name: str, options: TopologyOptions) -> None:
        self.topology_options[node_name] = options

    def get_topology(self, node_name: str) -> TopologyOptions:
        return self.topology_options.get(node_name, TopologyOptions())

    def _node_allocation(self, node_name: str) -> NodeAllocation:
        alloc = self.node_allocations.get(node_name)
        if alloc is None:
            alloc = self.node_allocations[node_name] = NodeAllocation(node_name)
        return alloc

    # -- read paths --------------------------------------------------------
    def available_numa_resources(
        self, node_name: str, reusable: Optional[Dict[int, Resources]] = None
    ) -> Tuple[Dict[int, Resources], Dict[int, Resources]]:
        """(total available, total allocated) per NUMA node (reference:
        node_allocation.go:155 getAvailableNUMANodeResources)."""
        opts = self.get_topology(node_name)
        allocated = self._node_allocation(node_name).allocated_numa_resources()
        available: Dict[int, Resources] = {}
        for node, res in opts.numa_node_resources.items():
            got = dict(res)
            for k, v in allocated.get(node, {}).items():
                got[k] = max(0, got.get(k, 0) - v)
            for k, v in (reusable or {}).get(node, {}).items():
                got[k] = got.get(k, 0) + v
            available[node] = got
        return available, allocated

    def available_cpus(
        self, node_name: str, preferred: Sequence[int] = ()
    ) -> Tuple[np.ndarray, AllocatedCPUs]:
        opts = self.get_topology(node_name)
        if opts.cpu_topology is None or not opts.cpu_topology.is_valid():
            raise CPUAllocationError(f"invalid cpu topology on {node_name}")
        return self._node_allocation(node_name).available_cpus(
            opts.cpu_topology, opts.max_ref_count, opts.reserved_cpus, preferred
        )

    # -- hints (reference: resource_manager.go:123 GetTopologyHints) -------
    def get_topology_hints(
        self, node_name: str, options: ResourceOptions
    ) -> Dict[ResourceName, List[NUMATopologyHint]]:
        opts = self.get_topology(node_name)
        if not opts.numa_node_resources:
            raise CPUAllocationError("insufficient resources on NUMA Node")
        total_available, _ = self.available_numa_resources(
            node_name, options.reusable_resources
        )
        self._trim_numa_cpus(node_name, total_available, options)
        return generate_resource_hints(
            opts.numa_node_resources, options.requests, total_available,
            options.numa_scorer,
        )

    def _trim_numa_cpus(
        self, node_name: str, total_available: Dict[int, Resources],
        options: ResourceOptions,
    ) -> None:
        """Cap per-NUMA available CPU by what the required bind policy can
        actually take (reference: resource_manager.go:141
        trimNUMANodeResources)."""
        if not options.required_cpu_bind_policy:
            return
        opts = self.get_topology(node_name)
        topo = opts.cpu_topology
        available, _ = self.available_cpus(node_name, options.preferred_cpus)
        for node, res in total_available.items():
            if res.get(ResourceName.CPU, 0) == 0:
                continue
            in_node = available & (topo.node_id == node)
            usable = _filter_by_required_policy(
                options.cpu_bind_policy, in_node, topo
            )
            limit = int(usable.sum()) * 1000
            if limit < res.get(ResourceName.CPU, 0):
                res[ResourceName.CPU] = limit

    # -- allocate (reference: resource_manager.go:169 Allocate) ------------
    def allocate(
        self, node_name: str, pod_uid: str, options: ResourceOptions
    ) -> PodAllocation:
        allocation = PodAllocation(
            pod_uid=pod_uid, cpu_exclusive_policy=options.cpu_exclusive_policy
        )
        if options.hint.affinity is not None:
            allocation.numa_resources = self._allocate_by_hint(node_name, options)
        if options.request_cpu_bind:
            allocation.cpuset = self._allocate_cpuset(
                node_name, allocation.numa_resources, options
            )
        return allocation

    def _allocate_by_hint(
        self, node_name: str, options: ResourceOptions
    ) -> Dict[int, Resources]:
        """Distribute the request over the hint's NUMA nodes as evenly as
        the free amounts allow (reference: resource_manager.go:221
        tryBestToDistributeEvenly; we sort candidate nodes by their actual
        free amount per resource — the reference's sort closure compares by
        slice index, which we treat as unintended)."""
        opts = self.get_topology(node_name)
        if not opts.numa_node_resources:
            raise CPUAllocationError("insufficient resources on NUMA Node")
        total_available, _ = self.available_numa_resources(
            node_name, options.reusable_resources
        )
        self._trim_numa_cpus(node_name, total_available, options)

        requests = dict(
            options.original_requests if options.request_cpu_bind else options.requests
        )
        numa_nodes = mask_bits(options.hint.affinity)
        resource_names_by_numa = set()
        for res in total_available.values():
            resource_names_by_numa.update(res)

        result: Dict[int, Resources] = {}
        for r, quantity in list(requests.items()):
            order = sorted(
                numa_nodes, key=lambda n: total_available.get(n, {}).get(r, 0)
            )
            for i, node in enumerate(order):
                split = _split_quantity(r, quantity, len(numa_nodes) - i, options, opts)
                allocated = min(total_available.get(node, {}).get(r, 0), split)
                if r == ResourceName.CPU and options.request_cpu_bind:
                    # cpuset pods take whole logical cpus: floor so the
                    # recorded NUMA amount always matches the cpuset taken
                    allocated = allocated // 1000 * 1000
                if allocated > 0:
                    result.setdefault(node, {})[r] = allocated
                    quantity -= allocated
            requests[r] = quantity

        for r, quantity in requests.items():
            if r in resource_names_by_numa and quantity > 0:
                raise CPUAllocationError(f"Insufficient NUMA {r.name}")
        return result

    def _allocate_cpuset(
        self,
        node_name: str,
        numa_resources: Dict[int, Resources],
        options: ResourceOptions,
    ) -> np.ndarray:
        """Take cpus, constrained to the allocated NUMA nodes when a hint
        was applied (reference: resource_manager.go:314 allocateCPUSet)."""
        opts = self.get_topology(node_name)
        topo = opts.cpu_topology
        available, allocated = self.available_cpus(node_name, options.preferred_cpus)
        if options.required_cpu_bind_policy:
            available = _filter_by_required_policy(
                options.cpu_bind_policy, available, topo
            )
        if int(available.sum()) < options.num_cpus_needed:
            raise CPUAllocationError("not enough cpus available to satisfy request")

        preferred_mask = cpuset_mask(topo, options.preferred_cpus)
        result = np.asarray([], dtype=np.int64)
        needed = options.num_cpus_needed
        if numa_resources:
            for node in sorted(numa_resources):
                in_node = available & (topo.node_id == node)
                num = min(
                    int(in_node.sum()),
                    numa_resources[node].get(ResourceName.CPU, 0) // 1000,
                )
                cpus = take_preferred_cpus(
                    topo, opts.max_ref_count, in_node, preferred_mask, allocated,
                    num, options.cpu_bind_policy, options.cpu_exclusive_policy,
                    self.default_strategy,
                )
                result = np.union1d(result, cpus)
            needed -= len(result)
            if needed != 0:
                raise CPUAllocationError("not enough cpus available to satisfy request")

        if needed > 0:
            available = available & ~cpuset_mask(topo, result)
            rest = take_preferred_cpus(
                topo, opts.max_ref_count, available, preferred_mask, allocated,
                needed, options.cpu_bind_policy, options.cpu_exclusive_policy,
                self.default_strategy,
            )
            result = np.union1d(result, rest)

        if options.required_cpu_bind_policy:
            _check_required_policy(options.cpu_bind_policy, result, topo)
        return result.astype(np.int64)

    # -- commit / rollback (reference: resource_manager.go:403,416) --------
    def update(self, node_name: str, allocation: PodAllocation) -> None:
        opts = self.get_topology(node_name)
        if opts.cpu_topology is None or not opts.cpu_topology.is_valid():
            return
        self._node_allocation(node_name).add(allocation)

    def release(self, node_name: str, pod_uid: str) -> None:
        self._node_allocation(node_name).release(pod_uid)

    def get_allocated_cpuset(self, node_name: str, pod_uid: str) -> Optional[np.ndarray]:
        alloc = self._node_allocation(node_name).pods.get(pod_uid)
        return None if alloc is None else alloc.cpuset


def _split_quantity(
    resource: ResourceName,
    quantity: int,
    numa_node_count: int,
    options: ResourceOptions,
    opts: TopologyOptions,
) -> int:
    """Even-split step (reference: resource_manager.go:277 splitQuantity):
    CPU for a required FullPCPUs bind rounds down to whole physical cores."""
    if resource != ResourceName.CPU:
        return quantity // numa_node_count
    if not options.request_cpu_bind:
        return quantity // numa_node_count
    if (
        options.required_cpu_bind_policy
        and options.cpu_bind_policy == CPUBindPolicy.FULL_PCPUS
        and opts.cpu_topology is not None
    ):
        per_core = opts.cpu_topology.cpus_per_core
        cores = (quantity // 1000) // per_core
        return (cores // numa_node_count) * per_core * 1000
    return (quantity // 1000) // numa_node_count * 1000


def _filter_by_required_policy(
    policy: CPUBindPolicy, available: np.ndarray, topo: CPUTopology
) -> np.ndarray:
    """FullPCPUs keeps only fully-free cores; SpreadByPCPUs one cpu per core
    (reference: resource_manager.go:595 filterCPUsByRequiredCPUBindPolicy)."""
    out = available.copy()
    if policy == CPUBindPolicy.FULL_PCPUS:
        for core in np.unique(topo.core_id[available]):
            members = topo.core_id == core
            if int((available & members).sum()) != int(members.sum()):
                out &= ~members
    elif policy == CPUBindPolicy.SPREAD_BY_PCPUS:
        keep = np.zeros_like(out)
        for core in np.unique(topo.core_id[available]):
            cpus = np.flatnonzero(available & (topo.core_id == core))
            keep[cpus[0]] = True
        out = keep
    return out


def _check_required_policy(
    policy: CPUBindPolicy, cpus: np.ndarray, topo: CPUTopology
) -> None:
    """Post-check (reference: resource_manager.go:629
    satisfiedRequiredCPUBindPolicy)."""
    cores = topo.core_id[cpus.astype(np.int64)] if len(cpus) else np.asarray([])
    if policy == CPUBindPolicy.FULL_PCPUS:
        if len(np.unique(cores)) * topo.cpus_per_core != len(cpus):
            raise CPUAllocationError(
                "insufficient CPUs to satisfy required cpu bind policy FullPCPUs"
            )
    elif policy == CPUBindPolicy.SPREAD_BY_PCPUS:
        if len(np.unique(cores)) != len(cpus):
            raise CPUAllocationError(
                "insufficient CPUs to satisfy required cpu bind policy SpreadByPCPUs"
            )
