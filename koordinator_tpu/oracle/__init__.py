"""Reference-semantics oracle: straight per-node/per-pod Python versions of
the Go decision functions (SURVEY.md Appendix A), kept deliberately
un-vectorized and float64-faithful (Go's ``math.Round`` paths use float64;
Python floats are the same IEEE doubles).

The JAX ops in ``koordinator_tpu.ops`` must match these bit-for-bit on
canonical-unit inputs — golden tests in tests/ enforce it. The oracle also
doubles as the measured "reference path" in bench comparisons.

``oracle.vectorized`` carries the SAME sequential semantics with the
inner node loop vectorized in int64 numpy — fast enough to prove device
identity at full BASELINE shapes (its authority: the differential sweep
against the scalar oracle in tests/test_oracle_vectorized.py).
"""

from koordinator_tpu.oracle.placement import (
    SequentialQuota,
    schedule_sequential,
    schedule_sequential_quota,
)
from koordinator_tpu.oracle.vectorized import (
    VectorQuota,
    gang_outcomes_np,
    oracle_args,
    schedule_vectorized,
)

__all__ = [
    "SequentialQuota",
    "VectorQuota",
    "gang_outcomes_np",
    "oracle_args",
    "schedule_sequential",
    "schedule_sequential_quota",
    "schedule_vectorized",
]
