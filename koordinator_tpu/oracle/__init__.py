"""Reference-semantics oracle: straight per-node/per-pod Python versions of
the Go decision functions (SURVEY.md Appendix A), kept deliberately
un-vectorized and float64-faithful (Go's ``math.Round`` paths use float64;
Python floats are the same IEEE doubles).

The JAX ops in ``koordinator_tpu.ops`` must match these bit-for-bit on
canonical-unit inputs — golden tests in tests/ enforce it. The oracle also
doubles as the measured "reference path" in bench comparisons.
"""
