"""Scalar host oracle for the LowNodeLoad Balance pass.

A direct transliteration of the reference's complete sweep —
pkg/descheduler/framework/plugins/loadaware/low_node_load.go:134-326 and
utilization_util.go (thresholds, classification, node/pod sorting,
eviction loop, headroom accounting) plus pkg/descheduler/utils/sorter —
written scalar-first: per-node dict maps, explicit comparator functions
under ``functools.cmp_to_key``, one pod at a time. No code is shared
with the plugin under test (``descheduler/loadaware.py``): this module
re-derives every decision from the reference so a differential run is
meaningful.

Determinism note: the reference sorts with Go's unstable ``sort.Sort``;
full ties are order-unspecified there. Oracle and plugin both refine
full ties by input order (stable sorts), the one departure — shared, so
it cancels in the differential.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, List, Optional, Tuple

from koordinator_tpu.apis.extension import (
    PriorityClass,
    QoSClass,
    ResourceName,
)

#: sorter/pod.go order maps, re-declared (no import from the module
#: under test's dependencies)
_PC_ORDER = {
    PriorityClass.NONE: 5, PriorityClass.PROD: 4, PriorityClass.MID: 3,
    PriorityClass.BATCH: 2, PriorityClass.FREE: 1,
}
_QOS_ORDER = {
    QoSClass.NONE: 5, QoSClass.SYSTEM: 4, QoSClass.LSE: 4,
    QoSClass.LSR: 3, QoSClass.LS: 2, QoSClass.BE: 1,
}


def _kube_qos(pod) -> int:
    reqs = {k: v for k, v in pod.requests.items() if v}
    lims = {k: v for k, v in pod.limits.items() if v}
    if not reqs and not lims:
        return 1  # besteffort
    # guaranteed needs requests == limits AND cpu+memory both limited
    if (reqs == lims and lims.get(ResourceName.CPU)
            and lims.get(ResourceName.MEMORY)):
        return 3
    return 2  # burstable


def _cost(pod, key: str) -> int:
    raw = pod.annotations.get(key)
    if not raw:
        return 0
    if not (raw[0] == "-" or raw == "0" or "1" <= raw[0] <= "9"):
        return 0
    try:
        return int(raw)
    except ValueError:
        return 0


def _most_requested(requested: int, capacity: int) -> int:
    if capacity == 0:
        return 0
    return min(requested, capacity) * 1000 // capacity


def _usage_scorer(weights: Dict) -> Callable:
    """sorter/scorer.go ResourceUsageScorer closure."""

    def score(usage_map: Dict, allocatable: Dict) -> int:
        total, weight_sum = 0, 0
        for resource, quantity in usage_map.items():
            w = int(weights.get(resource, 0))
            total += _most_requested(
                int(quantity), int(allocatable.get(resource, 0))
            ) * w
            weight_sum += w
        return total // weight_sum if weight_sum else 0

    return score


class _Detector:
    """Streak counters (anomaly.BasicDetector re-derivation)."""

    def __init__(self, need_abnormal: int):
        self.need_abnormal = need_abnormal
        self.abnormal = 0
        self.normal = 0
        self.anomalous = False

    def mark(self, is_normal: bool) -> bool:
        if is_normal:
            self.normal += 1
            self.abnormal = 0
            if self.anomalous and self.normal > 1:
                self.anomalous = False
        else:
            self.abnormal += 1
            self.normal = 0
            if self.abnormal > self.need_abnormal:
                self.anomalous = True
        return self.anomalous

    def reset(self) -> None:
        self.abnormal = 0
        self.normal = 0
        self.anomalous = False


class RebalanceOracle:
    """Stateful sweep oracle; one instance mirrors one plugin instance
    (detector streaks persist across sweeps)."""

    def __init__(self, args):
        self.args = args
        self.detectors: Dict[str, _Detector] = {}
        # node -> usage over every resource column (node-fit probe)
        self._full_usage: Dict[str, Dict] = {}

    # -- one full Balance pass ---------------------------------------------
    def sweep(
        self,
        snapshot,
        evict_allowed: Optional[Callable] = None,
    ) -> List[Tuple[str, str]]:
        """Returns the ordered eviction list [(node_name, pod_uid)]."""
        evictions: List[Tuple[str, str]] = []
        processed: set = set()
        for pool in self.args.node_pools:
            if self.args.paused:
                break
            self._pool_pass(pool, snapshot, evictions, processed,
                            evict_allowed or (lambda pod: True))
        return evictions

    def _pool_pass(self, pool, snapshot, evictions, processed,
                   evict_allowed) -> None:
        from koordinator_tpu.apis.types import selector_matches

        nodes = [
            n for n in snapshot.nodes
            if n.name not in processed
            and selector_matches(pool.node_selector, n.labels)
        ]
        if not nodes:
            return

        # newThresholds: fill the union of names (+memory always)
        resource_names = sorted(
            set(pool.low_thresholds) | set(pool.high_thresholds)
            | {ResourceName.MEMORY},
            key=int,
        )
        fill = 0.0 if pool.use_deviation_thresholds else 100.0
        low_pct = {
            r: float(pool.low_thresholds.get(r, fill))
            for r in resource_names
        }
        high_pct = {
            r: float(pool.high_thresholds.get(r, fill))
            for r in resource_names
        }

        # getNodeUsage: node -> usage map over resource_names; nodes
        # with no fresh metric drop out entirely
        usages: Dict[str, Dict] = {}
        pod_metrics: Dict[str, Dict[str, Dict]] = {}
        expiry = self.args.node_metric_expiration_seconds
        for node in nodes:
            metric = snapshot.node_metrics.get(node.name)
            if metric is None:
                continue
            if (expiry is not None
                    and snapshot.now - metric.update_time > expiry):
                continue
            usages[node.name] = {
                r: int(metric.node_usage.get(r, 0)) for r in resource_names
            }
            # full-column usage for the node-fit probe (the plugin's
            # fit check spans every resource column, thresholded or not)
            self._full_usage[node.name] = {
                r: int(metric.node_usage.get(r, 0)) for r in ResourceName
            }
            pod_metrics[node.name] = dict(metric.pod_usages)

        # getNodeThresholds, float64 formula
        if pool.use_deviation_thresholds:
            avg = self._average_percent(nodes, usages, resource_names)
        low_q: Dict[str, Dict] = {}
        high_q: Dict[str, Dict] = {}
        for node in nodes:
            if node.name not in usages:
                continue
            lq, hq = {}, {}
            for r in resource_names:
                cap = float(int(node.allocatable.get(r, 0)))
                if pool.use_deviation_thresholds:
                    if low_pct[r] == 0.0:
                        lq[r] = hq[r] = int(node.allocatable.get(r, 0))
                        continue
                    lo = min(max(avg[r] - low_pct[r], 0.0), 100.0)
                    hi = min(max(avg[r] + high_pct[r], 0.0), 100.0)
                else:
                    lo, hi = low_pct[r], high_pct[r]
                lq[r] = int(lo * 0.01 * cap)
                hq[r] = int(hi * 0.01 * cap)
            low_q[node.name] = lq
            high_q[node.name] = hq

        # classifyNodes
        low_nodes, source_nodes = [], []
        for node in nodes:
            u = usages.get(node.name)
            if u is None:
                continue
            if (not node.unschedulable and all(
                    u[r] <= low_q[node.name][r] for r in resource_names)):
                low_nodes.append(node)
            elif any(u[r] > high_q[node.name][r] for r in resource_names):
                source_nodes.append(node)

        for node in source_nodes:
            processed.add(node.name)
        source_names = {n.name for n in source_nodes}
        for node in nodes:
            if node.name in usages and node.name not in source_names:
                det = self.detectors.get(node.name)
                if det is not None:
                    det.mark(True)
        if not source_nodes:
            return

        # filterRealAbnormalNodes
        abnormal = []
        for node in source_nodes:
            det = self.detectors.get(node.name)
            if det is None:
                det = self.detectors[node.name] = _Detector(
                    pool.consecutive_abnormalities
                )
            if pool.consecutive_abnormalities <= 1 or det.mark(False):
                abnormal.append(node)
        if not abnormal:
            return
        for node in low_nodes:
            det = self.detectors.get(node.name)
            if det is not None:
                det.reset()
        if not low_nodes:
            return
        if len(low_nodes) <= self.args.number_of_nodes:
            return
        if len(low_nodes) == len(nodes):
            return

        # totalAvailableUsages over resource_names
        available = {r: 0 for r in resource_names}
        for node in low_nodes:
            for r in resource_names:
                available[r] += high_q[node.name][r] - usages[node.name][r]

        weights = {
            r: int(pool.resource_weights.get(r, 0)) for r in resource_names
        }

        # sortNodesByUsage descending
        node_scorer = _usage_scorer(weights)
        abnormal.sort(
            key=lambda n: node_scorer(
                usages[n.name],
                {r: int(n.allocatable.get(r, 0)) for r in resource_names},
            ),
            reverse=True,
        )

        pods_on: Dict[str, List] = {}
        for pod in snapshot.pods:
            if pod.node_name:
                pods_on.setdefault(pod.node_name, []).append(pod)

        for node in abnormal:
            self._evict_one_node(
                pool, snapshot, node, pods_on.get(node.name, []),
                usages, low_q, high_q, pod_metrics, available,
                resource_names, weights, low_nodes, evictions,
                evict_allowed,
            )
        for node in abnormal:
            det = self.detectors.get(node.name)
            if det is not None:
                det.mark(True)

    def _average_percent(self, nodes, usages, resource_names) -> Dict:
        """calcAverageResourceUsagePercent (float percent mean)."""
        totals = {r: 0.0 for r in resource_names}
        count = 0
        for node in nodes:
            u = usages.get(node.name)
            if u is None:
                continue
            count += 1
            for r in resource_names:
                cap = int(node.allocatable.get(r, 0))
                if cap == 0:
                    continue
                totals[r] += u[r] / cap * 100.0
        if count == 0:
            return {r: 0.0 for r in resource_names}
        return {r: totals[r] / count for r in resource_names}

    def _fits_some_low_node(self, pod, low_nodes, usages) -> bool:
        """nodeutil.PodFitsAnyNode simplification shared with the
        plugin: request fits under allocatable on a low node, across
        every resource column."""
        for node in low_nodes:
            metric_usage = self._full_usage.get(node.name, {})
            ok = True
            for r in ResourceName:
                used = int(metric_usage.get(r, 0))
                req = int(pod.requests.get(r, 0))
                if used + req > int(node.allocatable.get(r, 0)):
                    ok = False
                    break
            if ok:
                return True
        return False

    def _evict_one_node(
        self, pool, snapshot, node, node_pods, usages, low_q, high_q,
        pod_metrics, available, resource_names, weights, low_nodes,
        evictions, evict_allowed,
    ) -> None:
        node_usage = usages[node.name]
        node_high = high_q[node.name]
        metrics = pod_metrics.get(node.name, {})

        removable = []
        for pod in node_pods:
            if pod.is_daemonset:
                continue
            if (self.args.pod_filter is not None
                    and not self.args.pod_filter(pod)):
                continue
            if self.args.node_fit and not self._fits_some_low_node(
                    pod, low_nodes, usages):
                continue
            removable.append(pod)
        if not removable:
            return

        # sortPodsOnOneOverloadedNode: weights only for overused
        over_weights = {
            r: weights[r] for r in resource_names
            if node_usage[r] > node_high[r]
        }
        pod_scorer = _usage_scorer(over_weights)
        allocatable = {r: int(node.allocatable.get(r, 0))
                       for r in ResourceName}

        def compare(p1, p2) -> int:
            for fn in (
                lambda p: _PC_ORDER.get(
                    p.priority_class or PriorityClass.NONE, 5),
                lambda p: p.priority,
                _kube_qos,
                lambda p: _QOS_ORDER.get(p.qos, 5),
                lambda p: _cost(
                    p, "controller.kubernetes.io/pod-deletion-cost"),
                lambda p: _cost(p, "koordinator.sh/eviction-cost"),
            ):
                a, b = fn(p1), fn(p2)
                if a != b:
                    return -1 if a < b else 1
            m1, m2 = p1.uid in metrics, p2.uid in metrics
            if m1 != m2:
                return -1 if m1 else 1   # Reverse(cmpBool): metered first
            if m1:
                s1 = pod_scorer(metrics[p1.uid], allocatable)
                s2 = pod_scorer(metrics[p2.uid], allocatable)
                if s1 != s2:
                    return -1 if s1 > s2 else 1  # Reverse: heavier first
            if p1.creation_time != p2.creation_time:
                # PodCreationTimestamp: newer evicts first
                return -1 if p1.creation_time > p2.creation_time else 1
            return 0

        removable.sort(key=functools.cmp_to_key(compare))

        # evictPods loop
        for pod in removable:
            if not any(node_usage[r] > node_high[r]
                       for r in resource_names):
                det = self.detectors.get(node.name)
                if det is not None:
                    det.reset()
                return
            if any(available[r] <= 0 for r in resource_names):
                return
            if not evict_allowed(pod):
                continue
            evictions.append((node.name, pod.uid))
            pod_metric = metrics.get(pod.uid)
            if pod_metric is None:
                continue  # evicted, nothing to subtract (:339-341)
            for r in resource_names:
                q = int(pod_metric.get(r, 0))
                available[r] -= q
                node_usage[r] -= q
