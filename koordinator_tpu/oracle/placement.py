"""Sequential reference-path scheduler: pod-by-pod loop over Python scalars.

This mirrors the reference's scheduleOne cycle (Filter over nodes → Score →
pick best → assume into cache) with the same plugin combination as the
batched solver. It is the differential-test oracle for ops/binpack.py and
the measured "reference CPU path" in benchmarks.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from koordinator_tpu.oracle.scheduler import (
    fit_filter_node,
    least_allocated_score_node,
    loadaware_filter_node,
    loadaware_score_node,
)


def schedule_sequential(
    alloc: np.ndarray,          # [N,R]
    used_req: np.ndarray,       # [N,R] (copied, not mutated)
    usage: np.ndarray,          # [N,R]
    prod_usage: np.ndarray,     # [N,R]
    est_extra: np.ndarray,      # [N,R] (copied)
    prod_base: np.ndarray,      # [N,R] (copied)
    metric_fresh: Sequence[bool],
    schedulable: Sequence[bool],
    pod_req: np.ndarray,        # [P,R]
    pod_est: np.ndarray,        # [P,R]
    pod_is_prod: Sequence[bool],
    pod_is_daemonset: Sequence[bool],
    weights: Sequence[int],
    thresholds: Sequence[int],
    prod_thresholds: Sequence[int],
    fit_weight: int = 1,
    loadaware_weight: int = 1,
    score_according_prod: bool = False,
) -> List[int]:
    """Returns node index per pod (-1 = unschedulable), lowest-index
    tie-break, each pod seeing all prior placements."""
    n = alloc.shape[0]
    used_req = used_req.copy()
    est_extra = est_extra.copy()
    prod_base = prod_base.copy()
    assignments: List[int] = []
    for p in range(pod_req.shape[0]):
        best_node, best_score = -1, -1
        for i in range(n):
            if not schedulable[i]:
                continue
            if not fit_filter_node(pod_req[p], alloc[i], used_req[i]):
                continue
            if not loadaware_filter_node(
                alloc[i], usage[i], prod_usage[i], bool(metric_fresh[i]),
                thresholds, prod_thresholds,
                bool(pod_is_daemonset[p]), bool(pod_is_prod[p]),
            ):
                continue
            score = fit_weight * least_allocated_score_node(
                pod_req[p], alloc[i], used_req[i], weights
            ) + loadaware_weight * loadaware_score_node(
                pod_est[p], alloc[i], usage[i], est_extra[i], prod_base[i],
                bool(metric_fresh[i]), weights,
                bool(pod_is_prod[p]), score_according_prod,
            )
            if score > best_score:
                best_node, best_score = i, score
        assignments.append(best_node)
        if best_node >= 0:
            used_req[best_node] += pod_req[p]
            est_extra[best_node] += pod_est[p]
            if pod_is_prod[p]:
                prod_base[best_node] += pod_est[p]
    return assignments
