"""Sequential reference-path scheduler: pod-by-pod loop over Python scalars.

This mirrors the reference's scheduleOne cycle (Filter over nodes → Score →
pick best → assume into cache) with the same plugin combination as the
batched solver. It is the differential-test oracle for ops/binpack.py and
the measured "reference CPU path" in benchmarks.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from koordinator_tpu.oracle.scheduler import (
    fit_filter_node,
    least_allocated_score_node,
    loadaware_filter_node,
    loadaware_score_node,
)
from koordinator_tpu.quota.core import water_filling


def schedule_sequential(
    alloc: np.ndarray,          # [N,R]
    used_req: np.ndarray,       # [N,R] (copied, not mutated)
    usage: np.ndarray,          # [N,R]
    prod_usage: np.ndarray,     # [N,R]
    est_extra: np.ndarray,      # [N,R] (copied)
    prod_base: np.ndarray,      # [N,R] (copied)
    metric_fresh: Sequence[bool],
    schedulable: Sequence[bool],
    pod_req: np.ndarray,        # [P,R]
    pod_est: np.ndarray,        # [P,R]
    pod_is_prod: Sequence[bool],
    pod_is_daemonset: Sequence[bool],
    weights: Sequence[int],
    thresholds: Sequence[int],
    prod_thresholds: Sequence[int],
    fit_weight: int = 1,
    loadaware_weight: int = 1,
    score_according_prod: bool = False,
) -> List[int]:
    """Returns node index per pod (-1 = unschedulable), lowest-index
    tie-break, each pod seeing all prior placements."""
    n = alloc.shape[0]
    used_req = used_req.copy()
    est_extra = est_extra.copy()
    prod_base = prod_base.copy()
    assignments: List[int] = []
    for p in range(pod_req.shape[0]):
        best_node, best_score = -1, -1
        for i in range(n):
            if not schedulable[i]:
                continue
            if not fit_filter_node(pod_req[p], alloc[i], used_req[i]):
                continue
            if not loadaware_filter_node(
                alloc[i], usage[i], prod_usage[i], bool(metric_fresh[i]),
                thresholds, prod_thresholds,
                bool(pod_is_daemonset[p]), bool(pod_is_prod[p]),
            ):
                continue
            score = fit_weight * least_allocated_score_node(
                pod_req[p], alloc[i], used_req[i], weights
            ) + loadaware_weight * loadaware_score_node(
                pod_est[p], alloc[i], usage[i], est_extra[i], prod_base[i],
                bool(metric_fresh[i]), weights,
                bool(pod_is_prod[p]), score_according_prod,
            )
            if score > best_score:
                best_node, best_score = i, score
        assignments.append(best_node)
        if best_node >= 0:
            used_req[best_node] += pod_req[p]
            est_extra[best_node] += pod_est[p]
            if pod_is_prod[p]:
                prod_base[best_node] += pod_est[p]
    return assignments


class SequentialQuota:
    """Oracle-side single-level quota accounting mirroring ops/quota.py.

    Deliberately an independent implementation (not a GroupQuotaManager
    adapter): the differential tests derive their authority from two
    separately-written realizations of the same written semantics.
    """

    def __init__(self, min_, max_, auto_min, weight, allow_lent, total):
        self.min = np.asarray(min_, dtype=np.int64)
        self.max = np.asarray(max_, dtype=np.int64)
        self.auto_min = np.asarray(auto_min, dtype=np.int64)
        self.weight = np.asarray(weight, dtype=np.int64)
        self.allow_lent = list(allow_lent)
        self.total = np.asarray(total, dtype=np.int64)
        q, r = self.min.shape
        self.child_request = np.zeros((q, r), dtype=np.int64)
        self.used = np.zeros((q, r), dtype=np.int64)
        self.np_used = np.zeros((q, r), dtype=np.int64)

    def register_requests(self, pod_req, quota_ids):
        """OnPodAdd equivalent: every pod's request registers with its
        quota at creation, before any scheduling."""
        for p in range(pod_req.shape[0]):
            q = int(quota_ids[p])
            if q >= 0:
                self.child_request[q] += pod_req[p]

    def limited_request(self):
        real = self.child_request.copy()
        for i, lent in enumerate(self.allow_lent):
            if not lent:
                real[i] = np.maximum(real[i], self.min[i])
        return np.minimum(real, self.max)

    def runtime(self):
        req = self.limited_request()
        q, r = req.shape
        runtime = np.zeros((q, r), dtype=np.int64)
        for d in range(r):
            runtime[:, d] = water_filling(
                int(self.total[d]),
                req[:, d],
                self.min[:, d],
                self.auto_min[:, d],
                self.weight[:, d],
                self.allow_lent,
                exact_rational=True,
            )
        return np.minimum(runtime, self.max)

    def admit(self, quota_id, pod_req, non_preemptible, runtime_all=None):
        if quota_id < 0:
            return True
        dims = pod_req > 0
        runtime = (
            runtime_all if runtime_all is not None else self.runtime()
        )[quota_id]
        if np.any((self.used[quota_id] + pod_req)[dims] > runtime[dims]):
            return False
        if non_preemptible and np.any(
            (self.np_used[quota_id] + pod_req)[dims] > self.min[quota_id][dims]
        ):
            return False
        return True

    def assume(self, quota_id, pod_req, non_preemptible):
        if quota_id < 0:
            return
        self.used[quota_id] += pod_req
        if non_preemptible:
            self.np_used[quota_id] += pod_req


def schedule_sequential_quota(
    alloc, used_req, usage, prod_usage, est_extra, prod_base,
    metric_fresh, schedulable,
    pod_req, pod_est, pod_is_prod, pod_is_daemonset,
    pod_quota_id, pod_non_preemptible,
    quota: SequentialQuota,
    weights, thresholds, prod_thresholds,
    fit_weight=1, loadaware_weight=1, score_according_prod=False,
) -> List[int]:
    """Sequential oracle with the ElasticQuota PreFilter gate per pod."""
    n = alloc.shape[0]
    used_req = used_req.copy()
    est_extra = est_extra.copy()
    prod_base = prod_base.copy()
    quota.register_requests(pod_req, pod_quota_id)
    # requests are static within a solve, so the water-filled runtime is
    # computed once (mirrors the device path's hoist in ops/binpack.py)
    runtime_all = quota.runtime()
    assignments: List[int] = []
    for p in range(pod_req.shape[0]):
        if not quota.admit(
            int(pod_quota_id[p]), pod_req[p], bool(pod_non_preemptible[p]), runtime_all
        ):
            assignments.append(-1)
            continue
        best_node, best_score = -1, -1
        for i in range(n):
            if not schedulable[i]:
                continue
            if not fit_filter_node(pod_req[p], alloc[i], used_req[i]):
                continue
            if not loadaware_filter_node(
                alloc[i], usage[i], prod_usage[i], bool(metric_fresh[i]),
                thresholds, prod_thresholds,
                bool(pod_is_daemonset[p]), bool(pod_is_prod[p]),
            ):
                continue
            score = fit_weight * least_allocated_score_node(
                pod_req[p], alloc[i], used_req[i], weights
            ) + loadaware_weight * loadaware_score_node(
                pod_est[p], alloc[i], usage[i], est_extra[i], prod_base[i],
                bool(metric_fresh[i]), weights,
                bool(pod_is_prod[p]), score_according_prod,
            )
            if score > best_score:
                best_node, best_score = i, score
        assignments.append(best_node)
        if best_node >= 0:
            used_req[best_node] += pod_req[p]
            est_extra[best_node] += pod_est[p]
            if pod_is_prod[p]:
                prod_base[best_node] += pod_est[p]
            quota.assume(int(pod_quota_id[p]), pod_req[p], bool(pod_non_preemptible[p]))
    return assignments
