"""Per-node scheduler decision functions with reference (Go) semantics.

Each function mirrors one decision function from SURVEY.md Appendix A,
written as a direct scalar transliteration of the semantics (int64 Go
arithmetic == Python ints; float64 where the reference uses float64).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

MAX_NODE_SCORE = 100


def percent_rounded(used: int, total: int) -> int:
    """``round(used/total*100)``, half away from zero, in exact rational
    arithmetic: ``floor((200*used + total) / (2*total))``.

    DOCUMENTED DEVIATION from the reference: load_aware.go:215 computes
    this through float64 (``math.Round(float64(used)/float64(total)*100)``),
    whose division rounding can land an exact .5 boundary slightly below
    the half (e.g. used=23, total=40 → 57.4999999999999993 → 57, where the
    exact rational 57.5 rounds to 58). This framework defines the
    *infinitely-precise* result as the semantics — deterministic and
    hardware-independent — so both the oracle and the device path use the
    exact form. See percent_rounded_go_float64 for the reference quirk.
    """
    if total == 0:
        return 0
    return (200 * used + total) // (2 * total)


def percent_rounded_go_float64(used: int, total: int) -> int:
    """The reference's literal float64 path (load_aware.go:215), kept for
    documenting where the exact-rational semantics deviate from it."""
    if total == 0:
        return 0
    return int(math.floor(float(used) / float(total) * 100 + 0.5))


def least_requested_score(requested: int, capacity: int) -> int:
    """load_aware.go:388-397 (also upstream least_allocated semantics)."""
    if capacity == 0:
        return 0
    if requested > capacity:
        return 0
    return (capacity - requested) * MAX_NODE_SCORE // capacity


def fit_filter_node(
    pod_req: Sequence[int], alloc: Sequence[int], used: Sequence[int]
) -> bool:
    """Upstream NodeResourcesFit: every requested resource must fit."""
    for r, req in enumerate(pod_req):
        if req == 0:
            continue
        if used[r] + req > alloc[r]:
            return False
    return True


def least_allocated_score_node(
    pod_req: Sequence[int],
    alloc: Sequence[int],
    used: Sequence[int],
    weights: Sequence[int],
) -> int:
    """SURVEY.md A.6: weighted least-allocated over requests."""
    node_score = 0
    weight_sum = 0
    for r, w in enumerate(weights):
        node_score += least_requested_score(used[r] + pod_req[r], alloc[r]) * w
        weight_sum += w
    if weight_sum == 0:
        return 0
    return node_score // weight_sum


def loadaware_filter_node(
    alloc: Sequence[int],
    node_usage: Sequence[int],
    prod_usage: Sequence[int],
    metric_fresh: bool,
    thresholds: Sequence[int],
    prod_thresholds: Sequence[int],
    pod_is_daemonset: bool,
    pod_is_prod: bool,
) -> bool:
    """SURVEY.md A.1 (load_aware.go:123-255). True = node passes."""
    if pod_is_daemonset:
        return True
    if not metric_fresh:
        return True
    prod_mode = pod_is_prod and any(t > 0 for t in prod_thresholds)
    if prod_mode:
        usage_vec, thr_vec = prod_usage, prod_thresholds
    else:
        usage_vec, thr_vec = node_usage, thresholds
    for r, threshold in enumerate(thr_vec):
        if threshold == 0:
            continue
        if alloc[r] == 0:
            continue
        if percent_rounded(usage_vec[r], alloc[r]) >= threshold:
            return False
    return True


def loadaware_score_node(
    pod_est: Sequence[int],
    alloc: Sequence[int],
    node_usage: Sequence[int],
    est_extra: Sequence[int],
    prod_base: Sequence[int],
    metric_fresh: bool,
    weights: Sequence[int],
    pod_is_prod: bool,
    score_according_prod: bool = False,
) -> int:
    """SURVEY.md A.2 (load_aware.go:269-397) given the precomputed
    assigned-pod corrections (see state/cluster.py): non-prod base is
    node_usage + est_extra; prod base is prod_base."""
    if not metric_fresh:
        return 0
    prod_mode = score_according_prod and pod_is_prod
    node_score = 0
    weight_sum = 0
    for r, w in enumerate(weights):
        base = prod_base[r] if prod_mode else node_usage[r] + est_extra[r]
        estimated_used = base + pod_est[r]
        node_score += least_requested_score(estimated_used, alloc[r]) * w
        weight_sum += w
    if weight_sum == 0:
        return 0
    return node_score // weight_sum
