"""Vectorized host oracle: the sequential reference semantics at full scale.

``oracle/placement.py`` transliterates the reference's per-pod cycle into
Python scalars — authoritative but O(P*N*R) in interpreter time, which
capped oracle identity checks at reduced shapes. This module is the SAME
sequential semantics (pod-by-pod, each pod seeing all prior placements,
lowest-index tie-break) with the inner node loop vectorized in numpy
int64 — exact integer arithmetic, no float anywhere — fast enough to run
every BASELINE matrix config at its FULL shape.

Authority chain: scalar oracle (oracle/placement.py, transliterated from
pkg/scheduler/plugins/loadaware/load_aware.go:123-397 and SURVEY.md
Appendix A) == this module (tests/test_oracle_vectorized.py differential
sweep) == device scan == pallas kernel. The bench checks device output
against THIS oracle at full BASELINE shapes.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def _i64(x) -> np.ndarray:
    return np.asarray(x).astype(np.int64)


def oracle_args(state, pods, params) -> tuple:
    """Unpack (NodeState, PodBatch, ScoreParams) device structures into the
    positional numpy argument tuple shared by schedule_vectorized and the
    scalar oracle — the single adapter, so callers can't drift."""
    return (
        np.asarray(state.alloc), np.asarray(state.used_req),
        np.asarray(state.usage), np.asarray(state.prod_usage),
        np.asarray(state.est_extra), np.asarray(state.prod_base),
        np.asarray(state.metric_fresh), np.asarray(state.schedulable),
        np.asarray(pods.req), np.asarray(pods.est),
        np.asarray(pods.is_prod), np.asarray(pods.is_daemonset),
        np.asarray(params.weights), np.asarray(params.thresholds),
        np.asarray(params.prod_thresholds),
    )


def _percent_rounded(used: np.ndarray, total: np.ndarray) -> np.ndarray:
    """Exact-rational round(used/total*100), half away from zero
    (oracle/scheduler.py percent_rounded, vectorized)."""
    total_safe = np.maximum(total, 1)
    pct = (200 * used + total_safe) // (2 * total_safe)
    return np.where(total > 0, pct, 0)


def _least_requested(requested: np.ndarray, capacity: np.ndarray) -> np.ndarray:
    """(cap-req)*100//cap; 0 when cap==0 or req>cap (load_aware.go:388)."""
    cap_safe = np.maximum(capacity, 1)
    score = (capacity - requested) * 100 // cap_safe
    return np.where((capacity == 0) | (requested > capacity), 0, score)


class VectorQuota:
    """Single-level quota accounting over [Q,R] int64 arrays, semantics of
    oracle/placement.py SequentialQuota (itself mirroring SURVEY.md
    A.3/A.4) with the per-pod admit vectorized."""

    def __init__(self, min_, max_, auto_min, weight, allow_lent, total):
        self.min = _i64(min_)
        self.max = _i64(max_)
        self.auto_min = _i64(auto_min)
        self.weight = _i64(weight)
        self.allow_lent = np.asarray(allow_lent, dtype=bool)
        self.total = _i64(total)
        q, r = self.min.shape
        self.child_request = np.zeros((q, r), dtype=np.int64)
        self.used = np.zeros((q, r), dtype=np.int64)
        self.np_used = np.zeros((q, r), dtype=np.int64)

    def register_requests(self, pod_req, quota_ids):
        quota_ids = np.asarray(quota_ids)
        sel = quota_ids >= 0
        np.add.at(self.child_request, quota_ids[sel], _i64(pod_req)[sel])

    def runtime(self) -> np.ndarray:
        from koordinator_tpu.quota.core import water_filling

        real = self.child_request.copy()
        real[~self.allow_lent] = np.maximum(
            real[~self.allow_lent], self.min[~self.allow_lent]
        )
        req = np.minimum(real, self.max)
        runtime = np.zeros_like(req)
        for d in range(req.shape[1]):
            runtime[:, d] = water_filling(
                int(self.total[d]),
                req[:, d],
                self.min[:, d],
                self.auto_min[:, d],
                self.weight[:, d],
                self.allow_lent,
                exact_rational=True,
            )
        return np.minimum(runtime, self.max)

    def admit(self, quota_id, pod_req, non_preemptible, runtime_all):
        if quota_id < 0:
            return True
        dims = pod_req > 0
        if np.any(
            (self.used[quota_id] + pod_req)[dims] > runtime_all[quota_id][dims]
        ):
            return False
        if non_preemptible and np.any(
            (self.np_used[quota_id] + pod_req)[dims] > self.min[quota_id][dims]
        ):
            return False
        return True

    def assume(self, quota_id, pod_req, non_preemptible):
        if quota_id < 0:
            return
        self.used[quota_id] += pod_req
        if non_preemptible:
            self.np_used[quota_id] += pod_req


def _numa_score_vec(cap, free, req, most_allocated: bool) -> np.ndarray:
    """[N] NUMA least/most-allocated score (ops/binpack.py
    numa_node_score, itself from nodenumaresource/scoring.go): per
    requested resource ``requested = cap - free + req``; least =
    ``(cap-requested)*100//cap`` (0 when cap==0 or requested>cap); mean
    over requested resources."""
    member = req > 0
    requested = cap - free + req[None, :]
    capq = np.maximum(cap, 1)
    least = (cap - requested) * 100 // capq
    most = requested * 100 // capq
    per = np.where(
        member[None, :] & (cap > 0) & (requested <= cap),
        most if most_allocated else least,
        0,
    )
    w = int(member.sum())
    if w == 0:
        return np.zeros(cap.shape[0], dtype=np.int64)
    return per.sum(axis=-1) // w


def schedule_vectorized(
    alloc,
    used_req,
    usage,
    prod_usage,
    est_extra,
    prod_base,
    metric_fresh,
    schedulable,
    pod_req,
    pod_est,
    pod_is_prod,
    pod_is_daemonset,
    weights,
    thresholds,
    prod_thresholds,
    fit_weight: int = 1,
    loadaware_weight: int = 1,
    score_according_prod: bool = False,
    pod_quota_id=None,
    pod_non_preemptible=None,
    quota: Optional[VectorQuota] = None,
    numa_cap=None,
    numa_free=None,
    pod_has_numa=None,
    numa_node_policy=None,
    numa_most_allocated: bool = False,
    resv_node=None,
    resv_free=None,
    resv_allocate_once=None,
    resv_match=None,
    details: Optional[dict] = None,
) -> np.ndarray:
    """[P] node index per pod (-1 = unschedulable) — identical output to
    oracle/placement.py schedule_sequential / schedule_sequential_quota.

    Optional feature arrays mirror ops/binpack.py solve_batch:

    - NUMA (``numa_cap``/``numa_free`` [N,R], ``pod_has_numa`` [P],
      ``numa_node_policy`` [N]): every pod's score adds the NUMA
      least/most-allocated term; a placed pod with a NUMA policy (its
      own or the node's) consumes ``numa_free`` on the chosen node.
    - Reservations (``resv_node`` [V], ``resv_free`` [V,R],
      ``resv_allocate_once`` [V], ``resv_match`` [P,V]): a pod's
      matched reservations credit their free remainder back on their
      nodes for its Filter/Score; on placement the matched reservation
      with the most free capacity on the chosen node is consumed
      (allocate-once releases its remainder), and only the net request
      lands on the node.

    When ``details`` is a dict, the mutated per-feature end states land
    in it (numa_free, resv_free, numa_consumed, resv_vstar, resv_delta,
    resv_rem) for bit-comparison against the device solver's outputs.
    """
    alloc = _i64(alloc)
    used_req = _i64(used_req).copy()
    usage = _i64(usage)
    prod_usage = _i64(prod_usage)
    est_extra = _i64(est_extra).copy()
    prod_base = _i64(prod_base).copy()
    metric_fresh = np.asarray(metric_fresh, dtype=bool)
    schedulable = np.asarray(schedulable, dtype=bool)
    pod_req = _i64(pod_req)
    pod_est = _i64(pod_est)
    pod_is_prod = np.asarray(pod_is_prod, dtype=bool)
    pod_is_daemonset = np.asarray(pod_is_daemonset, dtype=bool)
    weights = _i64(weights)
    thresholds = _i64(thresholds)
    prod_thresholds = _i64(prod_thresholds)

    use_numa = numa_cap is not None
    if use_numa:
        numa_cap = _i64(numa_cap)
        numa_free = _i64(numa_free).copy()
        pod_has_numa = np.asarray(pod_has_numa, dtype=bool)
        numa_node_policy = np.asarray(numa_node_policy, dtype=bool)
        numa_consumed = np.zeros(pod_req.shape[0], dtype=bool)
    use_resv = resv_node is not None
    if use_resv:
        resv_node = _i64(resv_node)
        resv_free = _i64(resv_free).copy()
        resv_allocate_once = np.asarray(resv_allocate_once, dtype=bool)
        resv_match = np.asarray(resv_match, dtype=bool)
        resv_vstar = np.full(pod_req.shape[0], -1, dtype=np.int64)
        resv_delta = np.zeros_like(_i64(pod_req))
        resv_rem = np.zeros_like(_i64(pod_req))

    # The LoadAware filter reads only static state (usage/prod_usage and
    # the reported allocatable), so the per-node violation masks for both
    # pod modes are computed once for the whole batch (A.1).
    usage_pct = _percent_rounded(usage, alloc)
    prod_pct = _percent_rounded(prod_usage, alloc)
    checkable = alloc > 0
    viol_nonprod = (
        checkable & (thresholds > 0) & (usage_pct >= thresholds)
    ).any(axis=1)
    viol_prod = (
        checkable & (prod_thresholds > 0) & (prod_pct >= prod_thresholds)
    ).any(axis=1)
    prod_cfg = bool((prod_thresholds > 0).any())

    weight_sum = max(int(weights.sum()), 1)
    n = alloc.shape[0]
    n_pods = pod_req.shape[0]
    assignments = np.full(n_pods, -1, dtype=np.int64)
    if n == 0:
        # empty cluster: nothing placeable (solve_batch's shape early-out)
        if quota is not None:
            quota.register_requests(pod_req, pod_quota_id)
        return assignments

    use_q = quota is not None
    runtime_all = None
    if use_q:
        quota.register_requests(pod_req, pod_quota_id)
        runtime_all = quota.runtime()

    def class_cand(req, est, is_prod, is_daemonset, match_row=None):
        """[N] packed candidate vector (score, -1 where infeasible) for
        one pod shape against the CURRENT node state — the same math as
        the per-pod dense pass, vectorized over nodes."""
        u = used_req
        if match_row is not None:
            # matched reservations credit their free remainder back on
            # their nodes for this pod's Filter/Score (fit path only)
            credit = np.zeros_like(used_req)
            sel = np.flatnonzero(match_row)
            np.add.at(credit, resv_node[sel], resv_free[sel])
            u = used_req - credit
        mask = schedulable & (
            (req == 0) | (u + req <= alloc)
        ).all(axis=1)
        if not is_daemonset:
            viol = viol_prod if (is_prod and prod_cfg) else viol_nonprod
            mask = mask & ~(metric_fresh & viol)
        fit_per = _least_requested(u + req, alloc)
        fit_score = (fit_per * weights).sum(axis=1) // weight_sum
        la_base = (
            prod_base
            if (score_according_prod and is_prod)
            else usage + est_extra
        )
        la_per = _least_requested(la_base + est, alloc)
        la_score = np.where(
            metric_fresh, (la_per * weights).sum(axis=1) // weight_sum, 0
        )
        score = fit_weight * fit_score + loadaware_weight * la_score
        if use_numa:
            score = score + _numa_score_vec(
                numa_cap, numa_free, req, numa_most_allocated
            )
        return np.where(mask, score, -1)

    def class_cand_row(i, req, est, is_prod, is_daemonset, match_row=None):
        """The single-node row of class_cand — identical integer math on
        the [R] slice, so a cached vector patched at row i equals a full
        recompute. (Every mutation a placement makes — used_req,
        est_extra, prod_base, numa_free on the chosen node, and
        resv_free of reservations living on that node — lands on a
        single node row, so the single-row patch invariant holds for
        all features.)"""
        a, u = alloc[i], used_req[i]
        if match_row is not None:
            sel = np.flatnonzero(match_row & (resv_node == i))
            if sel.size:
                u = u - resv_free[sel].sum(axis=0)
        ok = bool(schedulable[i]) and bool(
            ((req == 0) | (u + req <= a)).all()
        )
        if ok and not is_daemonset:
            viol = viol_prod if (is_prod and prod_cfg) else viol_nonprod
            ok = not (bool(metric_fresh[i]) and bool(viol[i]))
        if not ok:
            return -1
        fit_per = _least_requested(u + req, a)
        fit_score = int((fit_per * weights).sum()) // weight_sum
        base = (
            prod_base[i]
            if (score_according_prod and is_prod)
            else usage[i] + est_extra[i]
        )
        la_per = _least_requested(base + est, a)
        la_score = (
            int((la_per * weights).sum()) // weight_sum
            if metric_fresh[i]
            else 0
        )
        score = fit_weight * fit_score + loadaware_weight * la_score
        if use_numa:
            score += int(_numa_score_vec(
                numa_cap[i:i + 1], numa_free[i:i + 1], req,
                numa_most_allocated,
            )[0])
        return score

    # Pod-shape cache: a placement mutates exactly ONE node row (every
    # feature's mutations — used_req/est_extra/prod_base, numa_free, and
    # resv_free of reservations living there — land on the chosen node),
    # so a cached class vector stays valid once that row is recomputed.
    # Repair is LAZY: each entry remembers the placement-history index
    # of its last repair and, on reuse, recomputes only the rows placed
    # since — total repair work tracks actual interleaving instead of
    # paying O(cache_size) on every placement.
    CACHE_CAP = 192
    cache = {}
    placed_rows: list = []  # chosen node per placement, in order

    for p in range(n_pods):
        req = pod_req[p]
        est = pod_est[p]
        is_prod = bool(pod_is_prod[p])
        is_ds = bool(pod_is_daemonset[p])
        match_row = resv_match[p] if use_resv else None
        if use_q and not quota.admit(
            int(pod_quota_id[p]), req, bool(pod_non_preemptible[p]), runtime_all
        ):
            continue

        key = (req.tobytes(), est.tobytes(), is_prod, is_ds)
        if use_resv:
            key = key + (match_row.tobytes(),)
        entry = cache.get(key)
        if entry is None:
            cand = class_cand(req, est, is_prod, is_ds, match_row)
            if len(cache) < CACHE_CAP:
                cache[key] = [req, est, is_prod, is_ds, match_row, cand,
                              len(placed_rows)]
        else:
            cand = entry[5]
            for i in set(placed_rows[entry[6]:]):
                cand[i] = class_cand_row(
                    i, entry[0], entry[1], entry[2], entry[3], entry[4]
                )
            entry[6] = len(placed_rows)

        best = int(cand.argmax())  # lowest index among ties
        if cand[best] < 0:
            continue
        assignments[p] = best
        net_req = req
        if use_resv:
            # consume the matched reservation with the most free capacity
            # on the chosen node (first max ties the argmax); an
            # allocate-once reservation releases its remainder
            on_node = match_row & (resv_node == best)
            fsum = np.where(on_node, resv_free.sum(axis=-1), -1)
            v_raw = int(fsum.argmax())
            if fsum[v_raw] > 0:
                delta = np.minimum(resv_free[v_raw], req)
                if resv_allocate_once[v_raw]:
                    rem = resv_free[v_raw] - delta
                    resv_free[v_raw] = 0
                else:
                    rem = np.zeros_like(delta)
                    resv_free[v_raw] = resv_free[v_raw] - delta
                resv_vstar[p] = v_raw
                resv_delta[p] = delta
                resv_rem[p] = rem
                net_req = req - delta - rem
        used_req[best] += net_req
        est_extra[best] += est
        if is_prod:
            prod_base[best] += est
        if use_numa and (
            bool(pod_has_numa[p]) or bool(numa_node_policy[best])
        ):
            numa_free[best] -= req
            numa_consumed[p] = True
        if use_q:
            quota.assume(int(pod_quota_id[p]), req, bool(pod_non_preemptible[p]))
        placed_rows.append(best)
    if details is not None:
        details["used_req"] = used_req
        details["est_extra"] = est_extra
        details["prod_base"] = prod_base
        if use_numa:
            details["numa_free"] = numa_free
            details["numa_consumed"] = numa_consumed
        if use_resv:
            details["resv_free"] = resv_free
            details["resv_vstar"] = resv_vstar
            details["resv_delta"] = resv_delta
            details["resv_rem"] = resv_rem
    return assignments


def solve_full_vectorized(
    state,
    pods,
    params,
    quota: Optional[VectorQuota] = None,
    pod_quota_id=None,
    pod_non_preemptible=None,
    gang_id=None,
    gang_min_member=None,
    gang_bound_count=None,
    gang_strict=None,
    gang_group_id=None,
    numa_aux=None,
    resv=None,
    fit_weight: int = 1,
    loadaware_weight: int = 1,
    score_according_prod: bool = False,
    numa_most_allocated: bool = False,
) -> dict:
    """End-to-end oracle for ops/binpack.py solve_batch with EVERY
    feature enabled: the sequential pass (quota admission, reservation
    credit/consume, NUMA score/consume) followed by the batch-end gang
    resolution and the rejected-pods release of node, reservation, NUMA
    and quota accounting. Returns a dict with ``assign`` (post-gang) and
    the final mutated arrays for bit-comparison against SolveResult.

    ``state``/``pods``/``params`` are the device structures;
    ``numa_aux``/``resv`` the solver's NumaAux/ResvArrays.
    """
    details: dict = {}
    kwargs = dict(
        fit_weight=fit_weight,
        loadaware_weight=loadaware_weight,
        score_according_prod=score_according_prod,
        pod_quota_id=pod_quota_id,
        pod_non_preemptible=pod_non_preemptible,
        quota=quota,
        details=details,
    )
    if numa_aux is not None:
        kwargs.update(
            numa_cap=np.asarray(state.numa_cap),
            numa_free=np.asarray(state.numa_free),
            pod_has_numa=np.asarray(pods.has_numa_policy),
            numa_node_policy=np.asarray(numa_aux.node_policy),
            numa_most_allocated=numa_most_allocated,
        )
    if resv is not None:
        kwargs.update(
            resv_node=np.asarray(resv.node),
            resv_free=np.asarray(resv.free),
            resv_allocate_once=np.asarray(resv.allocate_once),
            resv_match=np.asarray(resv.match),
        )
    raw = schedule_vectorized(*oracle_args(state, pods, params), **kwargs)
    out = {"raw_assign": raw, **details}
    if gang_id is None:
        out["assign"] = raw
        return out

    commit, waiting, rejected = gang_outcomes_np(
        raw, gang_id, gang_min_member, gang_bound_count, gang_strict,
        gang_group_id,
    )
    out["assign"] = np.where(commit | waiting, raw, -1)
    out["commit"], out["waiting"], out["rejected"] = commit, waiting, rejected

    # release the rejected Strict pods' holds (solve_batch epilogue)
    pod_req = _i64(np.asarray(pods.req))
    pod_est = _i64(np.asarray(pods.est))
    pod_is_prod = np.asarray(pods.is_prod, bool)
    rel_req = pod_req.copy()
    if resv is not None:
        rel_req = pod_req - details["resv_delta"] - details["resv_rem"]
    for p in np.flatnonzero(rejected):
        b = int(raw[p])
        out["used_req"][b] -= rel_req[p]
        out["est_extra"][b] -= pod_est[p]
        if pod_is_prod[p]:
            out["prod_base"][b] -= pod_est[p]
        if resv is not None and details["resv_vstar"][p] >= 0:
            out["resv_free"][int(details["resv_vstar"][p])] += (
                details["resv_delta"][p] + details["resv_rem"][p]
            )
        if numa_aux is not None and details["numa_consumed"][p]:
            out["numa_free"][b] += pod_req[p]
        if quota is not None and int(pod_quota_id[p]) >= 0:
            q = int(pod_quota_id[p])
            quota.used[q] -= pod_req[p]
            if bool(pod_non_preemptible[p]):
                quota.np_used[q] -= pod_req[p]
    return out


def gang_outcomes_np(
    assignments: np.ndarray,  # [P] raw scan assignment
    gang_id: np.ndarray,      # [P] int, -1 = not gang-managed
    min_member: np.ndarray,   # [G]
    bound_count=None,         # [G]
    strict=None,              # [G] bool
    group_id=None,            # [G]
) -> tuple:
    """Numpy re-derivation of ops/gang.py gang_outcomes (SURVEY.md A.5
    batch-end resolution): (commit[P], waiting[P], rejected[P])."""
    assignments = np.asarray(assignments)
    gang_id = np.asarray(gang_id)
    min_member = _i64(min_member)
    g = min_member.shape[0]
    bound_count = (
        _i64(bound_count) if bound_count is not None else np.zeros(g, np.int64)
    )
    strict = (
        np.asarray(strict, bool) if strict is not None else np.ones(g, bool)
    )
    group_id = (
        np.asarray(group_id) if group_id is not None else np.arange(g)
    )
    placed = assignments >= 0
    member_placed = placed & (gang_id >= 0)
    placed_per_gang = np.bincount(
        gang_id[member_placed], minlength=g
    ).astype(np.int64)
    valid = (placed_per_gang + bound_count) >= min_member
    group_invalid = np.bincount(
        group_id, weights=(~valid).astype(np.int64), minlength=g
    )
    gang_ok = group_invalid[group_id] == 0
    gid = np.maximum(gang_id, 0)
    pod_gang_ok = gang_ok[gid]
    commit = placed & ((gang_id < 0) | pod_gang_ok)
    waiting = member_placed & ~pod_gang_ok & ~strict[gid]
    rejected = member_placed & ~pod_gang_ok & strict[gid]
    return commit, waiting, rejected
