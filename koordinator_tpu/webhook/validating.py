"""Pod validating admission.

Reference: pkg/webhook/pod/validating/cluster_colocation_profile.go:35-140
— required QoS for colocation resources, immutability of QoS/priority on
update, forbidden QoS×priority combinations, and LSR/LSE integer-CPU
requirements.
"""

from __future__ import annotations

from typing import List, Optional

from koordinator_tpu.apis.extension import (
    PriorityClass,
    QoSClass,
    ResourceName,
    priority_class_of,
)
from koordinator_tpu.apis.types import PodSpec

#: QoS class -> priority classes it must NOT combine with
#: (forbidSpecialQoSClassAndPriorityClass calls, :58-59)
_FORBIDDEN = {
    QoSClass.BE: (PriorityClass.NONE, PriorityClass.PROD),
    QoSClass.LSR: (
        PriorityClass.NONE,
        PriorityClass.MID,
        PriorityClass.BATCH,
        PriorityClass.FREE,
    ),
}


class PodValidatingWebhook:
    """Validates pods at create/update; returns the list of violations
    (empty = admitted)."""

    def validate(
        self, pod: PodSpec, old_pod: Optional[PodSpec] = None
    ) -> List[str]:
        errs: List[str] = []
        if old_pod is not None:
            errs += self._validate_immutable(old_pod, pod)
        errs += self._validate_required_qos(pod)
        errs += self._validate_forbidden_combos(pod)
        errs += self._validate_resources(pod)
        return errs

    # update: QoS, priority class, and koordinator priority are immutable
    # (:52-54, validateImmutable*)
    def _validate_immutable(self, old: PodSpec, new: PodSpec) -> List[str]:
        errs = []
        if old.qos != new.qos:
            errs.append("labels.koordinator.sh/qosClass: field is immutable")
        old_pc = old.priority_class or priority_class_of(value=old.priority)
        new_pc = new.priority_class or priority_class_of(value=new.priority)
        if old_pc != new_pc:
            errs.append("spec.priority: field is immutable")
        if old.sub_priority != new.sub_priority:
            errs.append("labels.koordinator.sh/priority: field is immutable")
        return errs

    # batch resources require QoS BE (validateRequiredQoSClass :71-85)
    def _validate_required_qos(self, pod: PodSpec) -> List[str]:
        batch = pod.requests.get(ResourceName.BATCH_CPU, 0) or pod.requests.get(
            ResourceName.BATCH_MEMORY, 0
        )
        if not batch or pod.qos == QoSClass.BE:
            return []
        return [
            "labels.koordinator.sh/qosClass: must specify koordinator QoS "
            "BE with koordinator colocation resources"
        ]

    def _validate_forbidden_combos(self, pod: PodSpec) -> List[str]:
        forbidden = _FORBIDDEN.get(pod.qos)
        if forbidden is None:
            return []
        # __post_init__ guarantees priority_class is populated; it is the
        # authoritative class (the mutator may set it directly)
        pc = pod.priority_class or priority_class_of(value=pod.priority)
        if pc in forbidden:
            return [
                f"Pod: qosClass={pod.qos.name} and priorityClass={pc.name} "
                "cannot be used in combination"
            ]
        return []

    # LSR/LSE pods must declare integer CPUs (validateResources :123-140)
    def _validate_resources(self, pod: PodSpec) -> List[str]:
        if pod.qos not in (QoSClass.LSR, QoSClass.LSE):
            return []
        cpu = pod.requests.get(ResourceName.CPU, 0)
        if cpu == 0:
            return [
                "pod.spec.containers[*].resources.requests: "
                f"{pod.qos.name} Pod must declare the requested CPUs"
            ]
        if cpu % 1000 != 0:
            return [
                "pod.spec.containers[*].resources.requests: the requested "
                f"CPUs of {pod.qos.name} Pod must be integer"
            ]
        return []
