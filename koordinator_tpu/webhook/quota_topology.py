"""ElasticQuota topology guard: admission for quota create/update/delete.

Reference: pkg/webhook/elasticquota/quota_topology.go (ValidAddQuota :59,
ValidUpdateQuota :97, ValidDeleteQuota :153) and quota_topology_check.go:

- validateQuotaSelfItem (:38-67): min/max/shared-weight dimensions must be
  non-negative; every min key must exist in max with ``min <= max``;
- checkParentQuotaInfo (:166): the parent must exist and be ``is_parent``;
- checkTreeID (:110): a child's tree id must match its parent's;
- checkSubAndParentGroupMaxQuotaKeySame (:182): a non-root-parent child's
  max keys must equal its parent's max keys;
- checkMinQuotaValidate (:216): Σ sibling mins (self included) must fit
  the parent min, and Σ children mins must fit the quota's own min;
- ValidDeleteQuota forbids deleting a quota that still has children.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from koordinator_tpu.apis.types import QuotaSpec
from koordinator_tpu.quota.core import ROOT_QUOTA as ROOT


class QuotaTopologyError(Exception):
    """Admission rejection with the violated rule."""


class QuotaTopologyGuard:
    """Validates quota topology before specs reach the tree managers."""

    def __init__(self):
        self.quotas: Dict[str, QuotaSpec] = {}

    def _children(self, parent: str) -> List[QuotaSpec]:
        return [
            q for q in self.quotas.values() if (q.parent or ROOT) == parent
        ]

    # -- public admission ----------------------------------------------------

    def validate_add(self, spec: QuotaSpec) -> None:
        if spec.name in self.quotas:
            raise QuotaTopologyError(f"quota {spec.name} already exists")
        self._validate_self(spec)
        self._validate_topology(spec)
        self.quotas[spec.name] = spec

    def validate_update(self, spec: QuotaSpec) -> None:
        old = self.quotas.get(spec.name)
        if old is None:
            raise QuotaTopologyError(f"quota {spec.name} not found")
        if spec.tree_id != old.tree_id:
            # checkTreeID: the tree id cannot change on update
            raise QuotaTopologyError(
                f"quota {spec.name} tree id is immutable "
                f"({old.tree_id!r} -> {spec.tree_id!r})"
            )
        if not spec.is_parent and old.is_parent and self._children(spec.name):
            # checkIsParentChange (:148): a quota with children cannot
            # stop being a parent
            raise QuotaTopologyError(
                f"quota {spec.name} has children, isParent is forbidden to "
                "modify as false"
            )
        self._validate_self(spec)
        self._validate_topology(spec)
        self.quotas[spec.name] = spec

    def validate_delete(self, name: str) -> None:
        spec = self.quotas.get(name)
        if spec is None:
            raise QuotaTopologyError(f"quota {name} not found")
        children = self._children(name)
        if children:
            raise QuotaTopologyError(
                f"quota {name} still has children: "
                f"{sorted(c.name for c in children)}"
            )
        del self.quotas[name]

    # -- checks --------------------------------------------------------------

    def _validate_self(self, spec: QuotaSpec) -> None:
        for field_name, mapping in (("min", spec.min), ("max", spec.max)):
            for key, value in mapping.items():
                if value < 0:
                    raise QuotaTopologyError(
                        f"quota {spec.name} {field_name}[{key.name}] < 0"
                    )
        if spec.shared_weight is not None:
            for key, value in spec.shared_weight.items():
                if value < 0:
                    raise QuotaTopologyError(
                        f"quota {spec.name} sharedWeight[{key.name}] < 0"
                    )
        for key, value in spec.min.items():
            if key not in spec.max or spec.max[key] < value:
                raise QuotaTopologyError(
                    f"quota {spec.name} min > max on {key.name}"
                )

    def _validate_topology(self, spec: QuotaSpec) -> None:
        parent = spec.parent or ROOT
        # a non-parent child of root passes the remaining checks trivially
        # (quota_topology_check.go:86-89)
        if parent == ROOT and not spec.is_parent:
            return
        if parent != ROOT:
            parent_spec = self.quotas.get(parent)
            if parent_spec is None:
                raise QuotaTopologyError(
                    f"quota {spec.name} parent {parent} not found"
                )
            if not parent_spec.is_parent:
                raise QuotaTopologyError(
                    f"quota {spec.name} parent {parent} is not a parent group"
                )
            if parent_spec.tree_id != spec.tree_id:
                raise QuotaTopologyError(
                    f"quota {spec.name} tree id {spec.tree_id!r} differs "
                    f"from parent's {parent_spec.tree_id!r}"
                )
            if set(spec.max) != set(parent_spec.max):
                raise QuotaTopologyError(
                    f"quota {spec.name} max keys differ from parent "
                    f"{parent}'s max keys"
                )
            self._check_min_sum(spec, parent_spec)
        children = [c for c in self._children(spec.name) if c.name != spec.name]
        for child in children:
            # checkSubAndParentGroupMaxQuotaKeySame also walks children
            if set(child.max) != set(spec.max):
                raise QuotaTopologyError(
                    f"quota {spec.name} max keys differ from child "
                    f"{child.name}'s max keys"
                )
        # children's min must fit the (possibly shrunken) own min on EVERY
        # dimension any child declares (LessThanOrEqualCompletely)
        child_keys = {key for c in children for key in c.min}
        for key in child_keys:
            child_sum = sum(c.min.get(key, 0) for c in children)
            if child_sum > spec.min.get(key, 0):
                raise QuotaTopologyError(
                    f"quota {spec.name} children's min exceeds its own min "
                    f"on {key.name}"
                )

    def _check_min_sum(self, spec, parent_spec) -> None:
        siblings = [
            c for c in self._children(parent_spec.name) if c.name != spec.name
        ]
        for key, value in spec.min.items():
            total = value + sum(c.min.get(key, 0) for c in siblings)
            if total > parent_spec.min.get(key, 0):
                raise QuotaTopologyError(
                    f"all brothers' min > parent {parent_spec.name} min on "
                    f"{key.name}"
                )
