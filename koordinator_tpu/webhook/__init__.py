"""Admission webhooks: the typed ingress for pods and quotas.

Rebuild of /root/reference/pkg/webhook/: pod mutation
(ClusterColocationProfile injection + batch/mid resource translation,
pod/mutating/cluster_colocation_profile.go), pod validation
(pod/validating/cluster_colocation_profile.go), and the ElasticQuota
topology guard (elasticquota/quota_topology.go).
"""

from koordinator_tpu.webhook.mutating import (  # noqa: F401
    ClusterColocationProfile,
    PodMutatingWebhook,
)
from koordinator_tpu.webhook.validating import (  # noqa: F401
    PodValidatingWebhook,
)
from koordinator_tpu.webhook.quota_topology import (  # noqa: F401
    QuotaTopologyGuard,
    QuotaTopologyError,
)
