"""Admission webhooks: the typed ingress for pods, nodes, quotas, and
the SLO configmaps.

Rebuild of /root/reference/pkg/webhook/: pod mutation
(ClusterColocationProfile injection + batch/mid resource translation,
pod/mutating/cluster_colocation_profile.go), pod validation
(pod/validating/cluster_colocation_profile.go), the ElasticQuota
topology guard (elasticquota/quota_topology.go), node amplification
admit/validate (node/plugins/resourceamplification), and the SLO
configmap checkers (cm/plugins/sloconfig).
"""

from koordinator_tpu.webhook.mutating import (  # noqa: F401
    ClusterColocationProfile,
    PodMutatingWebhook,
)
from koordinator_tpu.webhook.validating import (  # noqa: F401
    PodValidatingWebhook,
)
from koordinator_tpu.webhook.quota_topology import (  # noqa: F401
    QuotaTopologyGuard,
    QuotaTopologyError,
)
from koordinator_tpu.webhook.node import (  # noqa: F401
    NodeMutatingWebhook,
    NodeValidatingWebhook,
)
from koordinator_tpu.webhook.cm import (  # noqa: F401
    SLOConfigValidatingWebhook,
)
