"""SLO-config admission: validate the cluster strategy configmaps.

Reference: pkg/webhook/cm/plugins/sloconfig — checkers for the
slo-controller configmaps reject out-of-range strategies before they
reach nodes (colocation_checker.go, cpu_burst_checker.go,
resource_qos_checker.go): thresholds within percent bounds, positive
windows, bvt group identities in the kernel's accepted set, resctrl
ranges ordered.
"""

from __future__ import annotations

from typing import List

from koordinator_tpu.manager.sloconfig import (
    CPUBurstStrategy,
    ColocationStrategy,
    QoSConfig,
    ResourceQOSStrategy,
    ResourceThresholdStrategy,
)

#: kernel-accepted bvt group identities (groupidentity rule values)
_BVT_VALUES = (-1, 0, 2)


def check_colocation(strategy: ColocationStrategy) -> List[str]:
    """colocation_checker.go — delegates to the typed is_valid plus
    the explicit messages the webhook reports."""
    v: List[str] = []
    if not 0 < strategy.cpu_reclaim_threshold_percent <= 100:
        v.append("cpuReclaimThresholdPercent must be in (0, 100]")
    if not 0 < strategy.memory_reclaim_threshold_percent <= 100:
        v.append("memoryReclaimThresholdPercent must be in (0, 100]")
    if strategy.degrade_time_minutes <= 0:
        v.append("degradeTimeMinutes must be positive")
    if strategy.update_time_threshold_seconds <= 0:
        v.append("updateTimeThresholdSeconds must be positive")
    if not 0 < strategy.resource_diff_threshold <= 1:
        v.append("resourceDiffThreshold must be in (0, 1]")
    if strategy.metric_aggregate_duration_seconds <= 0:
        v.append("metricAggregateDurationSeconds must be positive")
    if strategy.metric_report_interval_seconds <= 0:
        v.append("metricReportIntervalSeconds must be positive")
    if strategy.cpu_calculate_policy not in (
        "usage", "request", "maxUsageRequest"
    ):
        v.append(f"unknown cpu calculate policy "
                 f"{strategy.cpu_calculate_policy!r}")
    if strategy.memory_calculate_policy not in (
        "usage", "request", "maxUsageRequest"
    ):
        v.append(f"unknown memory calculate policy "
                 f"{strategy.memory_calculate_policy!r}")
    return v


def check_cpu_burst(strategy: CPUBurstStrategy) -> List[str]:
    """cpu_burst_checker.go bounds."""
    v: List[str] = []
    if strategy.policy not in ("none", "cpuBurstOnly", "cfsQuotaBurstOnly",
                               "auto"):
        v.append(f"unknown cpu burst policy {strategy.policy!r}")
    if strategy.cpu_burst_percent <= 0 or strategy.cpu_burst_percent > 10000:
        v.append("cpuBurstPercent must be in (0, 10000]")
    if strategy.cfs_quota_burst_percent < 100:
        v.append("cfsQuotaBurstPercent must be >= 100")
    if not 0 <= strategy.share_pool_threshold_percent <= 100:
        v.append("sharePoolThresholdPercent must be in [0, 100]")
    return v


def check_threshold(strategy: ResourceThresholdStrategy) -> List[str]:
    v: List[str] = []
    for name, pct in (
        ("cpuSuppressThresholdPercent",
         strategy.cpu_suppress_threshold_percent),
        ("memoryEvictThresholdPercent",
         strategy.memory_evict_threshold_percent),
        ("cpuEvictBEUsageThresholdPercent",
         strategy.cpu_evict_be_usage_threshold_percent),
    ):
        if not 0 < pct <= 100:
            v.append(f"{name} must be in (0, 100]")
    if strategy.cpu_suppress_policy not in ("cpuset", "cfsQuota"):
        v.append(f"unknown cpu suppress policy "
                 f"{strategy.cpu_suppress_policy!r}")
    return v


def _check_qos(tier: str, cfg: QoSConfig) -> List[str]:
    v: List[str] = []
    if cfg.cpu.group_identity not in _BVT_VALUES:
        v.append(f"{tier}: bvt group identity must be one of "
                 f"{_BVT_VALUES}, got {cfg.cpu.group_identity}")
    rq = cfg.resctrl
    if not (0 <= rq.cat_range_start_percent
            <= rq.cat_range_end_percent <= 100):
        v.append(f"{tier}: resctrl LLC range must satisfy "
                 f"0 <= start <= end <= 100")
    if not 0 < rq.mba_percent <= 100:
        v.append(f"{tier}: resctrl MBA percent must be in (0, 100]")
    for pct_name, pct in (("minLimitPercent", cfg.memory.min_limit_percent),
                          ("lowLimitPercent", cfg.memory.low_limit_percent),
                          ("throttlingPercent",
                           cfg.memory.throttling_percent)):
        if not 0 <= pct <= 100:
            v.append(f"{tier}: memory {pct_name} must be in [0, 100]")
    return v


def check_resource_qos(strategy: ResourceQOSStrategy) -> List[str]:
    """resource_qos_checker.go bounds per tier."""
    v: List[str] = []
    for tier in ("lsr", "ls", "be", "system"):
        v.extend(_check_qos(tier, getattr(strategy, tier)))
    return v


class SLOConfigValidatingWebhook:
    """The configmap admission entry (cm/plugins/sloconfig checkers):
    one validate() per config kind; empty list = admitted."""

    def validate_colocation(self, strategy: ColocationStrategy) -> List[str]:
        return check_colocation(strategy)

    def validate_cpu_burst(self, strategy: CPUBurstStrategy) -> List[str]:
        return check_cpu_burst(strategy)

    def validate_threshold(
        self, strategy: ResourceThresholdStrategy
    ) -> List[str]:
        return check_threshold(strategy)

    def validate_resource_qos(
        self, strategy: ResourceQOSStrategy
    ) -> List[str]:
        return check_resource_qos(strategy)
