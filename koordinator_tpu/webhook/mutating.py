"""Pod mutating admission: ClusterColocationProfile injection + extended
resource translation.

Reference: pkg/webhook/pod/mutating/cluster_colocation_profile.go —
profiles select pods by namespace + object label selectors (:71-78) and
inject labels/annotations/key-mappings/QoS/priority (:157-235); then
``mutatePodResourceSpec`` (:238-263) translates native cpu/memory
requests+limits into the priority class's extended resources (batch-*/
mid-*) via the ResourceNameMap, skipping None/Prod pods.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from koordinator_tpu.apis.extension import (
    PRIORITY_BANDS,
    PriorityClass,
    QoSClass,
    ResourceName,
    priority_class_of,
)
from koordinator_tpu.apis.types import PodSpec, selector_matches
from koordinator_tpu.state.cluster import translate_resource_by_priority


@dataclasses.dataclass
class ClusterColocationProfile:
    """A ClusterColocationProfile CR (apis/config/v1alpha1).

    Selectors are label subsets (the typed analogue of the reference's
    LabelSelectors); ``None`` means "match everything" like an absent
    selector.
    """

    name: str
    namespace_selector: Optional[Dict[str, str]] = None
    selector: Optional[Dict[str, str]] = None
    #: injected verbatim (profile.Spec.Labels / Annotations)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: keyNew -> keyOld copies (profile.Spec.LabelKeysMapping etc.)
    label_keys_mapping: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotation_keys_mapping: Dict[str, str] = dataclasses.field(default_factory=dict)
    qos_class: Optional[QoSClass] = None
    #: numeric k8s priority (profile.Spec.PriorityClassName resolved)
    priority: Optional[int] = None
    #: koordinator sub-priority within the band (KoordinatorPriority)
    koordinator_priority: Optional[int] = None

    def matches(self, pod: PodSpec, namespace_labels: Dict[str, str]) -> bool:
        if self.namespace_selector is not None and not selector_matches(
            self.namespace_selector, namespace_labels
        ):
            return False
        if self.selector is not None and not selector_matches(
            self.selector, pod.labels
        ):
            return False
        return True


#: node-selector value that matches no node label — the dict analogue of
#: the reference's unsatisfiable merged NodeSelectorRequirements (two In
#: requirements on one key with disjoint values)
UNSATISFIABLE = "\x00conflict"


class PodMutatingWebhook:
    """Applies every matching profile, the batch/mid resource rewrite,
    then multi-quota-tree affinity injection — the ingress every pod
    passes before reaching the scheduler."""

    def __init__(self, profiles: Optional[List[ClusterColocationProfile]] = None):
        self.profiles: Dict[str, ClusterColocationProfile] = {
            p.name: p for p in (profiles or [])
        }
        #: namespace -> labels (the reference reads Namespace objects)
        self.namespace_labels: Dict[str, Dict[str, str]] = {}
        #: optional right-sizer: pod -> recommended requests (the
        #: analysis.koordinator.sh consumption point; set by
        #: manager.recommendation.wire_recommendation)
        self.recommendation_for = None
        #: quota name -> QuotaSpec and quota-profile registries for the
        #: multi-quota-tree affinity mutator
        #: (multi_quota_tree_affinity.go:37-113)
        self.quota_specs: Dict[str, object] = {}
        self.quota_profiles: Dict[str, object] = {}

    def update_profile(self, profile: ClusterColocationProfile) -> None:
        self.profiles[profile.name] = profile

    # -- quota-tree registries (bus-fed) ------------------------------------

    def update_quota(self, spec) -> None:
        self.quota_specs[spec.name] = spec

    def remove_quota(self, name: str) -> None:
        self.quota_specs.pop(name, None)

    def update_quota_profile(self, profile) -> None:
        self.quota_profiles[profile.name] = profile

    def remove_quota_profile(self, name: str) -> None:
        self.quota_profiles.pop(name, None)

    def remove_profile(self, name: str) -> None:
        self.profiles.pop(name, None)

    def set_namespace_labels(self, namespace: str, labels: Dict[str, str]) -> None:
        self.namespace_labels[namespace] = dict(labels)

    # -- admission ----------------------------------------------------------

    def mutate(self, pod: PodSpec) -> PodSpec:
        """Mutate ``pod`` in place (and return it): profile injection in
        profile-name order, then extended-resource translation — which,
        like the reference (:66-69), only runs when at least one profile
        matched; unmanaged pods pass through untouched."""
        ns_labels = self.namespace_labels.get(pod.namespace, {})
        self._apply_recommendation(pod)
        matched = False
        for name in sorted(self.profiles):
            profile = self.profiles[name]
            if profile.matches(pod, ns_labels):
                self._apply_profile(pod, profile)
                matched = True
        if matched:
            self._mutate_resource_spec(pod)
        self._apply_tree_affinity(pod)
        return pod

    def _apply_tree_affinity(self, pod: PodSpec) -> None:
        """Multi-quota-tree node affinity (reference:
        pkg/webhook/pod/mutating/multi_quota_tree_affinity.go:37-113):
        when the pod's ElasticQuota belongs to a quota tree whose
        profile carries a node selector, inject that selector as
        REQUIRED node affinity, so tree pods stay on tree nodes even
        when other nodes score higher. The reference appends In
        requirements to every existing term (AND); in the dict selector
        model that is a key-wise merge, with a conflicting value
        resolving to an unsatisfiable sentinel — exactly as conflicting
        required In terms match no node."""
        quota_name = pod.quota or pod.namespace
        spec = self.quota_specs.get(quota_name)
        if spec is None:
            return
        tree_id = getattr(spec, "tree_id", "")
        if not tree_id:
            return
        selector = None
        for name in sorted(self.quota_profiles):
            profile = self.quota_profiles[name]
            if profile.effective_tree_id() == tree_id:
                selector = profile.node_selector
                break
        if not selector:
            return
        if pod.node_selector is None:
            pod.node_selector = dict(selector)
            return
        for key, value in selector.items():
            mine = pod.node_selector.get(key)
            if mine is not None and mine != value:
                pod.node_selector[key] = UNSATISFIABLE
            else:
                pod.node_selector[key] = value

    def _apply_recommendation(self, pod: PodSpec) -> None:
        """Right-size native requests from a covering Recommendation
        (before profile translation so batch/mid rewrites see the sized
        values). Limits only ever grow to keep limit >= request."""
        if self.recommendation_for is None:
            return
        recommended = self.recommendation_for(pod)
        if not recommended:
            return
        for res, value in recommended.items():
            if res not in pod.requests:
                continue  # only size resources the pod actually requests
            pod.requests[res] = int(value)
            if res in pod.limits and pod.limits[res] < pod.requests[res]:
                pod.limits[res] = pod.requests[res]

    def _apply_profile(self, pod: PodSpec, profile: ClusterColocationProfile) -> None:
        pod.labels.update(profile.labels)
        pod.annotations.update(profile.annotations)
        for key_new, key_old in profile.label_keys_mapping.items():
            if key_old in pod.labels:
                pod.labels[key_new] = pod.labels[key_old]
        for key_new, key_old in profile.annotation_keys_mapping.items():
            if key_old in pod.annotations:
                pod.annotations[key_new] = pod.annotations[key_old]
        if profile.qos_class is not None:
            pod.qos = profile.qos_class
        if profile.priority is not None:
            pod.priority = profile.priority
            pod.priority_class = priority_class_of(value=profile.priority)
        if profile.koordinator_priority is not None:
            pod.sub_priority = profile.koordinator_priority

    def _mutate_resource_spec(self, pod: PodSpec) -> None:
        """Translate native cpu/memory to the priority class's extended
        resources (mutatePodResourceSpec :238; replaceAndEraseResource).

        None/Prod pods keep native resources. BE/batch pods end up
        requesting batch-cpu/batch-memory — what the koord-manager
        overcommit calculator publishes on nodes.
        """
        priority_class = pod.priority_class or priority_class_of(
            value=pod.priority
        )
        if priority_class in (PriorityClass.NONE, PriorityClass.PROD):
            return
        for res in (pod.requests, pod.limits):
            for native in (ResourceName.CPU, ResourceName.MEMORY):
                extended = translate_resource_by_priority(native, priority_class)
                if extended == native:
                    continue
                if native in res:
                    res[extended] = res.pop(native)
        # restrictResourceRequestAndLimit: limit-only extended resources
        # gain a matching request
        for native in (ResourceName.CPU, ResourceName.MEMORY):
            extended = translate_resource_by_priority(native, priority_class)
            if extended == native:
                continue
            if extended in pod.limits and extended not in pod.requests:
                pod.requests[extended] = pod.limits[extended]
