"""Node admission: resource-amplification mutation + validation.

Reference: pkg/webhook/node/ — the mutating handler's
resourceamplification plugin (resource_amplification.go) intercepts node
UPDATEs: when the kubelet changed raw cpu/memory allocatable on a node
carrying an amplification-ratio annotation, it re-records the raw
capacity annotation and amplifies the visible allocatable, so the
scheduler keeps seeing normalized numbers; the validating handler guards
the annotation protocol itself.
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional

from koordinator_tpu.apis.extension import (
    ANNOTATION_NODE_RAW_ALLOCATABLE,
    ANNOTATION_RESOURCE_AMPLIFICATION_RATIO,
    ResourceName,
)
from koordinator_tpu.apis.types import NodeSpec

#: only cpu/memory amplify (resource_amplification.go supportedResources)
SUPPORTED = (ResourceName.CPU, ResourceName.MEMORY)


def parse_ratios(node: NodeSpec) -> Optional[dict]:
    raw = node.annotations.get(ANNOTATION_RESOURCE_AMPLIFICATION_RATIO)
    if not raw:
        return None
    ratios = json.loads(raw)
    if not isinstance(ratios, dict):
        raise ValueError("amplification ratio annotation must be a "
                         "JSON object of resource -> ratio")
    return {str(k): float(v) for k, v in ratios.items()}


class NodeMutatingWebhook:
    """Amplification admit (resource_amplification.go Admit)."""

    def mutate(self, node: NodeSpec,
               old_node: Optional[NodeSpec] = None) -> NodeSpec:
        """CREATE passes through (reference: Create -> nil); on UPDATE
        with a ratio annotation, a raw cpu/memory allocatable change is
        re-amplified and the raw values recorded."""
        if old_node is None:
            return node
        try:
            ratios = parse_ratios(node)
        except (ValueError, TypeError):
            return node  # validation rejects; never half-mutate
        if not ratios:
            return node
        # an UPDATE echoing the current (amplified) allocatable back is a
        # no-op — re-recording it as "raw" would COMPOUND the ratio on
        # every label patch. Only a value differing from the visible
        # allocatable is a fresh kubelet raw report.
        if all(
            node.allocatable.get(r) == old_node.allocatable.get(r)
            for r in SUPPORTED
        ):
            return node
        # the incoming allocatable is the kubelet's RAW report: record
        # it, then amplify the supported resources
        raw = dict(node.allocatable)
        node.raw_allocatable = raw
        node.annotations[ANNOTATION_NODE_RAW_ALLOCATABLE] = json.dumps(
            {str(int(r)): raw[r] for r in SUPPORTED if r in raw}
        )
        for r in SUPPORTED:
            ratio = ratios.get(str(int(r)), ratios.get(r.name.lower()))
            if ratio and r in raw:
                node.allocatable[r] = int(raw[r] * ratio)
        return node


class NodeValidatingWebhook:
    """Annotation-protocol guard (pkg/webhook/node/validating scope)."""

    def validate(self, node: NodeSpec,
                 old_node: Optional[NodeSpec] = None) -> List[str]:
        violations: List[str] = []
        try:
            ratios = parse_ratios(node)
        except (ValueError, TypeError) as e:
            return [f"malformed amplification ratio annotation: {e}"]
        if ratios:
            for key, ratio in ratios.items():
                if ratio < 1.0:
                    violations.append(
                        f"amplification ratio for {key} must be >= 1.0, "
                        f"got {ratio}"
                    )
        return violations
