"""Node admission: resource-amplification mutation + validation.

Reference: pkg/webhook/node/ — the mutating handler's
resourceamplification plugin (resource_amplification.go) intercepts node
UPDATEs: when the kubelet changed raw cpu/memory allocatable on a node
carrying an amplification-ratio annotation, it re-records the raw
capacity annotation and amplifies the visible allocatable, so the
scheduler keeps seeing normalized numbers; the validating handler guards
the annotation protocol itself.
"""

from __future__ import annotations

import json
import math
from typing import List, Optional

from koordinator_tpu.apis.extension import (
    ANNOTATION_NODE_RAW_ALLOCATABLE,
    ANNOTATION_RESOURCE_AMPLIFICATION_RATIO,
    ResourceName,
)
from koordinator_tpu.apis.types import NodeSpec

#: only cpu/memory amplify (resource_amplification.go supportedResources)
SUPPORTED = (ResourceName.CPU, ResourceName.MEMORY)


def parse_ratios(node: NodeSpec) -> Optional[dict]:
    raw = node.annotations.get(ANNOTATION_RESOURCE_AMPLIFICATION_RATIO)
    if not raw:
        return None
    ratios = json.loads(raw)
    if not isinstance(ratios, dict):
        raise ValueError("amplification ratio annotation must be a "
                         "JSON object of resource -> ratio")
    return {str(k): float(v) for k, v in ratios.items()}


def stored_raw_allocatable(node: NodeSpec) -> Optional[dict]:
    """The recorded raw capacity: the typed field when present, else
    parsed back from the annotation — raw state must survive
    serialization (the reference reads the annotation, never memory)."""
    if node.raw_allocatable is not None:
        return dict(node.raw_allocatable)
    text = node.annotations.get(ANNOTATION_NODE_RAW_ALLOCATABLE)
    if not text:
        return None
    try:
        parsed = json.loads(text)
    except ValueError:
        return None
    if not isinstance(parsed, dict):
        return None
    out = {}
    for key, value in parsed.items():
        for r in SUPPORTED:
            if key in (r.name.lower(), str(int(r))):
                try:
                    out[r] = int(value)
                except (ValueError, TypeError):
                    # corrupt annotation: treat as never-recorded — a
                    # bad value must not crash admission
                    return None
    return out or None


class NodeMutatingWebhook:
    """Amplification admit (resource_amplification.go Admit)."""

    def mutate(self, node: NodeSpec,
               old_node: Optional[NodeSpec] = None) -> NodeSpec:
        """CREATE passes through (reference: Create -> nil); on UPDATE
        with a ratio annotation, a raw cpu/memory allocatable change is
        re-amplified and the raw values recorded."""
        if old_node is None:
            return node
        try:
            ratios = parse_ratios(node)
        except (ValueError, TypeError):
            return node  # validation rejects; never half-mutate
        if not ratios:
            # amplification disabled: drop the stale raw record
            # (reference handleUpdate deletes the annotation here)
            node.annotations.pop(ANNOTATION_NODE_RAW_ALLOCATABLE, None)
            node.raw_allocatable = None
            return node
        # reference semantics: record raw when it was never recorded OR
        # the kubelet changed the supported resources; otherwise
        # re-amplify from the STORED raw — an echoed amplified value (or
        # a ratio change alone) must never compound
        changed = any(
            node.allocatable.get(r) != old_node.allocatable.get(r)
            for r in SUPPORTED
        )
        stored = stored_raw_allocatable(old_node)
        if changed or stored is None:
            raw = dict(node.allocatable)
        else:
            raw = stored
        node.raw_allocatable = raw
        # one shared encoding with the manager's cpu-normalization
        # plugin: lowercase resource names
        node.annotations[ANNOTATION_NODE_RAW_ALLOCATABLE] = json.dumps(
            {r.name.lower(): raw[r] for r in SUPPORTED if r in raw}
        )
        for r in SUPPORTED:
            ratio = ratios.get(str(int(r)), ratios.get(r.name.lower()))
            if ratio and math.isfinite(ratio) and r in raw:
                node.allocatable[r] = int(raw[r] * ratio)
        return node


class NodeValidatingWebhook:
    """Annotation-protocol guard (pkg/webhook/node/validating scope)."""

    def validate(self, node: NodeSpec,
                 old_node: Optional[NodeSpec] = None) -> List[str]:
        violations: List[str] = []
        try:
            ratios = parse_ratios(node)
        except (ValueError, TypeError) as e:
            return [f"malformed amplification ratio annotation: {e}"]
        if ratios:
            for key, ratio in ratios.items():
                # the explicit range also rejects NaN (all comparisons
                # False) and infinity; the 100x cap matches the
                # normalization guard protecting the int32 capacity
                # columns (manager/noderesource._MAX_NORMALIZATION_RATIO)
                if not 1.0 <= ratio <= 100.0:
                    violations.append(
                        f"amplification ratio for {key} must be in "
                        f"[1.0, 100.0], got {ratio}"
                    )
        return violations
