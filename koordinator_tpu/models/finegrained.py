"""Host-side fine-grained (NUMA cpuset + DeviceShare) integration for the
batched solver: the propose → validate → refine loop.

The reference's fine-grained allocators are inherently sequential greedy
algorithms (cpu_accumulator.go takeCPUs topology sort, device_allocator.go
jointAllocate); SURVEY.md §7 prescribes keeping them host-side and feeding
the batched solver per-pod×node feasibility/score rows. This module:

- detects *special* pods (cpuset-requesting LSE/LSR, NUMA-policy-affected,
  device-requesting) whose placement needs the host allocators;
- computes their ``Extras`` rows (mask = hint-merge + trial-allocate
  feasibility, score = DeviceShare score; the NUMA score itself is
  computed in-scan from aggregated inventories — ops/binpack.py
  ``numa_node_score``);
- replays the solver's assignment order against the real managers
  (validate): at each special pod's turn the rows are recomputed against
  the now-partially-applied state — if they differ from what the solver
  used, the batch is re-solved with the refreshed rows. On convergence
  the scan's choices are exactly the choices the sequential incremental
  path would have made.

Termination: the score-consistent phase is capped; after that only
feasibility is enforced (each re-solve permanently masks at least one
(pod, node) pair, so the loop is finite).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from typing import TYPE_CHECKING

from koordinator_tpu.apis.extension import NUM_RESOURCES
from koordinator_tpu.apis.types import ClusterSnapshot, NodeSpec, PodSpec
from koordinator_tpu.numa.hints import NUMATopologyPolicy

if TYPE_CHECKING:  # pragma: no cover
    from koordinator_tpu.scheduler.framework import CycleState


def _cycle_state():
    # imported lazily: scheduler <-> models would otherwise be a cycle
    from koordinator_tpu.scheduler.framework import CycleState

    return CycleState()


class FineGrained:
    """Bridges the batched solver and the host NUMA/device allocators.

    Wraps the *same* plugin instances the incremental chain uses, so both
    paths share one allocation state (reference: plugins hold the
    ResourceManager / nodeDeviceCache singletons).
    """

    def __init__(self, numa_plugin=None, device_plugin=None,
                 ports_plugin=None):
        self.numa_plugin = numa_plugin
        self.device_plugin = device_plugin
        self.ports_plugin = ports_plugin

    # -- topology lowering --------------------------------------------------

    def has_topology(self, node_names: List[str]) -> bool:
        if self.numa_plugin is None:
            return False
        mgr = self.numa_plugin.manager
        return any(
            mgr.get_topology(name).numa_node_resources for name in node_names
        )

    def numa_arrays(
        self, node_names: List[str]
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(cap [N,R], free [N,R], node_policy [N]) aggregated per node from
        the ResourceManager (reference: topology_options.go inventories)."""
        n = len(node_names)
        cap = np.zeros((n, NUM_RESOURCES), np.int32)
        free = np.zeros((n, NUM_RESOURCES), np.int32)
        policy = np.zeros(n, bool)
        mgr = self.numa_plugin.manager
        for i, name in enumerate(node_names):
            opts = mgr.get_topology(name)
            if not opts.numa_node_resources:
                continue
            policy[i] = opts.policy != NUMATopologyPolicy.NONE
            for res in opts.numa_node_resources.values():
                for r, v in res.items():
                    cap[i, int(r)] += v
            total_available, _ = mgr.available_numa_resources(name)
            for res in total_available.values():
                for r, v in res.items():
                    free[i, int(r)] += v
        return cap, free, policy

    def any_node_policy(self, node_names: List[str]) -> bool:
        if self.numa_plugin is None:
            return False
        mgr = self.numa_plugin.manager
        return any(
            mgr.get_topology(name).policy != NUMATopologyPolicy.NONE
            for name in node_names
        )

    # -- special-pod detection ----------------------------------------------

    def pod_flags(
        self, pod: PodSpec, node_policy_present: bool
    ) -> Tuple[bool, bool]:
        """(is_special, has_pod_numa_policy) in one annotation parse.

        *special* = needs host rows: cpuset-requesting pods, pods with
        their own NUMA policy, pods with requests on clusters where some
        node declares a policy (hint-merge gating), and pods with managed
        device requests."""
        special = False
        if self.ports_plugin is not None and getattr(pod, "host_ports", None):
            # host-port pods need the validate loop: batch-internal
            # conflicts are only visible through the plugin's holds
            special = True
        if self.device_plugin is not None and pod.device_requests:
            from koordinator_tpu.scheduler.plugins.deviceshare import (
                _PreFilterState as DevState,
            )

            try:
                special = special or not DevState(pod).skip
            except Exception:
                special = True  # malformed device spec: row computation rejects
        pod_policy = False
        if self.numa_plugin is not None and pod.requests:
            from koordinator_tpu.scheduler.plugins.nodenumaresource import (
                _PreFilterState as NumaState,
            )

            try:
                pf = NumaState(pod)
            except Exception:
                return True, False
            pod_policy = pf.pod_numa_policy != NUMATopologyPolicy.NONE
            special = (
                special
                or pf.request_cpu_bind
                or pod_policy
                or node_policy_present
            )
        return special, pod_policy

    def is_special(self, pod: PodSpec, node_policy_present: bool) -> bool:
        return self.pod_flags(pod, node_policy_present)[0]

    # -- rows: per-pod×node mask + extra score ------------------------------

    def _plugins(self):
        return [
            p
            for p in (self.numa_plugin, self.device_plugin, self.ports_plugin)
            if p is not None
        ]

    def rows(
        self, snapshot: ClusterSnapshot, pod: PodSpec, nodes: List[NodeSpec]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """(mask [N] bool, score [N] int32) against the managers' *current*
        state. Mask = NUMA filter (hint merge + trial allocate) ∧ device
        filter; score = device score only (NUMA score is in-scan)."""
        n = len(nodes)
        mask = np.ones(n, bool)
        score = np.zeros(n, np.int32)
        state = _cycle_state()
        for plugin in self._plugins():
            if not plugin.pre_filter(state, snapshot, pod).ok:
                return np.zeros(n, bool), score
        for i, node in enumerate(nodes):
            ok = True
            for plugin in self._plugins():
                if not plugin.filter(state, snapshot, pod, node).ok:
                    ok = False
                    break
            if not ok:
                mask[i] = False
                continue
            if self.device_plugin is not None:
                score[i] = self.device_plugin.score(state, snapshot, pod, node)
        return mask, score

    # -- validate / apply / rollback ----------------------------------------

    def apply(
        self, snapshot: ClusterSnapshot, pod: PodSpec, node: NodeSpec
    ) -> Tuple[bool, Optional[CycleState]]:
        """Reserve the pod's fine-grained allocation on the real managers
        (the incremental Reserve). Returns (ok, cycle_state); on failure
        everything is rolled back."""
        state = _cycle_state()
        plugins = self._plugins()
        for plugin in plugins:
            if not plugin.pre_filter(state, snapshot, pod).ok:
                return False, None
        for plugin in plugins:
            if not plugin.filter(state, snapshot, pod, node).ok:
                return False, None
        for i, plugin in enumerate(plugins):
            if not plugin.reserve(state, snapshot, pod, node).ok:
                for done in plugins[: i + 1]:
                    done.unreserve(state, snapshot, pod, node)
                return False, None
        return True, state

    def rollback(
        self, snapshot: ClusterSnapshot, pod: PodSpec, node: NodeSpec,
        state: "CycleState",
    ) -> None:
        for plugin in reversed(self._plugins()):
            plugin.unreserve(state, snapshot, pod, node)

    def pre_bind(
        self, snapshot: ClusterSnapshot, pod: PodSpec, node: NodeSpec,
        state: "CycleState",
    ) -> None:
        """Write the allocation annotations onto the pod (the incremental
        PreBind: resource-status cpuset + device allocation JSON)."""
        for plugin in self._plugins():
            plugin.pre_bind(state, snapshot, pod, node)
