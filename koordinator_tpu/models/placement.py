"""PlacementModel: the flagship batched placement solver.

Wraps the scan-based solver (ops/binpack.py) with host↔device staging and
typed in/out: takes a ``ClusterSnapshot``, returns pod→node assignments
with semantics identical to running the reference's Filter→Score→Reserve
cycle pod-by-pod (differentially tested against the oracle).

The node axis is shardable over a ``jax.sharding.Mesh`` (see
``koordinator_tpu.parallel``): scores are computed on node shards and the
argmax reduction rides ICI collectives inserted by GSPMD.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.apis.extension import NUM_RESOURCES, PriorityClass
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    GangMode,
    PodSpec,
    resources_to_vector,
)
from koordinator_tpu.models.finegrained import FineGrained
from koordinator_tpu.obs.device import DEVICE_OBS
from koordinator_tpu.obs.trace import TRACER
from koordinator_tpu.ops.binpack import (
    STAGED_NODE_FIELDS,
    Extras,
    SolveResult,
    NodeState,
    NumaAux,
    PodBatch,
    ResvArrays,
    ScoreParams,
    SolverConfig,
    bucket_row_update,
    scatter_node_rows_copied,
    scatter_node_rows_donated,
    schedule_batch,
    solve_batch,
)
from koordinator_tpu.ops.gang import GangState
from koordinator_tpu.ops.preempt import (
    PreemptorBatch,
    ResidentWorld,
    headroom_repack,
    preempt_scan,
    select_victims,
)
from koordinator_tpu.ops.quota import QuotaState
from koordinator_tpu.state.cluster import (
    DEFAULT_ESTIMATED_SCALING_FACTORS,
    DEFAULT_RESOURCE_WEIGHTS,
    DEFAULT_USAGE_THRESHOLDS,
    AggregatedArgs,
    NodeArrays,
    PendingPodArrays,
    ResidentPodArrays,
    _clip_i32,
    lower_nodes,
    lower_nodes_delta,
    lower_pending_pods,
    lower_resident_pods,
)
from koordinator_tpu.state.workingset import WORKING_SET


def measure_host_fallback_cells(
    config: SolverConfig = SolverConfig(),
    rounds: int = 5,
    ceiling: int = 1 << 18,
) -> int:
    """Startup micro-probe for the host/device routing cutoff (VERDICT
    r4 weak #6: the cutoff was a hand-set constant, brittle as shapes
    and link latency drift).

    Model: the host sequential path costs ~a per (node x pod) cell; a
    tiny device solve is dominated by a fixed dispatch+readback latency
    c (on a tunneled TPU, milliseconds). The crossover is c / a cells —
    solves smaller than that are faster on the host. Measured HERE, on
    this process's actual backend and link, in ~1 s. The device probe
    compiles at unroll=1 (latency c is dispatch+readback dominated, not
    compute, so the unroll doesn't move it — and the probe shouldn't
    pay a 32-unrolled compile). Memoized per backend.
    """
    import time

    from koordinator_tpu.oracle.vectorized import (
        oracle_args,
        schedule_vectorized,
    )
    from koordinator_tpu.testing import example_problem

    backend_key = (jax.devices()[0].platform, len(jax.devices()))
    cached = _MEASURED_CELLS.get(backend_key)
    if cached is not None:
        return cached

    def best_of(fn, n):
        times = []
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        return min(times)

    # host per-cell cost from the larger probe shape (amortizes the
    # per-pod python overhead the small shape over-weights)
    n, p = 64, 128
    state, pods, params = example_problem(n, p, seed=1234)
    args = oracle_args(state, pods, params)
    schedule_vectorized(*args)  # numpy warm
    host_best = best_of(lambda: schedule_vectorized(*args), rounds)
    per_cell = host_best / (n * p)

    probe_config = config._replace(unroll=1)
    solve = jax.jit(
        lambda s, p_, pr: schedule_batch(s, p_, pr, probe_config),
        static_argnums=(), donate_argnums=(),
    )
    run = lambda: np.asarray(solve(state, pods, params)[1])
    run()  # compile outside the timed rounds
    device_best = best_of(run, rounds)

    if per_cell <= 0:
        return 0
    cells = max(0, min(int(device_best / per_cell), ceiling))
    _MEASURED_CELLS[backend_key] = cells
    return cells


#: measured crossover per (platform, device count) — one probe per
#: process is plenty
_MEASURED_CELLS: Dict = {}


def _vec(mapping, dtype=np.int32) -> np.ndarray:
    out = np.zeros(NUM_RESOURCES, dtype=dtype)
    for k, v in mapping.items():
        out[int(k)] = v
    return out


class ScheduleResult(Dict[str, Optional[str]]):
    """Result of one batched schedule.

    Behaves as the ``{pod uid: node name | None}`` mapping of *committed*
    (bindable) placements. ``waiting`` lists placed-but-not-committed
    NonStrict gang members: they hold their node's resources at the Permit
    barrier and MUST NOT be bound yet (reference: waiting pods in the
    coscheduling Permit stage).
    """

    def __init__(self, assignments, waiting=None, fine_states=None,
                 resv_allocs=None, resv_committed=None):
        super().__init__(assignments)
        self.waiting: Dict[str, str] = dict(waiting or {})
        #: uid -> (node name, CycleState) for fine-grained (NUMA/device)
        #: allocations applied but not yet PreBind-annotated (waiting gang
        #: members); the scheduler annotates them when the barrier opens.
        self.fine_states: Dict[str, tuple] = dict(fine_states or {})
        #: uid -> (reservation name, delta vector) for *waiting* pods'
        #: reservation consumption — rolled back if the wait expires.
        self.resv_allocs: Dict[str, tuple] = dict(resv_allocs or {})
        #: uid -> (reservation name, delta vector) for COMMITTED pods'
        #: consumption this round — the scheduler keeps these
        #: rollback-able until the bind publishes (a deposed leader's
        #: FencingError abort must restore the credit).
        self.resv_committed: Dict[str, tuple] = dict(resv_committed or {})
        #: uid -> nominated node for pods that triggered preemption this
        #: round (victims evicted; the pod binds in a later round)
        self.nominations: Dict[str, str] = {}


class InFlightSchedule:
    """A dispatched-but-unmaterialized batched solve.

    Produced by :meth:`PlacementModel.schedule_async`: the device solve
    is in flight (jax dispatch is asynchronous), the staged generation
    it consumes is pinned against donation, and nothing has crossed
    back to host. :meth:`finalize` is the ONE read-back point — the
    serial loop calls it immediately (``schedule()``), the pipelined
    loop calls it publish-side (scheduler/pipeline.py) so staging for
    the next round overlaps this solve's device time."""

    __slots__ = (
        "model", "snapshot", "result", "node_names", "pod_uids",
        "pods_in_order", "node_by_name", "applied", "resv_specs",
        "n_real", "t_staged", "timings", "pinned", "_final",
    )

    def __init__(self, model, snapshot, result, node_names, pod_uids,
                 pods_in_order, node_by_name, applied, resv_specs,
                 n_real, t_staged, timings, pinned):
        self.model = model
        self.snapshot = snapshot
        self.result = result
        self.node_names = node_names
        self.pod_uids = pod_uids
        self.pods_in_order = pods_in_order
        self.node_by_name = node_by_name
        self.applied = applied
        self.resv_specs = resv_specs
        self.n_real = n_real
        self.t_staged = t_staged
        self.timings = timings
        self.pinned = pinned
        self._final: Optional["ScheduleResult"] = None

    def finalize(self) -> "ScheduleResult":
        """Materialize the solve and run the typed epilogue. Idempotent;
        blocks until the device compute lands. The np.asarray calls
        below are the pipeline's designated publish-side read-back."""
        if self._final is not None:
            return self._final
        model = self.model
        result = self.result
        n_real = self.n_real
        t_readback = time.perf_counter()
        # the annotate scope names this transfer in an active profiler
        # window with the same label the span below carries — host
        # trace and device profile line up in Perfetto (§17)
        with DEVICE_OBS.annotate("read_back"):
            assignments = np.asarray(result.assign)[:n_real]
            commit = np.asarray(result.commit)[:n_real]
            waiting = np.asarray(result.waiting)[:n_real]
            rejected = np.asarray(result.rejected)[:n_real]
        t_done = time.perf_counter()
        # solve wall: dispatch -> materialized (includes any overlap
        # window the pipelined loop spent elsewhere — by design, this
        # is the stage the pipeline hides)
        self.timings["solve_s"] = t_done - self.t_staged
        # retro spans from the timestamps already taken: the device
        # span covers dispatch->materialized (in a pipelined run it
        # overlaps the coordinator's next-round staging — that overlap
        # IS the pipeline, visible as crossing tracks in Perfetto); the
        # read-back span is the publish-side host transfer alone
        TRACER.emit("device_solve", cat="device", t0=self.t_staged,
                    t1=t_done)
        TRACER.emit("read_back", cat="device", t0=t_readback, t1=t_done)

        # fine-grained epilogue: release gang-rejected holds, annotate
        # committed pods (PreBind), keep waiting pods' holds for the
        # scheduler to annotate when the Permit barrier opens
        fine = model.fine
        fine_states: Dict[str, tuple] = {}
        for i, node_name, cstate in self.applied:
            pod = self.pods_in_order[i]
            node = self.node_by_name[node_name]
            if rejected[i]:
                fine.rollback(self.snapshot, pod, node, cstate)
            elif commit[i]:
                fine.pre_bind(self.snapshot, pod, node, cstate)
            else:  # waiting at the Permit barrier
                fine_states[pod.uid] = (node_name, cstate)

        # reservation consumption bookkeeping (the incremental Reserve's
        # mutation of the matched ReservationSpec)
        resv_allocs: Dict[str, tuple] = {}
        resv_committed: Dict[str, tuple] = {}
        if self.resv_specs is not None:
            resv_allocs, resv_committed = model._apply_reservations(
                self.snapshot, self.resv_specs, result,
                self.pods_in_order, commit, waiting,
            )

        out = ScheduleResult(
            assignments={
                uid: (self.node_names[a] if c else None)
                for uid, a, c in zip(self.pod_uids, assignments, commit)
            },
            waiting={
                uid: self.node_names[a]
                for uid, a, w in zip(self.pod_uids, assignments, waiting)
                if w
            },
            fine_states=fine_states,
            resv_allocs=resv_allocs,
            resv_committed=resv_committed,
        )
        if self.pinned is not None:
            model.staged_cache.unpin(self.pinned)
        self._final = out
        return out


class NodeStagingDelta:
    """How the staged node state last changed — consumed by the sidecar
    backend (service/client.RemoteSolver) to ship only the dirty rows
    over the wire instead of the world.

    ``base_epoch is None`` means the staged state was rebuilt from
    scratch (no delta exists); otherwise ``idx``/``rows`` carry the row
    update that takes a peer holding ``base_epoch`` to ``epoch``.
    """

    __slots__ = ("epoch", "base_epoch", "idx", "rows")

    def __init__(self, epoch: int, base_epoch: Optional[int] = None,
                 idx: Optional[np.ndarray] = None,
                 rows: Optional[Dict[str, np.ndarray]] = None):
        self.epoch = epoch
        self.base_epoch = base_epoch
        self.idx = idx
        self.rows = rows


def merge_staging_deltas(prev: Optional[NodeStagingDelta],
                         new: NodeStagingDelta) -> NodeStagingDelta:
    """Fold ``new`` onto an unshipped ``prev`` so the wire delta covers
    every ensure() since the sidecar last advanced its base.

    The pipelined tick path runs ensure() more than once per solve
    (prestage while the previous solve is in flight, catch-up at round
    start); shipping only the LAST ensure's delta would hand the
    sidecar a base it never held and force a full re-establish every
    tick. Rows are unioned with later writes winning; a full restage
    (``base_epoch is None``) poisons the chain and re-establishes."""
    if new.base_epoch is None or prev is None:
        return new
    if prev.base_epoch is None:
        # a pending full restage is still unshipped: everything after
        # it is already part of the from-scratch state
        return NodeStagingDelta(new.epoch)
    if new.idx is None or new.idx.size == 0:
        return NodeStagingDelta(new.epoch, prev.base_epoch,
                                prev.idx, prev.rows)
    if prev.idx is None or prev.idx.size == 0:
        return NodeStagingDelta(new.epoch, prev.base_epoch,
                                new.idx, new.rows)
    combined = np.concatenate([prev.idx, new.idx])
    # last occurrence of each index wins (the later ensure re-lowered
    # that row from newer truth)
    _, first_in_rev = np.unique(combined[::-1], return_index=True)
    sel = np.sort(combined.size - 1 - first_in_rev)
    rows = {
        f: np.concatenate([prev.rows[f], new.rows[f]])[sel]
        for f in prev.rows
    }
    return NodeStagingDelta(
        new.epoch, prev.base_epoch, combined[sel], rows
    )


def _staged_estimate(arrays: Optional[NodeArrays]) -> int:
    """Bytes about to land on device for a staging of ``arrays`` — the
    working-set admission estimate (host metadata sum over the staged
    columns; sharding pads a little past this, which the post-stage
    repricing via ``device_bytes()`` trues up)."""
    if arrays is None:
        return 0
    return int(sum(
        getattr(arrays, f).nbytes for f in STAGED_NODE_FIELDS
        if getattr(arrays, f, None) is not None
    ))


class StagedStateCache:
    """Device-resident cluster state reused across ``schedule()`` calls.

    A steady-state scheduling tick changes a handful of node rows
    (metric reports, binds, reservation churn), but the naive path pays
    O(N) host lowering plus a full host→device re-upload every solve.
    This cache keeps BOTH halves alive between solves: the host
    :class:`NodeArrays` is patched in place by
    :func:`state.cluster.lower_nodes_delta` (only the rows the
    snapshot's :class:`ClusterDeltaTracker` marked), and the staged
    device :class:`NodeState` is updated by a jitted
    ``.at[idx].set`` scatter with ``donate_argnums`` double-buffering —
    the [N,R] world never crosses the host↔device boundary again.

    Full-restage fallbacks (each keeps results bit-identical, only
    slower): no tracker on the snapshot, a different tracker than last
    solve, a node set/order change (``mark_structure``), a lowering
    whose NodeArrays predate delta support, or a model with a
    fine-grained manager (NUMA inventories ride a separate staging
    path). The dirty-row count is bucketed to powers of two (padding
    repeats the last row — same value, same result) so drifting dirty
    counts reuse one compiled scatter per bucket.

    Sharded staging (docs/DESIGN.md §19): with a node-sharded model
    (``PlacementModel(sharding=NamedSharding(mesh, P("nodes")))``), the
    staged world lives as a live ``NamedSharding``'d generation —
    ``stage_nodes`` pads the node axis to the per-shard bucket and
    splits it over the mesh ONCE; every later delta tick runs the SAME
    scatter program on the sharded generation, where GSPMD masks each
    shard's write to the rows it owns — the dirty rows land in their
    owning shard and the [N,R] world is never re-split. The host half,
    the epoch/wire-delta bookkeeping, the dirty-row buckets, and the
    pin double-buffer rules are all shard-agnostic and apply
    unchanged. One deliberate difference: the sharded scatter always
    takes the NON-donating twin — a persistent-cache replay of the
    donated multi-device scatter mis-aliases same-shaped outputs on
    this jax build (see the inline note in :meth:`ensure`); the
    single-device fast path keeps donation.
    """

    def __init__(self, model: "PlacementModel"):
        self.model = model
        self.arrays: Optional[NodeArrays] = None   # host, patched in place
        self.state: Optional[NodeState] = None     # staged, pre-solve
        self.tracker = None
        self.seen_epoch = -1
        #: staged-state version — the sidecar delta protocol's sync point
        self.epoch = 0
        self.last_delta: Optional[NodeStagingDelta] = None
        self.last_path: Optional[str] = None       # "full" | "delta"
        #: the staged generation a dispatched-but-unretired solve holds
        #: (pipelined tick path): while set, ensure()'s device scatter
        #: writes a FRESH generation (non-donating) instead of donating
        #: the pinned buffers out from under the in-flight computation
        self._pinned: Optional[NodeState] = None
        #: accumulated unshipped wire delta (merge of every delta-path
        #: ensure since take_wire_delta) — the pipelined loop runs
        #: ensure() more than once per solve, and the sidecar needs the
        #: whole base→current chain, not just the last link
        self._wire_delta: Optional[NodeStagingDelta] = None
        #: snapshot.now of the last ensure() — the time base the cached
        #: arrays' metric_fresh column was computed with. The runtime
        #: auditor's parity probe re-lowers sampled rows against THIS
        #: now (not wall time), so a freshness flip between solves can
        #: never read as staging drift.
        self.last_now: Optional[float] = None
        # schedule() is NOT reentrant — drive one model from one
        # scheduler loop. What this lock guarantees is narrower and
        # unconditional: ensure()'s compound mutation (in-place host
        # patch, donated device scatter, epoch/delta bookkeeping) is
        # atomic, and the (epoch, delta) pair it returns is captured
        # under the same hold — so a racing caller sees a consistent
        # cache and a loud donation error, never silently corrupted
        # rows or a mispaired sidecar delta. Every mutable attribute
        # above is mapped to this lock in graftcheck's lock-discipline
        # registry.
        self._lock = threading.Lock()
        #: the HBM working-set registration (docs/DESIGN.md §26): the
        #: in-process staged cluster rides the system lane — it demotes
        #: LAST, after every tenant world, mirroring the shed order
        self._ws_key = WORKING_SET.register_auto(
            "staged", self, tenant="_model", lane="system"
        )

    def ensure(self, snapshot: ClusterSnapshot, want_device: bool = True
               ) -> Tuple[NodeArrays, Optional[NodeState],
                          Dict[str, float],
                          Tuple[int, Optional[NodeStagingDelta]]]:
        """(host arrays, staged state, {"lower_s", "stage_s"},
        (epoch, delta)) for this snapshot — incrementally when the
        snapshot's tracker allows. The trailing (epoch, delta) pair is
        the sidecar wire protocol's sync point, captured under the same
        lock hold that produced it: reading it from the cache after
        ensure() returns could pair this call's epoch with a racing
        call's rows.

        ``want_device=False`` keeps only the host half fresh (the delta
        bookkeeping and sidecar rows still advance): callers that will
        restage anyway — a NodeState carrying NUMA inventories — skip
        the device scatter entirely; the device half is re-established
        from the current host arrays the next time it is wanted."""
        out = self._ensure(snapshot, want_device)
        # residency touch AFTER the cache lock released: the manager
        # reprices via device_bytes() (which takes the lock) and may
        # demote OTHER residents over the line; this cache is the
        # protected key and a mid-solve victim is skipped by the
        # non-blocking demote hooks below
        WORKING_SET.touch(self._ws_key)
        return out

    def _ensure(self, snapshot: ClusterSnapshot, want_device: bool
                ) -> Tuple[NodeArrays, Optional[NodeState],
                           Dict[str, float],
                           Tuple[int, Optional[NodeStagingDelta]]]:
        with self._lock:
            tracker = getattr(snapshot, "delta_tracker", None)
            # sync point: the epoch captured when the snapshot was TAKEN
            # (under the producer's lock) when available — a mark racing
            # in after that carries a later epoch and re-lowers next
            # tick. The live epoch is only a fallback for
            # single-threaded producers that mutate their snapshot in
            # place.
            epoch_now = getattr(snapshot, "delta_epoch", None)
            if epoch_now is None and tracker is not None:
                epoch_now = tracker.epoch
            t0 = time.perf_counter()
            if (
                tracker is not None
                and tracker is self.tracker
                and self.arrays is not None
                and tracker.structure_epoch <= self.seen_epoch
            ):
                dirty = tracker.dirty_since(self.seen_epoch)
                idx = lower_nodes_delta(
                    snapshot, self.arrays, dirty,
                    **self.model.lowering_kwargs(),
                )
                if idx is not None:
                    self.seen_epoch = epoch_now
                    self.last_now = snapshot.now
                    t1 = time.perf_counter()
                    base = self.epoch
                    if idx.size:
                        rows = {
                            f: np.ascontiguousarray(
                                getattr(self.arrays, f)[idx]
                            )
                            for f in STAGED_NODE_FIELDS
                        }
                        if want_device and self.state is not None:
                            sidx, srows = bucket_row_update(idx, rows)
                            if (self.state is self._pinned
                                    or self.model._node_shards > 1):
                                # non-donating twin, two reasons: (a)
                                # double buffer — an in-flight solve
                                # holds this generation, so write the
                                # next one beside it instead of
                                # donating its buffers out from under
                                # the dispatch; (b) a SHARDED world
                                # never donates — on this jax (0.4.x
                                # CPU) a persistent-compilation-cache
                                # replay of the donated MULTI-DEVICE
                                # scatter mis-applies the input→output
                                # alias map and hands back same-shaped
                                # columns swapped (used_req↔prod_usage,
                                # the bool masks); reproduced in ISSUE
                                # 10, one generation-sized copy per
                                # tick is the safe price until a fixed
                                # jax lets sharded donation back in.
                                cur = self.state
                                self.state = WORKING_SET.run_staged(
                                    self._ws_key, "scatter",
                                    lambda: scatter_node_rows_copied(
                                        cur, jnp.asarray(sidx), srows,
                                    ),
                                )
                            else:
                                # single-device, unpinned: the PR 6
                                # donating fast path. NOTE on the retry
                                # contract: an INJECTED alloc failure
                                # raises before the callable runs, so
                                # its retry re-invokes a never-executed
                                # donation; a real mid-execution OOM on
                                # the donated path falls through to the
                                # typed-error boundary instead of
                                # retrying a consumed buffer.
                                cur = self.state
                                self.state = WORKING_SET.run_staged(
                                    self._ws_key, "scatter",
                                    lambda: scatter_node_rows_donated(
                                        cur, jnp.asarray(sidx), srows,
                                    ),
                                )
                            jax.block_until_ready(self.state)
                        else:
                            self.state = None  # device half stale
                        self.epoch += 1
                        self.last_delta = NodeStagingDelta(
                            self.epoch, base, idx, rows
                        )
                    else:
                        self.last_delta = NodeStagingDelta(
                            self.epoch, base, idx, {}
                        )
                    self._wire_delta = merge_staging_deltas(
                        self._wire_delta, self.last_delta
                    )
                    if want_device and self.state is None:
                        # re-establish the device half from the current
                        # host arrays (content unchanged — the sidecar
                        # epoch does not move). This is ALSO the
                        # host-rung restage path of the working-set
                        # ladder: a demoted world comes back through
                        # here, headroom admitted first.
                        host_arrays = self.arrays
                        self.state = WORKING_SET.run_staged(
                            self._ws_key, "stage",
                            lambda: self.model.stage_nodes(host_arrays),
                            estimate=_staged_estimate(host_arrays),
                        )
                        jax.block_until_ready(self.state)
                    self.last_path = "delta"
                    return self.arrays, self.state, {
                        "lower_s": t1 - t0,
                        "stage_s": time.perf_counter() - t1,
                    }, (self.epoch, self.last_delta)
            # full (re)lower + (re)stage — cold path and every fallback
            if epoch_now is None:
                epoch_now = -1
            arrays = lower_nodes(snapshot, **self.model.lowering_kwargs())
            t1 = time.perf_counter()
            state = None
            if want_device:
                # the cold-rung restage path: re-lowered from typed
                # truth above, staged under the admission contract here
                state = WORKING_SET.run_staged(
                    self._ws_key, "stage",
                    lambda: self.model.stage_nodes(arrays),
                    estimate=_staged_estimate(arrays),
                )
                jax.block_until_ready(state)
            self.arrays = arrays
            self.state = state
            self.tracker = tracker
            self.seen_epoch = epoch_now
            self.last_now = snapshot.now
            self.epoch += 1
            self.last_delta = NodeStagingDelta(self.epoch)
            self._wire_delta = self.last_delta  # re-establish: chain reset
            self.last_path = "full"
            return arrays, state, {
                "lower_s": t1 - t0,
                "stage_s": time.perf_counter() - t1,
            }, (self.epoch, self.last_delta)

    def invalidate(self) -> None:
        """Forget the staged world: the next ensure() takes the full
        relower+restage path regardless of tracker state. The epoch is
        deliberately NOT reset — it must stay monotone so a sidecar
        re-establishing its delta base after the flip-back can never
        confuse a pre-outage base with a post-outage one."""
        with self._lock:
            self.arrays = None
            self.state = None
            self.tracker = None
            self.seen_epoch = -1
            self.last_delta = None
            self.last_path = None
            self.last_now = None
            self._wire_delta = None

    def take_wire_delta(self) -> Optional[Tuple[int, NodeStagingDelta]]:
        """Pop the accumulated ``(epoch, delta)`` sync point covering
        every ensure() since the last take — what one solve ships to the
        sidecar. Taking is optimistic: if the ship fails, the sidecar's
        ``delta-base-mismatch`` recovery re-establishes a full base at
        the current epoch, which is exactly where the next accumulation
        starts."""
        with self._lock:
            delta = self._wire_delta
            self._wire_delta = None
            if delta is None:
                return None
            return (self.epoch, delta)

    def pin(self, state: Optional[NodeState]) -> None:
        """Mark ``state`` as held by a dispatched, not-yet-retired solve
        (the pipelined tick path). Until :meth:`unpin`, a delta ensure()
        scatters into a fresh generation instead of donating the pinned
        buffers — the double-buffered generations of docs/DESIGN.md §15.
        The serial loop pins and unpins within one schedule() call, so
        its steady-state scatter keeps the donating fast path."""
        with self._lock:
            self._pinned = state

    def unpin(self, state: Optional[NodeState]) -> None:
        """The solve holding ``state`` retired; donation is safe again
        (identity-checked so a stale unpin cannot release a newer pin)."""
        with self._lock:
            if self._pinned is state:
                self._pinned = None

    def demote_device(self) -> bool:
        """Working-set ladder rung 1 (device → host): drop the staged
        device generation, keep the host arrays, tracker, and epoch —
        the next ensure() re-establishes the device half from the kept
        host state through the EXISTING staging path, bit-identical,
        without moving the sidecar epoch. Non-blocking by contract: a
        cache mid-solve (lock held) or with a pinned in-flight
        generation refuses (returns False) rather than waiting — the
        manager skips busy victims instead of stalling a solve."""
        if not self._lock.acquire(blocking=False):
            return False
        try:
            if self.state is None or self._pinned is not None:
                return False
            self.state = None
            return True
        finally:
            self._lock.release()

    def demote_cold(self) -> bool:
        """Working-set ladder rung 2 (host → cold): drop the host half
        too — the next ensure() re-lowers from typed truth via
        ``lower_nodes`` (the full path, parity-registered helpers, so
        placements stay bit-identical). The epoch stays monotone, same
        as :meth:`invalidate`, so a sidecar can never confuse a
        pre-demotion base with a post-restage one."""
        if not self._lock.acquire(blocking=False):
            return False
        try:
            if self._pinned is not None:
                return False
            if self.arrays is None and self.state is None:
                return False
            self.arrays = None
            self.state = None
            self.tracker = None
            self.seen_epoch = -1
            self.last_delta = None
            self.last_path = None
            self.last_now = None
            self._wire_delta = None
            return True
        finally:
            self._lock.release()

    def device_bytes(self) -> int:
        """Metadata-summed bytes of the staged device generations this
        cache currently holds (current + a pinned in-flight one) — the
        observatory's per-owner live-buffer attribution. No sync:
        ``nbytes`` is shape metadata."""
        with self._lock:
            generations = [self.state]
            if self._pinned is not None and self._pinned is not self.state:
                generations.append(self._pinned)
        total = 0
        for gen in generations:
            if gen is None:
                continue
            total += sum(
                getattr(a, "nbytes", 0) for a in gen if a is not None
            )
        return total

    def audit_view(self):
        """A consistent view of the staged world for the runtime
        auditor's parity probe: ``(arrays, state, tracker, seen_epoch,
        last_now)`` captured under the cache lock — the probe then
        re-lowers sampled rows from typed truth and compares against
        exactly this staging generation (scheduler/auditor.py). The
        host arrays are patched in place between solves only under the
        same lock, so a sweep running between scheduling rounds sees a
        settled generation, never a half-applied delta."""
        with self._lock:
            return (
                self.arrays, self.state, self.tracker,
                self.seen_epoch, self.last_now,
            )


class PlacementModel:
    """Compiled batched placement over a (possibly sharded) node axis."""

    #: score-consistency refinement rounds before freezing extra scores
    #: (feasibility is still enforced afterwards, so the loop terminates)
    MAX_SCORE_ITERS = 8

    @staticmethod
    def pod_bucket(p: int) -> int:
        """Round the pod-batch length up to a shape bucket (quarter steps
        between powers of two, floor 64) so churn batches of nearby sizes
        reuse one compiled program instead of recompiling per queue
        length. Padding pods are hard-blocked, so results are identical.
        The step family is the shared :func:`parallel.mesh.
        pow2_quarter_bucket` — the same buckets the sharded node widths
        and the multi-tenant pool's base/lane staging use."""
        from koordinator_tpu.parallel.mesh import pow2_quarter_bucket

        return pow2_quarter_bucket(p, floor=64)

    @staticmethod
    def resv_bucket(v: int) -> int:
        """Shape bucket for the reservation axis (next power of two,
        floor 8): a cluster whose Available-reservation count drifts by
        ones would otherwise trace a fresh program per count. Padding
        rows are inert — match all-False, zero free — so no pod can ever
        match or consume them."""
        return max(8, 1 << (v - 1).bit_length())

    @staticmethod
    def victim_bucket(p: int) -> int:
        """Shape bucket for the resident-victim axis (next power of two,
        floor 8): per-node resident counts drift by ones every tick, so
        an unbucketed ``[N, P]`` world would retrace the preempt solve
        per count. Padding columns are ``valid=False`` — never
        candidates, never reprieved — so results are identical."""
        return max(8, 1 << (p - 1).bit_length())

    @staticmethod
    def preemptor_bucket(k: int) -> int:
        """Shape bucket for the scanned-preemptor axis (next power of
        two, floor 4). The scheduler round path stays at
        MAX_PREEMPTIONS_PER_ROUND (=32) preemptors; the storm bench
        scans bigger batches, so the bucket itself is unbounded —
        graftcheck bounds the axis image at MAX_PODS. Padding rows are
        ``active=False``: the scan step carries the world through
        unchanged."""
        return max(4, 1 << (k - 1).bit_length())

    def __init__(
        self,
        config: SolverConfig = SolverConfig(),
        resource_weights=None,
        usage_thresholds=None,
        prod_usage_thresholds=None,
        aggregated: Optional[AggregatedArgs] = None,
        scaling_factors=None,
        sharding: Optional[jax.sharding.Sharding] = None,
        fine: Optional[FineGrained] = None,
        pod_bucketing: bool = True,
        use_pallas: Optional[bool] = None,
        backend=None,
        host_fallback_cells: int = 0,
    ):
        self.config = config
        self.resource_weights = dict(resource_weights or DEFAULT_RESOURCE_WEIGHTS)
        self.scaling_factors = dict(
            scaling_factors or DEFAULT_ESTIMATED_SCALING_FACTORS
        )
        #: aggregated (percentile) LoadAware mode — when its filter side is
        #: enabled, the filter threshold SET is the aggregated one and the
        #: lowering substitutes the percentile usage (load_aware.go:157-186)
        self.aggregated = aggregated
        if aggregated is not None and aggregated.filter_enabled:
            filter_thresholds = aggregated.usage_thresholds
        else:
            filter_thresholds = usage_thresholds or DEFAULT_USAGE_THRESHOLDS
        #: dict forms retained so the incremental plugin chain can be
        #: configured identically (scheduler/scheduler.py wiring)
        self.usage_thresholds = dict(filter_thresholds)
        self.prod_usage_thresholds = dict(prod_usage_thresholds or {})
        self.params = ScoreParams(
            weights=jnp.asarray(_vec(self.resource_weights)),
            thresholds=jnp.asarray(_vec(filter_thresholds)),
            prod_thresholds=jnp.asarray(_vec(prod_usage_thresholds or {})),
        )
        self.sharding = sharding
        #: how many ways the configured sharding splits the node axis
        #: (1 = unsharded). >1 turns on sharded staging: the node axis
        #: is padded to a per-shard bucket before every device_put so a
        #: live NamedSharding'd world stays equal-width per shard, and
        #: the staging cache's dirty-row scatter then lands each row in
        #: its owning shard (docs/DESIGN.md §19).
        from koordinator_tpu.parallel.mesh import node_shard_count

        self._node_shards = node_shard_count(sharding)
        self.fine = fine
        self.pod_bucketing = pod_bucketing
        #: remote solve backend (service.client.RemoteSolver) — the
        #: ``--placement-backend=sidecar`` boundary. None = in-process.
        self.backend = backend
        #: route plain solves with pods*nodes <= this through the host
        #: sequential path (oracle/placement.py): at tiny shapes a single
        #: host<->device round trip costs more than the whole solve
        #: (BENCH r2: 100x20 device 1.1k pods/s vs host 2.4k). 0 = off
        #: (the default; cmd/build_scheduler enables it for production).
        self.host_fallback_cells = host_fallback_cells
        #: which path the last _dispatch_solve took (observability/tests)
        self.last_solver: Optional[str] = None
        #: use the VMEM-resident pallas kernel for eligible plain solves
        #: (single TPU device, no quota/gang/reservation/NUMA/extras;
        #: bit-identical — ops/pallas_binpack.py). None = auto-detect.
        if use_pallas is None:
            devices = jax.devices()
            use_pallas = (
                sharding is None
                and len(devices) == 1  # multi-chip goes through sharding
                and devices[0].platform == "tpu"
            )
        self.use_pallas = use_pallas
        # static per-model eligibility (params/config never change after
        # construction; checking per solve would sync the device)
        from koordinator_tpu.ops.pallas_binpack import pallas_supported

        self._pallas_eligible = pallas_supported(self.params, self.config)
        #: the DEVICE_OBS wrapper records compile count/wall/signature
        #: per solve variant (docs/DESIGN.md §17) — call-transparent,
        #: and graftcheck still treats the binding as a jit factory
        self._solve = DEVICE_OBS.jit("solve_batch", jax.jit(
            solve_batch, static_argnames=("config",), donate_argnums=()
        ))
        # AOT warm pool (docs/DESIGN.md §21): a promoted/restarted
        # control plane restores this binding's hot signatures from
        # disk instead of re-tracing + recompiling. Adoption is legal
        # only because the binding never donates (§19.2: donated
        # executables replayed from a store mis-alias their outputs);
        # graftcheck's donation rule pins that at every adopt site.
        from koordinator_tpu.service.warmpool import WARM_POOL

        WARM_POOL.adopt(self._solve, solve_batch, config_argpos=3)
        #: joint place+evict variants (ops/preempt.py): per-preemptor
        #: victim selection, the scanned storm solve, and the defrag
        #: planner. Same binding discipline as solve_batch — static
        #: config (position 0), never donate (warm-pool adoption
        #: legality), DEVICE_OBS-wrapped so the runtime sentinel and
        #: graftcheck's signature-space census see every signature.
        self._preempt = DEVICE_OBS.jit("preempt_solve", jax.jit(
            select_victims, static_argnames=("config",), donate_argnums=()
        ))
        WARM_POOL.adopt(self._preempt, select_victims, config_argpos=0)
        self._preempt_scan = DEVICE_OBS.jit("preempt_solve_scan", jax.jit(
            preempt_scan, static_argnames=("config",), donate_argnums=()
        ))
        WARM_POOL.adopt(self._preempt_scan, preempt_scan, config_argpos=0)
        self._defrag = DEVICE_OBS.jit("defrag_repack", jax.jit(
            headroom_repack, static_argnames=("config",), donate_argnums=()
        ))
        WARM_POOL.adopt(self._defrag, headroom_repack, config_argpos=0)
        #: device-resident staging reused across schedule() calls when
        #: the snapshot carries a ClusterDeltaTracker (steady-state
        #: ticks re-lower + re-upload only the dirty node rows)
        self.staged_cache = StagedStateCache(self)
        # live-buffer attribution: the observatory's snapshot reports
        # how much of the process's device memory IS the staged world.
        # Registered through a weakref: the process-global observatory
        # must never pin a torn-down model's staged generations alive
        import weakref

        cache_ref = weakref.ref(self.staged_cache)

        def _staged_bytes():
            cache = cache_ref()
            return 0 if cache is None else cache.device_bytes()

        DEVICE_OBS.register_owner("staged_cache", _staged_bytes)
        #: cached [Vp,Np] reservation→node one-hot for the kernel's
        #: credit matmul — depends only on the (padded) reservation node
        #: table, so repeat solves against a static table reuse it
        self._resv_onehot: Optional[tuple] = None
        #: wall-time breakdown of the last schedule() call:
        #: {"lower_s", "stage_s", "solve_s"} (observability + bench)
        self.last_timings: Optional[Dict[str, float]] = None
        #: whether the last schedule() staged NUMA inventories — the
        #: staging cache skips its device half while this holds
        self._numa_staging = False

    def reset_staging(self) -> None:
        """Drop the staged device state so the next ``schedule()`` runs
        a full relower+restage. The failover layer
        (service/failover.py) calls this through its ``on_flip_back``
        hook: a recovered sidecar re-establishes its delta base from a
        from-scratch staging instead of a chain of deltas the outage
        may have partially delivered — recovery stays bit-identical by
        construction, just one full restage slower."""
        self.staged_cache.invalidate()

    def lowering_kwargs(self) -> dict:
        """The lower_nodes configuration this model schedules with —
        shared with the incremental plugin chain and the preemption
        path so every consumer lowers identically."""
        return {
            "scaling_factors": self.scaling_factors,
            "resource_weights": self.resource_weights,
            "aggregated": self.aggregated,
        }

    # -- joint place+evict (ops/preempt.py, docs/DESIGN.md §24) -------------

    def lower_residents(
        self, snapshot: ClusterSnapshot, arrays: NodeArrays
    ) -> ResidentPodArrays:
        """Lower the assigned-pod world for victim selection, P axis
        padded to :meth:`victim_bucket`."""
        resident = lower_resident_pods(
            snapshot, arrays, victim_bucket=self.victim_bucket
        )
        DEVICE_OBS.note_padding(
            "resident_pods", resident.max_residents, resident.p
        )
        return resident

    def resident_world(self, resident: ResidentPodArrays) -> ResidentWorld:
        """Stage the resident world on device — once per preemption
        round. Between evictions only ``valid`` shrinks; callers pass
        the staged world back in and the wrappers refresh just that
        mask from the host arrays."""
        return ResidentWorld(
            req=jnp.asarray(resident.req),
            priority=jnp.asarray(resident.priority),
            quota_id=jnp.asarray(resident.quota_id),
            preemptible=jnp.asarray(resident.preemptible),
            valid=jnp.asarray(resident.valid),
        )

    def _victim_uids(self, resident, node_index: int, mask) -> List[str]:
        uids = resident.uids[node_index]
        return [
            uids[j]
            for j in range(min(len(uids), mask.shape[0]))
            if mask[j]
        ]

    def select_victims_device(
        self,
        arrays: NodeArrays,
        resident: ResidentPodArrays,
        pod: PodSpec,
        quota_used=None,
        used_limit=None,
        world: Optional[ResidentWorld] = None,
    ) -> Optional[Tuple[str, List[str]]]:
        """One preemptor against the whole cluster in one dispatch.

        Returns ``(node_name, victim uids in importance order)`` — the
        oracle's ``find_preemption`` answer — or None. ``quota_used``/
        ``used_limit`` arm the ElasticQuota reprieve gate (both None =
        quota-unmanaged pod, gate off, like the oracle)."""
        if world is None:
            world = self.resident_world(resident)
        else:
            world = world._replace(valid=jnp.asarray(resident.valid))
        quota_on = quota_used is not None and used_limit is not None
        zeros = np.zeros(NUM_RESOURCES, dtype=np.int64)
        best, victims, _cand, _nv = self._preempt(
            self.config,
            jnp.asarray(_clip_i32(resources_to_vector(pod.requests))),
            jnp.int32(pod.priority),
            jnp.int32(resident.quota_id_of(pod.quota)),
            jnp.asarray(bool(pod.is_daemonset)),
            jnp.asarray(pod.priority_class == PriorityClass.PROD),
            jnp.asarray(_clip_i32(
                zeros if quota_used is None else np.asarray(quota_used)
            )),
            jnp.asarray(_clip_i32(
                zeros if used_limit is None else np.asarray(used_limit)
            )),
            jnp.asarray(quota_on),
            jnp.asarray(arrays.alloc),
            jnp.asarray(arrays.used_req),
            jnp.asarray(arrays.usage),
            jnp.asarray(arrays.prod_usage),
            jnp.asarray(arrays.metric_fresh),
            jnp.asarray(arrays.schedulable),
            jnp.asarray(resident.node_rank),
            self.params.thresholds,
            self.params.prod_thresholds,
            world,
        )
        b = int(best)
        if b < 0:
            return None
        row = np.asarray(victims[b])
        return arrays.names[b], self._victim_uids(resident, b, row)

    def preempt_scan_device(
        self,
        arrays: NodeArrays,
        resident: ResidentPodArrays,
        pods: List[PodSpec],
        quota_rows=None,
        world: Optional[ResidentWorld] = None,
    ) -> List[Optional[Tuple[str, List[str]]]]:
        """The scanned storm variant: the whole preemptor batch in ONE
        program, eviction deltas carried in-scan. ``quota_rows[k]`` is
        ``(quota_used, used_limit)`` or None per pod; rows are the
        round-start snapshot held constant — identical to the per-pod
        path whenever quota groups don't overlap within the round
        (docs/DESIGN.md §24)."""
        k = len(pods)
        if k == 0:
            return []
        kp = self.preemptor_bucket(k)
        DEVICE_OBS.note_padding("preemptor_batch", k, kp)
        req = np.zeros((kp, NUM_RESOURCES), dtype=np.int64)
        prio = np.zeros(kp, dtype=np.int32)
        quota = np.full(kp, -3, dtype=np.int32)
        is_ds = np.zeros(kp, dtype=bool)
        is_prod = np.zeros(kp, dtype=bool)
        q_used = np.zeros((kp, NUM_RESOURCES), dtype=np.int64)
        q_limit = np.zeros((kp, NUM_RESOURCES), dtype=np.int64)
        q_en = np.zeros(kp, dtype=bool)
        active = np.zeros(kp, dtype=bool)
        for i, pod in enumerate(pods):
            req[i] = resources_to_vector(pod.requests)
            prio[i] = pod.priority
            quota[i] = resident.quota_id_of(pod.quota)
            is_ds[i] = pod.is_daemonset
            is_prod[i] = pod.priority_class == PriorityClass.PROD
            row = quota_rows[i] if quota_rows is not None else None
            if row is not None:
                q_used[i], q_limit[i] = np.asarray(row[0]), np.asarray(row[1])
                q_en[i] = True
            active[i] = True
        batch = PreemptorBatch(
            req=jnp.asarray(_clip_i32(req)),
            priority=jnp.asarray(prio),
            quota_id=jnp.asarray(quota),
            is_daemonset=jnp.asarray(is_ds),
            is_prod=jnp.asarray(is_prod),
            quota_used=jnp.asarray(_clip_i32(q_used)),
            used_limit=jnp.asarray(_clip_i32(q_limit)),
            quota_enabled=jnp.asarray(q_en),
            active=jnp.asarray(active),
        )
        if world is None:
            world = self.resident_world(resident)
        else:
            world = world._replace(valid=jnp.asarray(resident.valid))
        best_nodes, victim_cols = self._preempt_scan(
            self.config,
            batch,
            jnp.asarray(arrays.alloc),
            jnp.asarray(arrays.used_req),
            jnp.asarray(arrays.usage),
            jnp.asarray(arrays.prod_usage),
            jnp.asarray(arrays.metric_fresh),
            jnp.asarray(arrays.schedulable),
            jnp.asarray(resident.node_rank),
            self.params.thresholds,
            self.params.prod_thresholds,
            world,
        )
        best_nodes = np.asarray(best_nodes)
        victim_cols = np.asarray(victim_cols)
        out: List[Optional[Tuple[str, List[str]]]] = []
        for i in range(k):
            b = int(best_nodes[i])
            if b < 0:
                out.append(None)
                continue
            out.append((
                arrays.names[b],
                self._victim_uids(resident, b, victim_cols[i]),
            ))
        return out

    def plan_defrag_device(
        self,
        arrays: NodeArrays,
        resident: ResidentPodArrays,
        target_req,
        max_victim_priority: int,
        world: Optional[ResidentWorld] = None,
    ) -> Optional[Tuple[str, List[str]]]:
        """Headroom repack: the cheapest node to drain until
        ``target_req`` (a gang-sized hole) fits, draining preemptible
        residents strictly below ``max_victim_priority``
        least-important-first. Returns ``(node_name, drain uids in
        eviction order)`` or None (None also when the hole already fits
        somewhere — no drain needed)."""
        if world is None:
            world = self.resident_world(resident)
        else:
            world = world._replace(valid=jnp.asarray(resident.valid))
        best, drain_mask, _nd, fits_now = self._defrag(
            self.config,
            jnp.asarray(_clip_i32(np.asarray(target_req))),
            jnp.int32(max_victim_priority),
            jnp.asarray(arrays.alloc),
            jnp.asarray(arrays.used_req),
            jnp.asarray(arrays.schedulable),
            jnp.asarray(resident.node_rank),
            world,
        )
        if bool(np.asarray(fits_now)[np.asarray(arrays.schedulable)].any()):
            return None  # a hole already exists; nothing to drain
        b = int(best)
        if b < 0:
            return None
        row = np.asarray(drain_mask[b])
        ordered = self._victim_uids(resident, b, row)
        ordered.reverse()  # eviction order: least important first
        return arrays.names[b], ordered

    # -- staging ------------------------------------------------------------

    def staged_node_count(self, n: int) -> int:
        """The node-axis width the staged world will have for ``n`` real
        nodes: the per-shard bucket target under sharded staging, ``n``
        itself otherwise. Extras/NUMA columns built against the real
        node set pad to this width so every device operand agrees."""
        if self._node_shards <= 1:
            return n
        from koordinator_tpu.parallel.mesh import shard_node_bucket

        return shard_node_bucket(n, self._node_shards)

    def stage_nodes(
        self, arrays: NodeArrays, numa_cap=None, numa_free=None
    ) -> NodeState:
        """Stage host node arrays onto devices (sharded if configured).

        Under a node-sharded ``NamedSharding`` the arrays are first
        padded to the per-shard bucket (:func:`parallel.mesh.
        shard_node_bucket`) with inert rows (``state.cluster.
        pad_node_rows``): every shard is equal-width, the padded rows
        can never win a placement, and the waste is gauged per stage
        (``shard_nodes`` padding bucket)."""
        if self._node_shards > 1:
            from koordinator_tpu.state.cluster import pad_node_rows

            target = self.staged_node_count(arrays.n)
            DEVICE_OBS.note_padding("shard_nodes", arrays.n, target)
            if target != arrays.n:
                pad = target - arrays.n
                arrays = pad_node_rows(arrays, target)
                if numa_cap is not None:
                    numa_cap = np.pad(numa_cap, ((0, pad), (0, 0)))
                if numa_free is not None:
                    numa_free = np.pad(numa_free, ((0, pad), (0, 0)))
        put = (
            (lambda x: jax.device_put(x, self.sharding))
            if self.sharding is not None
            else jnp.asarray
        )
        return NodeState(
            alloc=put(arrays.alloc),
            used_req=put(arrays.used_req),
            usage=put(arrays.usage),
            prod_usage=put(arrays.prod_usage),
            est_extra=put(arrays.est_extra),
            prod_base=put(arrays.prod_base),
            metric_fresh=put(arrays.metric_fresh),
            schedulable=put(arrays.schedulable),
            numa_cap=put(numa_cap) if numa_cap is not None else None,
            numa_free=put(numa_free) if numa_free is not None else None,
        )

    @staticmethod
    def stage_pods(arrays: PendingPodArrays) -> PodBatch:
        return PodBatch.build(
            req=jnp.asarray(arrays.req),
            est=jnp.asarray(arrays.est),
            is_prod=jnp.asarray(arrays.is_prod),
            is_daemonset=jnp.asarray(arrays.is_daemonset),
            quota_id=jnp.asarray(arrays.quota_id),
            non_preemptible=jnp.asarray(arrays.non_preemptible),
            gang_id=jnp.asarray(arrays.gang_id),
        )

    # -- solve --------------------------------------------------------------

    def solve(self, state: NodeState, pods: PodBatch):
        """Jitted solve on staged arrays; returns (new_state, assignments)."""
        r = self._solve(state, pods, self.params, self.config)
        return r.node_state, r.assign

    def schedule(self, snapshot: ClusterSnapshot) -> "ScheduleResult":
        """Typed end-to-end: snapshot → committed placements.

        Returns a :class:`ScheduleResult`: a ``{pod uid: node | None}``
        mapping of committed (bindable) placements, with
        ``result.waiting`` carrying NonStrict gang members that hold a
        node at the Permit barrier but must not be bound. Gangs, quotas,
        reservations, NUMA topology, and devices present in the snapshot /
        managers are all lowered onto the device solver; fine-grained
        (cpuset/device) placements are validated against the host
        allocators and the batch re-solved on conflict (propose →
        validate → refine, models/finegrained.py).

        The serial composition of the split pipeline
        (:meth:`schedule_async` + :meth:`InFlightSchedule.finalize`):
        dispatch and materialize back to back, so every existing caller
        keeps blocking semantics and bit-identical results.
        """
        return self.schedule_async(snapshot).finalize()

    def prestage(self, snapshot: ClusterSnapshot) -> Optional[Dict[str, float]]:
        """Warm the staging cache for an upcoming solve — the overlap
        half of the pipelined tick path (docs/DESIGN.md §15): re-lower
        and scatter the rows dirtied so far while the previous solve is
        still in flight, so the round-start catch-up ensure() touches
        only what changed after this call. Bit-identity is free: rows
        staged here from pre-epilogue truth are re-marked by the
        epilogue's own tracker marks and re-lowered from settled truth
        at catch-up. Taint-clean by design — no read-back, no blocking
        on the in-flight solve (a pinned generation is never donated).
        Returns the ensure() timing dict, or None when the snapshot
        carries no delta tracker (nothing to warm)."""
        if getattr(snapshot, "delta_tracker", None) is None:
            return None
        t0 = time.perf_counter()
        with DEVICE_OBS.annotate("prestage"):
            _, _, times, _ = self.staged_cache.ensure(
                snapshot, want_device=not self._numa_staging
            )
        # the overlap window's signature span: in a pipelined run this
        # slice visibly crosses the publisher track's device_solve span
        TRACER.emit("prestage", cat="stage", t0=t0,
                    args={"for_round": TRACER.round_id + 1})
        return times

    def schedule_async(self, snapshot: ClusterSnapshot) -> "InFlightSchedule":
        """Stage and dispatch one batched solve WITHOUT materializing
        results: the returned :class:`InFlightSchedule` carries the
        dispatched (device-future) solve; its :meth:`~InFlightSchedule.
        finalize` is the one read-back point, run publish-side by the
        pipelined loop (scheduler/pipeline.py). Fine-grained specials
        still run the propose→validate→refine loop inline (it reads
        proposals by design), so those rounds degrade to blocking —
        the plain churn path stays fully asynchronous."""
        t_start = time.perf_counter()
        gang_names = sorted(snapshot.gangs)
        quota_names = sorted(snapshot.quotas)
        gang_index = {name: i for i, name in enumerate(gang_names)}
        quota_index = {name: i for i, name in enumerate(quota_names)}

        # node lowering + staging: incremental (device-resident, dirty
        # rows only) when the snapshot carries a delta tracker — else
        # the classic full lower + stage below. When the fine-grained
        # manager reports NUMA topology the staged state is discarded
        # below (the NodeState then carries numa inventories the cache
        # does not cover), but the host-side delta lowering still
        # applies.
        staged_state = None
        cache_times = None
        self._staging_delta = None
        if getattr(snapshot, "delta_tracker", None) is not None:
            node_arrays, staged_state, cache_times, _ = (
                self.staged_cache.ensure(
                    snapshot,
                    # a NUMA-carrying NodeState restages below anyway —
                    # don't pay the cache's device half for it (flag set
                    # from the previous call's outcome; one extra stage
                    # on a topology flip, none in steady state)
                    want_device=not self._numa_staging,
                )
            )
            # the wire sync point covers EVERY ensure since the last
            # solve (pipelined prestages included), not just this one
            self._staging_delta = self.staged_cache.take_wire_delta()
        else:
            node_arrays = lower_nodes(
                snapshot,
                scaling_factors=self.scaling_factors,
                resource_weights=self.resource_weights,
                aggregated=self.aggregated,
            )
        pod_arrays = lower_pending_pods(
            snapshot.pending_pods,
            quota_index=quota_index or None,
            gang_index=gang_index or None,
            scaling_factors=self.scaling_factors,
            resource_weights=self.resource_weights,
        )
        uid_to_pod = {pod.uid: pod for pod in snapshot.pending_pods}
        pods_in_order = [uid_to_pod[uid] for uid in pod_arrays.uids]
        node_by_name = {node.name: node for node in snapshot.nodes}

        # -- fine-grained pod classification + NUMA lowering ---------------
        # one annotation parse per pod yields both the special set (host
        # rows needed) and the pod-level NUMA-policy flags (in-scan
        # consumption)
        numa_aux = None
        numa_cap = numa_free = None
        has_numa_policy_arr = None
        fine = self.fine
        specials: List[int] = []
        use_numa = fine is not None and fine.has_topology(node_arrays.names)
        node_policy_present = use_numa and fine.any_node_policy(node_arrays.names)
        if fine is not None:
            pod_policy = np.zeros(len(pods_in_order), bool)
            for i, pod in enumerate(pods_in_order):
                special, has_policy = fine.pod_flags(pod, node_policy_present)
                if special:
                    specials.append(i)
                pod_policy[i] = has_policy
        if use_numa:
            numa_cap, numa_free, node_policy = fine.numa_arrays(node_arrays.names)
            has_numa_policy_arr = jnp.asarray(pod_policy)
            # sharded staging pads the staged node axis: the per-node
            # policy column must match that width (padding rows carry
            # no policy — they are never placeable anyway)
            n_staged = self.staged_node_count(node_arrays.n)
            if n_staged != node_arrays.n:
                node_policy = np.pad(
                    node_policy, (0, n_staged - node_arrays.n)
                )
            numa_aux = NumaAux(node_policy=jnp.asarray(node_policy))

        t_host_done = time.perf_counter()
        self._numa_staging = numa_cap is not None or numa_free is not None
        if staged_state is not None and self._numa_staging:
            # NUMA inventories ride NodeState but live outside the
            # cache: restage fully (host arrays stay delta-maintained)
            staged_state = None
        if self._numa_staging:
            # a node_delta base without the numa columns would make the
            # sidecar solve against a numa-less state — never ship one
            self._staging_delta = None
        if staged_state is not None:
            state = staged_state
            # the solve about to dispatch holds this cache generation:
            # a concurrent prestage must double-buffer, not donate it.
            # Unpinned at finalize; a dispatch that raises instead is
            # released by the next schedule_async's pin (one extra
            # copied scatter at worst).
            self.staged_cache.pin(state)
        else:
            state = self.stage_nodes(node_arrays, numa_cap, numa_free)
        batch = self.stage_pods(pod_arrays)
        t_staged = time.perf_counter()
        # exact retro spans from the timestamps this function already
        # takes: host lowering, then host->device staging
        TRACER.emit("lower", cat="stage", t0=t_start, t1=t_host_done)
        TRACER.emit("stage", cat="stage", t0=t_host_done, t1=t_staged)
        cache_stage_s = cache_times["stage_s"] if cache_times else 0.0
        self.last_timings = {
            # host lowering work (node delta/full + pods + host rows),
            # excluding the device update the cache did inline
            "lower_s": (t_host_done - t_start) - cache_stage_s,
            "stage_s": (t_staged - t_host_done) + cache_stage_s,
            "solve_s": 0.0,  # filled after the solve loop below
        }
        if has_numa_policy_arr is not None:
            batch = batch._replace(has_numa_policy=has_numa_policy_arr)

        # a gang pod whose GangSpec hasn't been observed yet must not bind
        # solo (the incremental path rejects it at PreFilter; the batched
        # path hard-blocks it)
        blocked = np.array(
            [
                pod.gang is not None and pod.gang not in gang_index
                for pod in pods_in_order
            ],
            dtype=bool,
        )
        if blocked.any():
            batch = batch._replace(blocked=jnp.asarray(blocked))

        gang_state = None
        if gang_names:
            bound = {name: 0 for name in gang_names}
            for pod in snapshot.pods:
                if pod.gang in bound and pod.node_name is not None:
                    bound[pod.gang] += 1
            group_label = {}
            for i, name in enumerate(gang_names):
                spec = snapshot.gangs[name]
                group_label[name] = (
                    "/".join(sorted(spec.gang_group)) if spec.gang_group else name
                )
            gang_state = GangState.build(
                min_member=[snapshot.gangs[g].min_member for g in gang_names],
                bound_count=[bound[g] for g in gang_names],
                strict=[
                    snapshot.gangs[g].mode == GangMode.STRICT for g in gang_names
                ],
                group_id=[group_label[g] for g in gang_names],  # build densifies
            )

        quota_state = None
        if quota_names:
            quota_state = self._build_quota_state(
                snapshot, quota_names, quota_index, node_arrays
            )

        resv_arrays, resv_specs, resv_kernel_safe = self._build_resv(
            snapshot, node_arrays, pods_in_order
        )
        if resv_arrays is not None and self.pod_bucketing:
            resv_arrays = self._pad_resv(resv_arrays)
        # hoist the kernel's [Vp,N] reservation→node one-hot out of the
        # per-solve path: it depends only on the (padded) reservation
        # node table, so steady-state solves against a static table
        # reuse one cached device operand (ADVICE r5 low #3)
        resv_onehot = None
        if (resv_arrays is not None and self.backend is None
                and self.use_pallas and self._pallas_eligible):
            from koordinator_tpu.ops.pallas_binpack import (
                pallas_resv_supported,
            )

            if resv_kernel_safe and pallas_resv_supported(
                int(resv_arrays.node.shape[0]), node_arrays.n
            ):
                resv_onehot = self._resv_onehot_for(
                    int(resv_arrays.node.shape[0]), node_arrays.n
                )

        # -- special pods + required node selectors: host Extras rows ------
        # node selectors (the NodeAffinity slice the incremental fit
        # plugin enforces) become per-pod row masks, AND-ed back after any
        # refine-loop row refresh
        extras = None
        mask_np = score_np = None
        affinity_rows: Dict[int, np.ndarray] = {}
        selector_pods = [
            i for i, pod in enumerate(pods_in_order) if pod.node_selector
        ]
        # host-port pods WITH a fine manager are specials (the ports
        # plugin filters + holds through the validate loop); without one
        # (standalone model) they get a static conflict row against
        # assigned pods — conservative, no batch-internal resolution
        port_pods = []
        if fine is None or fine.ports_plugin is None:
            port_pods = [
                i for i, pod in enumerate(pods_in_order)
                if getattr(pod, "host_ports", None)
            ]
        if specials or selector_pods or port_pods:
            p, n = len(pods_in_order), node_arrays.n
            mask_np = np.ones((p, n), bool)
            score_np = np.zeros((p, n), np.int32)
            for i in specials:
                mask_np[i], score_np[i] = fine.rows(
                    snapshot, pods_in_order[i], snapshot.nodes
                )
            if selector_pods:
                from koordinator_tpu.apis.types import selector_matches

                for i in selector_pods:
                    selector = pods_in_order[i].node_selector
                    row = np.fromiter(
                        (
                            selector_matches(selector, node.labels)
                            for node in snapshot.nodes
                        ),
                        dtype=bool,
                        count=n,
                    )
                    affinity_rows[i] = row
                    mask_np[i] &= row
            if port_pods:
                from koordinator_tpu.scheduler.plugins.nodeports import (
                    pod_host_ports,
                )

                used_by_node = [set() for _ in range(n)]
                node_idx = {nd.name: j for j, nd in enumerate(snapshot.nodes)}
                for ap in snapshot.pods:
                    j = node_idx.get(ap.node_name)
                    if j is not None:
                        used_by_node[j] |= pod_host_ports(ap)
                # same-batch conflicts have no validate loop here, so
                # later pending claimants of an already-claimed port are
                # DEFERRED (all-False row, placed next round once the
                # first claimant is assigned) — delayed, never
                # conflicting. Only pods with at least one feasible node
                # claim: an unplaceable pod must not starve later
                # claimants of its ports.
                claimed: set = set()
                for i in port_pods:
                    want = pod_host_ports(pods_in_order[i])
                    if want & claimed:
                        mask_np[i] &= False
                        affinity_rows[i] = np.zeros(n, bool)
                        continue
                    row = np.fromiter(
                        (not (want & used_by_node[j]) for j in range(n)),
                        dtype=bool, count=n,
                    )
                    # claim only with a feasible node under the FULL
                    # accumulated mask (selector rows etc. included) —
                    # a pod unplaceable for any reason must not starve
                    # later claimants
                    if (mask_np[i] & row).any():
                        claimed |= want
                    affinity_rows[i] = affinity_rows.get(
                        i, np.ones(n, bool)) & row
                    mask_np[i] &= row
            extras = Extras(mask=jnp.asarray(mask_np), score=jnp.asarray(score_np))

        # -- pod-shape bucketing (compile amortization) ---------------------
        n_real = len(pods_in_order)
        if self.pod_bucketing:
            batch, extras, resv_arrays = self._pad_pods(
                batch, extras, resv_arrays, n_real
            )
        padded_p = int(batch.req.shape[0])

        def _extras_device():
            """Extras from the (unpadded) host rows, padded to the batch
            length — the refine loop rebuilds through this so re-solves
            keep matching scan dims. Under sharded staging the node
            columns additionally pad to the staged width (all-False
            mask: a padding node is never feasible)."""
            pad = padded_p - mask_np.shape[0]
            col_pad = self.staged_node_count(node_arrays.n) - mask_np.shape[1]
            if pad or col_pad:
                mask = np.pad(mask_np, ((0, pad), (0, col_pad)))
                score = np.pad(score_np, ((0, pad), (0, col_pad)))
            else:
                mask, score = mask_np, score_np
            return Extras(mask=jnp.asarray(mask), score=jnp.asarray(score))

        if extras is not None:
            extras = _extras_device()

        # -- propose → validate → refine loop ------------------------------
        applied: List[tuple] = []  # (idx, node_name, CycleState)
        iteration = 0
        while True:
            with DEVICE_OBS.annotate("device_solve"):
                result = self._dispatch_solve(
                    state,
                    batch,
                    quota_state,
                    gang_state,
                    extras,
                    resv_arrays,
                    numa_aux,
                    resv_kernel_safe=resv_kernel_safe,
                    resv_onehot=resv_onehot,
                )
            if not specials:
                break
            raw = np.asarray(result.raw_assign)
            frozen = iteration >= self.MAX_SCORE_ITERS
            dirty = False
            for i in specials:
                a = int(raw[i])
                if a < 0:
                    continue
                pod = pods_in_order[i]
                node = node_by_name[node_arrays.names[a]]
                if not frozen:
                    m_row, s_row = fine.rows(snapshot, pod, snapshot.nodes)
                    if i in affinity_rows:  # node selector always applies
                        m_row = m_row & affinity_rows[i]
                    if not np.array_equal(m_row, mask_np[i]) or not np.array_equal(
                        s_row, score_np[i]
                    ):
                        mask_np[i] = m_row
                        score_np[i] = s_row
                        dirty = True
                        break
                ok, cstate = fine.apply(snapshot, pod, node)
                if not ok:
                    mask_np[i, a] = False
                    dirty = True
                    break
                applied.append((i, node.name, cstate))
            if not dirty:
                break
            for i, node_name, cstate in reversed(applied):
                fine.rollback(
                    snapshot, pods_in_order[i], node_by_name[node_name], cstate
                )
            applied = []
            extras = _extras_device()
            iteration += 1

        TRACER.emit("dispatch", cat="device", t0=t_staged,
                    args={"solver": self.last_solver})
        return InFlightSchedule(
            model=self,
            snapshot=snapshot,
            result=result,
            node_names=node_arrays.names,
            pod_uids=pod_arrays.uids,
            pods_in_order=pods_in_order,
            node_by_name=node_by_name,
            applied=applied,
            resv_specs=resv_specs if resv_arrays is not None else None,
            n_real=n_real,
            t_staged=t_staged,
            timings=self.last_timings,
            pinned=staged_state,
        )

    def _dispatch_solve(self, state, batch, quota_state, gang_state,
                        extras, resv_arrays, numa_aux,
                        resv_kernel_safe: bool = True, resv_onehot=None):
        """Route eligible plain solves onto the pallas kernel (identical
        results, ~2x on TPU); everything else runs the fused scan. A
        configured remote backend (the solver sidecar) takes the whole
        solve instead — same arrays over the wire, same epilogue (and,
        when the staging cache produced a delta this round, only the
        dirty node rows cross the wire)."""
        if self.backend is not None:
            self.last_solver = "remote"
            kwargs = {}
            staging = getattr(self, "_staging_delta", None)
            if staging is not None and getattr(
                self.backend, "supports_staging_delta", False
            ):
                kwargs["staging"] = staging
            result = self.backend.solve_result(
                state, batch, self.params, self.config, quota_state,
                gang_state, extras, resv_arrays, numa_aux, **kwargs,
            )
            # a failover backend reports which side answered ("remote",
            # "local-fallback", "local-degraded") — surface it as the
            # model's solver tag so operators/tests see degraded solves
            self.last_solver = getattr(
                self.backend, "last_mode", None
            ) or "remote"
            return result
        n, p = int(state.alloc.shape[0]), int(batch.req.shape[0])
        plain = (
            quota_state is None
            and gang_state is None
            and extras is None
            and resv_arrays is None
            and numa_aux is None
        )
        if plain and 0 < n * p <= self.host_fallback_cells:
            self.last_solver = "host"
            return self._host_solve(state, batch)
        from koordinator_tpu.ops.pallas_binpack import pallas_routing_ok

        # the shared dispatch predicate (shape bounds, numa/reservation
        # gates — same one the solver sidecar uses); resv_kernel_safe is
        # _build_resv's host-side score-budget pre-check
        kernel_ok = pallas_routing_ok(
            state, batch, extras, resv_arrays, resv_kernel_safe, numa_aux
        )
        if kernel_ok and self.use_pallas and self._pallas_eligible:
            from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

            try:
                result = pallas_solve_batch(
                    state, batch, self.params, self.config,
                    quota_state, gang_state, numa_aux, resv_arrays,
                    # score budget pre-validated in _build_resv; skip
                    # the per-solve device->host sync
                    resv_score_checked=True,
                    resv_onehot=resv_onehot,
                )
                self.last_solver = "pallas"
                return result
            except Exception as e:
                # a real kernel failure must be visible, not a silent
                # 2x slowdown for the model's lifetime
                import warnings

                warnings.warn(
                    f"pallas placement kernel disabled after error: "
                    f"{type(e).__name__}: {e}",
                    RuntimeWarning,
                )
                self.use_pallas = False
        self.last_solver = "scan"
        return self._solve(
            state, batch, self.params, self.config, quota_state,
            gang_state, extras, resv_arrays, numa_aux,
        )

    def _host_solve(self, state, batch) -> SolveResult:
        """Tiny plain solves on the host sequential path (bit-identical
        to the scan by the differential-test contract of the oracles:
        scalar == vectorized == scan) — no device round trip. Uses the
        class-cached vectorized oracle: same sequential semantics,
        ~10-20x the scalar transliteration's throughput."""
        from koordinator_tpu.oracle.vectorized import schedule_vectorized

        req = np.asarray(batch.req).copy()
        blocked = np.asarray(batch.blocked)
        # blocked (and bucket-padding) pods can never fit — the same
        # hard-block encoding the pallas kernel uses
        req[blocked, 0] = 2**30
        assign = np.asarray(schedule_vectorized(
            np.asarray(state.alloc), np.asarray(state.used_req),
            np.asarray(state.usage), np.asarray(state.prod_usage),
            np.asarray(state.est_extra), np.asarray(state.prod_base),
            np.asarray(state.metric_fresh), np.asarray(state.schedulable),
            req, np.asarray(batch.est),
            np.asarray(batch.is_prod), np.asarray(batch.is_daemonset),
            np.asarray(self.params.weights),
            np.asarray(self.params.thresholds),
            np.asarray(self.params.prod_thresholds),
            fit_weight=self.config.fit_weight,
            loadaware_weight=self.config.loadaware_weight,
            score_according_prod=self.config.score_according_prod,
        ), dtype=np.int32)
        used = np.asarray(state.used_req).copy()
        estx = np.asarray(state.est_extra).copy()
        prodb = np.asarray(state.prod_base).copy()
        real_req = np.asarray(batch.req)
        est = np.asarray(batch.est)
        is_prod = np.asarray(batch.is_prod)
        for i, a in enumerate(assign):
            if a >= 0:
                used[a] += real_req[i]
                estx[a] += est[i]
                if is_prod[i]:
                    prodb[a] += est[i]
        falses = np.zeros(assign.shape[0], bool)
        return SolveResult(
            node_state=state._replace(
                used_req=used, est_extra=estx, prod_base=prodb
            ),
            quota_state=None,
            resv_free=None,
            assign=assign,
            commit=assign >= 0,
            waiting=falses,
            rejected=falses,
            raw_assign=assign,
            resv_vstar=None,
            resv_delta=None,
            numa_consumed=None,
        )

    def _pad_pods(self, batch, extras, resv, n_real):
        """Pad the pod axis up to the shape bucket with hard-blocked
        dummies (assignment -1, no accounting) — identical semantics, one
        compiled program per bucket."""
        target = self.pod_bucket(n_real)
        DEVICE_OBS.note_padding("pod_batch", n_real, target)
        if target == n_real:
            return batch, extras, resv
        pad = target - n_real

        def padp(a, fill):
            widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            return jnp.pad(a, widths, constant_values=fill)

        batch = batch._replace(
            req=padp(batch.req, 0),
            est=padp(batch.est, 0),
            is_prod=padp(batch.is_prod, False),
            is_daemonset=padp(batch.is_daemonset, False),
            quota_id=padp(batch.quota_id, -1),
            non_preemptible=padp(batch.non_preemptible, False),
            gang_id=padp(batch.gang_id, -1),
            blocked=padp(batch.blocked, True),
            has_numa_policy=(
                padp(batch.has_numa_policy, False)
                if batch.has_numa_policy is not None
                else None
            ),
        )
        if extras is not None:
            extras = Extras(
                mask=padp(extras.mask, False), score=padp(extras.score, 0)
            )
        if resv is not None:
            resv = resv._replace(match=padp(resv.match, False))
        return batch, extras, resv

    def _pad_resv(self, resv):
        """Pad the reservation axis to its shape bucket with inert rows
        (node 0, zero free, no matches) — identical semantics, one
        compiled program per bucket."""
        v = int(resv.node.shape[0])
        target = self.resv_bucket(v)
        DEVICE_OBS.note_padding("resv_table", v, target)
        if target == v:
            return resv
        pad = target - v

        def padv(a, fill):
            widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            return jnp.pad(a, widths, constant_values=fill)

        return resv._replace(
            node=padv(resv.node, 0),
            free=padv(resv.free, 0),
            allocate_once=padv(resv.allocate_once, False),
            match=jnp.pad(resv.match, [(0, 0), (0, pad)],
                          constant_values=False),
        )

    def _build_resv(self, snapshot, node_arrays, pods_in_order):
        """Lower Available reservations with free remainder to
        (:class:`ResvArrays`, spec list indexed by v, kernel_safe flag).
        ``kernel_safe`` is the packed-argmax score-budget verdict
        computed on the host arrays, so dispatch can route a
        pathological table to the scan without tripping the kernel
        breaker."""
        from koordinator_tpu.scheduler.plugins.reservation import (
            reservation_free,
            reservation_matches_pod,
        )

        index = node_arrays.index()
        specs, nodes, frees, once = [], [], [], []
        for resv in snapshot.reservations:
            if getattr(resv.state, "value", resv.state) != "Available":
                continue
            if resv.node_name not in index:
                continue
            free = reservation_free(resv)
            if not free.any():
                continue
            specs.append(resv)
            nodes.append(index[resv.node_name])
            frees.append(free)
            once.append(resv.allocate_once)
        if not specs:
            return None, [], True
        match = np.zeros((len(pods_in_order), len(specs)), bool)
        for i, pod in enumerate(pods_in_order):
            for v, resv in enumerate(specs):
                match[i, v] = reservation_matches_pod(resv, pod)
        node_np = np.asarray(nodes, np.int32)
        free_np = np.stack(frees).astype(np.int32)
        #: host copy of the reservation→node table for the one-hot cache
        self._resv_node_np = node_np
        from koordinator_tpu.ops.pallas_binpack import pallas_resv_score_safe

        kernel_safe = pallas_resv_score_safe(
            node_np, free_np, node_arrays.alloc
        )
        return (
            ResvArrays(
                node=jnp.asarray(node_np),
                free=jnp.asarray(free_np),
                allocate_once=jnp.asarray(np.asarray(once, bool)),
                match=jnp.asarray(match),
            ),
            specs,
            kernel_safe,
        )

    def _resv_onehot_for(self, v_padded: int, n_nodes: int):
        """The cached kernel credit-matmul one-hot for the current
        (bucket-padded) reservation node table — rebuilt only when the
        table or the node count actually changes."""
        node_np = self._resv_node_np
        padded = np.zeros(v_padded, np.int32)
        padded[: node_np.shape[0]] = node_np
        key = (padded.tobytes(), n_nodes)
        cached = self._resv_onehot
        if cached is not None and cached[0] == key:
            return cached[1]
        from koordinator_tpu.ops.pallas_binpack import resv_node_onehot

        onehot = resv_node_onehot(jnp.asarray(padded), n_nodes)
        self._resv_onehot = (key, onehot)
        return onehot

    def _apply_reservations(
        self, snapshot, resv_specs, result, pods_in_order, commit, waiting
    ):
        from koordinator_tpu.apis.types import (
            ReservationState,
            resources_to_vector,
            vector_to_resources,
        )

        vstar = np.asarray(result.resv_vstar)
        delta = np.asarray(result.resv_delta)
        keep = commit | waiting
        out: Dict[str, tuple] = {}
        committed: Dict[str, tuple] = {}
        tracker = getattr(snapshot, "delta_tracker", None)
        for i, pod in enumerate(pods_in_order):
            v = int(vstar[i])
            if v < 0 or not keep[i]:
                continue
            spec = resv_specs[v]
            cur = resources_to_vector(spec.allocated)
            spec.allocated = vector_to_resources(cur + delta[i])
            spec.allocated_pod_uids.append(pod.uid)
            if spec.allocate_once:
                spec.state = ReservationState.SUCCEEDED
            # committed AND waiting pods both record their consumption:
            # the scheduler must be able to roll either back while the
            # decision is still unpublished (WaitTime expiry for the
            # waiting, a fencing abort for the committed)
            if waiting[i]:
                out[pod.uid] = (spec.name, delta[i].copy())
            else:
                committed[pod.uid] = (spec.name, delta[i].copy())
            if tracker is not None:
                # the mutated allocation changes the node's lowered
                # reservation hold — the next delta must re-lower it
                tracker.mark_node(spec.node_name)
        return out, committed

    def _build_quota_state(self, snapshot, quota_names, quota_index, node_arrays):
        """Lower the (possibly hierarchical) quota tree to a device
        QuotaState.

        Requests are static within a solve, so the exact tree runtime —
        multi-level water-filling included — is computed once on the host
        through GroupQuotaManager (exact-rational mode, matching the
        device arithmetic) and shipped as the precomputed ``runtime``.
        The device then only tracks per-quota ``used`` as pods place.
        """
        from koordinator_tpu.apis.types import resources_to_vector
        from koordinator_tpu.quota.core import GroupQuotaManager

        q = len(quota_names)
        mn = np.zeros((q, NUM_RESOURCES), np.int64)
        mx = np.zeros((q, NUM_RESOURCES), np.int64)
        guar = np.zeros((q, NUM_RESOURCES), np.int64)
        weight = np.zeros((q, NUM_RESOURCES), np.int64)
        allow = np.ones(q, bool)
        child_request = np.zeros((q, NUM_RESOURCES), np.int64)
        used = np.zeros((q, NUM_RESOURCES), np.int64)
        for name, i in quota_index.items():
            spec = snapshot.quotas[name]
            mn[i] = resources_to_vector(spec.min)
            mx[i] = resources_to_vector(spec.max)
            guar[i] = resources_to_vector(spec.guaranteed)
            weight[i] = (
                resources_to_vector(spec.shared_weight)
                if spec.shared_weight is not None
                else mx[i]
            )
            allow[i] = spec.allow_lent_resource
        for pod in list(snapshot.pending_pods) + list(snapshot.pods):
            if pod.quota in quota_index:
                i = quota_index[pod.quota]
                vec = resources_to_vector(pod.requests)
                child_request[i] += vec
                if pod.node_name is not None:
                    used[i] += vec

        # one host manager per quota tree (quota_handler.go multi-tree):
        # each tree water-fills against its own total — the root quota's
        # total_resource (profile-created node pools) or the cluster total
        node_total = node_arrays.alloc.astype(np.int64).sum(axis=0)
        by_tree: Dict[str, list] = {}
        for name in quota_names:
            by_tree.setdefault(snapshot.quotas[name].tree_id, []).append(name)
        runtime = np.zeros((q, NUM_RESOURCES), np.int64)
        for tree_names in by_tree.values():
            mgr = GroupQuotaManager(exact_rational=True)
            mgr.cluster_total = node_total.copy()
            for name in tree_names:
                spec = snapshot.quotas[name]
                # only tree ROOTS carry the pool total (profile controller)
                if spec.total_resource is not None and (
                    spec.parent is None or spec.parent == "root"
                ):
                    mgr.cluster_total = resources_to_vector(spec.total_resource)
                mgr.update_quota(spec)
            for name in tree_names:
                i = quota_index[name]
                if child_request[i].any():
                    mgr.add_request(name, child_request[i])
            for name in tree_names:
                i = quota_index[name]
                rt = mgr.refresh_runtime(name)
                runtime[i] = rt if rt is not None else 0

        return QuotaState.build(
            min=mn,
            max=mx,
            guarantee=guar,
            weight=weight,
            allow_lent=allow,
            child_request=child_request,
            used=used,
            total=node_total,
            runtime=runtime,
        )
