"""PlacementModel: the flagship batched placement solver.

Wraps the scan-based solver (ops/binpack.py) with host↔device staging and
typed in/out: takes a ``ClusterSnapshot``, returns pod→node assignments
with semantics identical to running the reference's Filter→Score→Reserve
cycle pod-by-pod (differentially tested against the oracle).

The node axis is shardable over a ``jax.sharding.Mesh`` (see
``koordinator_tpu.parallel``): scores are computed on node shards and the
argmax reduction rides ICI collectives inserted by GSPMD.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
from koordinator_tpu.apis.types import ClusterSnapshot, GangMode
from koordinator_tpu.ops.binpack import (
    NodeState,
    PodBatch,
    ScoreParams,
    SolverConfig,
    schedule_batch,
)
from koordinator_tpu.ops.gang import GangState
from koordinator_tpu.ops.quota import QuotaState
from koordinator_tpu.state.cluster import (
    DEFAULT_ESTIMATED_SCALING_FACTORS,
    DEFAULT_RESOURCE_WEIGHTS,
    DEFAULT_USAGE_THRESHOLDS,
    NodeArrays,
    PendingPodArrays,
    lower_nodes,
    lower_pending_pods,
)


def _vec(mapping, dtype=np.int32) -> np.ndarray:
    out = np.zeros(NUM_RESOURCES, dtype=dtype)
    for k, v in mapping.items():
        out[int(k)] = v
    return out


class ScheduleResult(Dict[str, Optional[str]]):
    """Result of one batched schedule.

    Behaves as the ``{pod uid: node name | None}`` mapping of *committed*
    (bindable) placements. ``waiting`` lists placed-but-not-committed
    NonStrict gang members: they hold their node's resources at the Permit
    barrier and MUST NOT be bound yet (reference: waiting pods in the
    coscheduling Permit stage).
    """

    def __init__(self, assignments, waiting=None):
        super().__init__(assignments)
        self.waiting: Dict[str, str] = dict(waiting or {})


class PlacementModel:
    """Compiled batched placement over a (possibly sharded) node axis."""

    def __init__(
        self,
        config: SolverConfig = SolverConfig(),
        resource_weights=None,
        usage_thresholds=None,
        prod_usage_thresholds=None,
        scaling_factors=None,
        sharding: Optional[jax.sharding.Sharding] = None,
    ):
        self.config = config
        self.resource_weights = dict(resource_weights or DEFAULT_RESOURCE_WEIGHTS)
        self.scaling_factors = dict(
            scaling_factors or DEFAULT_ESTIMATED_SCALING_FACTORS
        )
        self.params = ScoreParams(
            weights=jnp.asarray(_vec(self.resource_weights)),
            thresholds=jnp.asarray(_vec(usage_thresholds or DEFAULT_USAGE_THRESHOLDS)),
            prod_thresholds=jnp.asarray(_vec(prod_usage_thresholds or {})),
        )
        self.sharding = sharding
        self._solve = jax.jit(schedule_batch, static_argnames=("config",))

    # -- staging ------------------------------------------------------------

    def stage_nodes(self, arrays: NodeArrays) -> NodeState:
        """Stage host node arrays onto devices (sharded if configured)."""
        put = (
            (lambda x: jax.device_put(x, self.sharding))
            if self.sharding is not None
            else jnp.asarray
        )
        return NodeState(
            alloc=put(arrays.alloc),
            used_req=put(arrays.used_req),
            usage=put(arrays.usage),
            prod_usage=put(arrays.prod_usage),
            est_extra=put(arrays.est_extra),
            prod_base=put(arrays.prod_base),
            metric_fresh=put(arrays.metric_fresh),
            schedulable=put(arrays.schedulable),
        )

    @staticmethod
    def stage_pods(arrays: PendingPodArrays) -> PodBatch:
        return PodBatch.build(
            req=jnp.asarray(arrays.req),
            est=jnp.asarray(arrays.est),
            is_prod=jnp.asarray(arrays.is_prod),
            is_daemonset=jnp.asarray(arrays.is_daemonset),
            quota_id=jnp.asarray(arrays.quota_id),
            non_preemptible=jnp.asarray(arrays.non_preemptible),
            gang_id=jnp.asarray(arrays.gang_id),
        )

    # -- solve --------------------------------------------------------------

    def solve(self, state: NodeState, pods: PodBatch):
        """Jitted solve on staged arrays; returns (new_state, assignments)."""
        return self._solve(state, pods, self.params, self.config)

    def schedule(self, snapshot: ClusterSnapshot) -> "ScheduleResult":
        """Typed end-to-end: snapshot → committed placements.

        Returns a :class:`ScheduleResult`: a ``{pod uid: node | None}``
        mapping of committed (bindable) placements, with
        ``result.waiting`` carrying NonStrict gang members that hold a
        node at the Permit barrier but must not be bound. Gangs and
        (single-level) quotas present in the snapshot are lowered onto the
        device solver: quota admission gates each pod, gang groups resolve
        all-or-nothing at batch end.
        """
        gang_names = sorted(snapshot.gangs)
        quota_names = sorted(snapshot.quotas)
        gang_index = {name: i for i, name in enumerate(gang_names)}
        quota_index = {name: i for i, name in enumerate(quota_names)}

        node_arrays = lower_nodes(
            snapshot,
            scaling_factors=self.scaling_factors,
            resource_weights=self.resource_weights,
        )
        pod_arrays = lower_pending_pods(
            snapshot.pending_pods,
            quota_index=quota_index or None,
            gang_index=gang_index or None,
            scaling_factors=self.scaling_factors,
            resource_weights=self.resource_weights,
        )
        state = self.stage_nodes(node_arrays)
        batch = self.stage_pods(pod_arrays)

        # a gang pod whose GangSpec hasn't been observed yet must not bind
        # solo (the incremental path rejects it at PreFilter; the batched
        # path hard-blocks it)
        uid_to_pod = {pod.uid: pod for pod in snapshot.pending_pods}
        blocked = np.array(
            [
                uid_to_pod[uid].gang is not None
                and uid_to_pod[uid].gang not in gang_index
                for uid in pod_arrays.uids
            ],
            dtype=bool,
        )
        if blocked.any():
            batch = batch._replace(blocked=jnp.asarray(blocked))

        gang_state = None
        if gang_names:
            bound = {name: 0 for name in gang_names}
            for pod in snapshot.pods:
                if pod.gang in bound and pod.node_name is not None:
                    bound[pod.gang] += 1
            group_label = {}
            for i, name in enumerate(gang_names):
                spec = snapshot.gangs[name]
                group_label[name] = (
                    "/".join(sorted(spec.gang_group)) if spec.gang_group else name
                )
            gang_state = GangState.build(
                min_member=[snapshot.gangs[g].min_member for g in gang_names],
                bound_count=[bound[g] for g in gang_names],
                strict=[
                    snapshot.gangs[g].mode == GangMode.STRICT for g in gang_names
                ],
                group_id=[group_label[g] for g in gang_names],  # build densifies
            )

        quota_state = None
        if quota_names:
            quota_state = self._build_quota_state(
                snapshot, quota_names, quota_index, node_arrays
            )

        result = self._solve(
            state, batch, self.params, self.config, quota_state, gang_state
        )
        if gang_state is not None:
            _, (assignments, commit, waiting) = result
            commit = np.asarray(commit)
            waiting = np.asarray(waiting)
        else:
            _, assignments = result
            commit = np.asarray(assignments) >= 0
            waiting = np.zeros_like(commit)
        assignments = np.asarray(assignments)
        return ScheduleResult(
            assignments={
                uid: (node_arrays.names[a] if c else None)
                for uid, a, c in zip(pod_arrays.uids, assignments, commit)
            },
            waiting={
                uid: node_arrays.names[a]
                for uid, a, w in zip(pod_arrays.uids, assignments, waiting)
                if w
            },
        )

    def _build_quota_state(self, snapshot, quota_names, quota_index, node_arrays):
        """Lower the (possibly hierarchical) quota tree to a device
        QuotaState.

        Requests are static within a solve, so the exact tree runtime —
        multi-level water-filling included — is computed once on the host
        through GroupQuotaManager (exact-rational mode, matching the
        device arithmetic) and shipped as the precomputed ``runtime``.
        The device then only tracks per-quota ``used`` as pods place.
        """
        from koordinator_tpu.apis.types import resources_to_vector
        from koordinator_tpu.quota.core import GroupQuotaManager

        q = len(quota_names)
        mn = np.zeros((q, NUM_RESOURCES), np.int64)
        mx = np.zeros((q, NUM_RESOURCES), np.int64)
        guar = np.zeros((q, NUM_RESOURCES), np.int64)
        weight = np.zeros((q, NUM_RESOURCES), np.int64)
        allow = np.ones(q, bool)
        child_request = np.zeros((q, NUM_RESOURCES), np.int64)
        used = np.zeros((q, NUM_RESOURCES), np.int64)
        for name, i in quota_index.items():
            spec = snapshot.quotas[name]
            mn[i] = resources_to_vector(spec.min)
            mx[i] = resources_to_vector(spec.max)
            guar[i] = resources_to_vector(spec.guaranteed)
            weight[i] = (
                resources_to_vector(spec.shared_weight)
                if spec.shared_weight is not None
                else mx[i]
            )
            allow[i] = spec.allow_lent_resource
        for pod in list(snapshot.pending_pods) + list(snapshot.pods):
            if pod.quota in quota_index:
                i = quota_index[pod.quota]
                vec = resources_to_vector(pod.requests)
                child_request[i] += vec
                if pod.node_name is not None:
                    used[i] += vec

        total = node_arrays.alloc.astype(np.int64).sum(axis=0)
        mgr = GroupQuotaManager(exact_rational=True)
        mgr.cluster_total = total.copy()
        for name in quota_names:
            mgr.update_quota(snapshot.quotas[name])
        for name, i in quota_index.items():
            if child_request[i].any():
                mgr.add_request(name, child_request[i])
        runtime = np.zeros((q, NUM_RESOURCES), np.int64)
        for name, i in quota_index.items():
            rt = mgr.refresh_runtime(name)
            runtime[i] = rt if rt is not None else 0

        return QuotaState.build(
            min=mn,
            max=mx,
            guarantee=guar,
            weight=weight,
            allow_lent=allow,
            child_request=child_request,
            used=used,
            total=total,
            runtime=runtime,
        )
