"""PlacementModel: the flagship batched placement solver.

Wraps the scan-based solver (ops/binpack.py) with host↔device staging and
typed in/out: takes a ``ClusterSnapshot``, returns pod→node assignments
with semantics identical to running the reference's Filter→Score→Reserve
cycle pod-by-pod (differentially tested against the oracle).

The node axis is shardable over a ``jax.sharding.Mesh`` (see
``koordinator_tpu.parallel``): scores are computed on node shards and the
argmax reduction rides ICI collectives inserted by GSPMD.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
from koordinator_tpu.apis.types import ClusterSnapshot
from koordinator_tpu.ops.binpack import (
    NodeState,
    PodBatch,
    ScoreParams,
    SolverConfig,
    schedule_batch,
)
from koordinator_tpu.state.cluster import (
    DEFAULT_ESTIMATED_SCALING_FACTORS,
    DEFAULT_RESOURCE_WEIGHTS,
    DEFAULT_USAGE_THRESHOLDS,
    NodeArrays,
    PendingPodArrays,
    lower_nodes,
    lower_pending_pods,
)


def _vec(mapping, dtype=np.int32) -> np.ndarray:
    out = np.zeros(NUM_RESOURCES, dtype=dtype)
    for k, v in mapping.items():
        out[int(k)] = v
    return out


class PlacementModel:
    """Compiled batched placement over a (possibly sharded) node axis."""

    def __init__(
        self,
        config: SolverConfig = SolverConfig(),
        resource_weights=None,
        usage_thresholds=None,
        prod_usage_thresholds=None,
        scaling_factors=None,
        sharding: Optional[jax.sharding.Sharding] = None,
    ):
        self.config = config
        self.resource_weights = dict(resource_weights or DEFAULT_RESOURCE_WEIGHTS)
        self.scaling_factors = dict(
            scaling_factors or DEFAULT_ESTIMATED_SCALING_FACTORS
        )
        self.params = ScoreParams(
            weights=jnp.asarray(_vec(self.resource_weights)),
            thresholds=jnp.asarray(_vec(usage_thresholds or DEFAULT_USAGE_THRESHOLDS)),
            prod_thresholds=jnp.asarray(_vec(prod_usage_thresholds or {})),
        )
        self.sharding = sharding
        self._solve = jax.jit(schedule_batch, static_argnames=("config",))

    # -- staging ------------------------------------------------------------

    def stage_nodes(self, arrays: NodeArrays) -> NodeState:
        """Stage host node arrays onto devices (sharded if configured)."""
        put = (
            (lambda x: jax.device_put(x, self.sharding))
            if self.sharding is not None
            else jnp.asarray
        )
        return NodeState(
            alloc=put(arrays.alloc),
            used_req=put(arrays.used_req),
            usage=put(arrays.usage),
            prod_usage=put(arrays.prod_usage),
            est_extra=put(arrays.est_extra),
            prod_base=put(arrays.prod_base),
            metric_fresh=put(arrays.metric_fresh),
            schedulable=put(arrays.schedulable),
        )

    @staticmethod
    def stage_pods(arrays: PendingPodArrays) -> PodBatch:
        return PodBatch.build(
            req=jnp.asarray(arrays.req),
            est=jnp.asarray(arrays.est),
            is_prod=jnp.asarray(arrays.is_prod),
            is_daemonset=jnp.asarray(arrays.is_daemonset),
            quota_id=jnp.asarray(arrays.quota_id),
            non_preemptible=jnp.asarray(arrays.non_preemptible),
        )

    # -- solve --------------------------------------------------------------

    def solve(self, state: NodeState, pods: PodBatch):
        """Jitted solve on staged arrays; returns (new_state, assignments)."""
        return self._solve(state, pods, self.params, self.config)

    def schedule(self, snapshot: ClusterSnapshot) -> Dict[str, Optional[str]]:
        """Typed end-to-end: snapshot → {pod uid: node name or None}."""
        node_arrays = lower_nodes(
            snapshot,
            scaling_factors=self.scaling_factors,
            resource_weights=self.resource_weights,
        )
        pod_arrays = lower_pending_pods(
            snapshot.pending_pods,
            scaling_factors=self.scaling_factors,
            resource_weights=self.resource_weights,
        )
        state = self.stage_nodes(node_arrays)
        batch = self.stage_pods(pod_arrays)
        _, assignments = self.solve(state, batch)
        assignments = np.asarray(assignments)
        return {
            uid: (node_arrays.names[a] if a >= 0 else None)
            for uid, a in zip(pod_arrays.uids, assignments)
        }
