"""End-to-end solver pipelines — the framework's "flagship models".

A *model* here is a compiled, device-resident decision program over cluster
state: placement (the scheduler's inner loop), rebalance (the descheduler's
loop). Each model owns its jitted computation and the host↔device staging.
"""

from koordinator_tpu.models.placement import PlacementModel  # noqa: F401
