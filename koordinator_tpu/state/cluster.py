"""Lower typed cluster snapshots onto the dense array substrate.

The TPU-first design stance (SURVEY.md §7): represent cluster state as dense
integer arrays — ``node_alloc[N,R]``, ``node_used[N,R]``, ``pod_req[P,R]``,
QoS/priority/quota/gang id vectors — so the scheduler's Filter/Score/bin-pack
inner loop is batched vector math instead of per-node Go callbacks.

Lowering runs host-side in exact integer arithmetic (Python ints ==
reference's int64). Everything numeric here is *canonical units*
(cpu=millicores, memory=MiB; apis/extension.py).

Reference semantics implemented here:
- pod usage estimator: pkg/scheduler/plugins/loadaware/estimator/
  default_estimator.go:57-110 (estimatedUsedByResource)
- assigned-pod estimation staleness rules: pkg/scheduler/plugins/loadaware/
  load_aware.go:337-376 (estimatedAssignedPodUsed)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

from koordinator_tpu.apis.extension import (
    NUM_RESOURCES,
    PriorityClass,
    ResourceName,
)
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    NodeMetric,
    PodSpec,
    resources_to_vector,
)

# Defaults matching the reference scheduler config
# (pkg/scheduler/apis/config/v1beta2/defaults.go:33-48).
DEFAULT_NODE_METRIC_EXPIRATION_SECONDS = 180.0
DEFAULT_RESOURCE_WEIGHTS = {ResourceName.CPU: 1, ResourceName.MEMORY: 1}
DEFAULT_USAGE_THRESHOLDS = {ResourceName.CPU: 65, ResourceName.MEMORY: 95}
DEFAULT_ESTIMATED_SCALING_FACTORS = {ResourceName.CPU: 85, ResourceName.MEMORY: 70}
# estimator zero-request defaults (default_estimator.go:36-39), canonical units
DEFAULT_MILLI_CPU_REQUEST = 250
DEFAULT_MEMORY_REQUEST_MIB = 200  # 200 * 1024 * 1024 bytes == 200 MiB


def go_round(x: float) -> int:
    """``math.Round`` semantics (half away from zero) for non-negative x."""
    return int(math.floor(x + 0.5))


@dataclasses.dataclass
class AggregatedArgs:
    """LoadAware aggregated-usage (percentile) mode configuration.

    Mirrors the reference's ``LoadAwareSchedulingAggregatedArgs``
    (pkg/scheduler/apis/config/types.go): the Filter substitutes a
    percentile usage + its own threshold set when ``usage_thresholds``
    and ``usage_pct`` are both set (helper.go:92 filterWithAggregation);
    the Score substitutes the percentile base when ``score_pct`` is set
    (helper.go:96 scoreWithAggregation). Durations select the aggregation
    window; None/0 means "the largest reported window" (helper.go:65).
    Percentiles are 50/90/95/99 keys into NodeMetric.aggregated_usage.
    """

    usage_thresholds: Optional[Dict] = None  # filter thresholds (agg set)
    usage_pct: Optional[int] = None          # filter aggregation percentile
    usage_duration_seconds: Optional[float] = None
    score_pct: Optional[int] = None          # score aggregation percentile
    score_duration_seconds: Optional[float] = None

    #: percentiles the NodeMetric reporter publishes (a typo'd percentile
    #: would otherwise silently disable the check on every node)
    VALID_PCTS = (50, 90, 95, 99)

    def __post_init__(self):
        for pct in (self.usage_pct, self.score_pct):
            if pct is not None and pct not in self.VALID_PCTS:
                raise ValueError(
                    f"aggregation percentile {pct} not reported; "
                    f"valid: {self.VALID_PCTS}"
                )

    @property
    def filter_enabled(self) -> bool:
        return bool(self.usage_thresholds) and self.usage_pct is not None

    @property
    def score_enabled(self) -> bool:
        return self.score_pct is not None


def target_aggregated_usage(
    metric: NodeMetric, duration_seconds: Optional[float], pct: Optional[int]
):
    """The percentile usage map for the requested window, or None.

    Reference: loadaware/helper.go:58-90 getTargetAggregatedUsage — no
    aggregated usages reported → None; no duration requested → the
    LARGEST reported window; a requested duration must match a reported
    window exactly. Windows are the primary ``aggregated_usage`` (at
    ``aggregated_duration``) plus the extra ``aggregated_windows``.
    """
    if pct is None:
        return None
    # (duration, by_pct) candidates: the primary window (duration may be
    # unreported -> treated as 0 for the max-window default) + extras
    candidates = []
    if metric.aggregated_usage:
        candidates.append(
            (metric.aggregated_duration or 0.0, metric.aggregated_usage)
        )
    candidates += [
        (dur, by_pct)
        for dur, by_pct in metric.aggregated_windows.items()
        if by_pct
    ]
    if not candidates:
        return None
    if duration_seconds:
        for dur, by_pct in candidates:
            if dur == duration_seconds:
                return by_pct.get(pct) or None
        return None
    _, by_pct = max(candidates, key=lambda t: t[0])
    return by_pct.get(pct) or None


def translate_resource_by_priority(
    resource: ResourceName, priority_class: PriorityClass
) -> ResourceName:
    """Map a native resource to the extended resource a pod of the given
    priority class actually requests (reference: apis/extension/resource.go
    TranslateResourceNameByPriorityClass)."""
    if priority_class == PriorityClass.BATCH:
        if resource == ResourceName.CPU:
            return ResourceName.BATCH_CPU
        if resource == ResourceName.MEMORY:
            return ResourceName.BATCH_MEMORY
    elif priority_class == PriorityClass.MID:
        if resource == ResourceName.CPU:
            return ResourceName.MID_CPU
        if resource == ResourceName.MEMORY:
            return ResourceName.MID_MEMORY
    return resource


def estimate_pod_used(
    pod: PodSpec,
    scaling_factors: Optional[Mapping[ResourceName, int]] = None,
    resource_weights: Optional[Mapping[ResourceName, int]] = None,
) -> Dict[ResourceName, int]:
    """Estimated usage of a pod, bit-exact with the reference estimator.

    Reference: default_estimator.go:63-110. For each weighted resource:
    use limit if limit > request (scaling factor forced to 100) else the
    request; zero quantity falls back to 250 mCPU / 200 MiB; the estimate is
    ``round(quantity * factor / 100)`` capped at the limit. Batch/Mid pods
    read their translated extended-resource quantities.
    """
    scaling_factors = scaling_factors or DEFAULT_ESTIMATED_SCALING_FACTORS
    resource_weights = resource_weights or DEFAULT_RESOURCE_WEIGHTS
    out: Dict[ResourceName, int] = {}
    for resource in resource_weights:
        real = translate_resource_by_priority(resource, pod.priority_class)
        req = int(pod.requests.get(real, 0))
        lim = int(pod.limits.get(real, 0))
        factor = int(scaling_factors.get(resource, 100))
        if lim > req:
            factor, quantity = 100, lim
        else:
            quantity = req
        if quantity == 0:
            if real in (ResourceName.CPU, ResourceName.BATCH_CPU, ResourceName.MID_CPU):
                out[resource] = DEFAULT_MILLI_CPU_REQUEST
            elif real in (
                ResourceName.MEMORY,
                ResourceName.BATCH_MEMORY,
                ResourceName.MID_MEMORY,
            ):
                out[resource] = DEFAULT_MEMORY_REQUEST_MIB
            else:
                out[resource] = 0
            continue
        estimated = go_round(quantity * factor / 100)
        if lim > 0 and estimated > lim:
            estimated = lim
        out[resource] = estimated
    return out


@dataclasses.dataclass
class NodeArrays:
    """Dense node-side state, host (numpy) resident until staged.

    All ``[N, R]`` arrays are int32 canonical units; masks are bool ``[N]``.
    """

    names: List[str]
    alloc: np.ndarray          # [N,R] allocatable
    used_req: np.ndarray       # [N,R] sum of assigned pod *requests* (Fit path)
    usage: np.ndarray          # [N,R] reported real usage (NodeMetric)
    prod_usage: np.ndarray     # [N,R] Σ reported usage of assigned prod pods
    est_extra: np.ndarray      # [N,R] assigned-pod estimation correction (see below)
    prod_base: np.ndarray      # [N,R] prod-mode score base (see lower_nodes)
    metric_fresh: np.ndarray   # [N] bool: NodeMetric exists and not expired
    schedulable: np.ndarray    # [N] bool
    #: [N] float64 metric update times (-inf = no metric) — host-only,
    #: never staged; lets the delta path recompute ``metric_fresh`` for
    #: every node as ``snapshot.now`` advances without touching rows
    metric_update_time: Optional[np.ndarray] = None

    @property
    def n(self) -> int:
        return len(self.names)

    def index(self) -> Dict[str, int]:
        return {name: i for i, name in enumerate(self.names)}


class ClusterDeltaTracker:
    """Event-driven dirty-node accounting for incremental lowering.

    Producers of a :class:`ClusterSnapshot` (the scheduler cache, a
    bench mutation driver) mark the node rows their mutations touch;
    a staging cache (models/placement.StagedStateCache) then re-lowers
    only those rows instead of the world. This is the snapshot-diff
    idiom of the reference's informer/cache layer: the event stream,
    not a full relist, drives what gets recomputed.

    Marks are kept as ``name -> epoch`` so multiple consumers can each
    diff against their own last-seen epoch; entries are bounded by the
    number of distinct node names and reset on structure changes.
    Anything that changes the node SET or its order (add/remove/rename)
    must call :meth:`mark_structure` — consumers then fall back to a
    full relower. Attach to a snapshot via ``snapshot.delta_tracker``.
    """

    def __init__(self) -> None:
        import threading

        self.epoch = 0            # monotonically increasing mark clock
        self.structure_epoch = 0  # last epoch the node set/order changed
        self._marks: Dict[str, int] = {}
        # markers run on different threads (informers under the cache
        # lock, plugin Reserve/Unreserve and the model epilogue without
        # it); an unlocked `epoch += 1` could let two racing marks share
        # one epoch and a mark land at an epoch <= the snapshot's
        # captured sync point — lost forever to `dirty_since`
        self._lock = threading.Lock()

    def mark_node(self, name: Optional[str]) -> None:
        """Node ``name``'s lowered row may have changed (pod assigned or
        removed, metric update, reservation hold change, spec change)."""
        if name is None:
            return
        with self._lock:
            self.epoch += 1
            self._marks[name] = self.epoch

    def mark_nodes(self, names) -> None:
        for name in names:
            self.mark_node(name)

    def mark_structure(self) -> None:
        """The node set or its order changed: delta consumers must fall
        back to a full relower (their row indices are stale)."""
        with self._lock:
            self.epoch += 1
            self.structure_epoch = self.epoch
            self._marks.clear()

    def dirty_since(self, epoch: int) -> List[str]:
        """Node names marked after ``epoch`` (consumer's last sync)."""
        with self._lock:
            return [
                name for name, at in self._marks.items() if at > epoch
            ]


@dataclasses.dataclass
class PendingPodArrays:
    """Dense pending-pod state in schedule order (priority desc, FIFO)."""

    uids: List[str]
    req: np.ndarray        # [P,R] requests
    est: np.ndarray        # [P,R] estimator output (loadaware score path)
    qos: np.ndarray        # [P] int8 QoSClass
    prio_class: np.ndarray  # [P] int8 PriorityClass
    priority: np.ndarray   # [P] int32 numeric priority
    is_prod: np.ndarray    # [P] bool
    is_daemonset: np.ndarray  # [P] bool
    non_preemptible: np.ndarray  # [P] bool
    quota_id: np.ndarray   # [P] int32, -1 if none
    gang_id: np.ndarray    # [P] int32, -1 if none

    @property
    def p(self) -> int:
        return len(self.uids)


def _clip_i32(a: np.ndarray) -> np.ndarray:
    info = np.iinfo(np.int32)
    return np.clip(a, info.min, info.max).astype(np.int32)


def _metric_fresh(now, update_time, metric_expiration_seconds):
    """The metric-expiration verdict, shared per-row helper style:
    scalar in :func:`_node_metric_row`, vectorized (numpy broadcasting
    over the cached ``metric_update_time`` column) in
    :func:`lower_nodes_delta`'s freshness-drift recompute. One
    definition means the full and delta paths can never disagree on
    what "fresh" means (graftcheck's delta-parity rule pins both paths
    to this registry)."""
    return (now - update_time) < metric_expiration_seconds


def _node_metric_row(
    metric: NodeMetric,
    assigned,
    *,
    now: float,
    metric_expiration_seconds: float,
    scaling_factors,
    resource_weights,
    aggregated: Optional[AggregatedArgs],
):
    """The metric-derived columns for ONE node: ``(usage, prod_usage,
    est_extra, prod_base, metric_fresh)`` as int64 vectors + bool.

    Shared by the full (:func:`lower_nodes`) and incremental
    (:func:`lower_nodes_delta`) lowerings so the two are bit-identical
    by construction — the delta path re-runs exactly this computation
    for dirty rows. ``assigned`` is the node's assigned pods in snapshot
    order (the order fixes the int64 accumulation sequence)."""
    agg_filter = aggregated is not None and aggregated.filter_enabled
    agg_score = aggregated is not None and aggregated.score_enabled
    prod_usage = np.zeros(NUM_RESOURCES, dtype=np.int64)
    prod_base = np.zeros(NUM_RESOURCES, dtype=np.int64)
    avg_vec = resources_to_vector(metric.node_usage)
    # Aggregated (percentile) mode folds into the array substrate at
    # lowering: the filter reads ``usage`` directly, so ``usage``
    # stores the filter-mode base (percentile when enabled; a missing
    # percentile lowers to zeros == the reference's per-resource skip,
    # load_aware.go:200-209); the score base is usage + est_extra, so
    # the score-mode substitution rides est_extra (exact fold:
    # est_extra += score_base - filter_base). Reference:
    # load_aware.go:157-186 (filter), :310-311 (score).
    filter_vec = avg_vec
    score_vec = avg_vec
    score_agg_nil = False
    if agg_filter:
        # a missing percentile lowers to zeros (resources_to_vector of
        # None) == the reference's per-resource skip
        filter_vec = resources_to_vector(target_aggregated_usage(
            metric, aggregated.usage_duration_seconds, aggregated.usage_pct
        ))
    if agg_score:
        agg = target_aggregated_usage(
            metric, aggregated.score_duration_seconds, aggregated.score_pct
        )
        # nil aggregated score base lowers to zeros: node usage
        # contributes nothing AND every assigned pod becomes
        # estimated (the OR clause at load_aware.go:357-358)
        score_vec = resources_to_vector(agg)
        score_agg_nil = agg is None
    fresh = _metric_fresh(now, metric.update_time, metric_expiration_seconds)
    est_sum = np.zeros(NUM_RESOURCES, dtype=np.int64)
    reported_sum = np.zeros(NUM_RESOURCES, dtype=np.int64)
    for pod in assigned:
        is_prod = pod.priority_class == PriorityClass.PROD
        reported = metric.pod_usages.get(pod.uid)
        rep_vec = resources_to_vector(reported) if reported else None
        if is_prod and rep_vec is not None:
            prod_usage += rep_vec  # prod Filter base
        should_estimate = (
            not reported
            or score_agg_nil
            or pod.assign_time >= metric.update_time
            or (metric.update_time - pod.assign_time) < metric.report_interval
        )
        if not should_estimate:
            # prod score base: non-estimated prod pods contribute their
            # reported usage (sumPodUsages' podUsages term)
            if is_prod and rep_vec is not None:
                prod_base += rep_vec
            continue
        est_vec = resources_to_vector(
            estimate_pod_used(pod, scaling_factors, resource_weights)
        )
        if rep_vec is not None:
            est_vec = np.maximum(est_vec, rep_vec)
            reported_sum += rep_vec
        est_sum += est_vec
        if is_prod:
            prod_base += est_vec
    # subtract reported usage of estimated pods only where the score
    # base covers it (load_aware.go:318-323 quantity.Cmp(q) >= 0
    # guard — against the aggregated base in score-aggregated mode),
    # then fold the score-base substitution into est_extra
    sub = np.where(score_vec >= reported_sum, reported_sum, 0)
    est_extra = (score_vec - filter_vec) + est_sum - sub
    return filter_vec, prod_usage, est_extra, prod_base, fresh


def _node_hold_rows(snapshot: ClusterSnapshot, index: Dict[str, int]):
    """``used_req`` int64 rows + per-node assigned-pod groups for the
    nodes in ``index`` (assigned pod requests + Available reservations'
    unallocated remainder — the net view of the reference's fake
    reserve pod + restore chain, scheduler/plugins/reservation.py).
    Shared by the full and delta lowerings; iteration order over
    ``snapshot.pods`` fixes the accumulation sequence for both."""
    used_req = np.zeros((len(index), NUM_RESOURCES), dtype=np.int64)
    assigned_by_node: Dict[str, List[PodSpec]] = {}
    for pod in snapshot.pods:
        if pod.node_name is None or pod.node_name not in index:
            continue
        used_req[index[pod.node_name]] += resources_to_vector(pod.requests)
        assigned_by_node.setdefault(pod.node_name, []).append(pod)
    for resv in snapshot.reservations:
        if (
            getattr(resv.state, "value", resv.state) == "Available"
            and resv.node_name in index
        ):
            alloc_vec = resources_to_vector(resv.allocatable or resv.requests)
            used_vec = resources_to_vector(resv.allocated)
            used_req[index[resv.node_name]] += np.maximum(
                alloc_vec - used_vec, 0
            )
    return used_req, assigned_by_node


def lower_nodes(
    snapshot: ClusterSnapshot,
    *,
    metric_expiration_seconds: float = DEFAULT_NODE_METRIC_EXPIRATION_SECONDS,
    scaling_factors: Optional[Mapping[ResourceName, int]] = None,
    resource_weights: Optional[Mapping[ResourceName, int]] = None,
    aggregated: Optional[AggregatedArgs] = None,
) -> NodeArrays:
    """Lower nodes + assigned pods + metrics to ``NodeArrays``.

    ``est_extra`` encodes the loadaware assigned-pod estimation correction
    (load_aware.go:299-327): for each node it is
    ``Σ_p max(estimate(p), reported(p))  −  min(Σ_p reported(p), node_usage)``
    over assigned pods p that *should be estimated* — a pod should be
    estimated iff it has no reported usage, its assign time missed the
    latest metric update, or it is still within the report interval.
    The subtraction mirrors the reference's guard: the estimated pods'
    actual usage is only subtracted from node usage when node usage covers
    it (per resource). Non-prod score estimated-used is then
    ``usage + est_extra + estimate(incoming_pod)``.

    Prod mode (ScoreAccordingProdUsage; load_aware.go:294-307 prodPod
    branch) never reads whole-node usage: its base is computed from prod
    pods only, with no node-usage subtraction guard —
    ``prod_base = Σ_{prod, estimated} max(estimate, reported)
               + Σ_{prod, not estimated, reported} reported``
    so prod score estimated-used is ``prod_base + estimate(incoming)``.

    ``prod_usage`` is the prod Filter path's base (load_aware.go:226-255
    filterProdUsage): Σ reported usage over assigned prod pods.
    """
    n = len(snapshot.nodes)
    names = [node.name for node in snapshot.nodes]
    index = {name: i for i, name in enumerate(names)}
    usage = np.zeros((n, NUM_RESOURCES), dtype=np.int64)
    prod_usage = np.zeros((n, NUM_RESOURCES), dtype=np.int64)
    est_extra = np.zeros((n, NUM_RESOURCES), dtype=np.int64)
    prod_base = np.zeros((n, NUM_RESOURCES), dtype=np.int64)
    metric_fresh = np.zeros(n, dtype=bool)
    schedulable = np.ones(n, dtype=bool)
    metric_update_time = np.full(n, -np.inf)
    alloc = np.zeros((n, NUM_RESOURCES), dtype=np.int64)

    for i, node in enumerate(snapshot.nodes):
        alloc[i] = resources_to_vector(node.allocatable)
        schedulable[i] = not node.unschedulable

    # assigned pod requests + Available reservation holds per node
    used_req, assigned_by_node = _node_hold_rows(snapshot, index)

    # metrics + estimation correction (per-node helper shared with the
    # delta lowering)
    for name, metric in snapshot.node_metrics.items():
        if name not in index:
            continue
        i = index[name]
        metric_update_time[i] = metric.update_time
        (
            usage[i], prod_usage[i], est_extra[i], prod_base[i],
            metric_fresh[i],
        ) = _node_metric_row(
            metric,
            assigned_by_node.get(name, ()),
            now=snapshot.now,
            metric_expiration_seconds=metric_expiration_seconds,
            scaling_factors=scaling_factors,
            resource_weights=resource_weights,
            aggregated=aggregated,
        )

    return NodeArrays(
        names=names,
        alloc=_clip_i32(alloc),
        used_req=_clip_i32(used_req),
        usage=_clip_i32(usage),
        prod_usage=_clip_i32(prod_usage),
        est_extra=_clip_i32(est_extra),
        prod_base=_clip_i32(prod_base),
        metric_fresh=metric_fresh,
        schedulable=schedulable,
        metric_update_time=metric_update_time,
    )


def lower_nodes_delta(
    snapshot: ClusterSnapshot,
    prev: NodeArrays,
    dirty_names,
    *,
    metric_expiration_seconds: float = DEFAULT_NODE_METRIC_EXPIRATION_SECONDS,
    scaling_factors: Optional[Mapping[ResourceName, int]] = None,
    resource_weights: Optional[Mapping[ResourceName, int]] = None,
    aggregated: Optional[AggregatedArgs] = None,
) -> Optional[np.ndarray]:
    """Incrementally re-lower ``prev``'s rows for ``dirty_names`` IN
    PLACE against ``snapshot``, plus any rows whose ``metric_fresh``
    flipped because ``snapshot.now`` advanced past (or back inside) the
    metric expiration window.

    Returns the sorted int32 row indices that were rewritten (possibly
    empty), or ``None`` when the node set/order no longer matches
    ``prev`` — the caller must then fall back to a full
    :func:`lower_nodes`. Dirty rows run through exactly the same
    per-node helpers as the full lowering, so the updated ``prev`` is
    bit-identical to a from-scratch lowering of ``snapshot`` provided
    every mutated node was marked (the :class:`ClusterDeltaTracker`
    contract; property-tested in tests/test_state_delta.py)."""
    if prev.metric_update_time is None:
        return None
    names = [node.name for node in snapshot.nodes]
    if names != prev.names:
        return None
    index = prev.index()
    dirty = {name for name in dirty_names if name in index}

    # freshness drift: ``now`` moved, so recompute every node's
    # expiration verdict from the cached update times (vectorized — no
    # per-node python) and fold flips into the changed-row set
    fresh_now = _metric_fresh(
        snapshot.now, prev.metric_update_time, metric_expiration_seconds
    )
    flipped = np.nonzero(fresh_now != prev.metric_fresh)[0]

    sub_index = {name: k for k, name in enumerate(sorted(dirty))}
    rows = np.fromiter(
        (index[name] for name in sorted(dirty)), dtype=np.int64,
        count=len(sub_index),
    )
    if len(sub_index):
        used_req, assigned_by_node = _node_hold_rows(snapshot, sub_index)
        for name, k in sub_index.items():
            i = index[name]
            node = snapshot.nodes[i]
            prev.alloc[i] = _clip_i32(resources_to_vector(node.allocatable))
            prev.schedulable[i] = not node.unschedulable
            prev.used_req[i] = _clip_i32(used_req[k])
            metric = snapshot.node_metrics.get(name)
            if metric is None:
                prev.metric_update_time[i] = -np.inf
                prev.usage[i] = 0
                prev.prod_usage[i] = 0
                prev.est_extra[i] = 0
                prev.prod_base[i] = 0
                prev.metric_fresh[i] = False
                continue
            prev.metric_update_time[i] = metric.update_time
            u, pu, ee, pb, fresh = _node_metric_row(
                metric,
                assigned_by_node.get(name, ()),
                now=snapshot.now,
                metric_expiration_seconds=metric_expiration_seconds,
                scaling_factors=scaling_factors,
                resource_weights=resource_weights,
                aggregated=aggregated,
            )
            prev.usage[i] = _clip_i32(u)
            prev.prod_usage[i] = _clip_i32(pu)
            prev.est_extra[i] = _clip_i32(ee)
            prev.prod_base[i] = _clip_i32(pb)
            prev.metric_fresh[i] = fresh

    # flips on otherwise-clean rows only touch the freshness mask
    dirty_rows = set(rows.tolist())
    for i in flipped:
        if int(i) not in dirty_rows:
            prev.metric_fresh[i] = fresh_now[i]
            dirty_rows.add(int(i))
    return np.asarray(sorted(dirty_rows), dtype=np.int32)


def lower_node_rows(
    snapshot: ClusterSnapshot,
    names: Sequence[str],
    *,
    metric_expiration_seconds: float = DEFAULT_NODE_METRIC_EXPIRATION_SECONDS,
    scaling_factors: Optional[Mapping[ResourceName, int]] = None,
    resource_weights: Optional[Mapping[ResourceName, int]] = None,
    aggregated: Optional[AggregatedArgs] = None,
) -> Dict[str, np.ndarray]:
    """Freshly lower just ``names``'s rows from typed truth, into new
    buffers: ``{staged field: [K, ...] array}`` aligned to ``names``.

    This is the runtime auditor's parity-probe path
    (scheduler/auditor.py): a bounded sample of rows is re-derived from
    the snapshot each sweep and compared bit-for-bit against the staged
    host/device arrays. Every row value routes through the SAME per-row
    helper registry as :func:`lower_nodes` / :func:`lower_nodes_delta`
    (graftcheck's delta-parity rule pins all three), so a mismatch is
    evidence of staging drift — a missed tracker mark, a corrupted
    staged row — never of the probe computing differently.

    ``names`` must be a subset of the snapshot's node names."""
    sub_index = {name: k for k, name in enumerate(names)}
    k_count = len(sub_index)
    node_by_name = {node.name: node for node in snapshot.nodes}
    alloc = np.zeros((k_count, NUM_RESOURCES), dtype=np.int64)
    usage = np.zeros((k_count, NUM_RESOURCES), dtype=np.int64)
    prod_usage = np.zeros((k_count, NUM_RESOURCES), dtype=np.int64)
    est_extra = np.zeros((k_count, NUM_RESOURCES), dtype=np.int64)
    prod_base = np.zeros((k_count, NUM_RESOURCES), dtype=np.int64)
    metric_fresh = np.zeros(k_count, dtype=bool)
    schedulable = np.ones(k_count, dtype=bool)
    used_req, assigned_by_node = _node_hold_rows(snapshot, sub_index)
    for name, k in sub_index.items():
        node = node_by_name[name]
        alloc[k] = resources_to_vector(node.allocatable)
        schedulable[k] = not node.unschedulable
        metric = snapshot.node_metrics.get(name)
        if metric is None:
            continue
        (
            usage[k], prod_usage[k], est_extra[k], prod_base[k],
            metric_fresh[k],
        ) = _node_metric_row(
            metric,
            assigned_by_node.get(name, ()),
            now=snapshot.now,
            metric_expiration_seconds=metric_expiration_seconds,
            scaling_factors=scaling_factors,
            resource_weights=resource_weights,
            aggregated=aggregated,
        )
    return {
        "alloc": _clip_i32(alloc),
        "used_req": _clip_i32(used_req),
        "usage": _clip_i32(usage),
        "prod_usage": _clip_i32(prod_usage),
        "est_extra": _clip_i32(est_extra),
        "prod_base": _clip_i32(prod_base),
        "metric_fresh": metric_fresh,
        "schedulable": schedulable,
    }


def _pad_width(target: int, n: int) -> int:
    """Rows to append to reach ``target`` (0 when already there) —
    the one arithmetic step of the padding path, kept in a helper so
    :func:`pad_node_rows` stays free of inline value math (the
    delta-parity registry contract)."""
    return max(0, target - n)


def _pad_axis0(a: np.ndarray, pad: int, fill=0) -> np.ndarray:
    """Append ``pad`` rows of ``fill`` along axis 0 (any trailing
    shape). Shared by every padding consumer so a padded row is
    all-``fill`` by construction, never an ad-hoc per-caller fold."""
    widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
    return np.pad(a, widths, constant_values=fill)


def _pad_names(names: List[str], pad: int) -> List[str]:
    """Names for appended padding rows — reserved, never a real node."""
    return names + [f"__pad_{i}__" for i in range(pad)]


def pad_node_rows(arrays: NodeArrays, target: int) -> NodeArrays:
    """``arrays`` grown to ``target`` rows with inert padding nodes —
    the sharded staging path's row source (parallel/mesh.py pads the
    node axis to a per-shard bucket before a mesh ``device_put``).

    Padding rows are unschedulable with zero allocatable and no metric
    (``metric_update_time`` −inf), so they can never win a placement or
    flip ``metric_fresh`` — semantics are unchanged, only the staged
    shape grows. Routed through the same padding helpers graftcheck's
    delta-parity rule pins (``_pad_width``/``_pad_axis0``/
    ``_pad_names``): the padded world stays bit-identical to lowering
    ``target − n`` permanently-empty nodes, and no caller can grow its
    own drifting inline variant. Returns new buffers (``np.pad``
    copies); the caller's in-place delta patching of the ORIGINAL
    arrays is unaffected."""
    pad = _pad_width(target, arrays.n)
    if pad == 0:
        return arrays
    return dataclasses.replace(
        arrays,
        names=_pad_names(arrays.names, pad),
        alloc=_pad_axis0(arrays.alloc, pad),
        used_req=_pad_axis0(arrays.used_req, pad),
        usage=_pad_axis0(arrays.usage, pad),
        prod_usage=_pad_axis0(arrays.prod_usage, pad),
        est_extra=_pad_axis0(arrays.est_extra, pad),
        prod_base=_pad_axis0(arrays.prod_base, pad),
        metric_fresh=_pad_axis0(arrays.metric_fresh, pad, fill=False),
        schedulable=_pad_axis0(arrays.schedulable, pad, fill=False),
        metric_update_time=(
            _pad_axis0(arrays.metric_update_time, pad, fill=-np.inf)
            if arrays.metric_update_time is not None else None
        ),
    )


def schedule_order(pods: Sequence[PodSpec]) -> List[int]:
    """Order pending pods the way the scheduler queue would: numeric
    priority descending, then sub-priority descending, then FIFO."""
    return sorted(
        range(len(pods)),
        key=lambda i: (-pods[i].priority, -pods[i].sub_priority, i),
    )


def lower_pending_pods(
    pods: Sequence[PodSpec],
    *,
    quota_index: Optional[Mapping[str, int]] = None,
    gang_index: Optional[Mapping[str, int]] = None,
    scaling_factors: Optional[Mapping[ResourceName, int]] = None,
    resource_weights: Optional[Mapping[ResourceName, int]] = None,
    in_schedule_order: bool = True,
) -> PendingPodArrays:
    """Lower pending pods to ``PendingPodArrays`` (schedule order by default)."""
    order = schedule_order(pods) if in_schedule_order else list(range(len(pods)))
    pods = [pods[i] for i in order]
    p = len(pods)
    req = np.zeros((p, NUM_RESOURCES), dtype=np.int64)
    est = np.zeros((p, NUM_RESOURCES), dtype=np.int64)
    qos = np.zeros(p, dtype=np.int8)
    prio_class = np.zeros(p, dtype=np.int8)
    priority = np.zeros(p, dtype=np.int32)
    is_prod = np.zeros(p, dtype=bool)
    is_daemonset = np.zeros(p, dtype=bool)
    non_preemptible = np.zeros(p, dtype=bool)
    quota_id = np.full(p, -1, dtype=np.int32)
    gang_id = np.full(p, -1, dtype=np.int32)
    for i, pod in enumerate(pods):
        req[i] = resources_to_vector(pod.requests)
        est[i] = resources_to_vector(
            estimate_pod_used(pod, scaling_factors, resource_weights)
        )
        qos[i] = int(pod.qos)
        prio_class[i] = int(pod.priority_class)
        priority[i] = pod.priority
        is_prod[i] = pod.priority_class == PriorityClass.PROD
        is_daemonset[i] = pod.is_daemonset
        non_preemptible[i] = not pod.preemptible
        if quota_index and pod.quota is not None:
            quota_id[i] = quota_index.get(pod.quota, -1)
        if gang_index and pod.gang is not None:
            gang_id[i] = gang_index.get(pod.gang, -1)
    return PendingPodArrays(
        uids=[pod.uid for pod in pods],
        req=_clip_i32(req),
        est=_clip_i32(est),
        qos=qos,
        prio_class=prio_class,
        priority=priority,
        is_prod=is_prod,
        is_daemonset=is_daemonset,
        non_preemptible=non_preemptible,
        quota_id=quota_id,
        gang_id=gang_id,
    )


# -- resident-pod world (the joint place+evict solve's victim side) ----------


@dataclasses.dataclass
class ResidentPodArrays:
    """Dense ``[N, P]`` resident-pod world for the device victim
    selection (ops/preempt.py), pre-sorted per node in the oracle's
    importance order (priority desc, then earlier assignment —
    scheduler/preemption._more_important), so a victim mask read along
    the P axis IS the oracle's ordered victim list.

    ``quota_ids`` maps quota-group names (``""`` = no quota) to the
    int32 ids in ``quota_id``; a preemptor's own id resolves through
    :meth:`quota_id_of` — an unseen group matches no resident, exactly
    like the oracle's string comparison. ``node_rank`` is the host
    oracle's node ITERATION order (first appearance of each
    ``node_name`` in ``snapshot.pods`` — the ``by_node`` dict order
    ``find_preemption`` walks), the final ranking tiebreak."""

    uids: List[List[str]]      # [N][<=P] resident uids, importance order
    req: np.ndarray            # [N,P,R] int32 requests
    priority: np.ndarray       # [N,P] int32
    quota_id: np.ndarray       # [N,P] int32
    preemptible: np.ndarray    # [N,P] bool
    valid: np.ndarray          # [N,P] bool (False = padding or evicted)
    node_rank: np.ndarray      # [N] int32
    quota_ids: Dict[str, int]  # quota name ("" = none) -> id
    max_residents: int         # real P before bucket padding

    @property
    def n(self) -> int:
        return self.req.shape[0]

    @property
    def p(self) -> int:
        return self.req.shape[1]

    def quota_id_of(self, quota: Optional[str]) -> int:
        """The preemptor-side id for ``quota`` — ``-2`` (matching no
        resident; padding is ``-3``) when no resident carries it."""
        return self.quota_ids.get(quota or "", -2)

    def columns_of(self, node_index: int, uids) -> List[int]:
        """P-axis columns of ``uids`` on ``node_index`` (host map-back
        for eviction application)."""
        wanted = set(uids)
        return [
            j for j, uid in enumerate(self.uids[node_index])
            if uid in wanted
        ]


def lower_resident_pods(
    snapshot: ClusterSnapshot,
    arrays: NodeArrays,
    *,
    victim_bucket=None,
) -> ResidentPodArrays:
    """Lower the assigned-pod world to :class:`ResidentPodArrays`.

    ``victim_bucket`` (e.g. ``PlacementModel.victim_bucket``) pads the
    P axis to a shape bucket so resident counts drifting by ones reuse
    one compiled victim-selection program; padding columns are
    ``valid=False`` and can never be candidates, so results are
    identical (the solver padding contract, docs/DESIGN.md §23)."""
    index = arrays.index()
    by_node: Dict[int, List[PodSpec]] = {}
    node_rank = np.full(arrays.n, np.iinfo(np.int32).max, dtype=np.int32)
    rank = 0
    for pod in snapshot.pods:
        if pod.node_name is None:
            continue
        i = index.get(pod.node_name)
        if i is None:
            continue
        if node_rank[i] == np.iinfo(np.int32).max:
            node_rank[i] = rank
            rank += 1
        by_node.setdefault(i, []).append(pod)

    quota_ids: Dict[str, int] = {}
    for pods in by_node.values():
        # stable sort on the oracle's importance key
        pods.sort(key=lambda p: (-p.priority, p.assign_time))
        for pod in pods:
            quota_ids.setdefault(pod.quota or "", len(quota_ids))

    max_residents = max((len(v) for v in by_node.values()), default=0)
    p = victim_bucket(max_residents) if victim_bucket else max_residents
    p = max(p, 1)  # a zero-width axis would degenerate the scan
    n = arrays.n
    req = np.zeros((n, p, NUM_RESOURCES), dtype=np.int64)
    priority = np.zeros((n, p), dtype=np.int32)
    quota_id = np.full((n, p), -3, dtype=np.int32)
    preemptible = np.zeros((n, p), dtype=bool)
    valid = np.zeros((n, p), dtype=bool)
    uids: List[List[str]] = [[] for _ in range(n)]
    for i, pods in by_node.items():
        uids[i] = [pod.uid for pod in pods]
        for j, pod in enumerate(pods):
            req[i, j] = resources_to_vector(pod.requests)
            priority[i, j] = pod.priority
            quota_id[i, j] = quota_ids[pod.quota or ""]
            preemptible[i, j] = pod.preemptible
            valid[i, j] = True
    return ResidentPodArrays(
        uids=uids,
        req=_clip_i32(req),
        priority=priority,
        quota_id=quota_id,
        preemptible=preemptible,
        valid=valid,
        node_rank=node_rank,
        quota_ids=quota_ids,
        max_residents=max_residents,
    )


def evict_resident_rows(
    snapshot: ClusterSnapshot,
    arrays: NodeArrays,
    resident: ResidentPodArrays,
    node_name: str,
    victim_uids,
    **lowering_kwargs,
) -> Optional[np.ndarray]:
    """Apply an eviction delta: victims leave ``snapshot.pods``, the
    touched node row re-lowers IN PLACE through the same per-row
    helpers as the full lowering (:func:`lower_nodes_delta` — the
    delta-parity contract), the resident columns invalidate, and the
    snapshot's delta tracker is marked so the staged device world
    scatters the row out exactly the way placed rows scatter in
    (models/placement.StagedStateCache).

    Returns the rewritten row indices (``None`` = structure drift, the
    caller must full-relower). The in-place update is bit-identical to
    re-lowering the filtered snapshot from scratch: request sums are
    integer arithmetic and the metric row re-derives from the reduced
    assigned set."""
    wanted = set(victim_uids)
    snapshot.pods = [pod for pod in snapshot.pods if pod.uid not in wanted]
    index = arrays.index()
    i = index.get(node_name)
    if i is not None:
        for j, uid in enumerate(resident.uids[i]):
            if uid in wanted and j < resident.p:
                resident.valid[i, j] = False
    tracker = getattr(snapshot, "delta_tracker", None)
    if tracker is not None:
        tracker.mark_node(node_name)
    return lower_nodes_delta(
        snapshot, arrays, [node_name], **lowering_kwargs
    )
