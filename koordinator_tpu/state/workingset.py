"""HBM working-set manager: graceful degradation for staged worlds.

The multi-tenant pool (docs/DESIGN.md §20) keeps every tenant's staged
``[N*,R]`` device world alive between solves — the right call while HBM
is plentiful, and the open scale limiter when it is not: K tenants is K
staged worlds, and nothing governed who stays resident. This module is
that governor. Every staged world (the in-process
:class:`models.placement.StagedStateCache`, the sidecar's
per-(connection, tenant) ``NodeStateCache``) registers here under one
process-wide byte budget (``--hbm-budget-bytes``), priced by the same
metadata-summed ``device_bytes()`` accounting the device observatory's
live-buffer attribution uses, and a three-rung residency ladder governs
device memory the way the warm pool governs programs:

- **device** — fully staged; solves run against the live generation.
- **host** — the device half is dropped, the host arrays (and the
  delta-protocol epoch) are kept: the next solve re-uploads through the
  EXISTING staging path, bit-identical, no re-lower, no epoch movement.
- **cold** — the host arrays are dropped too; the next solve re-lowers
  from typed truth (``state.cluster.lower_nodes`` in-process; the typed
  ``delta-base-mismatch`` → re-establish handshake over the wire).

Demotion is *policy, never a crash* (the Koordinator QoS thesis mapped
onto memory): victims are chosen best-effort-lane first, then lightest
``TenantRegistry`` weight, then least-recently-used — and a world whose
owner is mid-solve is simply skipped (demotion uses non-blocking lock
acquisition, which doubles as "never victimize an in-flight solve").
Admission of a new world — or a growth re-bucket — demotes victims
instead of allocating past the line. A real or injected allocation
failure (``RESOURCE_EXHAUSTED`` caught at the stage/scatter boundary by
:meth:`WorkingSetManager.run_staged`) triggers the same demotion plus a
bounded retry ladder; every outcome is typed and counted
(``scheduler_workingset_*`` in metrics/components.py), and placements
are bit-identical at every rung BY CONSTRUCTION — each rung re-enters a
staging path the delta-parity tests already pin.

Determinism: the manager keeps a logical clock (a counter bumped per
touch), not wall time — victim order is a pure function of the
registration/touch history, so chaos runs replay exactly.
"""

from __future__ import annotations

import threading
import weakref
from collections import deque
from typing import Callable, Dict, Optional

from koordinator_tpu.metrics.components import (
    HBM_BUDGET_BYTES,
    HBM_USED_BYTES,
    TENANT_RESIDENCY,
    WORKINGSET_ALLOC_FAILURES,
    WORKINGSET_DEMOTIONS,
    WORKINGSET_RESTAGES,
)

RUNG_DEVICE = "device"
RUNG_HOST = "host"
RUNG_COLD = "cold"
RUNGS = (RUNG_DEVICE, RUNG_HOST, RUNG_COLD)

#: victim precedence per QoS lane — best-effort worlds demote first,
#: system worlds (the scheduler's own staged cluster) demote last,
#: mirroring the admission gate's shed policy in reverse
_LANE_DEMOTE_RANK = {"be": 0, "ls": 1, "system": 2}

#: alloc-failure boundaries — the ``reason`` label domain of
#: ``scheduler_workingset_alloc_failures_total``
FAIL_STAGE = "stage"
FAIL_SCATTER = "scatter"
FAIL_WHERE = (FAIL_STAGE, FAIL_SCATTER)


class WorkingSetError(RuntimeError):
    """Base of the typed working-set failure family."""


class InjectedAllocFailure(WorkingSetError):
    """A chaos-armed allocation failure, raised at the same boundary a
    real ``RESOURCE_EXHAUSTED`` surfaces (before the staging callable
    runs, so a retry after demotion re-executes it exactly once)."""


class WorkingSetExhausted(WorkingSetError):
    """The bounded demote+retry ladder ran out: allocation still fails
    with nothing left to demote. Callers surface this as a typed error
    response (the sidecar's never-crash boundary) — a solve may fail
    loudly under true exhaustion, it may never be dropped silently."""


def is_alloc_failure(exc: BaseException) -> bool:
    """Whether ``exc`` is a device allocation failure the demote+retry
    ladder should absorb: the chaos-injected kind, or a runtime error
    whose message carries the XLA out-of-memory vocabulary."""
    if isinstance(exc, InjectedAllocFailure):
        return True
    text = f"{type(exc).__name__}: {exc}"
    return "RESOURCE_EXHAUSTED" in text or "Out of memory" in text \
        or "out of memory" in text


class _Resident:
    """One registered staged world (bookkeeping only — the world itself
    is held by weakref so an abandoned cache can never be kept alive,
    or demoted, by its accounting entry)."""

    __slots__ = ("key", "ref", "tenant", "lane", "weight", "rung",
                 "bytes", "last_use")

    def __init__(self, key: str, obj, tenant: str, lane: str,
                 weight: float):
        self.key = key
        self.ref = weakref.ref(obj)
        self.tenant = tenant
        self.lane = lane if lane in _LANE_DEMOTE_RANK else "ls"
        self.weight = float(weight)
        self.rung = RUNG_DEVICE
        self.bytes = 0
        self.last_use = 0

    def order_key(self):
        # demote best-effort first, then lightest weight, then LRU;
        # the key breaks exact ties deterministically
        return (_LANE_DEMOTE_RANK[self.lane], self.weight,
                self.last_use, self.key)


class WorkingSetManager:
    """The process-wide residency ledger and demotion engine.

    Lock shape (graftcheck-mapped): every mutable attribute below is
    guarded by ``_lock``, and the manager NEVER holds ``_lock`` while
    calling into a resident — victim lists are collected under the
    lock, the residents' ``demote_device()``/``demote_cold()`` hooks
    (which take their OWN locks, non-blocking) run outside it, and the
    accounting is re-entered afterwards. A resident calling back into
    the manager while holding its own lock (``touch`` from inside
    ``StagedStateCache.ensure``) therefore cannot deadlock: the only
    cross-object acquisition order is resident → manager."""

    def __init__(self, budget_bytes: Optional[int] = None, *,
                 max_alloc_retries: int = 4):
        self._lock = threading.Lock()
        self._residents: Dict[str, _Resident] = {}
        self._budget: Optional[int] = None
        self._squeeze: float = 1.0
        self._clock = 0
        self._auto = 0
        self._seq = 0
        self._events: deque = deque(maxlen=64)
        self._counts: Dict[str, Dict[str, int]] = {
            "demotions": {}, "restages": {}, "alloc_failures": {},
        }
        self._faults: Dict[str, int] = {}
        self._oversubscribed = 0
        self.max_alloc_retries = int(max_alloc_retries)
        #: migration-arbiter notification hook, ``(key, lane, reason)``
        #: (set by the control-plane builder to ``arbiter.note`` —
        #: docs/DESIGN.md §27). Demotions are UNDEFERRABLE — they are
        #: the memory-pressure safety valve, so they are recorded and
        #: counted against the disruption windows but never refused.
        #: Called with no lock held (beside the demotion counter).
        self.migration_hook: Optional[Callable[[str, str, str], None]] = None
        if budget_bytes:
            self.set_budget(budget_bytes)

    # -- registration --------------------------------------------------------

    def register(self, key: str, obj, *, tenant: str = "default",
                 lane: str = "ls", weight: float = 1.0) -> str:
        """Track ``obj`` (anything with ``device_bytes()`` /
        ``demote_device()`` / ``demote_cold()``) under ``key``. A new
        world starts on the device rung with 0 priced bytes — its first
        :meth:`touch` prices it and enforces the budget."""
        with self._lock:
            self._residents[key] = _Resident(key, obj, tenant, lane,
                                             weight)
        self._publish()
        return key

    def register_auto(self, prefix: str, obj, **kw) -> str:
        """Register under a generated ``prefix-N`` key (N monotone per
        process — deterministic given construction order)."""
        with self._lock:
            self._auto += 1
            n = self._auto
        return self.register(f"{prefix}-{n}", obj, **kw)

    def drop(self, key: str) -> None:
        """Forget a world (connection closed, cache LRU-evicted). The
        bytes come off the ledger; the arrays die with their owner."""
        with self._lock:
            self._residents.pop(key, None)
        self._publish()

    # -- budget --------------------------------------------------------------

    def set_budget(self, budget_bytes: Optional[int]) -> None:
        """(Re)set the byte line; 0/None means unlimited. Shrinking the
        line enforces immediately (demotions count ``budget``)."""
        budget = int(budget_bytes) if budget_bytes else None
        with self._lock:
            self._budget = budget
        HBM_BUDGET_BYTES.set(budget or 0)
        self.enforce(reason="budget")

    def squeeze(self, fraction: float) -> int:
        """One transient budget squeeze to ``fraction`` of the line
        (the ``budget-squeeze-mid-churn`` chaos fault): demote down to
        the squeezed line NOW, then restore the configured budget.
        Returns how many demotions it forced."""
        fraction = min(max(float(fraction), 0.0), 1.0)
        with self._lock:
            self._squeeze = fraction
        try:
            return self.enforce(reason="budget")
        finally:
            with self._lock:
                self._squeeze = 1.0

    def budget_bytes(self) -> Optional[int]:
        with self._lock:
            return self._budget

    def device_bytes(self) -> int:
        """Priced bytes currently on the device rung (the ledger view —
        repriced at each owner's last touch, no sync)."""
        with self._lock:
            return self._used_locked()

    def _used_locked(self) -> int:
        return sum(r.bytes for r in self._residents.values()
                   if r.rung == RUNG_DEVICE)

    def _effective_budget_locked(self) -> Optional[int]:
        if self._budget is None:
            return None
        return int(self._budget * self._squeeze)

    # -- the residency ledger ------------------------------------------------

    def touch(self, key: Optional[str], nbytes: Optional[int] = None,
              lane: Optional[str] = None) -> None:
        """Mark ``key`` used now and reprice it. A demoted world coming
        back with device bytes is a RESTAGE (counted by the rung it
        returns from); going over the line afterwards demotes victims
        (never ``key`` itself — the world just used is the protected
        one)."""
        if key is None:
            return
        with self._lock:
            r = self._residents.get(key)
            if r is None:
                return
            self._clock += 1
            r.last_use = self._clock
            if lane in _LANE_DEMOTE_RANK:
                r.lane = lane
            obj = r.ref()
            if nbytes is None and obj is not None:
                try:
                    nbytes = int(obj.device_bytes())
                except Exception:
                    nbytes = r.bytes
            if nbytes is not None:
                r.bytes = int(nbytes)
            if r.bytes > 0 and r.rung != RUNG_DEVICE:
                self._count_locked("restages", r.rung)
                WORKINGSET_RESTAGES.inc({"reason": r.rung})
                self._event_locked(r, r.rung, RUNG_DEVICE, "restage")
                r.rung = RUNG_DEVICE
        self.enforce(protect=key, reason="budget")

    def admit(self, key: Optional[str], nbytes: int) -> None:
        """Make headroom for ``nbytes`` about to be staged under
        ``key``: demote victims until the line holds, BEFORE the
        allocation — never allocate past the line and hope."""
        with self._lock:
            budget = self._effective_budget_locked()
            if budget is None:
                return
            need = self._used_locked() + int(nbytes) - budget
        if need > 0:
            self._demote_until(protect=key, reason="admission",
                               over_bytes=need)

    def enforce(self, protect: Optional[str] = None,
                reason: str = "budget") -> int:
        """Demote device-rung victims until priced usage fits the
        (possibly squeezed) line. Returns demotions applied."""
        with self._lock:
            budget = self._effective_budget_locked()
            if budget is None:
                self._publish_locked()
                return 0
            over = self._used_locked() - budget
        n = 0
        if over > 0:
            n = self._demote_until(protect=protect, reason=reason,
                                   over_bytes=over)
        self._publish()
        return n

    def _demote_until(self, protect: Optional[str], reason: str,
                      over_bytes: int) -> int:
        """Demote device→host victims (policy order) until
        ``over_bytes`` is freed or no victim remains; residents whose
        owner is busy (lock held) or gone are skipped. Oversubscription
        — the protected world alone is over the line — is counted, not
        fought: the solve proceeds and the NEXT admission re-balances."""
        freed = 0
        demoted = 0
        skipped: set = set()
        while freed < over_bytes:
            with self._lock:
                candidates = sorted(
                    (r for r in self._residents.values()
                     if r.rung == RUNG_DEVICE and r.key != protect
                     and r.key not in skipped),
                    key=_Resident.order_key,
                )
            if not candidates:
                with self._lock:
                    self._oversubscribed += 1
                break
            victim = candidates[0]
            obj = victim.ref()
            if obj is None:
                # owner gone: the entry's bytes were phantom charge —
                # prune and credit them without a demotion hook call
                with self._lock:
                    self._residents.pop(victim.key, None)
                freed += victim.bytes
                continue
            ok = False
            try:
                ok = bool(obj.demote_device())
            except Exception:
                ok = False
            if not ok:
                skipped.add(victim.key)
                continue
            with self._lock:
                freed += victim.bytes
                victim.bytes = 0
                self._count_locked("demotions", reason)
                self._event_locked(victim, RUNG_DEVICE, RUNG_HOST,
                                   reason)
                victim.rung = RUNG_HOST
            WORKINGSET_DEMOTIONS.inc({"reason": reason})
            if self.migration_hook is not None:
                self.migration_hook(victim.key, victim.lane, reason)
            demoted += 1
        self._publish()
        return demoted

    def demote(self, key: str, rung: str = RUNG_HOST,
               reason: str = "budget") -> bool:
        """Demote ONE named resident through its hooks with full
        ledger bookkeeping (tests and operator actions — the policy
        paths above pick their own victims). Returns False when the
        resident is unknown, gone, already at/below ``rung``, or its
        owner refuses (busy / pinned)."""
        if rung not in (RUNG_HOST, RUNG_COLD):
            raise ValueError(f"cannot demote to rung {rung!r}")
        with self._lock:
            r = self._residents.get(key)
            obj = None if r is None else r.ref()
            rung_from = None if r is None else r.rung
            lane = None if r is None else r.lane
        if obj is None or rung_from == RUNG_COLD or rung_from == rung:
            return False
        try:
            ok = bool(obj.demote_cold() if rung == RUNG_COLD
                      else obj.demote_device())
        except Exception:
            ok = False
        if not ok:
            return False
        with self._lock:
            r = self._residents.get(key)
            if r is not None:
                r.bytes = 0
                self._count_locked("demotions", reason)
                self._event_locked(r, rung_from, rung, reason)
                r.rung = rung
        WORKINGSET_DEMOTIONS.inc({"reason": reason})
        if self.migration_hook is not None:
            self.migration_hook(key, lane, reason)
        self._publish()
        return True

    def _demote_for_failure(self, protect: Optional[str]) -> int:
        """The allocation-failure response: free aggressively — demote
        every idle device-rung victim, and when the device rung is
        already empty, escalate the coldest host-rung world to cold
        (dropping host arrays can be what lets a host-RAM-backed device
        allocator breathe, and cold is the ladder's typed last rung)."""
        n = self._demote_until(protect=protect, reason="alloc-failure",
                               over_bytes=1 << 62)
        if n:
            return n
        with self._lock:
            hosts = sorted(
                (r for r in self._residents.values()
                 if r.rung == RUNG_HOST and r.key != protect),
                key=_Resident.order_key,
            )
        for victim in hosts:
            obj = victim.ref()
            if obj is None:
                with self._lock:
                    self._residents.pop(victim.key, None)
                continue
            try:
                ok = bool(obj.demote_cold())
            except Exception:
                ok = False
            if not ok:
                continue
            with self._lock:
                self._count_locked("demotions", "alloc-failure")
                self._event_locked(victim, RUNG_HOST, RUNG_COLD,
                                   "alloc-failure")
                victim.rung = RUNG_COLD
            WORKINGSET_DEMOTIONS.inc({"reason": "alloc-failure"})
            if self.migration_hook is not None:
                self.migration_hook(victim.key, victim.lane,
                                    "alloc-failure")
            self._publish()
            return 1
        return 0

    # -- the stage/scatter boundary ------------------------------------------

    def run_staged(self, key: Optional[str], where: str,
                   fn: Callable, estimate: Optional[int] = None):
        """Run ``fn`` — a device allocation: a full world staging
        (``where="stage"``) or a delta row scatter (``"scatter"``) —
        under the demote+retry contract. ``estimate`` (bytes about to
        land) makes headroom FIRST via :meth:`admit`; an allocation
        failure (real ``RESOURCE_EXHAUSTED`` or chaos-armed) is counted
        typed, answered by demotion, and retried a bounded number of
        times; exhaustion raises :class:`WorkingSetExhausted` — loud,
        typed, never silent."""
        if where not in FAIL_WHERE:
            raise ValueError(f"unknown staging boundary {where!r}")
        if estimate:
            self.admit(key, estimate)
        attempts = 0
        while True:
            try:
                self._consume_fault(where)
                return fn()
            except Exception as e:
                if not is_alloc_failure(e):
                    raise
                with self._lock:
                    self._count_locked("alloc_failures", where)
                WORKINGSET_ALLOC_FAILURES.inc({"reason": where})
                attempts += 1
                if attempts > self.max_alloc_retries:
                    raise WorkingSetExhausted(
                        f"device allocation at the {where} boundary "
                        f"still failing after {attempts} attempts with "
                        f"demotion between each; nothing left to evict"
                    ) from e
                self._demote_for_failure(protect=key)

    # -- chaos ---------------------------------------------------------------

    def arm_fault(self, where: str, n: int = 1) -> None:
        """Arm ``n`` injected allocation failures at ``where`` — each
        :meth:`run_staged` call there consumes one and raises BEFORE
        invoking its callable, so the post-demotion retry replays the
        staging exactly once (bit-identity by construction)."""
        if where not in FAIL_WHERE:
            raise ValueError(f"unknown staging boundary {where!r}")
        with self._lock:
            self._faults[where] = self._faults.get(where, 0) + int(n)

    def _consume_fault(self, where: str) -> None:
        with self._lock:
            pending = self._faults.get(where, 0)
            if pending <= 0:
                return
            self._faults[where] = pending - 1
        raise InjectedAllocFailure(
            f"injected allocation failure at the {where} boundary"
        )

    # -- accounting internals ------------------------------------------------

    def _count_locked(self, family: str, reason: str) -> None:
        c = self._counts[family]
        c[reason] = c.get(reason, 0) + 1

    def _event_locked(self, r: _Resident, rung_from: str, rung_to: str,
                      reason: str) -> None:
        self._seq += 1
        self._events.append({
            "seq": self._seq, "key": r.key, "tenant": r.tenant,
            "lane": r.lane, "from": rung_from, "to": rung_to,
            "reason": reason, "bytes": r.bytes,
        })

    def _publish_locked(self):
        used = self._used_locked()
        by_rung = {rung: 0 for rung in RUNGS}
        for r in self._residents.values():
            by_rung[r.rung] += 1
        return used, by_rung

    def _publish(self) -> None:
        with self._lock:
            used, by_rung = self._publish_locked()
        HBM_USED_BYTES.set(used)
        for rung, n in by_rung.items():
            TENANT_RESIDENCY.set(n, {"rung": rung})

    # -- read side -----------------------------------------------------------

    def status(self) -> dict:
        """The debug-mux / ``status()["workingset"]`` body: the budget
        line, per-rung census, typed counters, and the heaviest
        residents (bounded rows — 256 tenants do not serialize 256
        rows on every status poll)."""
        with self._lock:
            used, by_rung = self._publish_locked()
            rows = sorted(
                self._residents.values(),
                key=lambda r: (-r.bytes, r.key),
            )[:32]
            return {
                "budget_bytes": self._budget or 0,
                "effective_budget_bytes":
                    self._effective_budget_locked() or 0,
                "used_bytes": used,
                "residents": by_rung,
                "demotions": dict(self._counts["demotions"]),
                "restages": dict(self._counts["restages"]),
                "alloc_failures": dict(self._counts["alloc_failures"]),
                "oversubscribed": self._oversubscribed,
                "armed_faults": {
                    k: v for k, v in self._faults.items() if v
                },
                "rows": [
                    {"key": r.key, "tenant": r.tenant, "lane": r.lane,
                     "rung": r.rung, "bytes": r.bytes,
                     "weight": r.weight, "last_use": r.last_use}
                    for r in rows
                ],
            }

    def flight_payload(self) -> dict:
        """The flight recorder's ``workingset`` section: who got
        demoted and why — the bounded event ring plus the headline
        ledger, cached-only (a dump never walks live arrays)."""
        with self._lock:
            used, by_rung = self._publish_locked()
            return {
                "budget_bytes": self._budget or 0,
                "used_bytes": used,
                "residents": by_rung,
                "demotions": dict(self._counts["demotions"]),
                "restages": dict(self._counts["restages"]),
                "alloc_failures": dict(self._counts["alloc_failures"]),
                "events": list(self._events),
            }

    def pressure(self) -> dict:
        """The device observatory's compact section (obs/device.py
        ``live_snapshot``): line, charge, census — one lock hold."""
        with self._lock:
            used, by_rung = self._publish_locked()
            return {
                "budget_bytes": self._budget or 0,
                "used_bytes": used,
                "residents": by_rung,
            }

    def reset(self) -> None:
        """Forget every resident, fault, and local count (tests; the
        process singleton is shared). The global metric counters are
        monotone by contract and deliberately not reset."""
        with self._lock:
            self._residents.clear()
            self._budget = None
            self._squeeze = 1.0
            self._clock = 0
            self._seq = 0
            self._events.clear()
            self._counts = {
                "demotions": {}, "restages": {}, "alloc_failures": {},
            }
            self._faults = {}
            self._oversubscribed = 0
        HBM_BUDGET_BYTES.set(0)
        self._publish()


#: the process singleton every staged-world cache registers with —
#: unlimited until cmd wiring (or a test) sets ``--hbm-budget-bytes``
WORKING_SET = WorkingSetManager()


def _register_surfaces() -> None:
    # the flight recorder's `workingset` section + the observatory's
    # pressure view, registered once per process (re-import safe: a
    # duplicate flight name raises, which means it is already wired)
    from koordinator_tpu.obs.flight import FLIGHT

    try:
        FLIGHT.register_payload("workingset", WORKING_SET.flight_payload)
    except ValueError:
        pass
    from koordinator_tpu.obs.device import DEVICE_OBS

    DEVICE_OBS.set_pressure_source(WORKING_SET.pressure)


_register_surfaces()
