"""Array substrate: cluster state lowered to dense device arrays."""

from koordinator_tpu.state.cluster import (  # noqa: F401
    ClusterDeltaTracker,
    NodeArrays,
    PendingPodArrays,
    estimate_pod_used,
    lower_nodes,
    lower_nodes_delta,
    lower_pending_pods,
)
from koordinator_tpu.state.workingset import (  # noqa: F401
    WORKING_SET,
    InjectedAllocFailure,
    WorkingSetExhausted,
    WorkingSetManager,
)
