"""Streaming serving mode: continuous arrivals, adaptively-fired rounds.

Everything before this module is round-based: ``run_loop`` fires
``schedule_pending`` on a fixed cadence and the bench metric is pods/s
per tick. A production scheduler serving heavy traffic sees a
CONTINUOUS pod stream, and the metric that matters is per-pod
submit→bind latency at a sustained arrival rate (docs/DESIGN.md §22,
ROADMAP item 1). This module is the front end that closes the gap:

- **QoS-laned intake** (:class:`ArrivalGate`). Pods arrive on an
  open-loop process into three lanes — ``system`` > ``ls`` > ``be``,
  the same mapping as the solver sidecar's admission gate (DESIGN §12)
  — each lane carrying a *latency target*: the deadline by which a
  queued pod should be in a firing round. The intake is bounded:
  past ``capacity``, best-effort entries are shed first (an arriving
  higher-lane pod evicts the newest queued entry of the lowest lane
  strictly below it; an arrival that outranks nothing is itself
  refused, typed and counted — never silence).

- **Adaptive round triggering.** A round fires when EITHER the queued
  batch reaches the ``watermark`` (a burst amortizes into one
  dispatch instead of fragmenting into tiny ones) OR the oldest
  queued pod's lane deadline arrives (a lone urgent pod does not wait
  out a fixed cadence), whichever comes first. This is the tunable
  latency-vs-batch-efficiency trade; the trigger decides *when*
  rounds fire, never *what* they decide — replaying the same arrival
  batches through the fixed-cadence loop is bit-identical by
  construction (property-tested, bench-gated).

- **The round body is unchanged.** A fired round runs the existing
  ``begin_tick``/``commit_tick`` split — through a
  :class:`~koordinator_tpu.scheduler.pipeline.TickPipeline` when
  pipelined (solve N in flight while arrivals land, publish off the
  critical path) or the serial composition otherwise. Placement
  semantics, epilogues, publish fencing: all shared code.

- **Every submitted pod resolves.** ``bound`` when its bind publishes
  (the timeline closes — ``scheduler_pod_e2e_seconds`` is the
  headline histogram), ``shed-capacity`` when refused/evicted at
  intake, ``deadline-exceeded`` when ``max_pod_rounds`` retries are
  exhausted. Outcome accounting is the zero-silent-drop invariant the
  chaos slice pins: submitted == bound + shed + expired + in-flight.

Concurrency: handler/submitter threads call :meth:`StreamingLoop.
submit`; the loop thread (or a test's :meth:`StreamingLoop.pump`)
fires rounds; the pipeline's publisher thread resolves outcomes.
``ArrivalGate``'s mutable state is guarded by its condition
(graftcheck lock map); the loop's own bookkeeping by ``_lock``. The
gate lock never nests inside any other mapped lock.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from koordinator_tpu.metrics.components import (
    ROUNDS_SKIPPED,
    STREAM_ARRIVALS,
    STREAM_BATCH_PODS,
    STREAM_QUEUE_DEPTH,
    STREAM_SHED,
    STREAM_TRIGGERS,
)
from koordinator_tpu.obs.timeline import LANES, lane_of
from koordinator_tpu.obs.trace import TRACER

#: lane indices, mirroring service/admission (system > ls > be)
LANE_BY_NAME = {name: i for i, name in enumerate(LANES)}

#: terminal outcomes
OUTCOME_BOUND = "bound"
OUTCOME_SHED = "shed-capacity"
OUTCOME_EXPIRED = "deadline-exceeded"


@dataclasses.dataclass(frozen=True)
class StreamingConfig:
    """Trigger + intake tuning.

    ``watermark`` is the batch-size trigger: a round fires as soon as
    this many arrivals are queued. ``lane_deadline_s`` is the per-lane
    latency target (system, ls, be): the oldest queued pod's
    ``submit + lane deadline`` is the deadline trigger. The two
    together are the whole policy — watermark bounds dispatch
    amortization from below, deadlines bound queue wait from above.

    ``capacity`` bounds queued arrivals (shed past it, BE first);
    ``max_pod_rounds`` bounds how many rounds an unplaceable pod
    retries before resolving ``deadline-exceeded`` (0 = retry forever
    — the production default: capacity frees as churn evicts);
    ``idle_wake_s`` is the periodic backstop that re-fires a round
    while the scheduler still holds pending pods the intake no longer
    tracks (gang WaitTime releases, externally-applied pods);
    ``min_round_interval_s`` floors the inter-round gap so a trickle
    of deadline-armed singletons cannot drive the dispatch rate
    unboundedly (0 = no floor)."""

    watermark: int = 64
    lane_deadline_s: Tuple[float, float, float] = (0.002, 0.010, 0.050)
    capacity: int = 4096
    max_pod_rounds: int = 0
    idle_wake_s: float = 0.25
    min_round_interval_s: float = 0.0


class _Entry:
    __slots__ = ("uid", "lane", "submitted_at", "deadline_at",
                 "rounds_seen", "seq")

    def __init__(self, uid: str, lane: int, submitted_at: float,
                 deadline_at: float, seq: int = 0):
        self.uid = uid
        self.lane = lane
        self.submitted_at = submitted_at
        self.deadline_at = deadline_at
        self.rounds_seen = 0
        #: admission ordinal — the bus APPLICATION order, which the
        #: round log preserves so a fixed-round replay re-applies
        #: arrivals in exactly the order the pending queue saw them
        self.seq = seq


class ArrivalGate:
    """The QoS-laned, deadline-armed, bounded streaming intake.

    Pure bookkeeping — it never touches the bus or the scheduler; the
    :class:`StreamingLoop` owns those side effects. Every mutable
    attribute below is mapped to ``_lock`` (a Condition, shared with
    the loop's trigger wait) in graftcheck's lock-discipline registry.
    """

    def __init__(self, config: StreamingConfig = StreamingConfig(),
                 clock: Callable[[], float] = time.monotonic,
                 shed_hook: Optional[Callable] = None):
        self.cfg = config
        self._clock = clock
        #: optional (lane_name, reason, uid) callback fired OUTSIDE the
        #: lock for every shed/expired resolution — the loop wires the
        #: pod-timeline registry's failure fold here so the rolling
        #: stats surface sees the failure tail beside the survivor p99
        self._shed_hook = shed_hook
        self._lock = threading.Condition()
        #: per-lane FIFO of queued entries (arrival order per lane)
        self._lanes: List[deque] = [deque() for _ in LANES]
        self._by_uid: Dict[str, _Entry] = {}
        #: uid -> entry taken into the currently-firing round
        self._inflight: Dict[str, _Entry] = {}
        #: uid -> entry held at a gang Permit barrier (no deadline —
        #: a waiting pod fires no rounds; its siblings' arrivals do)
        self._waiting: Dict[str, _Entry] = {}
        #: terminal outcome per uid, bounded (oldest evicted)
        self._resolved: "deque" = deque(maxlen=8192)
        self._resolved_map: Dict[str, str] = {}
        self._stats = {
            "submitted": 0, "bound": 0, "shed_capacity": 0,
            "expired": 0, "timeline_dropped": 0,
        }
        self._seq = 0

    # -- intake (submitter threads) -----------------------------------------

    def admit(self, uid: str, lane: int,
              now: Optional[float] = None) -> Tuple[str, Optional[str]]:
        """Admit one arrival. Returns ``("queued", evicted_uid|None)``
        or ``("shed", None)`` — the caller applies the bus side
        effects (apply the admitted pod, delete the evicted one) and
        publishes the typed refusal."""
        at = self._clock() if now is None else now
        deadline = at + self.cfg.lane_deadline_s[lane]
        victim: Optional[_Entry] = None
        refused = False
        with self._lock:
            self._stats["submitted"] += 1
            queued = len(self._by_uid)
            if queued >= self.cfg.capacity:
                victim = self._pick_victim(lane)
                if victim is None:
                    refused = True
                    self._stats["shed_capacity"] += 1
                    self._resolve_locked(uid, OUTCOME_SHED)
                else:
                    self._by_uid.pop(victim.uid, None)
                    # _pick_victim always chose a lane TAIL: pop() is
                    # O(1) where remove() would scan the whole lane
                    # under the gate lock on the overload hot path
                    self._lanes[victim.lane].pop()
                    self._stats["shed_capacity"] += 1
                    self._resolve_locked(victim.uid, OUTCOME_SHED)
            if not refused:
                self._seq += 1
                entry = _Entry(uid, lane, at, deadline, seq=self._seq)
                self._by_uid[uid] = entry
                self._lanes[lane].append(entry)
                self._lock.notify_all()
            depths = self._depths_locked()
        # metric publishing rides OUTSIDE the gate lock (the admission
        # gate's _publish_depth discipline): registries have their own
        # locks and must never nest inside this one
        self._publish_depths(depths)
        if refused:
            STREAM_SHED.inc({"lane": LANES[lane], "reason": "capacity"})
            if self._shed_hook is not None:
                self._shed_hook(LANES[lane], "capacity", uid)
            return "shed", None
        STREAM_ARRIVALS.inc({"lane": LANES[lane]})
        if victim is not None:
            STREAM_SHED.inc({"lane": LANES[victim.lane],
                             "reason": "capacity"})
            if self._shed_hook is not None:
                self._shed_hook(LANES[victim.lane], "capacity", victim.uid)
        return "queued", victim.uid if victim is not None else None

    def _pick_victim(self, lane: int) -> Optional[_Entry]:
        """Overload eviction (call under ``_lock``): newest queued
        entry of the lowest-priority non-empty lane strictly below the
        arrival — the admission gate's policy (DESIGN §12) applied at
        the scheduler's front door."""
        for shed_lane in (LANE_BY_NAME["be"], LANE_BY_NAME["ls"]):
            if shed_lane <= lane:
                continue
            if self._lanes[shed_lane]:
                return self._lanes[shed_lane][-1]
        return None

    def note_timeline_drop(self, uid: str) -> None:
        """The pod timeline registry refused a sample at capacity
        (obs/timeline.py). The pod still schedules — but the refusal
        is BACKPRESSURE, so it lands in the shed accounting (reason
        ``timeline-capacity``) instead of vanishing into a silent
        counter."""
        with self._lock:
            entry = self._by_uid.get(uid)
            lane = entry.lane if entry is not None else LANE_BY_NAME["ls"]
            self._stats["timeline_dropped"] += 1
        STREAM_SHED.inc({"lane": LANES[lane],
                         "reason": "timeline-capacity"})

    # -- knob retuning (the SLO controller, koordinator_tpu/control) ---------

    def retune(self, watermark: Optional[int] = None,
               lane_deadline_s: Optional[Tuple[float, float, float]] = None,
               capacity: Optional[int] = None) -> StreamingConfig:
        """Replace the trigger/intake knobs live (the SLO controller's
        actuator). The config object is frozen, so a retune swaps in a
        ``dataclasses.replace`` copy under the gate lock — every reader
        already takes ``self.cfg`` under ``_lock``.

        A lane-deadline change re-stamps every QUEUED entry's
        ``deadline_at`` by the per-lane delta: entries were stamped
        ``t_i + old`` with a monotone clock, so a uniform shift to
        ``t_i + new`` preserves the per-lane deadline monotonicity the
        O(1) head-min trigger depends on. In-flight/waiting entries
        keep their stamps (their next requeue uses the new constant).
        Wakes a parked loop: a tightened deadline or lowered watermark
        may be due NOW."""
        with self._lock:
            old = self.cfg
            fields = {}
            if watermark is not None:
                fields["watermark"] = int(watermark)
            if lane_deadline_s is not None:
                fields["lane_deadline_s"] = tuple(lane_deadline_s)
            if capacity is not None:
                fields["capacity"] = int(capacity)
            if not fields:
                return old
            cfg = dataclasses.replace(old, **fields)
            if lane_deadline_s is not None:
                for lane, q in enumerate(self._lanes):
                    delta = (cfg.lane_deadline_s[lane]
                             - old.lane_deadline_s[lane])
                    if delta:
                        for e in q:
                            e.deadline_at += delta
            self.cfg = cfg
            self._lock.notify_all()
        return cfg

    def note_bound(self, uid: str) -> None:
        """A bind for a tracked pod landed on the bus from OUTSIDE this
        gate's own round resolution — the HA case: a standby's gate
        tracks the watch-fed intake while the leader places it.
        Queued/Permit-waiting entries resolve ``bound`` (the submission
        succeeded cluster-wide); an IN-FLIGHT entry is left alone — it
        belongs to this seat's firing round and resolves exactly once
        through :meth:`resolve_round`."""
        with self._lock:
            e = self._by_uid.pop(uid, None)
            if e is not None:
                try:
                    self._lanes[e.lane].remove(e)
                except ValueError:
                    pass
            elif uid in self._waiting:
                self._waiting.pop(uid)
            elif uid in self._inflight:
                return
            else:
                return
            self._stats["bound"] += 1
            self._resolve_locked(uid, OUTCOME_BOUND)
            depths = self._depths_locked()
        self._publish_depths(depths)

    # -- triggering ----------------------------------------------------------

    def due(self, now: Optional[float] = None) -> Optional[str]:
        """The trigger decision: ``"watermark"`` | ``"deadline"`` |
        None (nothing fires yet). Watermark outranks deadline in the
        report (both may hold at once). O(1): each lane's deque is
        deadline-ordered (every append stamps ``now + lane constant``
        with a monotone clock — requeues included), so the lane head
        carries the lane minimum."""
        at = self._clock() if now is None else now
        with self._lock:
            if len(self._by_uid) >= self.cfg.watermark:
                return "watermark"
            for q in self._lanes:
                if q and q[0].deadline_at <= at:
                    return "deadline"
        return None

    def next_deadline(self) -> Optional[float]:
        """The earliest queued deadline (the loop's wake-up time);
        None when nothing is queued. O(1) — see :meth:`due`."""
        with self._lock:
            heads = [q[0].deadline_at for q in self._lanes if q]
        return min(heads) if heads else None

    def wait_for_work(self, timeout: Optional[float],
                      depth: Optional[int] = None) -> None:
        """Park the loop until the queued depth CHANGES from ``depth``
        (an arrival landed — it may have crossed the watermark) or
        ``timeout`` passes. ``depth=None`` means "wait only while
        empty"."""
        with self._lock:
            if depth is None:
                if self._by_uid:
                    return
            elif len(self._by_uid) != depth:
                return
            self._lock.wait(timeout)

    def wake(self) -> None:
        """Nudge a parked loop (shutdown, config pokes)."""
        with self._lock:
            self._lock.notify_all()

    def take_round(self) -> List[_Entry]:
        """Claim every queued entry into the firing round (lane
        priority order, FIFO within a lane)."""
        with self._lock:
            batch: List[_Entry] = []
            for q in self._lanes:
                while q:
                    batch.append(q.popleft())
            for e in batch:
                self._by_uid.pop(e.uid, None)
                self._inflight[e.uid] = e
            depths = self._depths_locked()
        self._publish_depths(depths)
        return batch

    # -- round resolution (loop / publisher thread) -------------------------

    def resolve_round(self, result, now: Optional[float] = None
                      ) -> Dict[str, int]:
        """Fold one round's :class:`ScheduleResult` into outcomes:
        placed in-flight entries resolve ``bound``; entries the gang
        Permit barrier holds move to ``waiting``; unplaced entries
        requeue with a fresh lane deadline (or expire past
        ``max_pod_rounds``). Returns ``{bound, waiting, requeued,
        expired}`` counts."""
        at = self._clock() if now is None else now
        counts = {"bound": 0, "waiting": 0, "requeued": 0, "expired": 0}
        expired: List[_Entry] = []
        with self._lock:
            # a previously-waiting pod whose gang completed reports as
            # a committed placement in a later round's result
            for uid in list(self._waiting):
                node = result.get(uid)
                if node is not None and uid not in result.waiting:
                    e = self._waiting.pop(uid)
                    self._stats["bound"] += 1
                    self._resolve_locked(uid, OUTCOME_BOUND)
                    counts["bound"] += 1
            # a QUEUED entry the result covers: in pipelined mode round
            # N+1's batch is taken BEFORE round N retires, so a pod
            # round N's resolution requeued can be placed by round N+1
            # (whose snapshot spans ALL pending pods) while it sits in
            # the queue — without this scan its bound outcome would be
            # missed and the entry would leak in-flight forever
            for uid in list(self._by_uid):
                if uid not in result:
                    continue
                e = self._by_uid[uid]
                node = result[uid]
                if uid in result.waiting:
                    self._pop_queued_locked(e)
                    self._waiting[uid] = e
                    counts["waiting"] += 1
                elif node is not None:
                    self._pop_queued_locked(e)
                    self._stats["bound"] += 1
                    self._resolve_locked(uid, OUTCOME_BOUND)
                    counts["bound"] += 1
                # unplaced: stays queued with its existing deadline
            for uid, e in list(self._inflight.items()):
                if uid not in result:
                    continue  # not in this round (should not happen)
                self._inflight.pop(uid)
                node = result[uid]
                if uid in result.waiting:
                    self._waiting[uid] = e
                    counts["waiting"] += 1
                elif node is not None:
                    self._stats["bound"] += 1
                    self._resolve_locked(uid, OUTCOME_BOUND)
                    counts["bound"] += 1
                else:
                    e.rounds_seen += 1
                    if (self.cfg.max_pod_rounds
                            and e.rounds_seen >= self.cfg.max_pod_rounds):
                        self._stats["expired"] += 1
                        self._resolve_locked(uid, OUTCOME_EXPIRED)
                        expired.append(e)
                        counts["expired"] += 1
                    else:
                        e.deadline_at = at + self.cfg.lane_deadline_s[e.lane]
                        self._by_uid[uid] = e
                        self._lanes[e.lane].append(e)
                        counts["requeued"] += 1
            depths = self._depths_locked()
        for e in expired:
            STREAM_SHED.inc({"lane": LANES[e.lane], "reason": "deadline"})
            if self._shed_hook is not None:
                self._shed_hook(LANES[e.lane], "deadline-exceeded", e.uid)
        self._publish_depths(depths)
        return counts

    def requeue_taken(self, entries: List[_Entry],
                      now: Optional[float] = None) -> None:
        """A fired round FAILED (typed solver outage, fencing abort):
        its taken entries go back to the queue unharmed — the pods are
        still pending on the bus, the next round re-solves them."""
        at = self._clock() if now is None else now
        with self._lock:
            for e in entries:
                self._inflight.pop(e.uid, None)
                if e.uid in self._by_uid:
                    continue
                e.deadline_at = at + self.cfg.lane_deadline_s[e.lane]
                self._by_uid[e.uid] = e
                self._lanes[e.lane].append(e)
            depths = self._depths_locked()
            self._lock.notify_all()
        self._publish_depths(depths)

    def _pop_queued_locked(self, e: "_Entry") -> None:
        """Remove a queued entry from its lane + index (call under
        ``self._lock``)."""
        self._by_uid.pop(e.uid, None)
        try:
            self._lanes[e.lane].remove(e)
        except ValueError:
            pass

    def forget(self, uid: str) -> None:
        """A tracked pod vanished (deleted/evicted on the bus): drop
        it from intake bookkeeping without an outcome — the deletion
        is its own resolution."""
        with self._lock:
            e = self._by_uid.pop(uid, None)
            if e is not None:
                self._lanes[e.lane].remove(e)
            self._inflight.pop(uid, None)
            self._waiting.pop(uid, None)

    # -- read side -----------------------------------------------------------

    def _resolve_locked(self, uid: str, outcome: str) -> None:
        if len(self._resolved) == self._resolved.maxlen:
            old = self._resolved[0]
            self._resolved_map.pop(old, None)
        self._resolved.append(uid)
        self._resolved_map[uid] = outcome

    def outcome(self, uid: str) -> Optional[str]:
        """Terminal outcome for ``uid`` (None while still in flight or
        unknown/evicted-from-the-ring)."""
        with self._lock:
            return self._resolved_map.get(uid)

    def tracks(self, uid: str) -> bool:
        """Whether ``uid`` is ACTIVELY tracked — queued, in a firing
        round, or Permit-waiting. Deliberately excludes the resolved
        history: a pod deleted and re-created under the same
        namespace/name (the ordinary k8s recreate flow) is a NEW
        arrival and must re-enter the intake, not be skipped because
        its predecessor once resolved."""
        with self._lock:
            return (uid in self._by_uid or uid in self._inflight
                    or uid in self._waiting)

    def _depths_locked(self) -> List[int]:
        return [len(q) for q in self._lanes]

    @staticmethod
    def _publish_depths(depths: List[int]) -> None:
        for i, n in enumerate(depths):
            STREAM_QUEUE_DEPTH.set(n, {"lane": LANES[i]})

    def depth(self) -> int:
        with self._lock:
            return len(self._by_uid)

    def unresolved(self) -> int:
        """Entries not yet terminally resolved (queued + in-flight +
        Permit-waiting) — 0 when every submitted pod has an outcome."""
        with self._lock:
            return (len(self._by_uid) + len(self._inflight)
                    + len(self._waiting))

    def status(self) -> dict:
        with self._lock:
            return {
                "depth": {
                    LANES[i]: len(q) for i, q in enumerate(self._lanes)
                },
                "inflight": len(self._inflight),
                "waiting_permit": len(self._waiting),
                "capacity": self.cfg.capacity,
                "watermark": self.cfg.watermark,
                "lane_deadline_s": list(self.cfg.lane_deadline_s),
                "submitted": self._stats["submitted"],
                "bound": self._stats["bound"],
                "shed": {
                    "capacity": self._stats["shed_capacity"],
                    "deadline-exceeded": self._stats["expired"],
                    # backpressure, not a drop: the pod scheduled but
                    # its latency sample was refused at capacity
                    "timeline-capacity": self._stats["timeline_dropped"],
                },
            }


class StreamingLoop:
    """The adaptive serving loop over a wired scheduler.

    ``apply_fn(pod)`` lands an admitted arrival on the bus (the wiring
    wraps ``bus.apply``); ``delete_fn(uid)`` removes a shed victim /
    expired pod. ``pipelined=True`` builds a
    :class:`~koordinator_tpu.scheduler.pipeline.TickPipeline` owned by
    this loop (rounds overlap; outcomes resolve on the publisher
    thread); otherwise rounds run the serial
    ``scheduler.schedule_pending`` inline.

    Two drive modes: :meth:`run` (a real thread pacing itself on the
    trigger — production/bench) and :meth:`pump` (single-step with an
    injected ``now`` — the fake-clock determinism tests). Both share
    :meth:`fire_round`, so the tested trigger ordering IS the served
    one."""

    def __init__(self, scheduler, apply_fn: Callable,
                 delete_fn: Optional[Callable] = None,
                 config: StreamingConfig = StreamingConfig(),
                 pipelined: bool = False,
                 clock: Callable[[], float] = time.monotonic,
                 now_fn: Callable[[], float] = time.time,
                 auditor=None, log: Callable = print):
        self.scheduler = scheduler
        # the timeline registry's failure fold (obs/timeline.py): every
        # shed/expired resolution lands in the same rolling stats
        # surface the survivor percentiles come from
        _timelines = getattr(scheduler, "timelines", None)
        shed_hook = getattr(_timelines, "note_shed", None)
        self.gate = ArrivalGate(config, clock=clock, shed_hook=shed_hook)
        self._apply = apply_fn
        self._delete = delete_fn
        self._clock = clock
        self._now_fn = now_fn
        self._auditor = auditor
        self._log = log
        self._lock = threading.Lock()
        self._rounds = 0
        self._skipped = 0
        self._last_trigger: Optional[str] = None
        self._last_fired_at: Optional[float] = None
        #: bounded per-round batch log: (trigger, now, uid tuple) —
        #: what the bit-identity replay (bench leg 18, the property
        #: test) re-drives through the fixed-round loop
        self.round_log: deque = deque(maxlen=4096)
        self._stopped = threading.Event()
        #: set while no run() invocation is active — stop() waits on it
        #: so the pipeline is never torn down under a mid-round loop
        #: (run() may execute on a caller's thread, not only _thread)
        self._run_done = threading.Event()
        self._run_done.set()
        self._thread: Optional[threading.Thread] = None
        self.pipeline = None
        self._hooked_backend = None
        self._prev_flip = self._prev_degraded = None
        #: HA composition (DESIGN §25): when an elector is attached the
        #: trigger loop fires rounds only while the lease is held; a
        #: promoted standby adopts the watch-fed intake + knob state
        self._elector = None
        self._prev_started = None
        #: the SLO controller (koordinator_tpu/control/slo.py): when
        #: attached, the loop drives its reconcile cadence
        self._controller = None
        if pipelined:
            from koordinator_tpu.scheduler.pipeline import TickPipeline

            self.pipeline = TickPipeline(
                scheduler, log=log,
                on_result=self._on_round_result,
            )
            # failover flips quiesce the pipeline (run_loop's contract,
            # DESIGN §15): the epoch reset / full restage a flip
            # triggers must never race an in-flight tick's retire.
            # Originals restored on stop() — a re-wired scheduler must
            # not chain into a stopped loop's pipeline.
            backend = getattr(getattr(scheduler, "model", None),
                              "backend", None)
            if backend is not None and hasattr(backend, "on_flip_back"):
                self._hooked_backend = backend
                self._prev_flip = backend.on_flip_back

                def _flip_back(prev=self._prev_flip, p=self.pipeline):
                    p.drain("failover-flip", raise_deferred=False)
                    if prev is not None:
                        prev()

                backend.on_flip_back = _flip_back
                if hasattr(backend, "on_flip_degraded"):
                    self._prev_degraded = backend.on_flip_degraded

                    def _flip_degraded(prev=self._prev_degraded,
                                       p=self.pipeline):
                        p.drain("failover-flip", raise_deferred=False)
                        if prev is not None:
                            prev()

                    backend.on_flip_degraded = _flip_degraded
        # a pod deleted/evicted on the bus must leave intake
        # bookkeeping too; the scheduler's remove path already forgets
        # the timeline — chain the gate's forget beside it
        self._prev_remove = scheduler.remove_pod

        def _remove_pod(pod, _prev=self._prev_remove):
            _prev(pod)
            self.gate.forget(pod.uid)

        scheduler.remove_pod = _remove_pod
        # backpressure wiring: the timeline registry's capacity
        # refusals land in the gate's shed accounting (DESIGN §22)
        timelines = getattr(scheduler, "timelines", None)
        if timelines is not None and hasattr(timelines, "set_drop_hook"):
            timelines.set_drop_hook(self.gate.note_timeline_drop)

    @property
    def cfg(self) -> StreamingConfig:
        """The LIVE trigger/intake config. The gate owns the object —
        the SLO controller retunes it through :meth:`ArrivalGate.
        retune` — so the loop reads through rather than caching the
        construction-time copy."""
        return self.gate.cfg

    # -- HA composition (lease gate + promotion handoff, DESIGN §25) ---------

    def attach_elector(self, elector) -> None:
        """Fold the ``--leader-elect`` lease gate into the trigger
        loop: rounds fire only while ``elector.tick`` reports the
        lease held; a standby parks (draining deferred pipeline
        errors) and a promotion adopts the watch-fed intake + the
        controller's knob state via the chained
        ``on_started_leading``."""
        self._elector = elector
        self._prev_started = elector.on_started_leading

        def _promoted(_prev=self._prev_started):
            if _prev is not None:
                _prev()
            self.on_promoted()

        elector.on_started_leading = _promoted

    def attach_controller(self, controller) -> None:
        """Attach the SLO controller: the loop drives its reconcile
        cadence (leader-only under HA) and a promotion adopts the
        published knob state before the first post-failover round."""
        self._controller = controller

    def on_promoted(self) -> None:
        """Lease acquired: inherit the previous leader's convergence
        (knob state published on the bus) FIRST — the adopted deadlines
        govern how the swept intake re-arms — then sweep pending pods
        the watch fed while standby into the gate."""
        if self._controller is not None:
            self._controller.on_promoted()
        self.adopt_intake()

    def adopt_intake(self, now: Optional[float] = None) -> int:
        """Admit every pending pod the scheduler cache holds that the
        gate does not already track (idempotent: ``observe`` skips
        tracked uids, so a watch-fed standby whose gate mirrored every
        arrival adopts zero). Returns the number adopted."""
        adopted = 0
        for pod in list(self.scheduler.cache.pending.values()):
            if self.gate.tracks(pod.uid):
                continue
            self.observe(pod, now=now)
            adopted += 1
        return adopted

    def _lease_held(self, now: Optional[float] = None) -> bool:
        """Tick the lease gate (no elector = always leading). A tick
        both renews a held lease and attempts acquisition on an
        expired one — promotion fires inside it."""
        if self._elector is None:
            return True
        return self._elector.tick(
            self._now_fn() if now is None else now
        )

    def _standby_step(self) -> None:
        """Lease held elsewhere: fire nothing, but surface deferred
        publish-side errors the pipeline may still hold from the
        rounds fired while leading (run_loop's standby discipline) —
        a fencing abort forgets assumed-but-unbound pods."""
        from koordinator_tpu.client.leaderelection import FencingError

        if self.pipeline is None:
            return
        try:
            self.pipeline.drain("standby")
        except FencingError as e:
            forgotten = self.scheduler.forget_assumed_unbound()
            self._log(f"streaming standby: fenced publish surfaced: "
                      f"{e}; forgot {len(forgotten)} assumed pod(s)")

    # -- intake --------------------------------------------------------------

    def submit(self, pod, now: Optional[float] = None) -> str:
        """One open-loop arrival: admit (or shed) and land on the bus.
        Returns ``"queued"`` or ``"shed"`` — a shed pod never touches
        the bus, so the refusal is typed at the front door."""
        lane = LANE_BY_NAME[lane_of(pod)]
        verdict, evicted = self.gate.admit(pod.uid, lane, now=now)
        if evicted is not None and self._delete is not None:
            # the victim was already on the bus: evict it (DELETED
            # re-enters Scheduler.remove_pod → timeline forgotten)
            self._delete(evicted)
        if verdict == "queued":
            self._apply(pod)
        return verdict

    def observe(self, pod, now: Optional[float] = None) -> None:
        """Intake for a pending pod ANOTHER component applied to the
        bus (the wiring's watch routes them here): it is already in
        the scheduler's queue, so a shed verdict evicts it back off
        the bus — typed and observed, never a silent drop."""
        if self.gate.tracks(pod.uid):
            return  # loop.submit() already admitted it
        lane = LANE_BY_NAME[lane_of(pod)]
        verdict, evicted = self.gate.admit(pod.uid, lane, now=now)
        if evicted is not None and self._delete is not None:
            self._delete(evicted)
        if verdict == "shed" and self._delete is not None:
            self._delete(pod.uid)

    def observe_bound(self, pod) -> None:
        """A bind for ``pod`` landed on the bus (the wiring's watch
        routes assigned-pod events here). Resolves a queued/waiting
        gate entry ``bound`` — the HA standby's accounting: its
        watch-fed intake mirrors every arrival, and the LEADER's bind
        must resolve the mirror or the entry would leak unresolved
        forever. A uid in this loop's own firing round is left to
        :meth:`ArrivalGate.resolve_round` (exactly-once outcomes)."""
        self.gate.note_bound(pod.uid)

    # -- firing --------------------------------------------------------------

    def due(self, now: Optional[float] = None) -> Optional[str]:
        """The loop's trigger decision (gate triggers + the idle
        backstop + the min-interval floor)."""
        at = self._clock() if now is None else now
        with self._lock:
            last = self._last_fired_at
        if (last is not None and self.cfg.min_round_interval_s
                and at - last < self.cfg.min_round_interval_s):
            return None
        reason = self.gate.due(at)
        if reason is not None:
            return reason
        # backstop: pods pending in the scheduler but INVISIBLE to the
        # intake (gang WaitTime releases, pods applied before the loop
        # wired) — while the gate tracks anything, its own deadlines
        # govern and the backstop stays quiet
        if self.gate.depth() == 0 \
                and (last is None or at - last >= self.cfg.idle_wake_s) \
                and self.scheduler.cache.pending:
            return "idle"
        return None

    def fire_round(self, reason: str,
                   now: Optional[float] = None) -> List:
        """Fire one adaptively-triggered round through the shared tick
        machinery. Returns the taken arrival entries (requeued on a
        typed round failure)."""
        from koordinator_tpu.client.leaderelection import FencingError
        from koordinator_tpu.service.client import (
            SolverOverloaded,
            SolverUnavailable,
        )

        at = self._clock() if now is None else now
        bus_now = self._now_fn()
        if self._auditor is not None:
            if self.pipeline is not None and self._auditor.sweep_due():
                self.pipeline.drain("auditor-sweep")
            self._auditor.on_round(now=bus_now)
        batch = self.gate.take_round()
        STREAM_TRIGGERS.inc({"reason": reason})
        STREAM_BATCH_PODS.observe(len(batch))
        with self._lock:
            self._rounds += 1
            self._last_trigger = reason
            self._last_fired_at = at
            self.round_log.append((
                reason, bus_now,
                # admission (= bus application) order, NOT the lane-
                # priority claim order: the replay re-applies these in
                # the order the pending queue originally saw them
                tuple(e.uid for e in sorted(batch, key=lambda e: e.seq)),
            ))
        try:
            if self.pipeline is not None:
                self.pipeline.submit_round(now=bus_now, trigger=reason)
                self.pipeline.prestage(now=bus_now)
            else:
                out = self.scheduler.schedule_pending(now=bus_now,
                                                      trigger=reason)
                self._on_round_result(out)
        except (SolverUnavailable, SolverOverloaded) as e:
            with self._lock:
                self._skipped += 1
            ROUNDS_SKIPPED.inc({
                "reason": "solver-overloaded"
                if isinstance(e, SolverOverloaded)
                else "solver-unavailable"
            })
            self.gate.requeue_taken(batch, now=at)
            self._log(f"streaming round skipped: {e}")
        except FencingError as e:
            with self._lock:
                self._skipped += 1
            ROUNDS_SKIPPED.inc({"reason": "leadership-lost"})
            forgotten = self.scheduler.forget_assumed_unbound()
            self.gate.requeue_taken(batch, now=at)
            self._log(f"streaming round fenced: {e}; forgot "
                      f"{len(forgotten)} assumed-but-unbound pod(s)")
        except BaseException:
            # an UNTYPED failure (a deferred publish-side bug surfacing
            # at this round boundary, a stopped pipeline) still fails
            # loudly — but the taken batch goes back first, or its
            # entries would leak in-flight forever and break the
            # zero-silent-drop accounting the chaos slice pins
            self.gate.requeue_taken(batch, now=at)
            raise
        return batch

    def _on_round_result(self, result) -> None:
        """Round retired (publisher thread in pipelined mode, inline
        otherwise): fold outcomes, evict expired pods from the bus."""
        counts = self.gate.resolve_round(result)
        if counts["expired"] and self._delete is not None:
            for uid, node in result.items():
                if node is None and uid not in result.waiting \
                        and self.gate.outcome(uid) == OUTCOME_EXPIRED:
                    self._delete(uid)

    # -- drive modes ---------------------------------------------------------

    def pump(self, now: Optional[float] = None,
             drain: bool = True) -> Optional[str]:
        """Deterministic single step (fake-clock tests): fire at most
        one round if the trigger is due at ``now``; with ``drain``,
        wait the pipelined round out so outcomes are resolved on
        return. Returns the trigger reason or None. Under HA the step
        first ticks the lease gate — a standby pumps nothing (and
        surfaces deferred publish errors); the tick itself is the
        acquisition path, so a pump on an expired lease IS the
        promotion."""
        if not self._lease_held(now):
            self._standby_step()
            return None
        if self._controller is not None:
            self._controller.maybe_reconcile(now=now)
        reason = self.due(now)
        if reason is None:
            return None
        self.fire_round(reason, now=now)
        if drain and self.pipeline is not None:
            self.pipeline.drain("streaming-pump")
        return reason

    def run(self) -> None:
        """The serving loop body (blocks; use :meth:`start` for a
        thread). Paces itself on the trigger: sleeps to the earliest
        queued deadline, wakes early on arrivals (watermark), fires,
        repeats."""
        monitor = getattr(self.scheduler, "monitor", None)
        self._run_done.clear()
        try:
            self._run_body(monitor)
        finally:
            self._run_done.set()

    def _run_body(self, monitor) -> None:
        while not self._stopped.is_set():
            now = self._clock()
            if monitor is not None:
                monitor.check_stuck()
            if not self._lease_held():
                # standby: hold no rounds, keep the intake watch-fed,
                # retry on the elector's cadence (wake early on stop)
                self._standby_step()
                self._stopped.wait(self._elector.retry_period)
                continue
            if self._controller is not None:
                self._controller.maybe_reconcile(now=now)
            reason = self.due(now)
            if reason is not None:
                self.fire_round(reason, now=now)
                continue
            deadline = self.gate.next_deadline()
            if deadline is None:
                timeout = self.cfg.idle_wake_s
            else:
                timeout = max(0.0, deadline - now)
                if self.cfg.min_round_interval_s:
                    with self._lock:
                        last = self._last_fired_at
                    if last is not None:
                        floor = (last + self.cfg.min_round_interval_s
                                 - now)
                        timeout = max(timeout, floor)
            # parks on the gate condition keyed to the CURRENT depth:
            # an arrival notifies, so a watermark-crossing burst fires
            # immediately instead of waiting out the old deadline
            self.gate.wait_for_work(
                min(timeout, self.cfg.idle_wake_s),
                depth=self.gate.depth(),
            )

    def start(self) -> "StreamingLoop":
        self._thread = threading.Thread(
            target=self.run, daemon=True, name="koord-streaming"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        self.gate.wake()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        # run() may be executing on a CALLER's thread (run_loop's
        # streaming branch): wait it out before tearing the pipeline
        # down under a mid-round loop. Idempotent second stops sail
        # through (the event is set whenever no run() is active).
        self._run_done.wait(timeout=10.0)
        if self._hooked_backend is not None:
            self._hooked_backend.on_flip_back = self._prev_flip
            if hasattr(self._hooked_backend, "on_flip_degraded"):
                self._hooked_backend.on_flip_degraded = \
                    self._prev_degraded
            self._hooked_backend = None
        if self.pipeline is not None:
            try:
                self.pipeline.drain("streaming-stop",
                                    raise_deferred=False)
            finally:
                self.pipeline.stop()
        # unchain the remove_pod hook: a re-wired scheduler must not
        # keep forgetting into a stopped loop's gate
        self.scheduler.remove_pod = self._prev_remove
        # unchain the promotion hook likewise — a later promotion of
        # this elector must not adopt into a stopped loop
        if self._elector is not None:
            self._elector.on_started_leading = self._prev_started
            self._elector = None
        timelines = getattr(self.scheduler, "timelines", None)
        if timelines is not None and hasattr(timelines, "set_drop_hook"):
            timelines.set_drop_hook(None)

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Fire rounds until every tracked arrival resolves (or the
        wall timeout passes). Benches/tests call this after the last
        submission; returns True when fully drained. While the loop
        THREAD is running it stays the only round-firer (submit_round
        is coordinator-side single-threaded): this just wakes it and
        waits."""
        deadline = time.monotonic() + timeout_s
        running = self._thread is not None and self._thread.is_alive()
        while time.monotonic() < deadline:
            if not running and self.pipeline is not None:
                self.pipeline.drain("streaming-drain")
            if self.gate.unresolved() == 0 \
                    and not self.scheduler.cache.pending:
                if self.pipeline is not None:
                    # the last round may still be retiring: outcomes
                    # resolve on the publisher, so wait it out
                    self.pipeline.drain("streaming-drain")
                    if self.gate.unresolved() != 0 \
                            or self.scheduler.cache.pending:
                        continue
                return True
            if running:
                self.gate.wake()
                time.sleep(0.002)
            elif self.gate.depth() or self.scheduler.cache.pending:
                self.fire_round("idle")
            else:
                time.sleep(0.001)
        return self.gate.unresolved() == 0 \
            and not self.scheduler.cache.pending

    # -- read side -----------------------------------------------------------

    def status(self) -> dict:
        """Debug-mux payload (registered as ``streaming``): intake
        depths + shed accounting, trigger counters, and the rolling
        submit→bind p50/p99 the serving mode is judged on."""
        with self._lock:
            rounds = self._rounds
            skipped = self._skipped
            last = self._last_trigger
        out = {
            "rounds": rounds,
            "rounds_skipped": skipped,
            "last_trigger": last,
            "gate": self.gate.status(),
        }
        if self._elector is not None:
            out["leader"] = self._elector.is_leader()
        if self._controller is not None:
            out["slo"] = {"decisions": self._controller.decisions_total()}
        timelines = getattr(self.scheduler, "timelines", None)
        if timelines is not None:
            # the headline serving numbers: rolling-window submit→bind
            # percentiles + the dropped-sample backpressure counter
            out["latency"] = timelines.status()
        return out
