"""ElasticQuota PostFilter preemption: evict lower-priority same-quota
pods to make room (reference: pkg/scheduler/plugins/elasticquota/
preempt.go:103-294).

Semantics reproduced from ``SelectVictimsOnNode``:

- a pod can preempt a victim iff the victim is preemptible, has lower
  priority, and belongs to the SAME quota group (``canPreempt``,
  preempt.go:276-294);
- per node: remove every candidate victim; if the pod still doesn't fit
  the node, the node is unsuitable; otherwise *reprieve* victims from
  most-important down (priority desc, then earlier assignment —
  util.MoreImportantPod), re-adding each unless (a) the pod no longer
  fits with it back, or (b) the quota's ``used + podReq`` exceeds its
  ``usedLimit`` (runtime) — the reference checks (b) against the
  PostFilter-snapshot used, so when the quota is over its runtime no
  victim is reprieved (preempt.go:176-201);
- PodDisruptionBudget grouping (preempt.go:219-267) has no counterpart
  here (no PDB objects in the typed model).

Node fitness uses the same canonical filters as the solver (fit +
loadaware; usage does not change on eviction, matching the reference
where NodeMetric lags eviction).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    PodSpec,
    resources_to_vector,
)
from koordinator_tpu.oracle.scheduler import (
    fit_filter_node,
    loadaware_filter_node,
)
from koordinator_tpu.state.cluster import (
    DEFAULT_USAGE_THRESHOLDS,
    lower_nodes,
)
from koordinator_tpu.apis.extension import PriorityClass

#: CycleState key: callers batch-preempting many pods stash the lowered
#: node arrays here so each PostFilter doesn't re-lower the cluster
ARRAYS_STATE_KEY = "__preempt_node_arrays__"


def can_preempt(pod: PodSpec, victim: PodSpec) -> bool:
    """preempt.go:276-294 canPreempt: preemptible victim, strictly lower
    priority, same quota group."""
    if not victim.preemptible:
        return False
    if pod.priority <= victim.priority:
        return False
    return (pod.quota or "") == (victim.quota or "")


def _more_important(p: PodSpec) -> tuple:
    """Sort key for util.MoreImportantPod: higher priority first, then
    earlier assignment."""
    return (-p.priority, p.assign_time)


def select_victims_on_node(
    pod: PodSpec,
    node_index: int,
    candidates: Sequence[PodSpec],
    arrays,
    quota_used: Optional[np.ndarray],
    used_limit: Optional[np.ndarray],
    thresholds: np.ndarray,
    prod_thresholds: np.ndarray,
) -> Optional[List[PodSpec]]:
    """Victims on one node, or None if preemption there can't help."""
    victims = [v for v in candidates if can_preempt(pod, v)]
    if not victims:
        return None
    req = resources_to_vector(pod.requests)
    alloc = arrays.alloc[node_index].astype(np.int64)
    base_used = arrays.used_req[node_index].astype(np.int64)
    removed = sum(
        (resources_to_vector(v.requests) for v in victims),
        np.zeros_like(req),
    )
    is_ds = pod.is_daemonset
    is_prod = pod.priority_class == PriorityClass.PROD
    if not loadaware_filter_node(
        arrays.alloc[node_index],
        arrays.usage[node_index],
        arrays.prod_usage[node_index],
        bool(arrays.metric_fresh[node_index]),
        thresholds,
        prod_thresholds,
        is_ds,
        is_prod,
    ):
        return None  # eviction can't fix a usage-threshold failure
    if not fit_filter_node(req, alloc, base_used - removed):
        return None  # doesn't fit even with every victim gone

    # quota gate is constant across the reprieve loop (preempt.go:191-199
    # checks the PostFilter-snapshot used): over-runtime quota means no
    # reprieve at all
    quota_blocks = False
    if quota_used is not None and used_limit is not None:
        dims = req > 0
        quota_blocks = bool(np.any((quota_used + req)[dims] > used_limit[dims]))

    final: List[PodSpec] = []
    kept = base_used - removed
    for v in sorted(victims, key=_more_important):
        if quota_blocks:
            final.append(v)
            continue
        v_req = resources_to_vector(v.requests)
        if fit_filter_node(req, alloc, kept + v_req):
            kept = kept + v_req  # reprieved
        else:
            final.append(v)
    return final if final else None


def find_preemption(
    snapshot: ClusterSnapshot,
    pod: PodSpec,
    quota_used: Optional[np.ndarray] = None,
    used_limit: Optional[np.ndarray] = None,
    arrays=None,
    thresholds: Optional[np.ndarray] = None,
    prod_thresholds: Optional[np.ndarray] = None,
) -> Optional[Tuple[str, List[PodSpec]]]:
    """(node name, victims) for the cheapest viable preemption, or None.

    Candidate nodes are ranked by fewest victims then lowest top victim
    priority (the spirit of the reference's pickOneNodeForPreemption).
    """
    if thresholds is None:
        thresholds = resources_to_vector(DEFAULT_USAGE_THRESHOLDS)
    if prod_thresholds is None:
        prod_thresholds = resources_to_vector({})
    if arrays is None:
        arrays = lower_nodes(snapshot)
    by_node: Dict[str, List[PodSpec]] = {}
    for p in snapshot.pods:
        if p.node_name is not None:
            by_node.setdefault(p.node_name, []).append(p)
    index = arrays.index()

    best: Optional[Tuple[str, List[PodSpec]]] = None
    best_key = None
    for node_name, candidates in by_node.items():
        i = index.get(node_name)
        if i is None or not arrays.schedulable[i]:
            continue
        victims = select_victims_on_node(
            pod, i, candidates, arrays, quota_used, used_limit,
            thresholds, prod_thresholds,
        )
        if victims is None:
            continue
        key = (len(victims), max(v.priority for v in victims))
        if best_key is None or key < best_key:
            best, best_key = (node_name, victims), key
    return best


def plan_defrag(
    snapshot: ClusterSnapshot,
    target_req: np.ndarray,
    max_victim_priority: int,
    arrays=None,
) -> Optional[Tuple[str, List[PodSpec]]]:
    """Headroom repack oracle: the cheapest node to DRAIN until a
    ``target_req``-sized hole (a gang member's shape) fits, or None.

    Drain candidacy is preemptible residents strictly below
    ``max_victim_priority``; draining goes least-important-first (the
    reverse of the preemption reprieve order), so the plan evicts the
    cheapest tail of each fragmented node. Nodes where the hole already
    fits mean no drain is needed at all (returns None). Ranked by
    fewest drained, then node iteration order — the scalar twin of
    ``ops/preempt.headroom_repack``, property-tested bit-identical in
    tests/test_rebalance_oracle.py."""
    if arrays is None:
        arrays = lower_nodes(snapshot)
    for i in range(arrays.n):
        if arrays.schedulable[i] and fit_filter_node(
            target_req,
            arrays.alloc[i].astype(np.int64),
            arrays.used_req[i].astype(np.int64),
        ):
            return None  # a hole already exists somewhere
    by_node: Dict[str, List[PodSpec]] = {}
    for p in snapshot.pods:
        if p.node_name is not None:
            by_node.setdefault(p.node_name, []).append(p)
    index = arrays.index()

    best: Optional[Tuple[str, List[PodSpec]]] = None
    best_key = None
    for node_name, residents in by_node.items():
        i = index.get(node_name)
        if i is None or not arrays.schedulable[i]:
            continue
        cand = sorted(
            (
                p for p in residents
                if p.preemptible and p.priority < max_victim_priority
            ),
            key=_more_important,
        )
        alloc = arrays.alloc[i].astype(np.int64)
        kept = arrays.used_req[i].astype(np.int64)
        drained: List[PodSpec] = []
        fits = False
        for v in reversed(cand):
            kept = kept - resources_to_vector(v.requests)
            drained.append(v)
            if fit_filter_node(target_req, alloc, kept):
                fits = True
                break
        if not fits:
            continue
        key = (len(drained),)
        if best_key is None or key < best_key:
            best, best_key = (node_name, drained), key
    return best
