"""The scheduler: cache + plugin framework + the TPU placement backend.

The reference wires koordinator plugins into the k8s scheduling framework
and schedules pod-at-a-time (cmd/koord-scheduler/app/server.go). Here the
same plugin architecture exists, but the default backend is the batched
device solver — the ``--placement-backend=jax-tpu`` north star: every
scheduling round takes a consistent snapshot, solves the entire pending
queue on device, and commits the results through assume/forget.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from koordinator_tpu.apis.types import (
    GangSpec,
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
    ReservationSpec,
)
from koordinator_tpu.device.cache import NodeDeviceCache
from koordinator_tpu.gang.manager import GangManager
from koordinator_tpu.models.finegrained import FineGrained
from koordinator_tpu.models.placement import PlacementModel, ScheduleResult
from koordinator_tpu.numa.manager import ResourceManager, TopologyOptions
from koordinator_tpu.quota.core import GroupQuotaManager
from koordinator_tpu.quota.trees import QuotaTreeRegistry
from koordinator_tpu.scheduler.cache import SchedulerCache
from koordinator_tpu.scheduler.framework import (
    CycleState,
    ScheduleOutcome,
    SchedulingFramework,
)
from koordinator_tpu.scheduler.reservation_controller import (
    ReservationController,
)
from koordinator_tpu.obs.device import DEVICE_OBS
from koordinator_tpu.obs.timeline import PodTimelines, lane_of
from koordinator_tpu.obs.trace import TRACER
from koordinator_tpu.scheduler.monitor import (
    DebugRecorder,
    DebugServices,
    SchedulerMonitor,
)
from koordinator_tpu.scheduler.plugins import (
    CoschedulingPlugin,
    DefaultPreBind,
    DeviceSharePlugin,
    ElasticQuotaPlugin,
    LoadAwareScheduling,
    NodeNUMAResourcePlugin,
    NodeResourcesFit,
    ReservationPlugin,
)


class PendingTick:
    """One scheduling round between dispatch and retirement.

    ``begin_tick`` produces it: either an in-flight async solve
    (``inflight`` set) or an already-completed incremental round
    (``result`` set — the BatchedPlacement=false fallback has no device
    half to overlap). ``commit_tick`` consumes it exactly once."""

    __slots__ = ("at", "pending", "inflight", "solve_started", "result",
                 "round_id", "trigger")

    def __init__(self, at, pending=None, inflight=None,
                 solve_started=None, result=None, round_id=0,
                 trigger=None):
        self.at = at
        self.pending = pending or {}
        self.inflight = inflight
        self.solve_started = solve_started
        self.result = result
        #: trace-fabric round number: spans the publisher emits while
        #: retiring this tick carry the SAME id as the coordinator's
        #: staging spans, so cross-thread work joins one trace round
        self.round_id = round_id
        #: why this round fired (streaming mode: watermark | deadline |
        #: idle; None = fixed cadence) — annotated onto the round span
        self.trigger = trigger


class Scheduler:
    """Top-level scheduler with both backends.

    - ``schedule_pending()``: the batched device path (default) — one
      solve over the whole queue, assignments assumed into the cache.
    - ``schedule_one(uid)``: the incremental plugin-chain path (parity,
      debugging, one-off placements).
    """

    def __init__(
        self,
        model: Optional[PlacementModel] = None,
        cluster_total=None,
        enable_preemption: bool = True,
        preemption_backend: str = "device",
    ):
        self.cache = SchedulerCache()
        self.quota_registry = QuotaTreeRegistry(cluster_total=cluster_total or {})
        self.quota_manager = self.quota_registry.default
        self.gang_manager = GangManager()
        self.numa_manager = ResourceManager()
        self.device_cache = NodeDeviceCache()
        self.monitor = SchedulerMonitor(tracer=TRACER)
        self.debug = DebugRecorder()
        self.services = DebugServices()
        #: per-pod submit→staged→solved→published timelines feeding the
        #: scheduler_pod_e2e_seconds{lane} histograms (obs/timeline.py)
        self.timelines = PodTimelines()
        #: round id of the last commit_tick — THIS scheduler's round,
        #: unlike the process-global TRACER counter two wired
        #: schedulers share (the serial publish watchdog mark keys off
        #: it)
        self.last_round_id: Optional[int] = None
        #: pods placed at the Permit barrier: uid -> held node. They hold
        #: resources (assumed) but are not bound until their gang group
        #: completes.
        self._waiting: Dict[str, str] = {}
        #: when each waiting pod entered the Permit barrier (WaitTime expiry)
        self._waiting_since: Dict[str, float] = {}
        #: BatchedPlacement feature gate: False falls back to per-pod
        #: incremental cycles in schedule_pending
        self.batched_placement = True
        #: which victim-selection path _preempt_unplaced dispatches
        #: (docs/DESIGN.md §24): "device" (default) runs the vectorized
        #: joint place+evict solve (ops/preempt.py) with incremental
        #: eviction relowering; "host" keeps the scalar oracle walk
        #: (scheduler/preemption.py) as the hot path; "verify" runs
        #: BOTH and asserts bit-identical nominations — the parity
        #: harness mode the property tests drive.
        if preemption_backend not in ("device", "host", "verify"):
            raise ValueError(
                f"unknown preemption_backend {preemption_backend!r}"
            )
        self.preemption_backend = preemption_backend
        #: preemption eviction sink (set by client.wiring.wire_scheduler):
        #: deletes the victim from the bus so every wired component
        #: observes the eviction — the reference deletes victims via the
        #: API server (defaultpreemption). None = local cache only
        #: (standalone scheduler, no bus).
        self.evict_pod_fn = None
        #: migration arbiter (control/migration.py, docs/DESIGN.md §27):
        #: when set, every eviction source — preemption victims, defrag
        #: drains, rebalance sweeps — passes through it before touching
        #: the sink, and deferred victims stay placed. None = legacy
        #: unthrottled eviction, bit-identical to pre-arbiter behavior.
        self.migration_arbiter = None
        #: monotone round key feeding the arbiter's per-round budget
        self._migration_round = 0
        #: bind publisher (set by client.wiring.wire_scheduler): applies
        #: a round's committed placements back onto the bus. The serial
        #: loop's schedule_and_publish wrapper calls it inline; the
        #: pipelined loop (scheduler/pipeline.py) calls it from the
        #: publisher worker, off the round's critical path. None =
        #: standalone scheduler, nothing to publish to.
        self.publish_result = None
        #: waiting pods' fine-grained allocation state, annotated at the
        #: barrier (uid -> (node name, CycleState))
        self._fine_waiting: Dict[str, tuple] = {}
        #: waiting pods' reservation consumption (uid -> (resv name,
        #: delta vector)) — rolled back if the wait expires
        self._resv_waiting: Dict[str, tuple] = {}
        #: COMMITTED pods' reservation consumption for the current round
        #: (uid -> (resv name, delta vector)), kept until the bind
        #: publishes: a FencingError abort must roll these back too, or
        #: the deposed leader leaves the reservation's credit consumed
        #: (and an allocate_once reservation stuck SUCCEEDED) for a
        #: decision that never became observable. Cleared at round start.
        self._resv_inflight: Dict[str, tuple] = {}
        self.reservation_controller = ReservationController(self.cache)

        self._quota_plugin = ElasticQuotaPlugin(
            self.quota_registry, enable_preemption=enable_preemption
        )
        self._coscheduling = CoschedulingPlugin(
            self.gang_manager,
            on_release=self._on_gang_release,
            on_reject=self._on_gang_reject,
        )
        self._numa_plugin = NodeNUMAResourcePlugin(self.numa_manager)
        self._device_plugin = DeviceSharePlugin(self.device_cache)
        from koordinator_tpu.scheduler.plugins.nodeports import (
            NodePortsPlugin,
        )

        self._ports_plugin = NodePortsPlugin()
        fine = FineGrained(
            numa_plugin=self._numa_plugin,
            device_plugin=self._device_plugin,
            ports_plugin=self._ports_plugin,
        )
        if model is None:
            model = PlacementModel()
        # the model binds to THIS scheduler's managers — a model reused
        # across schedulers would otherwise apply holds to the old one's
        model.fine = fine
        self.model = model
        from koordinator_tpu.scheduler.plugins.lowering import (
            LOWERING_KEY,
            THRESHOLDS_KEY,
        )

        self.framework = SchedulingFramework(
            plugins=[
                ReservationPlugin(),
                self._coscheduling,
                self._quota_plugin,
                self._numa_plugin,
                self._device_plugin,
                self._ports_plugin,
                NodeResourcesFit(
                    weights=model.resource_weights,
                    weight=model.config.fit_weight,
                ),
                # configured from the model so the incremental chain and
                # the batched solver apply the same thresholds/modes
                LoadAwareScheduling(
                    resource_weights=model.resource_weights,
                    usage_thresholds=model.usage_thresholds,
                    prod_usage_thresholds=model.prod_usage_thresholds,
                    scaling_factors=model.scaling_factors,
                    score_according_prod=model.config.score_according_prod,
                    weight=model.config.loadaware_weight,
                ),
                DefaultPreBind(),
            ],
            debug=self.debug,
            cycle_seed={
                LOWERING_KEY: model.lowering_kwargs(),
                THRESHOLDS_KEY: (
                    np.asarray(model.params.thresholds),
                    np.asarray(model.params.prod_thresholds),
                ),
            },
        )
        self.services.register("pod-timelines", self.timelines.status)
        self.services.register("monitor", self.monitor.status)
        self.services.register(
            "Coscheduling",
            lambda: {
                name: {
                    "min_member": rec.spec.min_member,
                    "waiting": sorted(rec.waiting),
                    "bound": sorted(rec.bound),
                    "once_satisfied": rec.once_satisfied,
                }
                for name, rec in self.gang_manager.gangs.items()
            },
        )
        self.services.register(
            "ElasticQuota",
            lambda: {
                name: {
                    "request": info.request.tolist(),
                    "used": info.used.tolist(),
                    "runtime": info.runtime.tolist(),
                }
                for name, info in self.quota_manager.quotas.items()
            },
        )

    # -- informer-style event intake ---------------------------------------

    def add_node(self, node: NodeSpec) -> None:
        self.cache.add_node(node)

    def remove_node(self, name: str) -> None:
        """Node deleted: drop the node and every per-node auxiliary state
        (metric, NUMA topology, devices)."""
        self.cache.remove_node(name)
        self.cache.node_metrics.pop(name, None)
        self.numa_manager.update_topology(name, TopologyOptions())
        self.device_cache.update_node(name, [])

    def remove_quota(self, name: str) -> None:
        self.cache.quotas.pop(name, None)
        # the registry withdraws the quota's propagated accounting from
        # its ancestors before dropping the node
        self.quota_registry.remove_quota(name)

    def remove_gang(self, name: str) -> None:
        self.cache.gangs.pop(name, None)
        record = self.gang_manager.gangs.pop(name, None)
        key = self.gang_manager.gang_group_key.pop(name, None)
        group = self.gang_manager.groups.get(key) if key else None
        if record is not None:
            for uid in list(record.children):
                self.gang_manager.pod_gang.pop(uid, None)
                if group is not None:
                    # stale cycle attempts would wedge the group's
                    # schedule cycle (ganggroup.go:101-124 counts them)
                    group.child_cycle.pop(uid, None)
        if group is not None:
            group.gangs.discard(name)
            if not group.gangs:
                self.gang_manager.groups.pop(key, None)

    def remove_reservation(self, name: str) -> None:
        self.cache.reservations.pop(name, None)

    def remove_node_metric(self, name: str) -> None:
        self.cache.node_metrics.pop(name, None)

    def update_pod(self, pod: PodSpec) -> None:
        """Pod object changed (the informer MODIFIED path). Accounting
        side effects (quota/gang registration) only re-run when the
        accounted fields actually changed — a status update must not
        double-register requests."""
        old = self.cache.pods.get(pod.uid) or self.cache.pending.get(pod.uid)
        if old is None:
            self.add_pod(pod)
            return
        if old is pod:
            # in-process bus: the same object may have been MUTATED in
            # place by another component's bind (schedule_and_publish
            # re-applies the bound object). A standby must still observe
            # the binding, or a failover would re-place a bound pod.
            if (
                pod.node_name is not None
                and not getattr(pod, "waiting_permit", False)
                and pod.uid in self.cache.pending
            ):
                self._observe_binding(pod)
            return
        if (
            old.node_name is None
            and pod.node_name is not None
            and not getattr(pod, "waiting_permit", False)
        ):
            # another scheduler's Bind arrived as a fresh object: mirror
            # the assume (the reference's assign cache does this on the
            # informer update of a scheduled pod)
            self._observe_binding(pod)
            return
        accounted_changed = (
            old.quota != pod.quota
            or old.requests != pod.requests
            or old.gang != pod.gang
            or old.preemptible != pod.preemptible
        )
        assigned = old.node_name is not None
        if accounted_changed and not assigned:
            # the remove/add round-trip re-runs the quota/gang side
            # effects, but the pod never left the pending queue: its
            # timeline (the submit stamp above all) must survive, or
            # a mid-wait field refresh hides the queue-wait tail from
            # scheduler_pod_e2e_seconds
            with self.timelines.preserved(pod.uid):
                self.remove_pod(old)
                self.add_pod(pod)
            return
        # object refresh preserving placement state
        pod.node_name = old.node_name
        pod.assign_time = old.assign_time
        if accounted_changed:
            # assigned pod with changed accounting: swap the quota
            # request AND used deltas in place — a remove/add round trip
            # would drop the 'used' accounting (add_pod never re-accounts
            # already-assigned pods) and the NUMA/device holds
            self._quota_plugin.on_pod_delete(old)
            self._account_quota(old, release=True)
            if old.gang != pod.gang:
                self.gang_manager.on_pod_delete(pod.uid)
                if pod.gang:
                    self.gang_manager.on_pod_add(pod.uid, pod.gang)
                    self.gang_manager.on_pod_bound(pod.uid)
            self._quota_plugin.on_pod_add(pod)
            self._account_quota(pod)
        if pod.uid in self.cache.pods:
            self.cache.pods[pod.uid] = pod
        else:
            self.cache.pending[pod.uid] = pod

    def update_node_metric(self, metric: NodeMetric) -> None:
        self.cache.update_node_metric(metric)

    def update_gang(self, spec: GangSpec) -> None:
        self.cache.update_gang(spec)
        self.gang_manager.update_gang(spec)

    def update_quota(self, spec: QuotaSpec) -> None:
        self.cache.update_quota(spec)
        self.quota_registry.update_quota(spec)

    def update_reservation(self, spec: ReservationSpec) -> None:
        self.cache.update_reservation(spec)

    def update_node_topology(self, node_name: str, options: TopologyOptions) -> None:
        """NodeResourceTopology CRD intake (reference:
        nodenumaresource/topology_options.go sync)."""
        self.numa_manager.update_topology(node_name, options)

    def update_node_devices(self, node_name: str, entries) -> None:
        """Device CRD intake (reference: deviceshare/device_cache.go)."""
        self.device_cache.update_node(node_name, entries)

    def add_pod(self, pod: PodSpec) -> None:
        self.cache.add_pod(pod)
        bound = (
            pod.node_name is not None
            and not getattr(pod, "waiting_permit", False)
        )
        if not bound:
            # the pod entered the pending queue: open its timeline
            # (submit == enqueue on the in-process bus)
            self.timelines.submit(pod.uid, lane_of(pod))
        if pod.gang:
            self.gang_manager.on_pod_add(pod.uid, pod.gang)
            if bound:
                self.gang_manager.on_pod_bound(pod.uid)
        self._quota_plugin.on_pod_add(pod)
        if bound:
            # an already-bound pod entering the cache (restart catch-up /
            # standby watch): its quota 'used' was accounted by whoever
            # bound it — mirror it here, as the reference's OnPodAdd does
            # for scheduled pods (elasticquota plugin.go updatePodUsed)
            self._account_quota(pod)

    def _observe_binding(self, pod: PodSpec) -> None:
        """A binding decided elsewhere became visible: promote the pod
        pending -> assigned and mirror the accounting the deciding
        scheduler applied locally (quota used, gang bound)."""
        self.cache.promote_assigned(pod)
        # a bind this scheduler did not make is not its latency sample:
        # drop the timeline unobserved (a standby would otherwise leak
        # one open timeline per leader-bound pod until the ring evicts
        # genuine pending pods' stamps)
        self.timelines.forget(pod.uid)
        self._account_quota(pod)
        if pod.gang:
            self.gang_manager.on_pod_bound(pod.uid)

    def _release_node_holds(self, pod: PodSpec) -> None:
        """Release a pod's fine-grained node holds (NUMA cpuset +
        devices) — shared by the informer delete path and the fencing
        forget so the two release sequences cannot drift."""
        if pod.node_name is None:
            return
        self.numa_manager.release(pod.node_name, pod.uid)
        node_device = self.device_cache.get(pod.node_name)
        if node_device is not None:
            node_device.release(pod.uid)

    def remove_pod(self, pod: PodSpec) -> None:
        cached = self.cache.pods.get(pod.uid)
        was_assigned = cached is not None and cached.node_name is not None
        if was_assigned:
            # release any fine-grained holds (cpuset/NUMA + devices)
            self._release_node_holds(cached)
        self.cache.remove_pod(pod.uid)
        self.gang_manager.on_pod_delete(pod.uid)
        self._quota_plugin.on_pod_delete(pod)
        self._fine_waiting.pop(pod.uid, None)
        # a deleted waiting pod never ran: undo its reservation consumption
        self._rollback_reservation(pod.uid)
        # a deleted COMMITTED pod ran: its published credit is the
        # reservation controller's to reconcile — just drop the
        # rollback window entry
        self._resv_inflight.pop(pod.uid, None)
        if was_assigned and (
            not getattr(cached, "waiting_permit", False)
            or pod.uid in self._waiting
        ):
            # an assigned pod's quota 'used' was accounted at assume time
            # (bind or Permit hold, both local) or at bound-pod intake
            # (standby/restart); a STANDBY never accounts a Permit-held
            # pod (waiting_permit, not in our _waiting), so it must not
            # release one either
            self._account_quota(cached, release=True)
        self._waiting.pop(pod.uid, None)
        self._waiting_since.pop(pod.uid, None)
        # a deleted/evicted pod's open timeline is not a latency sample
        self.timelines.forget(pod.uid)

    # -- scheduling ---------------------------------------------------------

    def schedule_pending(self, now: Optional[float] = None,
                         trigger: Optional[str] = None) -> ScheduleResult:
        """One batched round: expire stale state (gang WaitTime,
        reservations), solve the whole pending queue on device, and assume
        committed placements (and waiting holds) into the cache.

        The serial composition of the split tick: :meth:`begin_tick`
        (round-start bookkeeping + snapshot + async dispatch) directly
        followed by :meth:`commit_tick` (materialize + epilogue). The
        pipelined loop (scheduler/pipeline.py) calls the halves from
        different threads so the epilogue and publish ride the publisher
        worker while the next round stages. ``trigger`` annotates why
        the round fired (streaming mode) onto its trace spans."""
        return self.commit_tick(self.begin_tick(now, trigger=trigger))

    def begin_tick(self, now: Optional[float] = None,
                   trigger: Optional[str] = None) -> "PendingTick":
        """Round start through solve DISPATCH: expire stale state, take
        the snapshot, and hand the pending queue to the model without
        materializing results. Raises the same typed solver errors a
        blocking round would (the dispatch is where a sidecar outage
        surfaces). ``trigger`` annotates WHY the round fired (the
        streaming mode's adaptive triggers, docs/DESIGN.md §22) onto
        the round's trace spans."""
        from koordinator_tpu.metrics.components import PENDING_PODS

        at0 = now if now is not None else time.time()
        # device observatory round boundary: drives an armed profiler
        # window over the next K rounds (one flag read when none is)
        DEVICE_OBS.on_round()
        rid = TRACER.begin_round()
        # watchdog mark: stays open until commit_tick retires the round
        # (scheduler/monitor.py flags it if it never does)
        TRACER.mark_open(f"round:{rid}", round_id=rid)
        t_begin = TRACER.now()
        try:
            # the previous round's committed binds are published by now
            # (or were forgotten on abort): their rollback window is
            # over. The pipelined loop preserves this ordering — a tick
            # begins only after the previous tick's publish retired.
            self._resv_inflight = {}
            if self.migration_arbiter is not None:
                self._migration_round += 1
                self.migration_arbiter.begin_round(self._migration_round)
            self.expire_waiting(at0)
            self.reservation_controller.sync(at0)
            if not self.batched_placement:
                return PendingTick(
                    at=at0, result=self._schedule_pending_incremental(now),
                    round_id=rid, trigger=trigger,
                )
            snapshot = self.cache.snapshot(now=now)
            pending = {pod.uid: pod for pod in snapshot.pending_pods}
            PENDING_PODS.set(len(pending))
            self.timelines.mark_many(pending, "staged")
            solve_started = time.monotonic()
            inflight = self.model.schedule_async(snapshot)
        except BaseException:
            # a FAILED round (the dispatch is where a sidecar outage
            # surfaces) is handled by run_loop's skip path — close the
            # mark or the watchdog flags the skipped round forever
            TRACER.mark_closed(f"round:{rid}")
            raise
        TRACER.emit("begin_tick", cat="tick", t0=t_begin,
                    round_id=rid,
                    args={"pending": len(pending),
                          **({"trigger": trigger} if trigger else {})})
        return PendingTick(
            at=at0, pending=pending, inflight=inflight,
            solve_started=solve_started, round_id=rid, trigger=trigger,
        )

    def commit_tick(self, tick: "PendingTick") -> ScheduleResult:
        """Materialize a :meth:`begin_tick` dispatch and run the typed
        epilogue: assume committed placements (and waiting holds) into
        the cache, resolve Permit barriers, run batched preemption."""
        from koordinator_tpu.metrics.components import (
            BATCH_SOLVE_DURATION,
            SCHEDULING_ATTEMPTS,
        )

        # the round this scheduler just committed — keyed off the tick,
        # not the process-global round counter, so two wired schedulers
        # in one process (leader + standby) never collide on watchdog
        # mark keys (wiring's serial publish wrapper reads this)
        self.last_round_id = tick.round_id
        if tick.result is not None:
            TRACER.mark_closed(f"round:{tick.round_id}", name="round",
                               cat="tick")
            return tick.result  # incremental fallback: epilogue ran inline
        at0 = tick.at
        pending = tick.pending
        try:
            result = tick.inflight.finalize()
        except BaseException:
            # solver died mid-solve: the round failed (and defers /
            # skips via the callers' typed handlers) — it is not STUCK
            TRACER.mark_closed(f"round:{tick.round_id}")
            raise
        try:
            t_epilogue = TRACER.now()
            BATCH_SOLVE_DURATION.observe(
                time.monotonic() - tick.solve_started)
            for uid, node in result.items():
                SCHEDULING_ATTEMPTS.inc(
                    {"result": "scheduled" if node is not None
                     else "unschedulable"}
                )
            at = at0
            for uid, node in result.items():
                if node is not None:
                    self.cache.assume_pod(uid, node, now=at)
                    self.gang_manager.on_pod_bound(uid)
                    # keep the host quota manager's used in sync with the
                    # device solve (the solve derives used from the
                    # snapshot; observers read the manager)
                    self._account_quota(pending.get(uid))
                    if uid in result.resv_committed:
                        # committed consumption stays rollback-able until
                        # the bind publishes (fencing-abort coverage)
                        self._resv_inflight[uid] = result.resv_committed[uid]
            for uid, node in result.waiting.items():
                # waiting gang members hold their node (and their quota,
                # as the incremental Reserve does) but are not bound —
                # flagged so bus observers (node agents) don't treat them
                # as running
                self.cache.assume_pod(uid, node, now=at)
                held = self.cache.pods.get(uid)
                if held is not None:
                    held.waiting_permit = True
                self._account_quota(pending.get(uid))
                self._waiting[uid] = node
                self._waiting_since.setdefault(uid, at)
                self.gang_manager.on_pod_waiting(uid)
                if uid in result.resv_allocs:
                    self._resv_waiting[uid] = result.resv_allocs[uid]
            self._fine_waiting.update(result.fine_states)
            self._resolve_waiting(result)
            self._preempt_unplaced(result, pending, at)
            self.timelines.mark_many(
                [uid for uid, node in result.items() if node is not None],
                "solved",
            )
        except BaseException:
            # a FAILED epilogue (a fenced preemption eviction raising
            # FencingError mid-takeover) is handled by run_loop's
            # skip/forget path — close the mark or the watchdog flags
            # the already-retired round as a ghost forever
            TRACER.mark_closed(f"round:{tick.round_id}")
            raise
        TRACER.emit("epilogue", cat="tick", t0=t_epilogue,
                    round_id=tick.round_id)
        TRACER.mark_closed(
            f"round:{tick.round_id}", name="round", cat="tick",
            args={
                "placed": sum(1 for v in result.values() if v is not None),
                "total": len(result),
                **({"trigger": tick.trigger} if tick.trigger else {}),
            },
        )
        return result

    def _schedule_pending_incremental(self, now: Optional[float]) -> ScheduleResult:
        """BatchedPlacement=false fallback: one incremental cycle per
        pending pod in schedule order (the reference's only mode)."""
        from koordinator_tpu.state.cluster import schedule_order

        held_before = set(self._waiting)
        pending = list(self.cache.pending.values())
        order = schedule_order(pending)
        assignments: Dict[str, Optional[str]] = {}
        waiting: Dict[str, str] = {}
        for idx in order:
            pod = pending[idx]
            outcome = self.schedule_one(pod.uid, now=now)
            if outcome.status == "bound":
                assignments[pod.uid] = outcome.node
            elif outcome.status == "waiting":
                waiting[pod.uid] = outcome.node
            else:
                assignments[pod.uid] = None
        # siblings released by a later member's Permit ALLOW — this
        # round's entrants AND previously-held ones — are bound, not
        # waiting: report them committed so the publish loop confirms
        # their (still-open) assumes, exactly like the batched path's
        # _resolve_waiting
        for uid, node in list(waiting.items()):
            pod = self.cache.pods.get(uid)
            if pod is not None and not getattr(pod, "waiting_permit", False):
                waiting.pop(uid)
                assignments[uid] = node
        for uid in held_before.difference(self._waiting, assignments):
            pod = self.cache.pods.get(uid)
            if pod is not None and pod.node_name is not None \
                    and not getattr(pod, "waiting_permit", False):
                assignments[uid] = pod.node_name
        return ScheduleResult(assignments, waiting=waiting)

    #: at most this many preemption scans per batched round
    MAX_PREEMPTIONS_PER_ROUND = 32

    def _preempt_unplaced(self, result: ScheduleResult, pending, now) -> None:
        """Batched PostFilter: for pods the solve could not place, try
        same-quota lower-priority preemption (preempt.go). Victims are
        evicted now; the preemptor binds in a later round once capacity
        frees — the reference's nominate-then-wait timing."""
        unplaced = [
            uid
            for uid, node in result.items()
            if node is None and uid not in result.waiting
        ]
        if not unplaced:
            return
        snapshot = self.cache.snapshot(now=now)
        assigned = [p for p in snapshot.pods if p.preemptible]
        if not assigned:
            return
        from koordinator_tpu.metrics.components import (
            PREEMPT_VICTIMS,
            PREEMPTION_ATTEMPTS,
        )
        from koordinator_tpu.scheduler.preemption import (
            ARRAYS_STATE_KEY,
            can_preempt,
        )
        from koordinator_tpu.state.cluster import (
            evict_resident_rows,
            lower_nodes,
        )

        backend = self.preemption_backend
        if backend != "host" and not self._quota_plugin.enable_preemption:
            return  # same gate the host post_filter applies internally
        min_priority = min(p.priority for p in assigned)
        arrays = None
        resident = world = None
        attempts = 0
        result.nominations = {}
        for uid in unplaced:
            if attempts >= self.MAX_PREEMPTIONS_PER_ROUND:
                break
            pod = pending.get(uid)
            if pod is None or pod.priority <= min_priority:
                continue  # no strictly-lower-priority victim can exist
            attempts += 1
            PREEMPTION_ATTEMPTS.inc()
            if arrays is None:
                arrays = lower_nodes(snapshot, **self.model.lowering_kwargs())
                if backend != "host":
                    resident = self.model.lower_residents(snapshot, arrays)
                    world = self.model.resident_world(resident)
            if backend == "host":
                # seeded like a plugin-chain cycle: the preemption filter
                # must run with the model's thresholds/aggregated profile
                state = CycleState(self.framework.cycle_seed)
                state[ARRAYS_STATE_KEY] = arrays
                nomination = self._quota_plugin.post_filter(
                    state, snapshot, pod
                )
                if nomination is None:
                    continue
                node_name, victims = nomination
                victim_uids = sorted(v.uid for v in victims)
                admitted = self._evict_victims(
                    victim_uids, source="preemption", node=node_name,
                    now=now, all_or_nothing=True,
                )
                if victim_uids and not admitted:
                    # the whole victim set deferred by the arbiter:
                    # nothing evicted, no nomination — the preemptor
                    # retries once budget frees (docs/DESIGN.md §27)
                    continue
                # later preemptors must see the eviction, not the stale
                # view
                wanted = set(victim_uids)
                snapshot.pods = [
                    p for p in snapshot.pods if p.uid not in wanted
                ]
                arrays = lower_nodes(snapshot, **self.model.lowering_kwargs())
                result.nominations[uid] = node_name
                continue
            # device joint place+evict (ops/preempt.py): one dispatch
            # per preemptor against the staged resident world; the
            # eviction delta re-lowers ONE node row in place instead of
            # re-lowering the cluster (the host loop's dominant cost)
            rows = self._quota_plugin.quota_rows(pod)
            got = self.model.select_victims_device(
                arrays, resident, pod,
                quota_used=rows[0] if rows is not None else None,
                used_limit=rows[1] if rows is not None else None,
                world=world,
            )
            if backend == "verify":
                state = CycleState(self.framework.cycle_seed)
                state[ARRAYS_STATE_KEY] = arrays
                want = self._quota_plugin.post_filter(
                    state, snapshot, pod
                )
                want_pair = (
                    None if want is None
                    else (want[0], [v.uid for v in want[1]])
                )
                if got != want_pair:
                    raise AssertionError(
                        f"preemption parity violation for {pod.uid}: "
                        f"device {got!r} != oracle {want_pair!r}"
                    )
            if got is None:
                continue
            node_name, ordered_uids = got
            n_cand = sum(
                1 for p in snapshot.pods
                if p.node_name == node_name and can_preempt(pod, p)
            )
            PREEMPT_VICTIMS.inc({"outcome": "selected"}, len(ordered_uids))
            PREEMPT_VICTIMS.inc(
                {"outcome": "reprieved"}, n_cand - len(ordered_uids)
            )
            admitted = self._evict_victims(
                sorted(ordered_uids), source="preemption",
                node=node_name, now=now, all_or_nothing=True,
            )
            if ordered_uids and not admitted:
                # deferred whole-batch: the resident world keeps its
                # rows, the hole stays unfree, no nomination
                continue
            PREEMPT_VICTIMS.inc({"outcome": "evicted"}, len(ordered_uids))
            evict_resident_rows(
                snapshot, arrays, resident, node_name, ordered_uids,
                **self.model.lowering_kwargs(),
            )
            result.nominations[uid] = node_name

    def _evict_victims(
        self,
        uids: List[str],
        source: str = "preemption",
        node: Optional[str] = None,
        now: Optional[float] = None,
        all_or_nothing: bool = False,
    ) -> List[str]:
        """Evict ``uids`` through the sink, arbitrated when a migration
        arbiter is wired (docs/DESIGN.md §27). Returns the admitted
        uids; deferred victims stay placed (typed + counted in the
        arbiter's ring). ``all_or_nothing`` is the preemption contract —
        a victim set is indivisible, a partial evict burns budget
        without freeing the hole. Without an arbiter the behavior is
        the legacy unthrottled loop, bit-identically."""
        if self.migration_arbiter is not None and uids:
            from koordinator_tpu.obs.timeline import lane_of

            victims = [self.cache.pods.get(uid) for uid in uids]
            lanes = [None if v is None else lane_of(v) for v in victims]
            gangs = [None if v is None else v.gang for v in victims]
            headroom: Dict[str, int] = {}
            for gang in set(g for g in gangs if g):
                spec = self.cache.gangs.get(gang)
                if spec is None:
                    continue
                live = sum(
                    1 for p in self.cache.pods.values()
                    if p.gang == gang and p.node_name
                )
                headroom[gang] = max(live - spec.min_member, 0)
            verdict = self.migration_arbiter.request(
                source, node, uids, lanes=lanes, gangs=gangs,
                gang_headroom=headroom, now=now,
                all_or_nothing=all_or_nothing,
            )
            if not verdict.apply:
                return []
            uids = list(verdict.admitted)
        for uid in uids:
            victim = self.cache.pods.get(uid)
            if victim is None:
                continue
            if self.evict_pod_fn is not None:
                # bus deletion; the DELETED watch event re-enters
                # remove_pod synchronously, so the local cache stays
                # coherent with every other wired component
                self.evict_pod_fn(victim)
            else:
                self.remove_pod(victim)
        return list(uids)

    def defrag_headroom(
        self,
        target_req,
        max_victim_priority: int,
        apply: bool = False,
        now: Optional[float] = None,
    ):
        """Headroom repack (docs/DESIGN.md §24): find the cheapest node
        to drain — preemptible residents strictly below
        ``max_victim_priority``, least-important-first — until a
        ``target_req``-sized hole (a gang member's shape) fits.

        Returns ``(node_name, drain uids in eviction order)`` or None
        (also None when the hole already fits somewhere). With
        ``apply=True`` the drains are evicted through the same sink as
        preemption victims. Backend follows ``preemption_backend``:
        device plan (ops/preempt.headroom_repack), host oracle
        (scheduler/preemption.plan_defrag), or both with a parity
        assert under "verify"."""
        from koordinator_tpu.metrics.components import DEFRAG_DRAINS
        from koordinator_tpu.scheduler.preemption import plan_defrag
        from koordinator_tpu.state.cluster import lower_nodes

        target = np.asarray(target_req)
        snapshot = self.cache.snapshot(now=now)
        arrays = lower_nodes(snapshot, **self.model.lowering_kwargs())
        if self.preemption_backend == "host":
            plan = plan_defrag(
                snapshot, target, max_victim_priority, arrays=arrays
            )
            got = (
                None if plan is None
                else (plan[0], [v.uid for v in plan[1]])
            )
        else:
            resident = self.model.lower_residents(snapshot, arrays)
            got = self.model.plan_defrag_device(
                arrays, resident, target, max_victim_priority
            )
            if self.preemption_backend == "verify":
                plan = plan_defrag(
                    snapshot, target, max_victim_priority, arrays=arrays
                )
                want = (
                    None if plan is None
                    else (plan[0], [v.uid for v in plan[1]])
                )
                if got != want:
                    raise AssertionError(
                        f"defrag parity violation: device {got!r} != "
                        f"oracle {want!r}"
                    )
        if got is not None and apply:
            # arbitrated (docs/DESIGN.md §27): the manual API obeys the
            # same budgets/cooldowns as the closed defrag loop; a
            # deferred drain stays placed and the plan reports only the
            # admitted slice. Partial admission is fine here — unlike a
            # preemption victim set, each drain independently shrinks
            # the hole's remaining deficit, and the defrag controller
            # (or operator) retries after the cooldown.
            admitted = self._evict_victims(
                got[1], source="defrag", node=got[0], now=now,
            )
            DEFRAG_DRAINS.inc(amount=len(admitted))
            got = (got[0], admitted)
        return got

    def rebalance_sweep(self, plugin, now: Optional[float] = None) -> List[str]:
        """Run one LoadAware Balance pass (descheduler/loadaware.py)
        against the live cache, with evictions routed through the
        scheduler's sink — and therefore through the migration arbiter
        when one is wired (docs/DESIGN.md §27). The plugin's backend
        field picks host/device/verify for the eviction walk itself.

        Evictions land via ``remove_pod``/``evict_pod_fn`` exactly like
        preemption victims, so they mark the cache's delta tracker and
        the next solve round re-lowers only the touched node rows (the
        ``evict_resident_rows`` one-row delta path) instead of paying a
        full-cluster re-lower. Returns the evicted uids in sweep
        order."""
        from koordinator_tpu.descheduler.framework import Evictor

        scheduler = self

        class _ArbitratedSink(Evictor):
            """Bridges the descheduler Evictor protocol onto the
            scheduler's arbitrated eviction path: a deferral surfaces
            as the protocol's refusal (False), which the sweep already
            treats as continue-without-subtracting."""

            def _do_evict(self, snapshot, pod, reason) -> bool:
                return bool(scheduler._evict_victims(
                    [pod.uid], source="rebalance", node=pod.node_name,
                    now=now,
                ))

        snapshot = self.cache.snapshot(now=now)
        sink = _ArbitratedSink()
        plugin.balance(snapshot, sink)
        return [p.uid for p in sink.evicted]

    def forget_assumed_unbound(self) -> List[str]:
        """Release every assumed-but-unbound pod back to pending,
        undoing its quota/gang/fine-grained/reservation holds.

        Called by ``run_loop`` when leadership is lost mid-round
        (FencingError): the aborted round's assumes were never
        published, so the deposed instance must not keep counting them
        — they would linger until assume expiry and poison a later
        re-election's first snapshot. Binds that DID publish are
        confirmed out of ``cache.assumed`` by the wiring's post-publish
        ``finish_binding``, so everything still in there is exactly the
        aborted round's decisions. Returns the forgotten uids."""
        forgotten: List[str] = []
        for uid in list(self.cache.assumed):
            pod = self.cache.pods.get(uid)
            if pod is None:
                self.cache.forget_pod(uid)  # orphan entry: just drop it
                continue
            if uid in self._waiting:
                self._release_waiting(uid)
            else:
                # the batch's validate loop applied real NUMA/device
                # holds for this placement — same release as remove_pod
                self._release_node_holds(pod)
                self._account_quota(pod, release=True)
                self._fine_waiting.pop(uid, None)
                # a committed pod's reservation consumption is recorded
                # in _resv_inflight until its bind publishes — this one
                # never will, so restore the credit (and an
                # allocate_once reservation's AVAILABLE state).
                # _resv_waiting cannot hold this uid: its entries exist
                # only for pods in _waiting, handled above.
                self._apply_resv_rollback(
                    uid, self._resv_inflight.pop(uid, None)
                )
                self.cache.forget_pod(uid)
            self.gang_manager.on_pod_forgotten(uid)
            forgotten.append(uid)
        return forgotten

    def expire_waiting(self, now: float) -> List[str]:
        """Reject waiting pods whose gang WaitTime has elapsed (reference:
        Permit wait timeout → unreserve → Strict group rejection,
        core/gang.go:43-95 WaitTime, core/core.go:390-408). Returns the
        released pod uids; their held node/quota/fine-grained resources go
        back and the pods return to the pending queue."""
        from koordinator_tpu.metrics.components import GANG_REJECTIONS

        released: List[str] = []
        for uid, since in list(self._waiting_since.items()):
            if uid not in self._waiting:
                self._waiting_since.pop(uid, None)
                continue
            pod = self.cache.pods.get(uid)
            if pod is None:
                self._waiting_since.pop(uid, None)
                self._waiting.pop(uid, None)
                continue
            spec = self.cache.gangs.get(pod.gang) if pod.gang else None
            wait_time = spec.wait_time if spec is not None else 600.0
            if not wait_time or (now - since) < wait_time:
                continue
            GANG_REJECTIONS.inc()
            # the timed-out pod plus (Strict mode) its whole gang group
            siblings = self.gang_manager.unreserve(uid)
            for r in {uid, *siblings}:
                if r in self._waiting:
                    self._release_waiting(r)
                    released.append(r)
        return released

    def _release_waiting(self, uid: str) -> None:
        """Release one waiting pod's holds (node, quota, fine-grained,
        reservation) and return it to pending."""
        self._waiting.pop(uid, None)
        self._waiting_since.pop(uid, None)
        pod = self.cache.pods.get(uid)
        self._account_quota(pod, release=True)
        held = self._fine_waiting.pop(uid, None)
        if held is not None and self.model.fine is not None:
            node = self.cache.nodes.get(held[0])
            if pod is not None and node is not None:
                self.model.fine.rollback(None, pod, node, held[1])
        self._rollback_reservation(uid)
        self.cache.forget_pod(uid)

    def _rollback_reservation(self, uid: str) -> None:
        """Undo a waiting pod's reservation consumption (the incremental
        Unreserve's reservation restore, plugins/reservation.py:114-132)."""
        self._apply_resv_rollback(uid, self._resv_waiting.pop(uid, None))

    def _apply_resv_rollback(self, uid: str, info) -> None:
        """Restore one pod's recorded reservation consumption: shared by
        the WaitTime-expiry path (``_resv_waiting``) and the fencing
        abort's committed-but-unpublished path (``_resv_inflight``)."""
        if info is None:
            return
        from koordinator_tpu.apis.types import (
            ReservationState,
            resources_to_vector,
            vector_to_resources,
        )
        import numpy as np

        name, delta = info
        resv = self.cache.reservations.get(name)
        if resv is None:
            return
        cur = resources_to_vector(resv.allocated)
        resv.allocated = vector_to_resources(np.maximum(cur - delta, 0))
        if uid in resv.allocated_pod_uids:
            resv.allocated_pod_uids.remove(uid)
        if resv.allocate_once and resv.state == ReservationState.SUCCEEDED:
            resv.state = ReservationState.AVAILABLE
        tracker = getattr(self.cache, "delta_tracker", None)
        if tracker is not None:
            tracker.mark_node(resv.node_name)

    def _account_quota(self, pod: Optional[PodSpec], release: bool = False) -> None:
        if pod is None or not pod.quota:
            return
        from koordinator_tpu.apis.types import resources_to_vector

        vec = resources_to_vector(pod.requests)
        self.quota_registry.manager_for_quota(pod.quota).add_used(
            pod.quota,
            -vec if release else vec,
            non_preemptible=not pod.preemptible,
        )

    def _resolve_waiting(self, result: ScheduleResult) -> None:
        """Open the Permit barrier for previously-waiting pods whose gang
        group is now satisfied: report them as committed placements."""
        if not self._waiting:
            return
        assigned_count: Dict[str, int] = {}
        for pod in self.cache.pods.values():
            if pod.gang and pod.node_name is not None:
                assigned_count[pod.gang] = assigned_count.get(pod.gang, 0) + 1

        def group_of(gang_name: str) -> List[str]:
            spec = self.cache.gangs.get(gang_name)
            if spec is None or not spec.gang_group:
                return [gang_name]
            return list(spec.gang_group)

        for uid, node in list(self._waiting.items()):
            pod = self.cache.pods.get(uid)
            if pod is None or pod.gang is None:
                self._waiting.pop(uid, None)
                continue
            satisfied = all(
                assigned_count.get(g, 0)
                >= (self.cache.gangs[g].min_member if g in self.cache.gangs else 1)
                for g in group_of(pod.gang)
            )
            if satisfied:
                self._waiting.pop(uid)
                self._waiting_since.pop(uid, None)
                info = self._resv_waiting.pop(uid, None)
                if info is not None:
                    # consumption becomes final once the bind PUBLISHES;
                    # until then a fencing abort can still roll it back
                    self._resv_inflight[uid] = info
                result.waiting.pop(uid, None)
                result[uid] = node
                # bindable, but the assume stays open until the publish
                # confirms it (finish_binding in the wiring) — an
                # aborted round must be able to forget this decision
                self.cache.open_permit(uid)
                self.gang_manager.on_pod_bound(uid)
                self._fine_pre_bind(uid)

    def _fine_pre_bind(self, uid: str) -> None:
        """Annotate a newly-committed pod's fine-grained allocation (its
        deferred PreBind) once the Permit barrier opens."""
        held = self._fine_waiting.pop(uid, None)
        if held is None or self.model.fine is None:
            return
        node_name, cstate = held
        pod = self.cache.pods.get(uid)
        node = self.cache.nodes.get(node_name)
        if pod is not None and node is not None:
            # pre_bind only annotates from the CycleState — no snapshot
            # needed (avoids an O(cluster) copy per released gang member)
            self.model.fine.pre_bind(None, pod, node, cstate)

    def _on_gang_release(self, uids: List[str]) -> None:
        """Incremental path: the Permit barrier opened — waiting siblings
        become bindable. Same abort-safety contract as the batched
        path's `_resolve_waiting`: the assume stays open (and the
        reservation consumption rollback-able) until a publish confirms
        the bind, so a fencing-aborted round forgets these too."""
        for uid in uids:
            self.cache.open_permit(uid)
            self._waiting.pop(uid, None)
            self._waiting_since.pop(uid, None)
            info = self._resv_waiting.pop(uid, None)
            if info is not None:
                self._resv_inflight[uid] = info
            self._fine_pre_bind(uid)

    def _on_gang_reject(self, uids: List[str]) -> None:
        """A Strict gang-group rejection released these waiting siblings:
        return their node/quota/fine-grained/reservation holds."""
        for uid in uids:
            if uid in self._waiting:
                self._release_waiting(uid)

    def schedule_one(self, pod_uid: str, now: Optional[float] = None) -> ScheduleOutcome:
        snapshot = self.cache.snapshot(now=now)
        pod = self.cache.pending.get(pod_uid)
        if pod is None:
            return ScheduleOutcome(pod_uid, None, "error", "pod not pending")
        outcome = self.framework.schedule_one(snapshot, pod)
        if outcome.status == "nominated" and outcome.victims:
            # evict the victims (the reference deletes them via the API
            # server and records nominatedNodeName); the preemptor stays
            # pending and binds once the capacity frees
            self._evict_victims(outcome.victims)
            return outcome
        if outcome.status in ("bound", "waiting") and outcome.node:
            self.cache.assume_pod(pod_uid, outcome.node, now=now)
            if outcome.status == "bound":
                self.gang_manager.on_pod_bound(pod_uid)
            else:
                at = now if now is not None else time.time()
                held = self.cache.pods.get(pod_uid)
                if held is not None:
                    held.waiting_permit = True
                self._waiting[pod_uid] = outcome.node
                self._waiting_since.setdefault(pod_uid, at)
                state = outcome.cycle_state
                if state is not None:
                    # keep the cycle state for fine-grained rollback /
                    # deferred PreBind, and the reservation delta for
                    # rollback on WaitTime expiry
                    self._fine_waiting[pod_uid] = (outcome.node, state)
                    resv_name = state.get("reservation_allocated")
                    delta = state.get("reservation_allocated_delta")
                    if resv_name and delta is not None:
                        self._resv_waiting[pod_uid] = (resv_name, delta)
        return outcome
