"""Shared per-cycle lowering cache for the incremental path.

Plugins need the same canonical arrays the batched solver uses
(allocatable, requested, usage, estimation corrections). They are lowered
once per snapshot and cached in the CycleState; reservation restore and
in-cycle reserves adjust a per-node ``extra_used`` delta on top.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import numpy as np

from koordinator_tpu.state.cluster import NodeArrays, lower_nodes

_VIEW_KEY = "__node_view__"
#: CycleState seed key: lower_nodes kwargs (scaling factors, resource
#: weights, LoadAware aggregated profile) — set by the Scheduler's
#: framework cycle_seed from PlacementModel.lowering_kwargs() so the
#: incremental chain lowers exactly as the batched solver does
LOWERING_KEY = "__lowering_kwargs__"
#: CycleState seed key: (thresholds[R], prod_thresholds[R]) numpy vectors
#: the LoadAware filter runs with — consumed by the preemption path so
#: it never nominates a node the configured filter would reject
THRESHOLDS_KEY = "__loadaware_thresholds__"


@dataclasses.dataclass
class NodeView:
    arrays: NodeArrays
    index: Dict[str, int]
    #: per-node adjustment applied by reservation restore / in-cycle
    #: reserves, added to arrays.used_req (numpy [R] vectors)
    extra_used: Dict[str, np.ndarray]


def node_view(state, snapshot) -> NodeView:
    view = state.get(_VIEW_KEY)
    if view is None or view.arrays.n != len(snapshot.nodes):
        arrays = lower_nodes(snapshot, **(state.get(LOWERING_KEY) or {}))
        view = NodeView(arrays=arrays, index=arrays.index(), extra_used={})
        state[_VIEW_KEY] = view
    return view
