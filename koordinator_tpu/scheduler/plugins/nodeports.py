"""NodePorts: host-port conflict filtering.

Reference: the upstream k8s NodePorts plugin the reference vendors with
its scheduling framework (pinned k8s.io/kubernetes v1.24,
pkg/scheduler/framework/plugins/nodeports) and exercises in its e2e
suite (test/e2e/scheduling/hostport_predicates.go scope). A pod
requesting a host port is unschedulable on any node where an assigned
pod already holds the same (protocol, port).

``PodSpec.host_ports`` entries are ints (TCP implied) or
``"<proto>:<port>"`` strings; upstream's hostIP dimension is collapsed
(ports are node-global), which is the conservative direction — a
conflict upstream would allow on disjoint hostIPs is rejected here.

One instance serves both scheduling paths: the incremental framework
chain (filter/reserve/unreserve) and the batched propose→validate→
refine loop through FineGrained — transient ``_holds`` make
batch-internal conflicts visible before the next solve iteration, while
committed pods are counted from the snapshot (their ``node_name`` is
set), so holds are membership-idempotent with snapshot state.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Tuple

from koordinator_tpu.scheduler.framework import CycleState, Plugin, Status

_STATE_KEY = "NodePorts/used"


def pod_host_ports(pod) -> FrozenSet[str]:
    """Normalized "proto:port" set for a pod (empty = no host ports)."""
    out = set()
    for entry in getattr(pod, "host_ports", None) or ():
        if isinstance(entry, int):
            out.add(f"tcp:{entry}")
        else:
            text = str(entry).lower()
            out.add(text if ":" in text else f"tcp:{text}")
    return frozenset(out)


class NodePortsPlugin(Plugin):
    name = "NodePorts"

    def __init__(self):
        #: pod uid -> (node_name, ports) reserved THIS solve (the
        #: validate-loop holds); pruned lazily against the snapshot
        self._holds: Dict[str, Tuple[str, FrozenSet[str]]] = {}

    # -- read side -----------------------------------------------------------

    def _snapshot_used(self, state: CycleState, snapshot,
                       node_name: str) -> FrozenSet[str]:
        """Ports held by assigned pods on the node. The whole
        node -> ports map is built in ONE O(pods) pass and cached per
        cycle — per-node snapshot scans would make a rows() computation
        O(nodes x pods)."""
        by_node = state.get(_STATE_KEY) if state is not None else None
        if by_node is None:
            by_node = {}
            for p in snapshot.pods:
                if p.node_name is not None:
                    ports = pod_host_ports(p)
                    if ports:
                        by_node.setdefault(p.node_name, set()).update(ports)
            if state is not None:
                state[_STATE_KEY] = by_node
        return frozenset(by_node.get(node_name, ()))

    def _held(self, state: CycleState, snapshot, node_name: str,
              skip_uid: str) -> FrozenSet[str]:
        """Live validate-loop holds on the node. Holds whose pod is gone
        from the snapshot entirely (deleted mid-flight) are pruned so a
        vanished pod can't phantom-block its port forever — ONCE per
        cycle, not per node (the live-uid set is O(pods))."""
        if not self._holds:
            return frozenset()
        pruned_key = "NodePorts/pruned"
        if state is None or not state.get(pruned_key):
            live = {p.uid for p in snapshot.pods}
            live.update(p.uid for p in snapshot.pending_pods)
            for uid in [u for u in self._holds if u not in live]:
                del self._holds[uid]
            if state is not None:
                state[pruned_key] = True
        out = set()
        for uid, (node, ports) in self._holds.items():
            if node == node_name and uid != skip_uid:
                out |= ports
        return frozenset(out)

    # -- framework stages ----------------------------------------------------

    def filter(self, state: CycleState, snapshot, pod, node) -> Status:
        want = pod_host_ports(pod)
        if not want:
            return Status.success()
        used = self._snapshot_used(state, snapshot, node.name)
        if want & used or want & self._held(state, snapshot, node.name,
                                            pod.uid):
            return Status.unschedulable_(
                "node(s) didn't have free ports for the requested pod ports"
            )
        return Status.success()

    def reserve(self, state: CycleState, snapshot, pod, node) -> Status:
        want = pod_host_ports(pod)
        if want:
            self._holds[pod.uid] = (node.name, want)
        return Status.success()

    def unreserve(self, state: CycleState, snapshot, pod, node) -> None:
        self._holds.pop(pod.uid, None)
