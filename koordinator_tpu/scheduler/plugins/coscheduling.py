"""Coscheduling plugin (incremental path): wraps the GangManager state
machine (gang/manager.py; SURVEY.md A.5)."""

from __future__ import annotations

from koordinator_tpu.gang.manager import GangManager, PermitResult
from koordinator_tpu.scheduler.framework import CycleState, Plugin, Status


class CoschedulingPlugin(Plugin):
    name = "Coscheduling"

    def __init__(self, manager: GangManager, on_release=None, on_reject=None):
        self.manager = manager
        self.on_release = on_release
        #: called with the waiting sibling uids released by a Strict
        #: gang-group rejection — their held resources must be returned
        self.on_reject = on_reject

    def _rejected(self, uids) -> None:
        if uids and self.on_reject is not None:
            self.on_reject(list(uids))

    def score_weight(self) -> int:
        return 0

    def pre_filter(self, state: CycleState, snapshot, pod) -> Status:
        reason = self.manager.pre_filter(pod.uid)
        if reason is None:
            return Status.success()
        return Status.unschedulable_(reason)

    def permit(self, state: CycleState, snapshot, pod, node):
        result, wait = self.manager.permit(pod.uid)
        if result == PermitResult.ALLOW:
            released = self.manager.allow_gang_group(
                self.manager.pod_gang.get(pod.uid, "")
            )
            if self.on_release is not None:
                # siblings that were waiting at the barrier are bindable now
                self.on_release([u for u in released if u != pod.uid])
            return ("allow", 0.0)
        if result == PermitResult.WAIT:
            return ("wait", wait)
        return ("allow", 0.0)

    def unreserve(self, state: CycleState, snapshot, pod, node) -> None:
        self._rejected(self.manager.unreserve(pod.uid))

    def post_filter(self, state: CycleState, snapshot, pod) -> None:
        # a member failed filtering entirely: strict gangs reject the group
        # (core.go:318 rejectGangGroupById); the released waiting siblings
        # are surfaced so the scheduler returns their holds
        gang = self.manager.pod_gang.get(pod.uid)
        if gang is not None:
            self._rejected(self.manager.unreserve(pod.uid))
