"""Reservation plugin (incremental path): restore, match, allocate.

Reference semantics (pkg/scheduler/plugins/reservation/transformer.go:
restoreMatchedReservation / restoreUnmatchedReservations): an Available
reservation holds its unallocated remainder ``(allocatable - allocated)+``
on its node; pods consuming it are accounted individually. This substrate
encodes exactly that net view at lowering time (state/cluster.py adds the
remainder hold into ``used_req``), so:

- unmatched pods see the remainder as occupied — nothing to do;
- matched pods get the remainder *credited back* for Filter/Score
  (the reservation's free capacity is available to them);
- Reserve allocates the pod onto the matched reservation with the most
  free capacity on the chosen node (deterministic lowest-index
  tie-break; the reference nominates by reservation score — documented
  deviation: the choice among matched reservations on one node differs
  only in which reservation is consumed first).

Owner matching is by label subset (``owner_labels ⊆ pod.labels``), the
typed analogue of the reference's owner selectors.

Both paths implement the full chain: the remainder *hold* is encoded in
the lowering (state/cluster.py); the per-pod matched *credit* and
consumption run here for the incremental path and in the device scan for
the batched path (ops/binpack.py ``ResvArrays``: match matrix +
reservation-free carry, best-free consumption, allocate_once hold
release), with host bookkeeping in models/placement.py
``_apply_reservations``.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from koordinator_tpu.apis.types import (
    selector_matches,
    PodSpec,
    ReservationSpec,
    ReservationState,
    resources_to_vector,
    vector_to_resources,
)
from koordinator_tpu.scheduler.framework import CycleState, Plugin, Status
from koordinator_tpu.scheduler.plugins.lowering import node_view

_MATCH_KEY = "__resv_matched__"


def is_reserve_pod(pod: PodSpec) -> bool:
    """Placement probes for reservations themselves (the descheduler's
    migration probe) — the reference's reservationutil.IsReservePod.
    Reserve pods never *match* reservations (they would burn real
    allocate_once capacity from a throwaway solve), but they still see
    reserved capacity as occupied through the lowering's remainder hold."""
    return pod.uid.startswith("__resv__")


def reservation_matches_pod(resv: ReservationSpec, pod: PodSpec) -> bool:
    """Owner match: explicit pod-uid owners (migration reservations,
    reference: reservation_types.go ReservationOwner.Object) or label
    owners (every owner label present on the pod)."""
    if is_reserve_pod(pod):
        return False
    if resv.state != ReservationState.AVAILABLE or resv.node_name is None:
        return False
    if resv.owner_pod_uids:
        return pod.uid in resv.owner_pod_uids
    if not resv.owner_labels:
        return False
    return selector_matches(resv.owner_labels, pod.labels)


def reservation_free(resv: ReservationSpec) -> np.ndarray:
    alloc = resources_to_vector(resv.allocatable or resv.requests)
    used = resources_to_vector(resv.allocated)
    return np.maximum(alloc - used, 0)


class ReservationPlugin(Plugin):
    name = "Reservation"

    def before_pre_filter(self, state: CycleState, snapshot, pod) -> bool:
        """Credit matched reservations' free remainder back to their nodes
        for this pod's cycle (the BeforePreFilter restore)."""
        view = node_view(state, snapshot)
        matched: Dict[str, List[ReservationSpec]] = {}
        changed = False
        for resv in snapshot.reservations:
            if not reservation_matches_pod(resv, pod):
                continue
            free = reservation_free(resv)
            if not free.any():
                continue
            matched.setdefault(resv.node_name, []).append(resv)
            extra = view.extra_used.setdefault(
                resv.node_name, np.zeros_like(free)
            )
            view.extra_used[resv.node_name] = extra - free
            changed = True
        state[_MATCH_KEY] = matched
        return changed

    def reserve(self, state: CycleState, snapshot, pod, node) -> Status:
        matched = state.get(_MATCH_KEY, {}).get(node.name, [])
        if not matched:
            return Status.success()
        # most free capacity wins; ties -> first in snapshot order
        best = max(matched, key=lambda r: int(reservation_free(r).sum()))
        req = resources_to_vector(pod.requests)
        alloc_vec = resources_to_vector(best.allocatable or best.requests)
        old_allocated = resources_to_vector(best.allocated)
        new_allocated = np.minimum(old_allocated + req, alloc_vec)
        best.allocated = vector_to_resources(new_allocated)
        best.allocated_pod_uids.append(pod.uid)
        if best.allocate_once:
            best.state = ReservationState.SUCCEEDED
        tracker = getattr(snapshot, "delta_tracker", None)
        if tracker is not None:
            tracker.mark_node(best.node_name)
        state["reservation_allocated"] = best.name
        # remember the clamped delta actually added — unreserve must subtract
        # exactly this, not the raw request
        state["reservation_allocated_delta"] = new_allocated - old_allocated
        return Status.success()

    def unreserve(self, state: CycleState, snapshot, pod, node) -> None:
        name = state.get("reservation_allocated")
        if not name:
            return
        delta = state.get("reservation_allocated_delta")
        for resv in snapshot.reservations:
            if resv.name == name:
                sub = (
                    delta
                    if delta is not None
                    else resources_to_vector(pod.requests)
                )
                cur = resources_to_vector(resv.allocated)
                resv.allocated = vector_to_resources(np.maximum(cur - sub, 0))
                if pod.uid in resv.allocated_pod_uids:
                    resv.allocated_pod_uids.remove(pod.uid)
                if resv.state == ReservationState.SUCCEEDED and resv.allocate_once:
                    resv.state = ReservationState.AVAILABLE
                tracker = getattr(snapshot, "delta_tracker", None)
                if tracker is not None:
                    tracker.mark_node(resv.node_name)
                break
