"""LoadAwareScheduling plugin (incremental path).

Host counterpart of ops/loadaware.py (SURVEY.md A.1/A.2); the estimation
corrections come from the same lowering the batched path uses.
"""

from __future__ import annotations

from koordinator_tpu.apis.extension import PriorityClass
from koordinator_tpu.apis.types import resources_to_vector
from koordinator_tpu.oracle.scheduler import (
    loadaware_filter_node,
    loadaware_score_node,
)
from koordinator_tpu.scheduler.framework import CycleState, Plugin, Status
from koordinator_tpu.scheduler.plugins.lowering import node_view
from koordinator_tpu.state.cluster import (
    DEFAULT_ESTIMATED_SCALING_FACTORS,
    DEFAULT_RESOURCE_WEIGHTS,
    DEFAULT_USAGE_THRESHOLDS,
    estimate_pod_used,
)


class LoadAwareScheduling(Plugin):
    name = "LoadAwareScheduling"

    def __init__(
        self,
        resource_weights=None,
        usage_thresholds=None,
        prod_usage_thresholds=None,
        scaling_factors=None,
        score_according_prod: bool = False,
        weight: int = 1,
    ):
        self.resource_weights = dict(resource_weights or DEFAULT_RESOURCE_WEIGHTS)
        self.weights_vec = resources_to_vector(self.resource_weights)
        self.thresholds = resources_to_vector(
            usage_thresholds or DEFAULT_USAGE_THRESHOLDS
        )
        self.prod_thresholds = resources_to_vector(prod_usage_thresholds or {})
        self.scaling_factors = dict(
            scaling_factors or DEFAULT_ESTIMATED_SCALING_FACTORS
        )
        self.score_according_prod = score_according_prod
        self.weight = weight

    def score_weight(self) -> int:
        return self.weight

    def _pod_flags(self, pod):
        return pod.is_daemonset, pod.priority_class == PriorityClass.PROD

    def filter(self, state: CycleState, snapshot, pod, node) -> Status:
        view = node_view(state, snapshot)
        i = view.index[node.name]
        a = view.arrays
        is_ds, is_prod = self._pod_flags(pod)
        ok = loadaware_filter_node(
            a.alloc[i], a.usage[i], a.prod_usage[i], bool(a.metric_fresh[i]),
            self.thresholds, self.prod_thresholds, is_ds, is_prod,
        )
        if ok:
            return Status.success()
        return Status.unschedulable_("node(s) usage exceed threshold")

    def score(self, state: CycleState, snapshot, pod, node) -> int:
        view = node_view(state, snapshot)
        i = view.index[node.name]
        a = view.arrays
        _, is_prod = self._pod_flags(pod)
        est = resources_to_vector(
            estimate_pod_used(pod, self.scaling_factors, self.resource_weights)
        )
        return loadaware_score_node(
            est, a.alloc[i], a.usage[i], a.est_extra[i], a.prod_base[i],
            bool(a.metric_fresh[i]), self.weights_vec, is_prod,
            self.score_according_prod,
        )
