"""DefaultPreBind: applies accumulated object patches once at PreBind.

Reference: pkg/scheduler/plugins/defaultprebind/plugin.go — plugins queue
mutations during the cycle; this plugin materializes them in one place
(annotations on the pod, allocation onto the reservation object).
"""

from __future__ import annotations

import json

from koordinator_tpu.apis.extension import (
    ANNOTATION_RESERVATION_ALLOCATED,
    ANNOTATION_RESOURCE_STATUS,
)
from koordinator_tpu.scheduler.framework import CycleState, Plugin, Status


class DefaultPreBind(Plugin):
    name = "DefaultPreBind"

    def score_weight(self) -> int:
        return 0

    def pre_bind(self, state: CycleState, snapshot, pod, node) -> Status:
        if state.get("reservation_allocated"):
            pod.annotations[ANNOTATION_RESERVATION_ALLOCATED] = state[
                "reservation_allocated"
            ]
        status = state.get("resource_status")
        if status:
            pod.annotations[ANNOTATION_RESOURCE_STATUS] = json.dumps(status)
        return Status.success()
