"""NodeNUMAResource plugin: CPUSet/NUMA-aware fine-grained CPU allocation.

Rebuild of reference pkg/scheduler/plugins/nodenumaresource/plugin.go
(PreFilter :219, Filter :275, Score via scoring.go, Reserve :375,
PreBind :431) plus the scheduler-level topology manager admit
(pkg/scheduler/frameworkext/topologymanager/manager.go:56 Admit). Pods of
QoS LSE/LSR with integer CPU requests get pinned logical CPUs laid out by
the topology-aligned accumulator; NUMA topology policies gate placement
per node via hint merge.
"""

from __future__ import annotations

import json
import math
from typing import Dict, List, Optional

import numpy as np

from koordinator_tpu.apis.extension import (
    ANNOTATION_RESOURCE_SPEC,
    ANNOTATION_RESOURCE_STATUS,
    QoSClass,
    ResourceName,
)
from koordinator_tpu.numa.accumulator import CPUAllocationError
from koordinator_tpu.numa.hints import (
    NUMATopologyHint,
    NUMATopologyPolicy,
    merge_hints,
)
from koordinator_tpu.numa.manager import (
    MAX_NODE_SCORE,
    ResourceManager,
    ResourceOptions,
)
from koordinator_tpu.numa.topology import CPUBindPolicy, CPUExclusivePolicy
from koordinator_tpu.scheduler.framework import CycleState, Plugin, Status

_STATE_KEY = "nodenumaresource.state"
_AFFINITY_KEY = "nodenumaresource.affinity"  # + node name


class _PreFilterState:
    def __init__(self, pod):
        annotations = pod.annotations or {}
        spec = {}
        if ANNOTATION_RESOURCE_SPEC in annotations:
            spec = json.loads(annotations[ANNOTATION_RESOURCE_SPEC])
        self.bind_policy = CPUBindPolicy(spec.get("cpuBindPolicy", "Default"))
        self.exclusive_policy = CPUExclusivePolicy(
            spec.get("cpuExclusivePolicy", "None")
        )
        self.required_bind_policy = bool(spec.get("requiredCPUBindPolicy", False))
        self.pod_numa_policy = NUMATopologyPolicy(
            spec.get("numaTopologyPolicy", "")
        )
        cpu_milli = pod.requests.get(ResourceName.CPU, 0)
        # LSE/LSR integer-cpu pods get a cpuset (reference: plugin.go
        # requestCPUBind — AllowUseCPUSet: qos LSE/LSR + integer request)
        self.request_cpu_bind = (
            pod.qos in (QoSClass.LSE, QoSClass.LSR) and cpu_milli > 0
        ) or self.required_bind_policy
        self.num_cpus_needed = cpu_milli // 1000
        self.requests = dict(pod.requests)
        self.invalid_integer = self.request_cpu_bind and cpu_milli % 1000 != 0


class NodeNUMAResourcePlugin(Plugin):
    """Fine-grained CPU + NUMA-aligned placement."""

    name = "NodeNUMAResource"

    def __init__(
        self,
        resource_manager: Optional[ResourceManager] = None,
        scorer: str = "LeastAllocated",
    ):
        self.manager = resource_manager or ResourceManager()
        self.scorer = scorer

    # -- PreFilter (reference: plugin.go:219) ------------------------------
    def pre_filter(self, state: CycleState, snapshot, pod) -> Status:
        try:
            pf = _PreFilterState(pod)
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            return Status.unschedulable_(f"invalid resource spec annotation: {e}")
        if pf.invalid_integer:
            return Status.unschedulable_("the requested CPUs must be integer")
        state[_STATE_KEY] = pf
        return Status.success()

    def _effective_policy(self, pf, opts) -> NUMATopologyPolicy:
        if pf.pod_numa_policy != NUMATopologyPolicy.NONE:
            return pf.pod_numa_policy
        return opts.policy

    def _options(self, pf, opts, affinity=None) -> ResourceOptions:
        requests = dict(pf.requests)
        ratio = getattr(opts, "amplification_ratio", 1.0)
        if pf.request_cpu_bind and ratio and ratio > 1:
            # amplified nodes account raw cpus for cpuset pods (reference:
            # plugin.go:503-505 AmplifyResourceList)
            requests[ResourceName.CPU] = int(
                math.ceil(requests.get(ResourceName.CPU, 0) * ratio)
            )
        return ResourceOptions(
            requests=requests,
            original_requests=dict(pf.requests),
            num_cpus_needed=pf.num_cpus_needed,
            request_cpu_bind=pf.request_cpu_bind,
            required_cpu_bind_policy=pf.required_bind_policy,
            cpu_bind_policy=pf.bind_policy,
            cpu_exclusive_policy=pf.exclusive_policy,
            hint=affinity or NUMATopologyHint(None, False, 0),
            numa_scorer=self.scorer,
        )

    # -- Filter (reference: plugin.go:275 + topology_hint.go:30) -----------
    def filter(self, state: CycleState, snapshot, pod, node) -> Status:
        pf = state.get(_STATE_KEY)
        if pf is None:
            return Status.success()
        opts = self.manager.get_topology(node.name)
        if pf.request_cpu_bind:
            if opts.cpu_topology is None or not opts.cpu_topology.is_valid():
                return Status.unschedulable_("node(s) invalid CPU topology")
        policy = self._effective_policy(pf, opts)
        if policy == NUMATopologyPolicy.NONE:
            return Status.success()
        numa_nodes = opts.numa_nodes
        if not numa_nodes:
            return Status.unschedulable_("node(s) missing NUMA resources")
        # topology-manager Admit: gather hints, merge under the policy,
        # trial-allocate (reference: topologymanager/manager.go:56-78)
        options = self._options(pf, opts)
        try:
            hints = self.manager.get_topology_hints(node.name, options)
        except CPUAllocationError:
            return Status.unschedulable_("node(s) Insufficient NUMA Node resources")
        providers_hints = [{str(int(r)): hints[r] for r in hints}]
        best, admit = merge_hints(policy, numa_nodes, providers_hints)
        if not admit:
            return Status.unschedulable_("node(s) NUMA Topology affinity error")
        state[f"{_AFFINITY_KEY}.{node.name}"] = best
        if best.affinity is not None or pf.request_cpu_bind:
            try:
                self.manager.allocate(node.name, pod.uid, self._options(pf, opts, best))
            except CPUAllocationError as e:
                return Status.unschedulable_(str(e))
        return Status.success()

    # -- Score (reference: scoring.go — least/most allocated over the
    # node's NUMA resources including this pod's request) ------------------
    def score(self, state: CycleState, snapshot, pod, node) -> int:
        pf = state.get(_STATE_KEY)
        if pf is None or not pf.requests:
            return 0
        opts = self.manager.get_topology(node.name)
        if not opts.numa_node_resources:
            return 0
        total_available, _ = self.manager.available_numa_resources(node.name)
        score_sum, weight_sum = 0, 0
        for r, req in pf.requests.items():
            cap = sum(
                res.get(r, 0) for res in opts.numa_node_resources.values()
            )
            free = sum(res.get(r, 0) for res in total_available.values())
            requested = cap - free + req
            if cap == 0 or requested > cap:
                s = 0
            elif self.scorer == "MostAllocated":
                s = requested * MAX_NODE_SCORE // cap
            else:
                s = (cap - requested) * MAX_NODE_SCORE // cap
            score_sum += s
            weight_sum += 1
        return score_sum // weight_sum if weight_sum else 0

    # -- Reserve / Unreserve (reference: plugin.go:375) --------------------
    def reserve(self, state: CycleState, snapshot, pod, node) -> Status:
        pf = state.get(_STATE_KEY)
        if pf is None:
            return Status.success()
        opts = self.manager.get_topology(node.name)
        affinity = state.get(f"{_AFFINITY_KEY}.{node.name}")
        if not pf.request_cpu_bind and (affinity is None or affinity.affinity is None):
            return Status.success()
        try:
            allocation = self.manager.allocate(
                node.name, pod.uid, self._options(pf, opts, affinity)
            )
        except CPUAllocationError as e:
            return Status.unschedulable_(str(e))
        self.manager.update(node.name, allocation)
        state[f"{self.name}.allocation"] = (node.name, allocation)
        return Status.success()

    def unreserve(self, state: CycleState, snapshot, pod, node) -> None:
        held = state.pop(f"{self.name}.allocation", None)
        if held is not None:
            self.manager.release(held[0], held[1].pod_uid)

    # -- PreBind (reference: plugin.go:431 — annotate resource status) -----
    def pre_bind(self, state: CycleState, snapshot, pod, node) -> Status:
        held = state.get(f"{self.name}.allocation")
        if held is None:
            return Status.success()
        _, allocation = held
        status: Dict[str, object] = {}
        if len(allocation.cpuset):
            status["cpuset"] = [int(c) for c in allocation.cpuset]
        if allocation.numa_resources:
            status["numaNodeResources"] = [
                {"node": n, "resources": {int(k): v for k, v in res.items()}}
                for n, res in sorted(allocation.numa_resources.items())
            ]
        if status:
            pod.annotations[ANNOTATION_RESOURCE_STATUS] = json.dumps(status)
        return Status.success()
