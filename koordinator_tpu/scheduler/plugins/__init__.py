"""Built-in scheduler plugins (the reference's seven, rebuilt).

Each plugin implements the host extension points (framework.py) for the
incremental path; the hot math delegates to the same canonical-unit
functions the batched solver uses, so the two paths can't drift.
"""

from koordinator_tpu.scheduler.plugins.fit import NodeResourcesFit  # noqa: F401
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareScheduling  # noqa: F401
from koordinator_tpu.scheduler.plugins.elasticquota import ElasticQuotaPlugin  # noqa: F401
from koordinator_tpu.scheduler.plugins.coscheduling import CoschedulingPlugin  # noqa: F401
from koordinator_tpu.scheduler.plugins.reservation import ReservationPlugin  # noqa: F401
from koordinator_tpu.scheduler.plugins.nodenumaresource import (  # noqa: F401
    NodeNUMAResourcePlugin,
)
from koordinator_tpu.scheduler.plugins.deviceshare import DeviceSharePlugin  # noqa: F401
from koordinator_tpu.scheduler.plugins.defaultprebind import DefaultPreBind  # noqa: F401
