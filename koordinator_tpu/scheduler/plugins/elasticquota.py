"""ElasticQuota plugin (incremental path): PreFilter admission + accounting.

Wraps the host GroupQuotaManager (quota/core.py; SURVEY.md A.3). Pod
requests register at pod creation via ``on_pod_add``; Reserve moves used.
"""

from __future__ import annotations

from koordinator_tpu.apis.types import resources_to_vector
from koordinator_tpu.quota.core import GroupQuotaManager
from koordinator_tpu.scheduler.framework import CycleState, Plugin, Status


class ElasticQuotaPlugin(Plugin):
    name = "ElasticQuota"

    def __init__(
        self,
        manager: GroupQuotaManager,
        enable_runtime_quota: bool = True,
        enable_check_parent: bool = False,
    ):
        self.manager = manager
        self.enable_runtime_quota = enable_runtime_quota
        self.enable_check_parent = enable_check_parent

    def score_weight(self) -> int:
        return 0

    # informer events ------------------------------------------------------

    def on_pod_add(self, pod) -> None:
        if pod.quota:
            self.manager.add_request(
                pod.quota,
                resources_to_vector(pod.requests),
                non_preemptible=not pod.preemptible,
            )

    def on_pod_delete(self, pod) -> None:
        if pod.quota:
            self.manager.add_request(
                pod.quota,
                -resources_to_vector(pod.requests),
                non_preemptible=not pod.preemptible,
            )

    # cycle ----------------------------------------------------------------

    def pre_filter(self, state: CycleState, snapshot, pod) -> Status:
        if not pod.quota:
            return Status.success()
        ok = self.manager.can_admit(
            pod.quota,
            resources_to_vector(pod.requests),
            non_preemptible=not pod.preemptible,
            check_parents=self.enable_check_parent,
        )
        if ok:
            return Status.success()
        return Status.unschedulable_(f"insufficient quota {pod.quota}")

    def reserve(self, state: CycleState, snapshot, pod, node) -> Status:
        if pod.quota:
            self.manager.add_used(
                pod.quota,
                resources_to_vector(pod.requests),
                non_preemptible=not pod.preemptible,
            )
        return Status.success()

    def unreserve(self, state: CycleState, snapshot, pod, node) -> None:
        if pod.quota:
            self.manager.add_used(
                pod.quota,
                -resources_to_vector(pod.requests),
                non_preemptible=not pod.preemptible,
            )
