"""ElasticQuota plugin (incremental path): PreFilter admission,
accounting, multi-tree routing, and PostFilter preemption.

Wraps per-tree host GroupQuotaManagers (quota/core.py + quota/trees.py;
SURVEY.md A.3). Pod requests register at pod creation via ``on_pod_add``;
Reserve moves used; PostFilter selects same-quota lower-priority victims
(reference: plugin.go:210-321, preempt.go).
"""

from __future__ import annotations

from typing import Optional

from koordinator_tpu.apis.types import resources_to_vector
from koordinator_tpu.quota.core import GroupQuotaManager
from koordinator_tpu.quota.trees import QuotaTreeRegistry
from koordinator_tpu.scheduler.framework import CycleState, Plugin, Status


class ElasticQuotaPlugin(Plugin):
    name = "ElasticQuota"

    def __init__(
        self,
        manager,
        enable_runtime_quota: bool = True,
        enable_check_parent: bool = False,
        enable_preemption: bool = True,
    ):
        # accept a bare GroupQuotaManager (single default tree) or a
        # QuotaTreeRegistry (multi-tree, quota_handler.go)
        if isinstance(manager, GroupQuotaManager):
            registry = QuotaTreeRegistry()
            registry.default = manager
            registry.trees[""] = manager
            manager = registry
        self.registry: QuotaTreeRegistry = manager
        self.enable_runtime_quota = enable_runtime_quota
        self.enable_check_parent = enable_check_parent
        self.enable_preemption = enable_preemption

    @property
    def manager(self) -> GroupQuotaManager:
        """The default tree's manager (single-tree compatibility)."""
        return self.registry.default

    def _mgr(self, quota_name) -> GroupQuotaManager:
        return self.registry.manager_for_quota(quota_name)

    def score_weight(self) -> int:
        return 0

    # informer events ------------------------------------------------------

    def on_pod_add(self, pod) -> None:
        if pod.quota:
            self._mgr(pod.quota).add_request(
                pod.quota,
                resources_to_vector(pod.requests),
                non_preemptible=not pod.preemptible,
            )

    def on_pod_delete(self, pod) -> None:
        if pod.quota:
            self._mgr(pod.quota).add_request(
                pod.quota,
                -resources_to_vector(pod.requests),
                non_preemptible=not pod.preemptible,
            )

    # cycle ----------------------------------------------------------------

    def pre_filter(self, state: CycleState, snapshot, pod) -> Status:
        if not pod.quota:
            return Status.success()
        ok = self._mgr(pod.quota).can_admit(
            pod.quota,
            resources_to_vector(pod.requests),
            non_preemptible=not pod.preemptible,
            check_parents=self.enable_check_parent,
        )
        if ok:
            return Status.success()
        return Status.unschedulable_(f"insufficient quota {pod.quota}")

    def reserve(self, state: CycleState, snapshot, pod, node) -> Status:
        if pod.quota:
            self._mgr(pod.quota).add_used(
                pod.quota,
                resources_to_vector(pod.requests),
                non_preemptible=not pod.preemptible,
            )
        return Status.success()

    def unreserve(self, state: CycleState, snapshot, pod, node) -> None:
        if pod.quota:
            self._mgr(pod.quota).add_used(
                pod.quota,
                -resources_to_vector(pod.requests),
                non_preemptible=not pod.preemptible,
            )

    # PostFilter preemption (plugin.go:302, preempt.go) --------------------

    def quota_rows(self, pod):
        """``(quota_used, used_limit)`` for the pod's quota group, or
        None for a quota-unmanaged pod — the PostFilter-snapshot rows
        the preemption reprieve gate checks (preempt.go:176-201).
        Shared by the host oracle path and the device joint solve so
        both see identical quota state at dispatch time."""
        if not pod.quota:
            return None
        mgr = self._mgr(pod.quota)
        info = mgr.quotas.get(pod.quota)
        if info is None:
            return None
        used_limit = (
            mgr.refresh_runtime(pod.quota)
            if self.enable_runtime_quota
            else info.max
        )
        return info.used, used_limit

    def post_filter(self, state: CycleState, snapshot, pod):
        """Try preempting same-quota lower-priority pods; returns
        ``(node name, [victim PodSpec])`` or None."""
        if not self.enable_preemption:
            return None
        from koordinator_tpu.scheduler.preemption import (
            ARRAYS_STATE_KEY,
            find_preemption,
        )

        rows = self.quota_rows(pod)
        quota_used, used_limit = rows if rows is not None else (None, None)
        from koordinator_tpu.scheduler.plugins.lowering import THRESHOLDS_KEY

        arrays = state.get(ARRAYS_STATE_KEY) if state is not None else None
        thr = state.get(THRESHOLDS_KEY) if state is not None else None
        return find_preemption(
            snapshot, pod, quota_used=quota_used, used_limit=used_limit,
            arrays=arrays,
            thresholds=thr[0] if thr else None,
            prod_thresholds=thr[1] if thr else None,
        )
