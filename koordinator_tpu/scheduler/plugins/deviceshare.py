"""DeviceShare plugin: GPU/RDMA/FPGA partial + multi-device allocation.

Rebuild of reference pkg/scheduler/plugins/deviceshare/plugin.go
(PreFilter :150, Filter :272, Reserve :377, PreBind :475) + scoring.go.
Device requests come from ``PodSpec.device_requests`` (the reference's
extended resource names); allocation hints and joint-allocate specs from
pod annotations. Composes with NodeNUMAResource: if the topology manager
stored a NUMA affinity for the node, device candidates are filtered to
those NUMA nodes.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from koordinator_tpu.apis.extension import (
    ANNOTATION_DEVICE_ALLOCATED,
    ANNOTATION_DEVICE_ALLOCATE_HINTS,
    ANNOTATION_DEVICE_JOINT_ALLOCATE,
)
from koordinator_tpu.device.allocator import (
    AutopilotAllocator,
    DeviceHint,
    DeviceUnschedulable,
    JointAllocate,
    normalize_device_requests,
)
from koordinator_tpu.device.cache import (
    DeviceResourceName,
    DeviceType,
    NodeDeviceCache,
)
from koordinator_tpu.scheduler.framework import CycleState, Plugin, Status

_STATE_KEY = "deviceshare.state"
_NUMA_AFFINITY_KEY = "nodenumaresource.affinity"  # set by NodeNUMAResource


class _PreFilterState:
    def __init__(self, pod):
        known = {r.value for r in DeviceResourceName}
        raw = {}
        for name, v in (pod.device_requests or {}).items():
            # unmanaged vendor extended resources fall through to the
            # default fit path (reference: utils.go only collects known
            # device resource names)
            if name in known:
                raw[DeviceResourceName(name)] = int(v)
        self.pod_requests = normalize_device_requests(raw)
        self.skip = not self.pod_requests
        annotations = pod.annotations or {}
        self.hints: Dict[DeviceType, DeviceHint] = {}
        if ANNOTATION_DEVICE_ALLOCATE_HINTS in annotations:
            for t, h in json.loads(
                annotations[ANNOTATION_DEVICE_ALLOCATE_HINTS]
            ).items():
                self.hints[DeviceType(t)] = DeviceHint(
                    selector=h.get("selector"),
                    vf_selector=h.get("vfSelector"),
                    allocate_strategy=h.get("allocateStrategy", ""),
                    exclusive_policy=h.get("exclusivePolicy", ""),
                )
        self.joint: Optional[JointAllocate] = None
        if ANNOTATION_DEVICE_JOINT_ALLOCATE in annotations:
            j = json.loads(annotations[ANNOTATION_DEVICE_JOINT_ALLOCATE])
            self.joint = JointAllocate(
                device_types=[DeviceType(t) for t in j.get("deviceTypes", [])],
                required_scope=j.get("requiredScope", ""),
            )


class DeviceSharePlugin(Plugin):
    name = "DeviceShare"

    def __init__(self, cache: Optional[NodeDeviceCache] = None,
                 scorer: str = "LeastAllocated"):
        self.cache = cache or NodeDeviceCache()
        self.scorer = scorer

    def _allocator(self, state, pf, node) -> Optional[AutopilotAllocator]:
        node_device = self.cache.get(node.name)
        if node_device is None:
            return None
        affinity = state.get(f"{_NUMA_AFFINITY_KEY}.{node.name}")
        numa_mask = affinity.affinity if affinity is not None else None
        return AutopilotAllocator(
            node_device,
            pf.pod_requests,
            hints=pf.hints,
            joint_allocate=pf.joint,
            numa_affinity=numa_mask,
            scorer=self.scorer,
        )

    def pre_filter(self, state: CycleState, snapshot, pod) -> Status:
        try:
            pf = _PreFilterState(pod)
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            return Status.unschedulable_(f"invalid device request: {e}")
        except DeviceUnschedulable as e:
            return Status.unschedulable_(str(e))
        if not pf.skip:
            state[_STATE_KEY] = pf
        return Status.success()

    def filter(self, state: CycleState, snapshot, pod, node) -> Status:
        pf = state.get(_STATE_KEY)
        if pf is None:
            return Status.success()
        try:
            allocator = self._allocator(state, pf, node)
            if allocator is None:
                return Status.unschedulable_("node(s) no devices")
            allocator.allocate()
        except DeviceUnschedulable as e:
            return Status.unschedulable_(str(e))
        return Status.success()

    def score(self, state: CycleState, snapshot, pod, node) -> int:
        pf = state.get(_STATE_KEY)
        if pf is None:
            return 0
        try:
            allocator = self._allocator(state, pf, node)
        except DeviceUnschedulable:
            return 0
        if allocator is None:
            return 0
        return min(allocator.score(), 100)

    def reserve(self, state: CycleState, snapshot, pod, node) -> Status:
        pf = state.get(_STATE_KEY)
        if pf is None:
            return Status.success()
        try:
            allocator = self._allocator(state, pf, node)
            if allocator is None:
                return Status.unschedulable_("node(s) no devices")
            allocations = allocator.allocate()
        except DeviceUnschedulable as e:
            return Status.unschedulable_(str(e))
        self.cache.get(node.name).apply(pod.uid, allocations)
        state[f"{self.name}.allocation"] = (node.name, allocations)
        return Status.success()

    def unreserve(self, state: CycleState, snapshot, pod, node) -> None:
        held = state.pop(f"{self.name}.allocation", None)
        if held is not None:
            node_device = self.cache.get(held[0])
            if node_device is not None:
                node_device.release(pod.uid)

    def pre_bind(self, state: CycleState, snapshot, pod, node) -> Status:
        held = state.get(f"{self.name}.allocation")
        if held is None:
            return Status.success()
        _, allocations = held
        pod.annotations[ANNOTATION_DEVICE_ALLOCATED] = json.dumps(
            {
                t.value: [
                    {
                        "minor": a.minor,
                        "resources": {k.value: v for k, v in a.resources.items()},
                        **(
                            {"vfs": a.vf_bus_ids} if a.vf_bus_ids else {}
                        ),
                    }
                    for a in allocs
                ]
                for t, allocs in allocations.items()
            }
        )
        return Status.success()
