"""NodeResourcesFit: resource fit + LeastAllocated scoring (incremental path).

Host counterpart of ops/fit.py (SURVEY.md A.6). Node requested totals are
computed once per snapshot through the same lowering as the batched path.
"""

from __future__ import annotations

from koordinator_tpu.apis.types import resources_to_vector
from koordinator_tpu.oracle.scheduler import (
    fit_filter_node,
    least_allocated_score_node,
)
from koordinator_tpu.scheduler.framework import CycleState, Plugin, Status
from koordinator_tpu.scheduler.plugins.lowering import node_view


class NodeResourcesFit(Plugin):
    name = "NodeResourcesFit"

    def __init__(self, weights=None, weight: int = 1):
        from koordinator_tpu.state.cluster import DEFAULT_RESOURCE_WEIGHTS

        self.weights = resources_to_vector(weights or DEFAULT_RESOURCE_WEIGHTS)
        self.weight = weight

    def score_weight(self) -> int:
        return self.weight

    def filter(self, state: CycleState, snapshot, pod, node) -> Status:
        # required node selector (spec.nodeSelector — the slice of node
        # affinity the upstream NodeAffinity filter enforces)
        if pod.node_selector:
            from koordinator_tpu.apis.types import selector_matches

            if not selector_matches(pod.node_selector, node.labels):
                return Status.unschedulable_(
                    "node(s) didn't match Pod's node selector"
                )
        view = node_view(state, snapshot)
        i = view.index[node.name]
        req = resources_to_vector(pod.requests)
        used = view.arrays.used_req[i] + view.extra_used.get(node.name, 0)
        if fit_filter_node(req, view.arrays.alloc[i], used):
            return Status.success()
        return Status.unschedulable_("insufficient resources")

    def score(self, state: CycleState, snapshot, pod, node) -> int:
        view = node_view(state, snapshot)
        i = view.index[node.name]
        req = resources_to_vector(pod.requests)
        used = view.arrays.used_req[i] + view.extra_used.get(node.name, 0)
        return least_allocated_score_node(
            req, view.arrays.alloc[i], used, self.weights
        )
