"""The scheduling framework: extension points, cache, plugins, solver glue.

TPU-native rebuild of the reference's scheduler layer (pkg/scheduler/):
the *framework extension* architecture is preserved — plugins implement
PreFilter/Filter/Score/Reserve/Permit/PreBind extension points behind a
stable interface (reference: pkg/scheduler/frameworkext/interface.go) —
but the hot math lives on the array substrate: every built-in plugin also
exposes its batched formulation, and ``Scheduler.schedule_pending`` runs
the whole queue through the device solver (models/placement.py) while the
per-pod incremental path exists for parity, debugging and tiny clusters.
"""

from koordinator_tpu.scheduler.framework import (  # noqa: F401
    CycleState,
    Plugin,
    SchedulingFramework,
)
from koordinator_tpu.scheduler.auditor import StateAuditor  # noqa: F401
from koordinator_tpu.scheduler.cache import SchedulerCache  # noqa: F401
from koordinator_tpu.scheduler.scheduler import Scheduler  # noqa: F401
