"""TickPipeline: the overlapped stage/solve/publish scheduling loop.

The serial loop serializes one round end to end — lower + stage, device
solve (blocking read-back), typed epilogue, bus publish — so the round
floor is the SUM of the stages even though jax dispatch is already
asynchronous and the publish needs nothing from the next round. This is
the scheduling-cycle/binding-cycle split of the reference (kube-scheduler
runs binding in a goroutine off the scheduling loop) done TPU-native
(docs/DESIGN.md §15):

  coordinator (run_loop):  retire-wait → begin_tick (catch-up stage +
                           async dispatch) → prestage the overlap window
  publisher (ONE worker):  finalize (the read-back) → epilogue →
                           publish → post-epilogue prestage

Ordering contract — the reason placements stay bit-identical to the
serial loop by construction: ``begin_tick(N+1)`` runs strictly after
tick N RETIRED (epilogue applied, binds published), so every solve
consumes the same truth-lowered staged state and pending queue the
serial loop would have. What overlaps is everything the next round does
NOT depend on: the device compute's wall time, the read-back, the bus
publish, and the re-lowering of rows dirtied by informer traffic (the
prestage — any row the retiring epilogue later touches is re-marked by
its tracker mark and re-lowered from settled truth at the next
``begin_tick``'s catch-up ensure, so a stale prestage can never
survive into a solve).

Failure containment: a publish-side failure (FencingError from a fenced
eviction, a typed solver error) is recorded and re-raised at the NEXT
round boundary (``submit_round``/``drain``), where ``run_loop``'s
existing handlers — including the fencing-forget rollback
(``Scheduler.forget_assumed_unbound``) — treat it exactly like a serial
round's failure. The already-staged next round is safe either way: the
forget's tracker marks force its rows back through truth-lowering.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional

from koordinator_tpu.metrics.components import (
    PIPELINE_DEFERRED_ERRORS,
    PIPELINE_DRAINS,
    PIPELINE_INFLIGHT,
    ROUND_CRITICAL_PATH,
    TICK_STAGE_DURATION,
)
from koordinator_tpu.obs.flight import FLIGHT
from koordinator_tpu.obs.trace import TRACER

#: publisher-queue shutdown sentinel
_STOP = object()


class TickPipeline:
    """Depth-1 tick pipeline over a :class:`~koordinator_tpu.scheduler.
    Scheduler`: one dispatched-but-unretired tick at most, retired by a
    bounded single-worker publisher.

    ``publish`` defaults to the scheduler's wiring-bound
    ``publish_result`` (None on a standalone scheduler — the epilogue
    still runs, nothing is published). ``on_result`` is a per-round
    result hook for benches/tests (called on the publisher thread, in
    round order).

    Concurrency: the coordinator thread calls ``submit_round`` /
    ``prestage`` / ``drain`` / ``stop``; the publisher worker retires
    ticks. Every mutable attribute below is mapped to ``_lock`` in
    graftcheck's lock-discipline registry; the retire handoff itself
    rides ``_retired`` (an Event) and the bounded queue.
    """

    def __init__(self, scheduler, publish: Optional[Callable] = None,
                 log: Callable = print,
                 on_result: Optional[Callable] = None,
                 prestage_after_publish: bool = True):
        self.scheduler = scheduler
        self._publish = (
            publish if publish is not None
            else getattr(scheduler, "publish_result", None)
        )
        self._log = log
        self._on_result = on_result
        #: re-lower bind-dirty rows on the publisher right after the
        #: epilogue lands, so the next round's catch-up ensure starts
        #: near-empty (benches may disable to isolate stage costs)
        self._prestage_after_publish = prestage_after_publish
        self._lock = threading.Lock()
        self._retired = threading.Event()
        self._retired.set()
        self._queue: queue.Queue = queue.Queue(maxsize=1)
        self._inflight = False
        self._pending_error: Optional[BaseException] = None
        self._rounds = 0
        self._last: Optional[dict] = None
        self._stopped = False
        self._worker = threading.Thread(
            target=self._run, name="koord-tick-publisher", daemon=True
        )
        self._worker.start()

    # -- coordinator side ----------------------------------------------------

    def submit_round(self, now: Optional[float] = None,
                     trigger: Optional[str] = None) -> float:
        """One pipelined round's critical path: wait for the previous
        tick to retire (surfacing any deferred publish-side error at
        this round boundary), then stage + dispatch this round and hand
        it to the publisher. Returns the critical-path seconds — what
        the round actually cost the loop; the solve compute and publish
        drain in the background. ``trigger`` annotates why the round
        fired (the streaming mode's adaptive triggers) onto its trace
        spans."""
        t0 = time.perf_counter()
        self._surface(wait=True)
        TRACER.emit("retire_wait", cat="pipeline", t0=t0)
        with self._lock:
            if self._stopped:
                raise RuntimeError("tick pipeline is stopped")
            self._rounds += 1
        tick = self.scheduler.begin_tick(now, trigger=trigger)
        with self._lock:
            self._inflight = True
        self._retired.clear()
        PIPELINE_INFLIGHT.set(1)
        self._queue.put(tick)
        wall = time.perf_counter() - t0
        ROUND_CRITICAL_PATH.observe(wall)
        return wall

    def prestage(self, now: Optional[float] = None) -> None:
        """The overlap window: warm the next round's staging from
        current truth while the in-flight solve computes. The staging
        cache double-buffers (the dispatched generation is pinned), and
        bit-identity is free — see the module docstring."""
        self.scheduler.model.prestage(
            self.scheduler.cache.snapshot(now=now)
        )

    def drain(self, reason: str = "drain",
              raise_deferred: bool = True) -> None:
        """Quiesce: block until no tick is in flight (epilogue applied,
        publish done). The auditor's sweeps and the failover flip hooks
        call this so neither ever observes a half-retired round;
        ``raise_deferred=False`` (the hook form) leaves any deferred
        error pending for the next round boundary instead of raising it
        from inside a flip."""
        PIPELINE_DRAINS.inc({"reason": reason})
        if raise_deferred:
            self._surface(wait=True)
        else:
            self._retired.wait()

    #: how long stop() waits for a retire before abandoning the worker
    #: (a daemon thread) — shutdown must complete even if a publish is
    #: wedged on a half-open connection or a hung device
    STOP_TIMEOUT_S = 30.0

    def stop(self) -> None:
        """Drain and stop the publisher worker. A deferred error still
        pending at shutdown is logged, not raised — callers that care
        drain first. The retire wait is BOUNDED: a wedged publisher is
        logged and abandoned (the worker is a daemon thread), never
        allowed to hang process exit."""
        retired = self._retired.wait(timeout=self.STOP_TIMEOUT_S)
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            err = self._pending_error
        if not retired:
            self._log(f"tick pipeline stop: publisher still retiring "
                      f"after {self.STOP_TIMEOUT_S}s — abandoning the "
                      f"worker (wedged publish?)")
            # the retire may have completed in the instant between the
            # timeout and _stopped being set above — the worker would
            # then loop back to the queue having read _stopped=False.
            # Feed it _STOP so it exits on either interleaving (a truly
            # wedged worker exits on its own _stopped check instead,
            # leaving the sentinel unread in a dead pipeline's queue).
            try:
                self._queue.put_nowait(_STOP)
            except queue.Full:
                pass
            return
        if err is not None:
            self._log(f"tick pipeline stop: dropping deferred error: "
                      f"{err!r}")
        self._queue.put(_STOP)
        self._worker.join(timeout=5.0)

    def status(self) -> dict:
        """Debug-mux surface (registered as ``tick-pipeline``)."""
        with self._lock:
            return {
                "inflight": self._inflight,
                "rounds": self._rounds,
                "last_round": self._last,
                "pending_error": (
                    repr(self._pending_error)
                    if self._pending_error is not None else None
                ),
                "stopped": self._stopped,
            }

    def _surface(self, wait: bool) -> None:
        """Surface a deferred publish-side error at a round boundary."""
        if wait:
            self._retired.wait()
        with self._lock:
            err, self._pending_error = self._pending_error, None
        if err is not None:
            raise err

    # -- publisher side ------------------------------------------------------

    def _run(self) -> None:
        from koordinator_tpu.client.leaderelection import FencingError
        from koordinator_tpu.service.client import (
            SolverOverloaded,
            SolverUnavailable,
        )

        while True:
            tick = self._queue.get()
            if tick is _STOP:
                return
            try:
                self._retire(tick)
            except Exception as e:
                kind = "other"
                if isinstance(e, FencingError):
                    kind = "fencing"
                elif isinstance(e, (SolverUnavailable, SolverOverloaded)):
                    kind = "solver"
                PIPELINE_DEFERRED_ERRORS.inc({"kind": kind})
                TRACER.instant("pipeline-deferred-error", cat="pipeline",
                               args={"kind": kind})
                # the round that FAILED never reached _retire's
                # record_round — put it in the ring (error-flagged,
                # whatever stage timings it got to) so the dump this
                # very failure triggers contains the anomalous round,
                # not just the rounds leading up to it
                inflight = getattr(tick, "inflight", None)
                FLIGHT.record_round({
                    "round": getattr(tick, "round_id", None),
                    "at": getattr(tick, "at", None),
                    "error": f"{type(e).__name__}: {e}",
                    **(dict(inflight.timings)
                       if inflight is not None else {}),
                })
                # anomaly: the flight recorder preserves the rounds that
                # led up to the deferred publish-side failure
                FLIGHT.trigger(
                    "pipeline-deferred-error",
                    detail=f"{type(e).__name__}: {e}",
                )
                with self._lock:
                    self._pending_error = e
            finally:
                with self._lock:
                    self._inflight = False
                    stopped = self._stopped
                if not stopped:
                    # an abandoned worker must NOT touch the global
                    # gauge: a re-invoked loop's fresh pipeline owns it
                    # by now, and clobbering it to 0 would hide that
                    # pipeline's in-flight tick from the runbook's
                    # wedged-publisher signal
                    PIPELINE_INFLIGHT.set(0)
                self._retired.set()
            if stopped:
                # an abandoning stop() already returned without queueing
                # _STOP — exit now rather than block on the queue forever
                return

    def _abandoned(self, stage: str) -> bool:
        """True once ``stop()`` timed out and walked away from this
        worker mid-wedge. In the clean shutdown path ``_stopped`` is
        only ever set while no tick is retiring, so observing it here
        means abandonment: every later side effect — publish, metrics,
        prestage — must be dropped, because a re-invoked loop's fresh
        pipeline may own the scheduler's shared state by now. (A call
        the worker is already wedged INSIDE cannot be un-run — this
        gate bounds what happens after the current blocking call
        returns.)"""
        with self._lock:
            if not self._stopped:
                return False
        self._log(f"tick pipeline: late {stage} after an abandoning "
                  f"stop — dropping the rest of the retire")
        return True

    def _retire(self, tick) -> None:
        """Materialize + epilogue + publish one tick (the binding-cycle
        half of the round), then prestage the rows the epilogue just
        dirtied so they're off the next round's critical path."""
        result = self.scheduler.commit_tick(tick)
        if self._abandoned("epilogue"):
            return
        rid = getattr(tick, "round_id", 0)
        # watchdog mark: a publish wedged on a half-open connection is
        # exactly what the span-fed monitor exists to flag
        TRACER.mark_open(f"publish:{rid}", round_id=rid)
        t_pub = time.perf_counter()
        try:
            if self._publish is not None:
                self._publish(result)
        finally:
            # a FAILED publish (fenced, solver died) is not a STUCK
            # publish: its error defers to the round boundary, so the
            # mark must close or the watchdog flags a ghost forever
            publish_s = time.perf_counter() - t_pub
            TRACER.mark_closed(f"publish:{rid}")
        if self._abandoned("publish"):
            return
        timings = (
            dict(tick.inflight.timings) if tick.inflight is not None
            else {}
        )
        timings["publish_s"] = publish_s
        for stage in ("lower", "stage", "solve"):
            v = timings.get(f"{stage}_s")
            if v is not None:
                TICK_STAGE_DURATION.observe(v, {"stage": stage})
        TICK_STAGE_DURATION.observe(publish_s, {"stage": "publish"})
        placed = sum(1 for v in result.values() if v is not None)
        with self._lock:
            self._last = {
                "placed": placed, "total": len(result),
                "waiting": len(result.waiting), **timings,
            }
        model = getattr(self.scheduler, "model", None)
        backend = getattr(model, "backend", None)
        FLIGHT.record_round({
            "round": rid,
            "at": tick.at,
            "trigger": getattr(tick, "trigger", None),
            "placed": placed,
            "total": len(result),
            "waiting": len(result.waiting),
            "staged_epoch": getattr(
                getattr(model, "staged_cache", None), "epoch", None
            ),
            "solver": getattr(model, "last_solver", None),
            "degraded": getattr(backend, "degraded", None),
            **timings,
        })
        if self._on_result is not None:
            self._on_result(result)
        self._log(f"round: {placed}/{len(result)} placed, "
                  f"{len(result.waiting)} waiting")
        if self._prestage_after_publish and not self._abandoned("prestage"):
            self.scheduler.model.prestage(
                self.scheduler.cache.snapshot(now=tick.at)
            )
