"""Reservation lifecycle controller: expiration, status sync, GC.

Rebuild of the reference's reservation controller
(pkg/scheduler/plugins/reservation/controller/controller.go:186-266 and
garbage_collection.go:35-82):

- a reservation expires when it is neither Succeeded nor Failed and its
  ``expiration_time`` has passed, or its ``ttl`` (age since
  ``create_time``) has elapsed (ttl == 0 disables), or its bound node no
  longer exists;
- Expired/Succeeded reservations are garbage-collected ``gc_seconds``
  after the transition (default 24h, defaultGCDuration);
- status sync recomputes current owners + allocated from the live pods
  consuming the reservation, releasing capacity held by deleted pods
  (controller.go syncStatus).

Expired reservations stop holding node capacity automatically: the
snapshot lowering only encodes holds for Available reservations
(state/cluster.py).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from koordinator_tpu.apis.types import (
    ReservationSpec,
    ReservationState,
    resources_to_vector,
    vector_to_resources,
)

DEFAULT_GC_SECONDS = 24 * 3600.0


class ReservationController:
    """Periodic reconciler over the scheduler cache's reservations."""

    def __init__(self, cache, gc_seconds: float = DEFAULT_GC_SECONDS):
        self.cache = cache
        self.gc_seconds = gc_seconds
        #: reservation name -> when it left the active states
        self._done_time: Dict[str, float] = {}

    def sync(self, now: float) -> None:
        """One reconcile pass: expire → sync status → GC."""
        tracker = getattr(self.cache, "delta_tracker", None)
        for resv in list(self.cache.reservations.values()):
            if self._needs_expiration(resv, now):
                resv.state = ReservationState.EXPIRED
                if tracker is not None:
                    # the node stops holding the remainder: re-lower it
                    tracker.mark_node(resv.node_name)
            if resv.state == ReservationState.AVAILABLE:
                self._sync_status(resv)
            if resv.state in (ReservationState.EXPIRED, ReservationState.FAILED,
                              ReservationState.SUCCEEDED):
                self._done_time.setdefault(resv.name, now)
            else:
                self._done_time.pop(resv.name, None)
        self._gc(now)

    # -- expiration (controller.go:255-266 isReservationNeedExpiration) ----

    def _needs_expiration(self, resv: ReservationSpec, now: float) -> bool:
        if resv.state in (
            ReservationState.FAILED,
            ReservationState.SUCCEEDED,
            ReservationState.EXPIRED,
        ):
            return False
        # bound to a node that no longer exists: expires unconditionally
        # (controller.go:190 — checked before the TTL gates)
        if (
            resv.node_name is not None
            and resv.node_name not in self.cache.nodes
        ):
            return True
        if resv.ttl is not None and resv.ttl == 0:
            return False
        if resv.expiration_time is not None and now >= resv.expiration_time:
            return True
        if resv.ttl is not None and (now - resv.create_time) >= resv.ttl:
            return True
        return False

    # -- status sync (controller.go:207-253 syncStatus) ---------------------

    def _sync_status(self, resv: ReservationSpec) -> None:
        if resv.node_name is None:
            return
        live = [uid for uid in resv.allocated_pod_uids if uid in self.cache.pods]
        if live == resv.allocated_pod_uids:
            return
        allocated = np.zeros_like(resources_to_vector({}))
        for uid in live:
            allocated = allocated + resources_to_vector(
                self.cache.pods[uid].requests
            )
        # mask to the reservation's allocatable dimensions + clamp
        alloc_vec = resources_to_vector(resv.allocatable or resv.requests)
        allocated = np.minimum(np.where(alloc_vec > 0, allocated, 0), alloc_vec)
        resv.allocated = vector_to_resources(allocated)
        resv.allocated_pod_uids = live
        tracker = getattr(self.cache, "delta_tracker", None)
        if tracker is not None:
            # released capacity changes the node's lowered hold
            tracker.mark_node(resv.node_name)

    # -- GC (garbage_collection.go:40-82) -----------------------------------

    def _gc(self, now: float) -> None:
        for name, done in list(self._done_time.items()):
            resv = self.cache.reservations.get(name)
            if resv is None:
                self._done_time.pop(name, None)
                continue
            if now - done >= self.gc_seconds:
                self.cache.reservations.pop(name, None)
                self._done_time.pop(name, None)
