"""Scheduler cache: the live cluster model with assume/forget semantics.

Mirrors the reference's scheduler cache + loadaware podAssignCache
(pkg/scheduler/plugins/loadaware/pod_assign_cache.go): assumed pods count
against node resources immediately (before the API server confirms the
bind), with their assign timestamps driving the loadaware estimation
staleness rules. ``snapshot()`` produces the consistent typed view each
scheduling cycle (and each batched solve) runs against.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    GangSpec,
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
    ReservationSpec,
)
from koordinator_tpu.state.cluster import ClusterDeltaTracker


class SchedulerCache:
    """Every mutation marks the delta tracker with the node rows it
    touches (the informer/cache snapshot-diff idiom): snapshots carry
    the tracker, so the model's staging cache re-lowers only what
    actually changed between scheduling rounds. Gang/quota updates
    don't mark — they never enter the node arrays (lowered per solve).

    Concurrency: every mutable mapping below is mapped to ``_lock`` in
    graftcheck's lock-discipline registry (docs/DESIGN.md §11) — any
    access outside ``with self._lock`` fails tier-1 statically.
    """

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self.nodes: Dict[str, NodeSpec] = {}
        self.pods: Dict[str, PodSpec] = {}          # assigned (incl. assumed)
        self.pending: Dict[str, PodSpec] = {}
        self.assumed: Dict[str, float] = {}         # uid -> assume time
        self.node_metrics: Dict[str, NodeMetric] = {}
        self.gangs: Dict[str, GangSpec] = {}
        self.quotas: Dict[str, QuotaSpec] = {}
        self.reservations: Dict[str, ReservationSpec] = {}
        self.delta_tracker = ClusterDeltaTracker()

    # -- informer-style updates --------------------------------------------

    def add_node(self, node: NodeSpec) -> None:
        with self._lock:
            if node.name in self.nodes:
                # spec update in place: same node set/order, one dirty row
                self.delta_tracker.mark_node(node.name)
            else:
                self.delta_tracker.mark_structure()
            self.nodes[node.name] = node

    def remove_node(self, name: str) -> None:
        with self._lock:
            if self.nodes.pop(name, None) is not None:
                self.delta_tracker.mark_structure()

    def add_pod(self, pod: PodSpec) -> None:
        """A pod object appeared: pending if unassigned, else running."""
        with self._lock:
            if pod.node_name:
                self.pods[pod.uid] = pod
                self.delta_tracker.mark_node(pod.node_name)
            else:
                self.pending[pod.uid] = pod

    def remove_pod(self, uid: str) -> None:
        with self._lock:
            pod = self.pods.pop(uid, None)
            if pod is not None:
                self.delta_tracker.mark_node(pod.node_name)
            self.pending.pop(uid, None)
            self.assumed.pop(uid, None)

    def promote_assigned(self, pod: PodSpec) -> None:
        """A binding became visible through the bus (another scheduler's
        Bind, or in-place mutation on the in-process bus): move the pod
        from pending to assigned without touching assign bookkeeping."""
        with self._lock:
            self.pending.pop(pod.uid, None)
            prev = self.pods.get(pod.uid)
            if prev is not None and prev.node_name != pod.node_name:
                self.delta_tracker.mark_node(prev.node_name)
            self.pods[pod.uid] = pod
            self.delta_tracker.mark_node(pod.node_name)

    def update_node_metric(self, metric: NodeMetric) -> None:
        with self._lock:
            self.node_metrics[metric.node_name] = metric
            self.delta_tracker.mark_node(metric.node_name)

    def update_gang(self, spec: GangSpec) -> None:
        with self._lock:
            self.gangs[spec.name] = spec

    def update_quota(self, spec: QuotaSpec) -> None:
        with self._lock:
            self.quotas[spec.name] = spec

    def update_reservation(self, spec: ReservationSpec) -> None:
        with self._lock:
            # stamp creation for TTL expiry (the CRD's creationTimestamp);
            # an unset create_time with a live TTL would expire immediately
            if spec.ttl and not spec.create_time:
                spec.create_time = time.time()
            prev = self.reservations.get(spec.name)
            if prev is not None and prev.node_name != spec.node_name:
                self.delta_tracker.mark_node(prev.node_name)
            self.reservations[spec.name] = spec
            self.delta_tracker.mark_node(spec.node_name)

    # -- assume / forget (reference: scheduler cache AssumePod) -------------

    def assume_pod(self, uid: str, node_name: str, now: Optional[float] = None) -> None:
        with self._lock:
            pod = self.pending.pop(uid, None)
            if pod is None:
                return
            pod.node_name = node_name
            pod.assign_time = now if now is not None else time.time()
            self.pods[uid] = pod
            self.assumed[uid] = pod.assign_time
            self.delta_tracker.mark_node(node_name)

    def forget_pod(self, uid: str) -> None:
        """Bind failed / gang rejected: back to pending."""
        with self._lock:
            pod = self.pods.pop(uid, None)
            self.assumed.pop(uid, None)
            if pod is not None:
                self.delta_tracker.mark_node(pod.node_name)
                pod.node_name = None
                pod.waiting_permit = False
                self.pending[pod.uid] = pod

    def open_permit(self, uid: str) -> None:
        """The Permit barrier opened: the pod becomes bindable. The
        assume entry is KEPT — only the publish confirmation
        (:meth:`finish_binding`) closes it, so a round that aborts
        after opening the barrier (FencingError) can still forget the
        never-published decision."""
        with self._lock:
            pod = self.pods.get(uid)
            if pod is not None:
                pod.waiting_permit = False

    def finish_binding(self, uid: str) -> None:
        with self._lock:
            self.assumed.pop(uid, None)
            pod = self.pods.get(uid)
            if pod is not None:
                pod.waiting_permit = False  # the Permit barrier opened

    # -- snapshot -----------------------------------------------------------

    def snapshot(self, now: Optional[float] = None) -> ClusterSnapshot:
        with self._lock:
            return ClusterSnapshot(
                nodes=list(self.nodes.values()),
                pods=list(self.pods.values()),
                pending_pods=list(self.pending.values()),
                node_metrics=dict(self.node_metrics),
                gangs=dict(self.gangs),
                quotas=dict(self.quotas),
                reservations=list(self.reservations.values()),
                now=now if now is not None else time.time(),
                delta_tracker=self.delta_tracker,
                # captured under the lock: marks landing after this
                # point carry a later epoch and re-lower next tick
                delta_epoch=self.delta_tracker.epoch,
            )
