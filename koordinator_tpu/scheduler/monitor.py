"""Scheduler monitor + debug services.

- SchedulerMonitor: flags slow/stuck scheduling cycles (reference:
  pkg/scheduler/frameworkext/scheduler_monitor.go:44-103).
- DebugRecorder: runtime-togglable score/filter dumps (reference:
  pkg/scheduler/frameworkext/debug.go and the /debug/flags HTTP toggles).
- DebugServices: per-plugin debug endpoints as plain dict payloads
  (reference: frameworkext/services/services.go — there gin HTTP, here an
  in-process registry any HTTP layer can front).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class SchedulerMonitor:
    def __init__(self, timeout_seconds: float = 10.0, log=print):
        self.timeout = timeout_seconds
        self.log = log
        self._lock = threading.Lock()
        self._active: Dict[str, float] = {}
        self.slow_cycles: List[Dict] = []

    def cycle_started(self, pod_uid: str, at: Optional[float] = None) -> None:
        with self._lock:
            self._active[pod_uid] = at if at is not None else time.monotonic()

    def cycle_finished(self, pod_uid: str, duration: float) -> None:
        with self._lock:
            self._active.pop(pod_uid, None)
            if duration > self.timeout:
                record = {"pod": pod_uid, "duration_s": duration}
                self.slow_cycles.append(record)
                self.log(f"scheduler monitor: slow cycle {record}")

    def check_stuck(self) -> List[str]:
        """Pods whose cycle has been running past the timeout right now."""
        now = time.monotonic()
        with self._lock:
            return [
                uid for uid, t0 in self._active.items() if now - t0 > self.timeout
            ]


class DebugRecorder:
    """Score/filter dump collection, toggled at runtime."""

    def __init__(self) -> None:
        self.dump_scores = False
        self.dump_filters = False
        self.scores: List[Dict] = []
        self.filters: List[Dict] = []

    def record_scores(self, pod_uid: str, scores: Dict[str, int]) -> None:
        if self.dump_scores:
            self.scores.append({"pod": pod_uid, "scores": dict(scores)})

    def record_filter(self, pod_uid: str, node: str, plugin: str, status) -> None:
        if self.dump_filters:
            self.filters.append(
                {
                    "pod": pod_uid,
                    "node": node,
                    "plugin": plugin,
                    "reason": status.reason,
                }
            )


class DebugServices:
    """Named debug endpoints: plugins register callables returning dicts."""

    def __init__(self) -> None:
        self._services: Dict[str, Callable[[], Dict]] = {}

    def register(self, plugin_name: str, fn: Callable[[], Dict]) -> None:
        self._services[plugin_name] = fn

    def query(self, plugin_name: str) -> Optional[Dict]:
        fn = self._services.get(plugin_name)
        return fn() if fn is not None else None

    def names(self) -> List[str]:
        return sorted(self._services)
