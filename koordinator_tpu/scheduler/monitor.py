"""Scheduler monitor + debug services.

- SchedulerMonitor: a span-fed stuck-cycle watchdog (reference:
  pkg/scheduler/frameworkext/scheduler_monitor.go:44-103). The seed
  version kept its own per-pod start-time dict fed by host-side
  ``cycle_started``/``cycle_finished`` calls — a recording path the
  batched device solve never exercised (only the incremental fallback
  fed it). That path is deleted: the watchdog now reads the trace
  fabric's open marks (``round:<id>``/``publish:<id>``, opened by
  ``begin_tick`` and the tick publisher — obs/trace.py), so "stuck"
  means the thing that actually matters — a round that never retired
  or a publish wedged on a half-open connection — and every detection
  counts into ``scheduler_stuck_cycles_total{kind}``.
- DebugRecorder: runtime-togglable score/filter dumps (reference:
  pkg/scheduler/frameworkext/debug.go and the /debug/flags HTTP
  toggles), extended with a bounded ring of placement-explain payloads
  (obs/explain.py answers through it).
- DebugServices: per-plugin debug endpoints as plain dict payloads
  (reference: frameworkext/services/services.go — there gin HTTP, here
  an in-process registry any HTTP layer can front).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Dict, List, Optional


class SchedulerMonitor:
    """Watchdog over the tracer's open round/publish marks.

    ``check_stuck`` is cheap (one dict snapshot) and side-effect-safe
    to call from anywhere: the scheduling loop calls it at round start,
    and the debug mux's ``monitor`` service calls it on GET — which is
    the path that still works when the loop itself is wedged behind a
    stuck publish. Each stuck mark is counted ONCE
    (``scheduler_stuck_cycles_total{kind}``) no matter how many
    monitors watch the tracer — the counted-stuck flag lives with the
    mark itself (``SpanTracer.flag_stuck``), so a leader + standby in
    one process, or a mux status() reader racing the loop's check,
    never double-count — and the flag clears when the mark closes."""

    def __init__(self, tracer=None, timeout_seconds: float = 10.0,
                 log=print):
        if tracer is None:
            from koordinator_tpu.obs.trace import TRACER

            tracer = TRACER
        self.tracer = tracer
        self.timeout = timeout_seconds
        self.log = log

    def check_stuck(self, now: Optional[float] = None) -> List[str]:
        """Open marks older than the timeout right now. Newly-stuck
        marks are logged and counted; a mark is never double-counted."""
        stuck, _ = self._check(now)
        return stuck

    def _check(self, now: Optional[float] = None):
        """One pass over one open-marks snapshot: returns (stuck keys,
        the snapshot) so status() reports ages consistent with the
        verdict instead of re-snapshotting the tracer."""
        from koordinator_tpu.metrics.components import STUCK_CYCLES

        if now is None:
            now = self.tracer.now()
        newly: List[tuple] = []
        open_marks = self.tracer.open_marks()
        stuck: List[str] = []
        for key, (t0, track, _rid) in open_marks.items():
            age = now - t0
            if age <= self.timeout:
                continue
            stuck.append(key)
            # flag_stuck is the tracer-level test-and-set: True only
            # for the first flagging of a still-open mark, across ALL
            # monitors sharing the tracer (a mark that closed since
            # our snapshot is never flagged)
            if self.tracer.flag_stuck(key):
                newly.append((key, age, track))
        for key, age, track in newly:
            kind = key.split(":", 1)[0]
            STUCK_CYCLES.inc({"kind": kind})
            self.log(
                f"scheduler monitor: {kind} stuck for {age:.1f}s "
                f"(> {self.timeout}s): {key} on {track}"
            )
        return stuck, (now, open_marks)

    def status(self) -> Dict[str, object]:
        """Debug-mux payload — running the check on read is the point:
        the mux thread observes a wedge the blocked loop cannot."""
        stuck, (now, open_marks) = self._check()
        return {
            "timeout_s": self.timeout,
            "stuck": stuck,
            "open_marks": {
                k: {"age_s": now - t0, "track": track, "round": rid}
                for k, (t0, track, rid) in open_marks.items()
            },
        }


class DebugRecorder:
    """Score/filter/explain dump collection, toggled at runtime."""

    #: bounded explain history (every /explain answer lands here)
    MAX_EXPLAINS = 64

    def __init__(self) -> None:
        self.dump_scores = False
        self.dump_filters = False
        self.scores: List[Dict] = []
        self.filters: List[Dict] = []
        self.explains: deque = deque(maxlen=self.MAX_EXPLAINS)

    def record_scores(self, pod_uid: str, scores: Dict[str, int]) -> None:
        if self.dump_scores:
            self.scores.append({"pod": pod_uid, "scores": dict(scores)})

    def record_filter(self, pod_uid: str, node: str, plugin: str, status) -> None:
        if self.dump_filters:
            self.filters.append(
                {
                    "pod": pod_uid,
                    "node": node,
                    "plugin": plugin,
                    "reason": status.reason,
                }
            )

    def record_explain(self, payload: Dict) -> None:
        """Explain answers are always kept (bounded): by the time an
        operator asks "why", a toggle-first flow would have lost the
        interesting one."""
        self.explains.append(payload)


class DebugServices:
    """Named debug endpoints: plugins register callables returning dicts."""

    def __init__(self) -> None:
        self._services: Dict[str, Callable[[], Dict]] = {}

    def register(self, plugin_name: str, fn: Callable[[], Dict]) -> None:
        self._services[plugin_name] = fn

    def query(self, plugin_name: str) -> Optional[Dict]:
        fn = self._services.get(plugin_name)
        return fn() if fn is not None else None

    def names(self) -> List[str]:
        return sorted(self._services)
